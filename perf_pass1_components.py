"""Standalone device times of each sub-stage of the fused pass-1 program
at chunk shape B=256, to find where the ~70 ms/chunk goes. Chip only.
"""
import time

from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

configure_jax_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from bench import _load  # noqa: E402
from fabric_token_sdk_tpu.models import range_verifier as rv  # noqa: E402
from fabric_token_sdk_tpu.ops import ec, limbs, pallas_fb  # noqa: E402

B = 256


def timeit(label, fn, iters=8):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"  {label:>28}: {dt*1e3:7.2f} ms")
    return out


def main():
    pp, proofs, coms = _load()
    reps = (B + len(proofs) - 1) // len(proofs)
    proofs = (proofs * reps)[:B]
    coms = (coms * reps)[:B]
    v = rv.BatchRangeVerifier(pp)
    params = v.params
    n = params.bit_length
    nv = 2 + 2 * params.rounds + 3

    ch = list(range(B))
    st = v._dispatch_pass1(proofs, coms, ch)
    jax.block_until_ready(st[1])

    # Build the same inputs the fused program sees
    rng = np.random.default_rng(0)
    sc4 = jnp.asarray(rng.integers(0, 2**16, (B, 4, 16), dtype=np.uint32))
    allpts = []
    for i in ch:
        d = proofs[i].data
        allpts += ([d.D, d.C] + proofs[i].ipa.L + proofs[i].ipa.R
                   + [d.T1, d.T2, coms[i]])
    proj = limbs.points_to_projective_limbs(allpts).reshape(B, nv, 3, 16)
    inf_np = (proj[:, :, 2] == 0).all(-1).astype(np.uint8)
    xy = jnp.asarray(proj[:, :, :2])
    inf = jnp.asarray(inf_np)
    ip_u8 = jnp.asarray(rng.integers(0, 255, (B, 32), dtype=np.uint8))

    derive = jax.jit(lambda s: rv._derive_pass1_scalars(s, n))
    yinv, k_fixed, dc_sc = timeit("derive_pass1_scalars", lambda: derive(sc4))
    pts = timeit("reconstruct_points", lambda: rv._reconstruct_points(xy, inf))

    gather = jax.jit(lambda t, y: pallas_fb.fixed_base_gather_fused(t, y))
    rgp_pts = timeit("rgp gather (pallas)",
                     lambda: gather(params.tables_t_rgp, yinv))

    kmsm = jax.jit(lambda t, s: pallas_fb.fixed_base_msm_fused(t, s))
    k1 = timeit("K fixed MSM (pallas)",
                lambda: kmsm(params.tables_t_k, k_fixed))
    kvar = jax.jit(lambda p, s: ec.msm_windowed(p, s))
    k2 = timeit("K var 2-term (xla)", lambda: kvar(pts[:, :2], dc_sc))

    aff_b = jax.jit(lambda p: ec.to_affine_batch(p))
    rgp_aff = timeit("to_affine_batch(rgp 64)", lambda: aff_b(rgp_pts))

    tab = jax.jit(lambda p: rv._limbs_to_bytes_dev(ec.to_affine_batch(p)))
    rgp_bytes = timeit("affine+bytes rgp", lambda: tab(rgp_pts))
    k_pt = ec.add(k1, k2)
    tak = jax.jit(lambda p: rv._limbs_to_bytes_dev(ec.to_affine(p)))
    k_bytes = timeit("affine+bytes K", lambda: tak(k_pt))

    xipa = rv._xipa_device_fn(params)
    timeit("xipa SHA", lambda: xipa(rgp_bytes, k_bytes, ip_u8))

    rdig = jax.jit(lambda a, b: rv._round_digests(a, b, params.rounds))
    timeit("round digests SHA", lambda: rdig(xy, inf))

    # whole fused program for comparison
    run, nv_, o_inf, o_ip = rv._pass1_fused_fn(params)
    packed = np.zeros((B, o_ip + 8), dtype=np.uint32)
    packed[:, :64] = np.asarray(sc4).reshape(B, 64)
    xyu16 = proj[:, :, :2].astype("<u2")
    packed[:, 64:o_inf] = np.ascontiguousarray(
        xyu16.reshape(B, -1)).view("<u4")
    packed[:, o_inf:o_ip] = inf_np
    packed[:, o_ip:] = np.ascontiguousarray(np.asarray(ip_u8)).view("<u4")
    pk = jnp.asarray(packed)
    timeit("FULL fused pass-1", lambda: run(
        params.tables_t_rgp, params.tables_t_k, pk), iters=4)


if __name__ == "__main__":
    main()
