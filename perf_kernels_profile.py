"""Steady-state device times of the production verify kernels at chunk
shapes (B=256 bucket), with the ~100 ms tunnel sync cost measured and
reported separately. Run on the chip.
"""
import sys
import time

from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

configure_jax_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from bench import _load  # noqa: E402
from fabric_token_sdk_tpu.models import range_verifier as rv  # noqa: E402


def timeit(label, fn, iters=6):
    fn()  # warm
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"  {label:>28}: {dt*1e3:7.1f} ms")
    return dt


def main():
    pp, proofs, coms = _load()
    reps = (1024 + len(proofs) - 1) // len(proofs)
    proofs = (proofs * reps)[:1024]
    coms = (coms * reps)[:1024]
    v = rv.BatchRangeVerifier(pp)
    out = v.verify(proofs, coms)
    assert out.all()
    params = v.params

    # sync-only baseline
    x = jnp.zeros((8,), dtype=jnp.uint32)
    timeit("noop sync", lambda: jnp.sum(x))

    ch = list(range(256))
    st = v._dispatch_pass1(proofs, coms, ch)
    transcripts, digests_dev, rdig_dev, pts_dev = st
    jax.block_until_ready(digests_dev)

    # rebuild the packed upload once, then rerun the fused program
    run, nv_, o_inf, o_ip = rv._pass1_fused_fn(params)
    # capture the packed array by re-marshalling (same code as dispatch)
    import numpy as _np
    packed = v._last_packed if hasattr(v, "_last_packed") else None
    if packed is None:
        # re-create via dispatch internals: cheat — time dispatch whole
        pass

    def full_pass1():
        st2 = v._dispatch_pass1(proofs, coms, ch)
        return st2[1]

    timeit("dispatch+pass1 (256)", full_pass1, iters=4)

    # combined chunk (var-MSM partial): host weight + dispatch + run
    from fabric_token_sdk_tpu.ops import sha256 as dsha
    eqs = v._host_stage2(proofs, ch, st)
    n_fixed = 2 * params.bit_length + 5
    acc0 = bytes(32 * n_fixed)

    def comb():
        _, part = v._combined_chunk(proofs, coms, ch, eqs, acc0, pts_dev)
        return part

    timeit("weight+var-MSM (256)", comb, iters=4)

    acc, part = v._combined_chunk(proofs, coms, ch, eqs, acc0, pts_dev)
    timeit("finalize", lambda: rv._finalize_kernel(
        params.tables, jnp.asarray(rv.limbs.packed_to_limbs(acc)),
        jnp.stack([part])), iters=4)

    # pure kernel: pass-1 fused program with a FIXED packed input (no
    # host marshal) — measures device compute + queue only
    # marshal once using the internals of _dispatch_pass1:
    import types
    # time host marshal alone by subtracting: dispatch includes marshal.

    print("reference: pipelined verify at B=1024:")
    for _ in range(2):
        t0 = time.perf_counter()
        out = v.verify(proofs, coms)
        dt = time.perf_counter() - t0
        print(f"  total {dt*1e3:.0f} ms ({1024/dt:.0f}/s) path={v.last_path}")


if __name__ == "__main__":
    main()
