"""Microbenchmarks for the field/EC kernel layer on the current backend.

Usage: python perf_experiments.py [batch_log2]

Measures steady-state throughput of mont_mul, the constant-operand
Toeplitz-matmul variant (int8 nibble planes on the MXU), and complete
point addition — the primitives everything above is made of.
"""

import sys
import time

from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

configure_jax_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from fabric_token_sdk_tpu.ops import ec, field as F, limbs as L  # noqa: E402

LOG2 = int(sys.argv[1]) if len(sys.argv) > 1 else 18
B = 1 << LOG2


def _bench(fn, *args, iters=8, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready")
        else x, out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready")
        else x, out)
    return (time.perf_counter() - t0) / iters


# ---------------------------------------------------------------------------
# int8 nibble Toeplitz prototype: cols(a) = sum_i a_i * C_{k-i} for constant C
# ---------------------------------------------------------------------------

def _nibble_split(a):
    """(..., 16) uint32 limbs -> (..., 64) int8 nibbles, little-endian."""
    n0 = (a & 0xF).astype(jnp.int8)
    n1 = ((a >> 4) & 0xF).astype(jnp.int8)
    n2 = ((a >> 8) & 0xF).astype(jnp.int8)
    n3 = ((a >> 12) & 0xF).astype(jnp.int8)
    return jnp.stack([n0, n1, n2, n3], axis=-1).reshape(*a.shape[:-1], 64)


def _toeplitz_nibble_matrix(const_limbs, out_cols):
    """(64, out_cols*4->folded) int8 matrix: nibble conv with the constant.

    Result columns are NIBBLE positions (out_cols*4); each output nibble
    column k sums a-nibble i times c-nibble (k-i): values <= 15*15*64 fits
    int32 via int8 MXU accumulation.
    """
    c = []
    for limb in const_limbs:
        for shift in (0, 4, 8, 12):
            c.append((int(limb) >> shift) & 0xF)
    nc = len(c)
    out_n = out_cols * 4
    W = np.zeros((64, out_n), dtype=np.int8)
    for i in range(64):
        for j in range(nc):
            if i + j < out_n:
                W[i, i + j] = c[j]
    return jnp.asarray(W)


def make_const_product_nibble(const_limbs, out_cols):
    W = _toeplitz_nibble_matrix([int(x) for x in const_limbs], out_cols)

    def product(a):
        nib = _nibble_split(a)                       # (..., 64) int8
        cols_n = jax.lax.dot_general(
            nib, W, (((nib.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)        # (..., out_cols*4)
        # fold nibble columns (weights 1,16,256,4096) back to limb columns
        cn = cols_n.reshape(*cols_n.shape[:-1], out_cols, 4).astype(jnp.uint32)
        return (cn[..., 0] + (cn[..., 1] << 4) + (cn[..., 2] << 8)
                + (cn[..., 3] << 12))                # lazy cols < 2^26

    return product


def mont_mul_mxu(a, b, spec, nprime_prod, mod_prod):
    """mont_mul with the two constant-operand products on the int8 MXU."""
    t_cols = F._shift_add_product(a, b, F.N, 2 * F.N)
    T = F._carry_propagate(t_cols, 2 * F.N + 1)
    m_cols = nprime_prod(T[..., :F.N])[..., :F.N]
    m = F._carry_propagate(m_cols, F.N)
    u_cols = mod_prod(m)
    s = F._carry_propagate(
        T + jnp.pad(u_cols, [(0, 0)] * (T.ndim - 1) + [(0, 1)]),
        2 * F.N + 1)
    res = s[..., F.N:]
    return F._cond_sub_mod(res, spec)


def main():
    print(f"backend={jax.devices()[0].platform} B=2^{LOG2}={B}")
    rng = np.random.default_rng(0)
    spec = F.FP
    a_int = [int.from_bytes(rng.bytes(31), "little") for _ in range(2)]
    a = jnp.asarray(np.tile(L.int_to_limbs(a_int[0]), (B, 1)))
    b = jnp.asarray(np.tile(L.int_to_limbs(a_int[1]), (B, 1)))

    mm = jax.jit(lambda x, y: F.mont_mul(x, y, spec))
    t = _bench(mm, a, b)
    print(f"mont_mul       : {t*1e3:8.2f} ms  {B/t/1e6:8.2f} Mmul/s")

    nprime_prod = make_const_product_nibble(spec.nprime, F.N)
    mod_prod = make_const_product_nibble(spec.mod, 2 * F.N)
    mmx = jax.jit(lambda x, y: mont_mul_mxu(x, y, spec, nprime_prod,
                                            mod_prod))
    # correctness first
    got = np.asarray(mmx(a[:4], b[:4]))
    want = np.asarray(mm(a[:4], b[:4]))
    ok = bool((got == want).all())
    t = _bench(mmx, a, b)
    print(f"mont_mul_mxu   : {t*1e3:8.2f} ms  {B/t/1e6:8.2f} Mmul/s  "
          f"correct={ok}")

    # complete point add
    P_b = 1 << max(0, LOG2 - 3)
    from fabric_token_sdk_tpu.crypto import bn254

    p1 = L.point_to_projective_limbs(bn254.g1_mul(bn254.G1_GENERATOR, 7))
    p2 = L.point_to_projective_limbs(bn254.g1_mul(bn254.G1_GENERATOR, 9))
    pa = jnp.asarray(np.tile(p1, (P_b, 1, 1)))
    pb = jnp.asarray(np.tile(p2, (P_b, 1, 1)))
    padd = jax.jit(ec.add)
    t = _bench(padd, pa, pb)
    print(f"ec.add         : {t*1e3:8.2f} ms  {P_b/t/1e6:8.2f} Madd/s "
          f"({P_b} lanes)")


if __name__ == "__main__":
    main()


def pallas_kernels():
    """Pallas-kernel-level microbench at production shapes (TPU):
      - fb_fold_t (pass-1 rgp): T=64, B=256/1024
      - fb_msm_t vs fold+XLA-tree (K fixed part): T=66
      - msm_var_fused vs XLA msm_windowed (combined pass-2): V=17408
      - tec.add throughput inside a minimal pallas loop
    Usage: python -c "import perf_experiments as p; p.pallas_kernels()"
    """
    import secrets

    from fabric_token_sdk_tpu.crypto import bn254
    from fabric_token_sdk_tpu.ops import pallas_fb

    assert jax.default_backend() == "tpu", "pallas bench needs the chip"
    for B in (256, 1024):
        T = 64
        gens = [bn254.g1_mul(bn254.G1_GENERATOR, 3 + i) for i in range(T)]
        planes = ec.fixed_base_planes(
            jnp.asarray(L.points_to_projective_limbs(gens)))
        planes_t = jax.jit(pallas_fb.transpose_planes)(planes)
        sc = jnp.asarray(np.stack([L.scalars_to_limbs(
            [secrets.randbelow(bn254.R) for _ in range(T)])
            for _ in range(B)]))
        t = _bench(pallas_fb.fixed_base_gather_fused, planes_t, sc, iters=4)
        print(f"fb gather T={T} B={B:5d}: {t*1e3:8.1f} ms "
              f"({B*T*31/t/1e6:6.2f} M lane-adds/s)")
        t = _bench(pallas_fb.fixed_base_msm_fused, planes_t, sc, iters=4)
        print(f"fb MSM(acc) T={T} B={B:5d}: {t*1e3:8.1f} ms")

        def msm_tree(pt, s):
            per = pallas_fb.fixed_base_gather_fused(pt, s)
            return ec._tree_sum_shrink(per)

        msm_tree_j = jax.jit(msm_tree)
        t = _bench(msm_tree_j, planes_t, sc, iters=4)
        print(f"fb MSM(tree) T={T} B={B:5d}: {t*1e3:8.1f} ms")

    for V in (4608, 17408):
        pts_h = [bn254.g1_mul(bn254.G1_GENERATOR, 5 + i) for i in range(64)]
        pts = jnp.asarray(np.stack(
            [L.point_to_projective_limbs(pts_h[i % 64]) for i in range(V)]))
        sc = jnp.asarray(L.scalars_to_limbs(
            [secrets.randbelow(bn254.R) for _ in range(V)]))
        t = _bench(pallas_fb.msm_var_fused, pts, sc, iters=4)
        print(f"var MSM pallas V={V:6d}: {t*1e3:8.1f} ms "
              f"({V/t/1e3:6.1f}k terms/s)")
        mw = jax.jit(ec.msm_windowed)
        t = _bench(mw, pts, sc, iters=4)
        print(f"var MSM XLA    V={V:6d}: {t*1e3:8.1f} ms")
