"""Phase-level profile of the batched range verifier on the current backend.

Times pass-1 (transcript points), host phase a/b, and pass-2 (combined MSM)
separately at a given batch size. Run on the real chip:
    python profile_verifier.py [BATCH]
"""

import sys
import time

from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

configure_jax_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from bench import _load  # noqa: E402
from fabric_token_sdk_tpu.models import range_verifier as rv  # noqa: E402
from fabric_token_sdk_tpu.ops import limbs  # noqa: E402
from fabric_token_sdk_tpu.crypto import bn254  # noqa: E402

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 128


def main():
    pp, proofs, coms = _load()
    reps = (BATCH + len(proofs) - 1) // len(proofs)
    proofs = (proofs * reps)[:BATCH]
    coms = (coms * reps)[:BATCH]

    t0 = time.perf_counter()
    v = rv.BatchRangeVerifier(pp)
    params = v.params
    print(f"tables: {time.perf_counter()-t0:.2f}s", flush=True)

    # warm-up full verify (compiles everything)
    t0 = time.perf_counter()
    out = v.verify(proofs, coms)
    print(f"warmup verify: {time.perf_counter()-t0:.2f}s all={out.all()}",
          flush=True)

    # ---- phase timings (steady state)
    n = params.bit_length
    live = list(range(BATCH))
    t0 = time.perf_counter()
    transcripts = {i: rv._host_phase_a(proofs[i], coms[i], params)
                   for i in live}
    t_host_a = time.perf_counter() - t0

    b_bucket = rv._bucket_rows(len(live))
    zero_sc = np.zeros(limbs.NLIMBS, dtype=np.uint32)
    id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
    t0 = time.perf_counter()
    if rv._FRNATIVE is not None:
        yinv_np = limbs.packed_to_limbs(
            b"".join(transcripts[i].yinv_packed for i in live)
        ).reshape(len(live), n, limbs.NLIMBS)
        k_fixed_np = limbs.packed_to_limbs(
            b"".join(transcripts[i].k_fixed_packed for i in live)
        ).reshape(len(live), n + 2, limbs.NLIMBS)
    else:
        yinv_np = np.stack(
            [limbs.scalars_to_limbs(transcripts[i].yinv_pows) for i in live])
        k_fixed_np = np.stack(
            [limbs.scalars_to_limbs(transcripts[i].k_fixed_scalars)
             for i in live])
    yinv = jnp.asarray(rv._pad_rows(yinv_np, b_bucket, zero_sc))
    k_fixed = jnp.asarray(rv._pad_rows(k_fixed_np, b_bucket, zero_sc))
    dc_pts_np = np.stack(
        [limbs.points_to_projective_limbs(
            [proofs[i].data.D, proofs[i].data.C]) for i in live])
    dc_pts = jnp.asarray(rv._pad_rows(dc_pts_np, b_bucket, id_pt))
    dc_sc_np = np.stack(
        [limbs.scalars_to_limbs(transcripts[i].k_var_scalars)
         for i in live])
    dc_sc = jnp.asarray(rv._pad_rows(dc_sc_np, b_bucket, zero_sc))
    t_marshal = time.perf_counter() - t0

    fused = params.tables_t_rgp is not None
    t0 = time.perf_counter()
    if fused:
        from fabric_token_sdk_tpu.ops import pallas_fb

        rgp_dev = pallas_fb.fixed_base_gather_fused(params.tables_t_rgp,
                                                    yinv)
    else:
        rgp_dev = rv._rgp_gather_kernel(params.tables, params.rgp_idx, yinv)
    rgp_dev.block_until_ready()
    t_rgp = time.perf_counter() - t0

    t0 = time.perf_counter()
    rgp_aff = rv._affine_rows_kernel(rgp_dev)
    rgp_aff.block_until_ready()
    t_rgp_aff = time.perf_counter() - t0

    t0 = time.perf_counter()
    if fused:
        k_dev = rv._k_var_add_kernel(
            pallas_fb.fixed_base_msm_fused(params.tables_t_k, k_fixed),
            dc_pts, dc_sc)
    else:
        k_dev = rv._k_pass_kernel(params.tables, params.k_idx, k_fixed,
                                  dc_pts, dc_sc)
    k_aff = rv._affine_kernel(k_dev)
    k_aff.block_until_ready()
    t_k = time.perf_counter() - t0

    t0 = time.perf_counter()
    rgp_bytes = rv.affine_batch_to_bytes(np.asarray(rgp_aff)[:len(live)])
    k_bytes = rv.affine_batch_to_bytes(np.asarray(k_aff)[:len(live)])
    equations = {}
    for row, i in enumerate(live):
        rgp_hex = [bytes(rgp_bytes[row, j]).hex().encode("ascii")
                   for j in range(n)]
        k_hex = bytes(k_bytes[row]).hex().encode("ascii")
        equations[i] = rv._host_phase_b(proofs[i], transcripts[i], rgp_hex,
                                        k_hex, params)
    t_host_b = time.perf_counter() - t0

    t0 = time.perf_counter()
    ok = v._verify_combined(proofs, coms, live, equations)
    t_combined = time.perf_counter() - t0

    total = t_host_a + t_marshal + t_rgp + t_rgp_aff + t_k + t_host_b + \
        t_combined
    print(f"B={BATCH}  total={total:.3f}s  ({BATCH/total:.1f}/s)  ok={ok}")
    for name, t in [("host_a", t_host_a), ("marshal", t_marshal),
                    ("rgp_gather", t_rgp), ("rgp_affine", t_rgp_aff),
                    ("k_pass+affine", t_k), ("host_b(+bytes)", t_host_b),
                    ("combined_msm", t_combined)]:
        print(f"  {name:>14}: {t:.3f}s  {100*t/total:.1f}%")


if __name__ == "__main__":
    main()
