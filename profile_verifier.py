"""Phase-level profile of the batched range verifier on the current backend.

Reports (a) the end-to-end pipelined verify time at a given batch size and
(b) a barriered per-phase breakdown of one chunk (phases serialized with
block_until_ready, so the sum exceeds the pipelined wall time — that gap is
the host/device overlap the pipeline buys). Run on the real chip:
    python profile_verifier.py [BATCH]
"""

import sys
import time

from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

configure_jax_cache()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from bench import _load  # noqa: E402
from fabric_token_sdk_tpu.models import range_verifier as rv  # noqa: E402

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 1024


def main():
    pp, proofs, coms = _load()
    reps = (BATCH + len(proofs) - 1) // len(proofs)
    proofs = (proofs * reps)[:BATCH]
    coms = (coms * reps)[:BATCH]

    t0 = time.perf_counter()
    v = rv.BatchRangeVerifier(pp)
    print(f"tables: {time.perf_counter()-t0:.2f}s", flush=True)

    t0 = time.perf_counter()
    out = v.verify(proofs, coms)
    print(f"warmup verify: {time.perf_counter()-t0:.2f}s all={out.all()}",
          flush=True)

    # ---- end-to-end pipelined (steady state)
    for _ in range(2):
        t0 = time.perf_counter()
        out = v.verify(proofs, coms)
        total = time.perf_counter() - t0
        print(f"B={BATCH}  pipelined total={total:.3f}s "
              f"({BATCH/total:.1f}/s)  ok={bool(out.all())} "
              f"path={v.last_path}", flush=True)

    # ---- barriered breakdown of ONE chunk
    ch = list(range(min(rv._CHUNK_ROWS, BATCH)))
    t0 = time.perf_counter()
    st = v._dispatch_pass1(proofs, coms, ch)
    t_dispatch = time.perf_counter() - t0
    transcripts, digests_dev, _rdig, pts_dev = st
    t0 = time.perf_counter()
    jax.block_until_ready(digests_dev)
    t_pass1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    words = np.asarray(digests_dev)[:len(ch)]
    t_transfer = time.perf_counter() - t0
    t0 = time.perf_counter()
    from fabric_token_sdk_tpu.ops import sha256 as dsha

    x_ipa = [vv % rv.R for vv in dsha.digest_words_to_ints(words)]
    t_xipa = time.perf_counter() - t0
    t0 = time.perf_counter()
    rch = rv._round_challenges_batch(proofs, ch, v.params.rounds)
    t_round = time.perf_counter() - t0
    rr = v.params.rounds
    t0 = time.perf_counter()
    ch_packed_all = inv_packed_all = None
    if rv._FRNATIVE is not None:
        from fabric_token_sdk_tpu.ops import limbs

        ch_packed_all = limbs.pack_scalars(
            [rch[row, r] for row in range(len(ch)) for r in range(rr)])
        inv_packed_all = rv._FRNATIVE.batch_inv(ch_packed_all)
    eqs = {}
    for row, i in enumerate(ch):
        sl = slice(row * rr * 32, (row + 1) * rr * 32)
        eqs[i] = rv._host_phase_b(
            proofs[i], transcripts[i], x_ipa[row], list(rch[row]), v.params,
            ch_packed_all[sl] if ch_packed_all is not None else None,
            inv_packed_all[sl] if inv_packed_all is not None else None)
    t_phase_b = time.perf_counter() - t0
    n_fixed = 2 * v.params.bit_length + 5
    fixed_acc = (bytes(32 * n_fixed) if rv._FRNATIVE is not None
                 else [0] * n_fixed)
    t0 = time.perf_counter()
    fixed_acc, part = v._combined_chunk(proofs, coms, ch, eqs, fixed_acc,
                                        pts_dev)
    t_comb_host = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.block_until_ready(part)
    t_comb_dev = time.perf_counter() - t0
    t0 = time.perf_counter()
    ok = v._combined_finalize(fixed_acc, [part])
    t_final = time.perf_counter() - t0

    total = (t_dispatch + t_pass1 + t_transfer + t_xipa + t_round
             + t_phase_b + t_comb_host + t_comb_dev + t_final)
    bc = len(ch)
    print(f"chunk={bc}  barriered total={total:.3f}s  ({bc/total:.1f}/s)  "
          f"ok={ok}")
    for name, t in [("phase_a+marshal+disp", t_dispatch),
                    ("pass1 device", t_pass1),
                    ("bytes transfer", t_transfer),
                    ("x_ipa batch", t_xipa),
                    ("round chall", t_round),
                    ("phase_b", t_phase_b),
                    ("comb host+disp", t_comb_host),
                    ("comb device", t_comb_dev),
                    ("finalize", t_final)]:
        print(f"  {name:>20}: {t:.3f}s  {100*t/total:.1f}%")


if __name__ == "__main__":
    main()
