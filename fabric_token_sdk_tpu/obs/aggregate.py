"""Multi-process metrics federation over a spool directory.

The telemetry plane (obs/telemetry.py) is strictly single-process: an
NWO platform runs N node processes and each one's registry is invisible
to the others. Real fleets solve this with a scrape fan-out; inside one
host we do not need sockets — a spool directory is enough:

- every child process runs a :class:`SpoolPublisher` that atomically
  writes its full exposition to ``<spool>/<node>.prom`` (tmp +
  ``os.replace``, so a reader never sees a torn file);
- the parent's :class:`FleetAggregator` reads every ``*.prom``, injects
  a ``node="<name>"`` label into each sample, and merges the documents
  into one grammar-valid exposition — family names are NEVER rewritten,
  so the stable-family inventory is unchanged and an existing dashboard
  query picks up the new ``node`` dimension for free.

Merge semantics (both tested directly):

- HELP/TYPE conflicts: first document wins, the conflict is counted in
  ``fleet_merge_conflicts_total{kind="help"|"type"}`` — a fleet must
  not serve two HELP lines for one family.
- label collisions: a sample that already carries a ``node`` label (a
  child federating its own children, or a user label) has it renamed to
  ``node_orig`` and counted under ``kind="label"`` — the injected fleet
  dimension must stay authoritative.

The aggregator also publishes the federation's own health as new
``fleet_*`` families (node count, merged samples, per-node spool age)
and a JSON summary for the new ``/fleetz`` endpoint.
"""

from __future__ import annotations

import glob
import os
import re
import threading
import time

from .metrics import (GLOBAL, MetricsProvider, escape_help_text,
                      escape_label_value, sanitize_label_name)

_FLEET_FAMILIES = {
    "fleet_nodes":
        "Node expositions merged in the most recent federation collect.",
    "fleet_samples":
        "Samples in the most recent federated exposition.",
    "fleet_merge_conflicts_total":
        "Federation merge conflicts, by kind (help, type, label, parse).",
    "fleet_node_age_seconds":
        "Age of each node's spool exposition at the last collect.",
    "fleet_tenants":
        "Distinct tenant tms ids across the fleet's merged exposition "
        "(every tms_id label value in the most recent collect).",
}

_HELP_LINE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_LINE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)$")
_SAMPLE_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$")
_LABEL_PAIR = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SUFFIX = re.compile(r"_(bucket|sum|count)$")


def _unescape(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str) -> dict[str, dict]:
    """Exposition text -> ``{family: {"help", "type", "samples"}}`` where
    each sample is ``(sample_name, [(label, value), ...], value_str)``.

    Values stay strings (``NaN``/``+Inf``/float reprs) so a
    parse-then-render round trip cannot reformat a number. Histogram
    ``_bucket``/``_sum``/``_count`` samples attach to their base family.
    Malformed lines raise ``ValueError`` — the publisher wrote this text
    with our own renderer, so leniency would only hide corruption."""
    families: dict[str, dict] = {}

    def fam(name: str) -> dict:
        return families.setdefault(
            name, {"help": None, "type": None, "samples": []})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            m = _HELP_LINE.match(line)
            if m:
                f = fam(m.group(1))
                if f["help"] is None:
                    f["help"] = m.group(2)
                continue
            m = _TYPE_LINE.match(line)
            if m:
                f = fam(m.group(1))
                if f["type"] is None:
                    f["type"] = m.group(2)
                continue
            continue  # other comments are legal exposition, dropped
        m = _SAMPLE_LINE.match(line)
        if not m:
            raise ValueError(f"malformed sample line: {line!r}")
        sample_name, label_blob, value = m.groups()
        labels = [(k, _unescape(v))
                  for k, v in _LABEL_PAIR.findall(label_blob or "")]
        base = sample_name
        stripped = _SUFFIX.sub("", sample_name)
        if stripped in families:
            base = stripped
        fam(base)["samples"].append((sample_name, labels, value))
    return families


class _Merge:
    """Accumulator for one federation pass."""

    def __init__(self):
        self.families: dict[str, dict] = {}
        self.conflicts: dict[str, int] = {}
        self.samples = 0

    def _conflict(self, kind: str) -> None:
        self.conflicts[kind] = self.conflicts.get(kind, 0) + 1

    def add(self, doc: dict[str, dict], node: str | None) -> None:
        for name, f in doc.items():
            mine = self.families.setdefault(
                name, {"help": f["help"], "type": f["type"], "samples": []})
            if f["help"] is not None and mine["help"] is None:
                mine["help"] = f["help"]
            elif (f["help"] is not None and mine["help"] is not None
                  and f["help"] != mine["help"]):
                self._conflict("help")
            if f["type"] is not None and mine["type"] is None:
                mine["type"] = f["type"]
            elif (f["type"] is not None and mine["type"] is not None
                  and f["type"] != mine["type"]):
                self._conflict("type")
            for sample_name, labels, value in f["samples"]:
                if node is not None:
                    out = []
                    for k, v in labels:
                        if k == "node":
                            self._conflict("label")
                            k = "node_orig"
                        out.append((k, v))
                    labels = out + [("node", node)]
                mine["samples"].append((sample_name, labels, value))
                self.samples += 1

    def render(self) -> str:
        lines = []
        for name in sorted(self.families):
            f = self.families[name]
            if not f["samples"]:
                continue
            lines.append(
                f"# HELP {name} "
                f"{escape_help_text(f['help'] if f['help'] is not None else name)}")
            lines.append(f"# TYPE {name} {f['type'] or 'gauge'}")
            for sample_name, labels, value in f["samples"]:
                if labels:
                    blob = ",".join(
                        f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
                        for k, v in labels)
                    lines.append(f"{sample_name}{{{blob}}} {value}")
                else:
                    lines.append(f"{sample_name} {value}")
        return "\n".join(lines) + "\n"


def merge_expositions(docs: dict[str, str],
                      self_text: str | None = None) -> tuple[str, _Merge]:
    """Merge ``{node: exposition_text}`` into one document. ``self_text``
    (the federating process's own exposition) is merged WITHOUT a node
    label — the parent is the scrape target itself, not a fleet member.
    Returns ``(text, merge_stats)``."""
    merge = _Merge()
    if self_text is not None:
        merge.add(parse_exposition(self_text), node=None)
    for node in sorted(docs):
        try:
            merge.add(parse_exposition(docs[node]), node=node)
        except ValueError:
            merge._conflict("parse")
    return merge.render(), merge


class SpoolPublisher:
    """Child-side half: atomically publish this process's exposition to
    ``<spool>/<node>.prom``. ``publish()`` on demand, or ``start()`` for
    a daemon-thread cadence (NWO node processes)."""

    def __init__(self, spool_dir: str | os.PathLike, node: str,
                 provider: MetricsProvider | None = None,
                 interval_s: float = 2.0):
        self.spool_dir = os.fspath(spool_dir)
        self.node = node
        self.provider = provider or GLOBAL
        self.interval_s = interval_s
        self.path = os.path.join(self.spool_dir, f"{node}.prom")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(self.spool_dir, exist_ok=True)

    def publish(self) -> str:
        text = self.provider.prometheus_text()
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, self.path)
        return self.path

    def start(self) -> "SpoolPublisher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"fts-spool-{self.node}",
                daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.publish()
            except OSError:
                pass  # spool dir raced away (teardown); keep serving

    def stop(self, final_publish: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_publish:
            try:
                self.publish()
            except OSError:
                pass


class FleetAggregator:
    """Parent-side half: merge every spool exposition (+ the parent's own
    registry) into one federated document, and account the federation
    itself in ``fleet_*`` families."""

    def __init__(self, spool_dir: str | os.PathLike,
                 provider: MetricsProvider | None = None,
                 clock=time.time):
        self.spool_dir = os.fspath(spool_dir)
        self.provider = provider or GLOBAL
        self.clock = clock
        self._lock = threading.Lock()
        self._last: dict | None = None
        for fam, help_text in _FLEET_FAMILIES.items():
            self.provider.describe(fam, help_text)

    def _read_spool(self) -> tuple[dict[str, str], dict[str, float]]:
        docs: dict[str, str] = {}
        ages: dict[str, float] = {}
        now = self.clock()
        for path in sorted(glob.glob(os.path.join(self.spool_dir,
                                                  "*.prom"))):
            node = os.path.splitext(os.path.basename(path))[0]
            try:
                with open(path) as f:
                    docs[node] = f.read()
                ages[node] = max(0.0, now - os.path.getmtime(path))
            except OSError:
                continue  # torn down between glob and read
        return docs, ages

    def collect(self) -> str:
        """One federation pass -> merged exposition text.

        fleet_* instruments are updated BEFORE the parent's own registry
        renders, so the federated document already describes this very
        collect (same self-observation convention as telemetry
        scrapes)."""
        docs, ages = self._read_spool()
        # pre-pass for the sample/conflict gauges: merge children only,
        # cheap relative to the exposition sizes at fleet scale
        _, pre = merge_expositions(docs)
        self.provider.gauge("fleet_nodes").set(float(len(docs)))
        self.provider.gauge("fleet_samples").set(float(pre.samples))
        # fleet-wide tenant cardinality: how many distinct tms_id label
        # values survive federation (children's slo_tenant_* /
        # serve_tenant_* / rpc_tenant_* series, node labels and all)
        tenants = {v for f in pre.families.values()
                   for _, labels, _ in f["samples"]
                   for k, v in labels if k == "tms_id"}
        self.provider.gauge("fleet_tenants").set(float(len(tenants)))
        for kind, n in pre.conflicts.items():
            self.provider.counter("fleet_merge_conflicts_total",
                                  kind=kind).add(n)
        for node, age in ages.items():
            self.provider.gauge("fleet_node_age_seconds",
                                node=node).set(round(age, 3))
        text, merge = merge_expositions(
            docs, self_text=self.provider.prometheus_text())
        with self._lock:
            self._last = {
                "ts": self.clock(),
                "nodes": {
                    node: {"age_s": round(ages.get(node, 0.0), 3),
                           "bytes": len(docs[node])}
                    for node in docs},
                "samples": merge.samples,
                "conflicts": pre.conflicts,
            }
        return text

    def summary(self) -> dict:
        """JSON view for /fleetz (runs a fresh spool scan so the page is
        live even if nothing scraped /metrics recently)."""
        docs, ages = self._read_spool()
        with self._lock:
            last = self._last
        return {
            "spool_dir": self.spool_dir,
            "nodes": {
                node: {"age_s": round(ages.get(node, 0.0), 3),
                       "bytes": len(docs[node])}
                for node in sorted(docs)},
            "last_collect": last,
        }

    # ------------------------------------------------------------- traces
    def span_records(self) -> list[dict]:
        """Every span record published to the spool by fleet members
        (``*.spans.jsonl``, written by ``SpanSpoolExporter``)."""
        from .tracing import read_span_spool

        return read_span_spool(self.spool_dir)

    def traces(self) -> dict[str, list[dict]]:
        """Fleet-wide traces: spool span records from every node grouped
        by trace_id — the cross-process view the federated ``/tracez``
        serves (one trace spans the client's ``rpc.call``, the sidecar's
        ``rpc.serve`` and its ``serve.request``)."""
        from .tracing import assemble_traces

        return assemble_traces(self.span_records())
