"""Exporters: span trees -> Chrome/Perfetto trace-event JSON.

The Trace Event Format (consumed by chrome://tracing and Perfetto's
legacy-JSON importer) represents each span as a complete event
(``ph: "X"``) with microsecond ``ts``/``dur``; span events become instant
events (``ph: "i"``). Parent/child structure survives two ways: visually
through ts/dur containment on one thread track, and exactly through the
``trace_id``/``span_id``/``parent_id`` args on every event — the
round-trip test reconstructs the tree from those.
"""

from __future__ import annotations

import json
import os
import threading

from .tracing import Span


def _span_events(span: Span, pid: int, tid: int) -> list[dict]:
    ts = span.start * 1e6
    args = {
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "parent_id": span.parent_id,
        **span.attributes,
    }
    if span.links:
        args["links"] = list(span.links)
    out = [{
        "name": span.name,
        "ph": "X",
        "ts": ts,
        "dur": (span.duration or 0.0) * 1e6,
        "pid": pid,
        "tid": tid,
        "args": args,
    }]
    for name, offset, attrs in span.events:
        out.append({
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": ts + offset * 1e6,
            "pid": pid,
            "tid": tid,
            "args": {"span_id": span.span_id, **(attrs or {})},
        })
    for child in span.children:
        out.extend(_span_events(child, pid, tid))
    return out


def spans_to_chrome_trace(spans: list[Span], process_name: str =
                          "fabric_token_sdk_tpu") -> dict:
    """Root spans (with their subtrees) -> a Trace Event Format dict.

    Each root span gets its own thread track so concurrent requests do
    not visually overlap.
    """
    pid = os.getpid()
    events: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    for tid, root in enumerate(spans, start=1):
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": f"trace_{root.trace_id}"},
        })
        events.extend(_span_events(root, pid, tid))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


_write_lock = threading.Lock()


def write_chrome_trace(path: str, spans: list[Span],
                       process_name: str = "fabric_token_sdk_tpu") -> str:
    """Serialize root spans to `path` (atomic enough for one process)."""
    doc = spans_to_chrome_trace(spans, process_name=process_name)
    with _write_lock:
        with open(path, "w") as fh:
            json.dump(doc, fh)
    return path
