"""SLO burn-rate monitor over serve results.

Multi-window availability / p99-latency tracking in the Google SRE
workbook style: the error-budget *burn rate* is the ratio between the
observed error rate and the rate that would exactly exhaust the budget
over the SLO period — burn 1.0 spends the budget on schedule, burn 14.4
exhausts a 30-day budget in 2 days. A **fast-burn** condition (high burn
sustained on the short window, confirmed on the long window) is the
page-worthy signal; here it can optionally trip the serve circuit
breaker's ``force_open`` kill switch so overload degrades to host
fallback instead of a deadline-miss storm.

Everything is clock-injectable and lock-protected: ``record`` is called
from the serve dispatcher loop while gauges are scraped from the
telemetry server's request threads.

:class:`TenantSloMonitor` runs the same multi-window machinery per
``tms_id`` under a bounded-cardinality tenant table (LRU eviction above
``max_tenants``; an evicted tenant's metric series are removed from the
registry so departed tenants cannot leak gauges forever), and adds
Jain's fairness index across tenants so one gauge answers "is the front
door fair right now".

Exported families (stable names, see ROADMAP):
  slo_availability_ratio{window}    rolling success fraction
  slo_p99_seconds{window}           rolling p99 of successful latencies
  slo_error_budget_burn_rate{window}
  slo_window_requests{window}       sample count behind the two above
  slo_fast_burn_active              1 while the fast-burn condition holds
  slo_fast_burn_trips_total         edge-triggered trip count
  slo_tenant_availability{tms_id}   short-window success fraction
  slo_tenant_p99_seconds{tms_id}    short-window p99 of ok latencies
  slo_tenant_burn_rate{tms_id,window}
  slo_tenant_budget_remaining{tms_id}
  slo_tenant_evictions_total        LRU evictions from the tenant table
  slo_fairness_index{basis}         Jain's index (throughput | p99)
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass

from .journal import (EVENT_SLO_BURN, EVENT_TENANT_FAST_BURN, JOURNAL)
from .metrics import GLOBAL, MetricsProvider

#: Bound on retained (timestamp, ok, latency) events. At the ROADMAP
#: target of 10k verifies/s a 300 s window would want 3M events; beyond
#: this cap the window degrades to "most recent N" — still a valid SLI
#: estimator, and bounded memory matters more on a long-running node.
_EVENT_KEEP = 262144

_SLO_FAMILIES = {
    "slo_availability_ratio":
        "Rolling fraction of serve requests completing ok per window.",
    "slo_p99_seconds":
        "Rolling p99 latency of successful serve requests per window.",
    "slo_error_budget_burn_rate":
        "Observed error rate over allowed error rate per window; "
        "1.0 spends the error budget exactly on schedule.",
    "slo_window_requests":
        "Serve results currently inside each SLO window.",
    "slo_fast_burn_active":
        "1 while the fast-burn condition (short- and long-window burn "
        "above the fast_burn threshold) holds.",
    "slo_fast_burn_trips_total":
        "Edge-triggered count of fast-burn episodes.",
}

#: Per-tenant families. Every ``tms_id``-labelled series is bounded by
#: the TenantSloMonitor's ``max_tenants`` LRU table (eviction removes
#: the tenant's series from the registry), so the exposition cannot
#: grow without bound under a million-client front door.
_TENANT_SLO_FAMILIES = {
    "slo_tenant_availability":
        "Short-window success fraction per tenant tms id.",
    "slo_tenant_p99_seconds":
        "Short-window p99 latency of a tenant's successful requests.",
    "slo_tenant_burn_rate":
        "Per-tenant error-budget burn rate per window; 1.0 spends the "
        "tenant's budget exactly on schedule.",
    "slo_tenant_budget_remaining":
        "Fraction of a tenant's cumulative error budget left "
        "(1 untouched, 0 exhausted), clamped to [0, 1].",
    "slo_tenant_evictions_total":
        "Tenants LRU-evicted from the bounded per-tenant SLO table.",
    "slo_fairness_index":
        "Jain's fairness index across tenants (1.0 perfectly fair), "
        "by basis: short-window served throughput or p99 latency.",
}

#: Per-tenant retained events: smaller than the global cap — the table
#: holds up to ``max_tenants`` of these deques.
_TENANT_EVENT_KEEP = 8192


def _window_stats(events, now: float, window: float,
                  availability_target: float) -> dict:
    """Multi-window SLI arithmetic over ``(ts, ok, latency)`` events —
    shared by the global monitor and the per-tenant monitor so both
    compute burn exactly the same way. Caller holds its own lock."""
    cutoff = now - window
    n = ok_n = 0
    lat: list[float] = []
    for t, ok, latency in events:
        if t < cutoff:
            continue
        n += 1
        if ok:
            ok_n += 1
            if latency is not None:
                lat.append(latency)
    availability = ok_n / n if n else 1.0
    budget = 1.0 - availability_target
    burn = ((1.0 - availability) / budget) if budget > 0 else 0.0
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))] if lat else 0.0
    return {"window": f"{int(window)}s", "requests": n, "ok": ok_n,
            "availability": availability, "burn": burn, "p99": p99}


def jain_index(values) -> float:
    """Jain's fairness index J = (Σx)² / (n·Σx²) over per-tenant
    allocations: 1.0 is perfectly fair, 1/n is one tenant taking
    everything (zeros count — a starved tenant lowers the index).
    Empty or all-zero input reads 1.0 (nothing is being served
    unfairly)."""
    xs = [float(v) for v in values]
    if not xs:
        return 1.0
    sq = sum(x * x for x in xs)
    return (sum(xs) ** 2) / (len(xs) * sq) if sq > 0 else 1.0


@dataclass(frozen=True)
class SloPolicy:
    """Serve-path SLO targets and burn thresholds.

    ``windows`` orders short -> long; the fast-burn condition requires
    the burn rate to exceed ``fast_burn`` on EVERY window (the classic
    multi-window guard against paging on a 5-request blip)."""
    availability_target: float = 0.999
    p99_target_s: float = 1.0
    windows: tuple = (60.0, 300.0)
    fast_burn: float = 14.4
    min_volume: int = 32
    recover_burn: float = 1.0


class SloMonitor:
    """Rolling multi-window SLI tracker with an optional breaker hook.

    ``record(ok, latency_s)`` is the single write path; gauges update on
    every record so a scrape between records always sees a consistent
    (if slightly stale) picture. ``on_fast_burn`` / ``on_recover`` fire
    edge-triggered from inside ``record`` on the caller's thread."""

    def __init__(self, policy: SloPolicy | None = None,
                 provider: MetricsProvider | None = None,
                 clock=time.monotonic,
                 on_fast_burn=None, on_recover=None):
        self.policy = policy or SloPolicy()
        self.provider = provider or GLOBAL
        self.clock = clock
        self.on_fast_burn = on_fast_burn
        self.on_recover = on_recover
        self.fast_burn_active = False
        self.trips = 0
        self._events: deque = deque(maxlen=_EVENT_KEEP)
        self._lock = threading.Lock()
        for fam, help_text in _SLO_FAMILIES.items():
            self.provider.describe(fam, help_text)

    # ------------------------------------------------------------ wiring
    def bind_breaker(self, breaker) -> None:
        """Wire fast-burn to the circuit breaker's kill switch: trip ->
        ``force_open`` (serve degrades to host fallback), recovery ->
        ``force_close``. Replaces any previously-set hooks."""
        self.on_fast_burn = breaker.force_open
        self.on_recover = breaker.force_close

    # ----------------------------------------------------------- updates
    def record(self, ok: bool, latency_s: float | None = None) -> None:
        now = self.clock()
        with self._lock:
            self._events.append((now, bool(ok), latency_s))
            horizon = now - max(self.policy.windows)
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()
            stats = [self._window_stats(now, w)
                     for w in self.policy.windows]
        self._publish(stats)
        self._check_burn(stats)

    def _window_stats(self, now: float, window: float) -> dict:
        """Caller holds the lock."""
        return _window_stats(self._events, now, window,
                             self.policy.availability_target)

    def _publish(self, stats: list[dict]) -> None:
        for st in stats:
            w = st["window"]
            self.provider.gauge("slo_availability_ratio", window=w).set(
                st["availability"])
            self.provider.gauge("slo_p99_seconds", window=w).set(st["p99"])
            self.provider.gauge("slo_error_budget_burn_rate",
                                window=w).set(st["burn"])
            self.provider.gauge("slo_window_requests", window=w).set(
                st["requests"])

    def _check_burn(self, stats: list[dict]) -> None:
        volume_ok = all(st["requests"] >= self.policy.min_volume
                        for st in stats)
        burning = volume_ok and all(st["burn"] >= self.policy.fast_burn
                                    for st in stats)
        recovered = all(st["burn"] <= self.policy.recover_burn
                        for st in stats)
        if burning and not self.fast_burn_active:
            self.fast_burn_active = True
            self.trips += 1
            self.provider.counter("slo_fast_burn_trips_total").add()
            self.provider.gauge("slo_fast_burn_active").set(1)
            JOURNAL.record(EVENT_SLO_BURN, phase="trip",
                           burn=[round(st["burn"], 3) for st in stats],
                           availability=[round(st["availability"], 6)
                                         for st in stats])
            JOURNAL.incident(
                "slo_fast_burn",
                reason="burn rate >= {:.1f} on all windows: {}".format(
                    self.policy.fast_burn,
                    [round(st["burn"], 2) for st in stats]))
            if self.on_fast_burn is not None:
                self.on_fast_burn()
        elif self.fast_burn_active and recovered:
            self.fast_burn_active = False
            self.provider.gauge("slo_fast_burn_active").set(0)
            JOURNAL.record(EVENT_SLO_BURN, phase="recover",
                           burn=[round(st["burn"], 3) for st in stats])
            if self.on_recover is not None:
                self.on_recover()
        else:
            self.provider.gauge("slo_fast_burn_active").set(
                1 if self.fast_burn_active else 0)

    # ----------------------------------------------------------- reading
    def summary(self) -> dict:
        """Point-in-time view for /statusz and the BENCH report."""
        now = self.clock()
        with self._lock:
            stats = [self._window_stats(now, w)
                     for w in self.policy.windows]
        return {
            "availability_target": self.policy.availability_target,
            "p99_target_s": self.policy.p99_target_s,
            "fast_burn_active": self.fast_burn_active,
            "trips": self.trips,
            "windows": {st["window"]: {
                "requests": st["requests"],
                "availability": round(st["availability"], 6),
                "burn_rate": round(st["burn"], 3),
                "p99_s": round(st["p99"], 6),
            } for st in stats},
        }


@dataclass(frozen=True)
class TenantSloPolicy(SloPolicy):
    """Per-tenant SLO policy: the global targets/windows plus the
    bounded-cardinality knobs.

    max_tenants: LRU bound on the tenant table; recording a request for
        a new tenant past the bound evicts the least-recently-active
        tenant AND removes its ``slo_tenant_*`` series from the metrics
        registry (counted in ``slo_tenant_evictions_total``).
    eval_interval_s: minimum spacing between full evaluation passes
        (window stats, gauge publishes, trip/recovery checks, fairness
        indices). 0.0 evaluates on every record — exact, right for
        tests and moderate rates; the front-door bench runs at 100k+
        rows/s where a per-record O(tenants * window) pass would
        dominate, so it sets a small positive cadence instead.
    """
    max_tenants: int = 256
    eval_interval_s: float = 0.0


class _TenantState:
    """One tenant's rolling window + cumulative budget ledger."""

    __slots__ = ("events", "ok_total", "total", "sheds", "trips",
                 "fast_burn_active", "stats")

    def __init__(self):
        self.events: deque = deque(maxlen=_TENANT_EVENT_KEEP)
        self.ok_total = 0
        self.total = 0
        self.sheds = 0
        self.trips = 0
        self.fast_burn_active = False
        self.stats: list[dict] = []    # last eval's per-window stats


class TenantSloMonitor:
    """Per-``tms_id`` multi-window SLI tracker with LRU-bounded
    cardinality, edge-triggered per-tenant fast-burn, and fleet
    fairness indices.

    ``record(tenant, ok, latency_s)`` is the write path (called from
    the serve event loop for every terminal result). Evaluation —
    window stats, gauge publishes, trip/recovery edges, Jain fairness
    — runs as a full pass over the table at most every
    ``eval_interval_s`` seconds, so an idle tenant's recovery is still
    detected while any traffic flows.

    Hooks fire edge-triggered with the tms_id: ``on_fast_burn(t)`` /
    ``on_recover(t)`` on burn transitions, ``on_evict(t)`` when the
    LRU table evicts (the serve layer uses it to drop that tenant's
    ``serve_tenant_*`` series too). ``shedding(t)`` is the query the
    TenantShedPolicy consults at admission.
    """

    def __init__(self, policy: TenantSloPolicy | None = None,
                 provider: MetricsProvider | None = None,
                 clock=time.monotonic, on_fast_burn=None, on_recover=None,
                 on_evict=None):
        self.policy = policy or TenantSloPolicy()
        self.provider = provider or GLOBAL
        self.clock = clock
        self.on_fast_burn = on_fast_burn
        self.on_recover = on_recover
        self.on_evict = on_evict
        self.evictions = 0
        self._tenants: OrderedDict[str, _TenantState] = OrderedDict()
        self._last_eval: float | None = None
        self._lock = threading.Lock()
        for fam, help_text in _TENANT_SLO_FAMILIES.items():
            self.provider.describe(fam, help_text)

    # ----------------------------------------------------------- updates
    def record(self, tenant: str, ok: bool,
               latency_s: float | None = None) -> None:
        tenant = tenant or "default"
        now = self.clock()
        evicted: list[str] = []
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None:
                state = self._tenants[tenant] = _TenantState()
            else:
                self._tenants.move_to_end(tenant)
            state.events.append((now, bool(ok), latency_s))
            state.total += 1
            if ok:
                state.ok_total += 1
            while len(self._tenants) > self.policy.max_tenants:
                gone, _ = self._tenants.popitem(last=False)
                self.evictions += 1
                evicted.append(gone)
        for gone in evicted:
            self.provider.counter("slo_tenant_evictions_total").add()
            for fam in ("slo_tenant_availability", "slo_tenant_p99_seconds",
                        "slo_tenant_burn_rate",
                        "slo_tenant_budget_remaining"):
                self.provider.remove_series(fam, tms_id=gone)
            if self.on_evict is not None:
                self.on_evict(gone)
        self._maybe_eval(now)

    def note_shed(self, tenant: str, rows: int = 1) -> None:
        """Account a policy shed against the tenant WITHOUT recording a
        window event: a ``shed_tenant_slo`` verdict is the policy
        acting, not the service failing — feeding it back into the
        tenant's own error window would make the shed self-sustaining
        (the tenant could never recover while being shed)."""
        with self._lock:
            state = self._tenants.get(tenant or "default")
            if state is not None:
                state.sheds += rows

    def _maybe_eval(self, now: float) -> None:
        with self._lock:
            due = (self._last_eval is None
                   or now - self._last_eval >= self.policy.eval_interval_s)
            if not due:
                return
            self._last_eval = now
        self._eval(now)

    def _eval(self, now: float) -> None:
        """One full pass: stats + trip/recovery edges under the lock,
        then gauge publishes, journal events, incidents, and hooks
        outside it (an incident snapshot pulls status sources that may
        re-enter ``summary()``)."""
        pol = self.policy
        horizon = now - max(pol.windows)
        trips: list[tuple[str, list[dict]]] = []
        recoveries: list[str] = []
        published: list[tuple[str, list[dict]]] = []
        throughput: list[float] = []
        p99s: list[float] = []
        with self._lock:
            for tenant, state in self._tenants.items():
                ev = state.events
                while ev and ev[0][0] < horizon:
                    ev.popleft()
                stats = [_window_stats(ev, now, w, pol.availability_target)
                         for w in pol.windows]
                state.stats = stats
                published.append((tenant, stats))
                throughput.append(stats[0]["ok"])
                if stats[0]["p99"] > 0:
                    p99s.append(stats[0]["p99"])
                volume_ok = all(st["requests"] >= pol.min_volume
                                for st in stats)
                burning = volume_ok and all(st["burn"] >= pol.fast_burn
                                            for st in stats)
                recovered = all(st["burn"] <= pol.recover_burn
                                for st in stats)
                if burning and not state.fast_burn_active:
                    state.fast_burn_active = True
                    state.trips += 1
                    trips.append((tenant, stats))
                elif state.fast_burn_active and recovered:
                    state.fast_burn_active = False
                    recoveries.append(tenant)
        budget = 1.0 - pol.availability_target
        for tenant, stats in published:
            st0 = stats[0]
            # tenant-bounded: series below are LRU-evicted above
            # TenantSloPolicy.max_tenants (remove_series on eviction)
            self.provider.gauge("slo_tenant_availability",
                                tms_id=tenant).set(st0["availability"])
            self.provider.gauge("slo_tenant_p99_seconds",
                                tms_id=tenant).set(st0["p99"])
            for st in stats:
                self.provider.gauge("slo_tenant_burn_rate", tms_id=tenant,
                                    window=st["window"]).set(st["burn"])
            self.provider.gauge(
                "slo_tenant_budget_remaining",
                tms_id=tenant).set(self._budget_remaining(tenant, budget))
        self.provider.gauge("slo_fairness_index", basis="throughput").set(
            jain_index(throughput))
        # fairness over LATENCY uses inverse p99 so "bigger = better
        # served" on both bases: equal p99s read 1.0 either way, but a
        # tenant starved into 10x the latency drags the index down
        self.provider.gauge("slo_fairness_index", basis="p99").set(
            jain_index([1.0 / p for p in p99s]))
        for tenant, stats in trips:
            JOURNAL.record(EVENT_TENANT_FAST_BURN, phase="trip",
                           tms_id=tenant,
                           burn=[round(st["burn"], 3) for st in stats])
            JOURNAL.incident(
                "tenant_fast_burn",
                reason="tenant {} burn rate >= {:.1f} on all windows: "
                       "{}".format(tenant, pol.fast_burn,
                                   [round(st["burn"], 2) for st in stats]))
            if self.on_fast_burn is not None:
                self.on_fast_burn(tenant)
        for tenant in recoveries:
            JOURNAL.record(EVENT_TENANT_FAST_BURN, phase="recover",
                           tms_id=tenant)
            if self.on_recover is not None:
                self.on_recover(tenant)

    def _budget_remaining(self, tenant: str, budget: float) -> float:
        state = self._tenants.get(tenant)
        if state is None or state.total == 0 or budget <= 0:
            return 1.0
        spent = (1.0 - state.ok_total / state.total) / budget
        return max(0.0, min(1.0, 1.0 - spent))

    # ----------------------------------------------------------- reading
    def shedding(self, tenant: str) -> bool:
        """True while the tenant's fast-burn episode is active (the
        TenantShedPolicy's admission query)."""
        with self._lock:
            state = self._tenants.get(tenant or "default")
            return state.fast_burn_active if state is not None else False

    def tenants(self) -> list[str]:
        with self._lock:
            return list(self._tenants)

    def summary(self) -> dict:
        """Point-in-time per-tenant table for /tenantz, /statusz, and
        incident snapshots. Read-only: no trips, no gauge writes."""
        now = self.clock()
        pol = self.policy
        budget = 1.0 - pol.availability_target
        tenants: dict[str, dict] = {}
        with self._lock:
            for tenant, state in self._tenants.items():
                stats = [_window_stats(state.events, now, w,
                                       pol.availability_target)
                         for w in pol.windows]
                tenants[tenant] = {
                    "requests": state.total,
                    "availability": round(stats[0]["availability"], 6),
                    "p99_s": round(stats[0]["p99"], 6),
                    "burn_rate": {st["window"]: round(st["burn"], 3)
                                  for st in stats},
                    "budget_remaining": round(
                        self._budget_remaining(tenant, budget), 6),
                    "sheds": state.sheds,
                    "trips": state.trips,
                    "fast_burn_active": state.fast_burn_active,
                }
            evictions = self.evictions
        return {
            "max_tenants": pol.max_tenants,
            "tenants": tenants,
            "evictions": evictions,
            "fairness": {
                "throughput": round(jain_index(
                    [t["requests"] for t in tenants.values()]), 6),
                "p99": round(jain_index(
                    [1.0 / t["p99_s"] for t in tenants.values()
                     if t["p99_s"] > 0]), 6),
            },
        }
