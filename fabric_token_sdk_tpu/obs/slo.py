"""SLO burn-rate monitor over serve results.

Multi-window availability / p99-latency tracking in the Google SRE
workbook style: the error-budget *burn rate* is the ratio between the
observed error rate and the rate that would exactly exhaust the budget
over the SLO period — burn 1.0 spends the budget on schedule, burn 14.4
exhausts a 30-day budget in 2 days. A **fast-burn** condition (high burn
sustained on the short window, confirmed on the long window) is the
page-worthy signal; here it can optionally trip the serve circuit
breaker's ``force_open`` kill switch so overload degrades to host
fallback instead of a deadline-miss storm.

Everything is clock-injectable and lock-protected: ``record`` is called
from the serve dispatcher loop while gauges are scraped from the
telemetry server's request threads.

Exported families (stable names, see ROADMAP):
  slo_availability_ratio{window}    rolling success fraction
  slo_p99_seconds{window}           rolling p99 of successful latencies
  slo_error_budget_burn_rate{window}
  slo_window_requests{window}       sample count behind the two above
  slo_fast_burn_active              1 while the fast-burn condition holds
  slo_fast_burn_trips_total         edge-triggered trip count
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from .journal import EVENT_SLO_BURN, JOURNAL
from .metrics import GLOBAL, MetricsProvider

#: Bound on retained (timestamp, ok, latency) events. At the ROADMAP
#: target of 10k verifies/s a 300 s window would want 3M events; beyond
#: this cap the window degrades to "most recent N" — still a valid SLI
#: estimator, and bounded memory matters more on a long-running node.
_EVENT_KEEP = 262144

_SLO_FAMILIES = {
    "slo_availability_ratio":
        "Rolling fraction of serve requests completing ok per window.",
    "slo_p99_seconds":
        "Rolling p99 latency of successful serve requests per window.",
    "slo_error_budget_burn_rate":
        "Observed error rate over allowed error rate per window; "
        "1.0 spends the error budget exactly on schedule.",
    "slo_window_requests":
        "Serve results currently inside each SLO window.",
    "slo_fast_burn_active":
        "1 while the fast-burn condition (short- and long-window burn "
        "above the fast_burn threshold) holds.",
    "slo_fast_burn_trips_total":
        "Edge-triggered count of fast-burn episodes.",
}


@dataclass(frozen=True)
class SloPolicy:
    """Serve-path SLO targets and burn thresholds.

    ``windows`` orders short -> long; the fast-burn condition requires
    the burn rate to exceed ``fast_burn`` on EVERY window (the classic
    multi-window guard against paging on a 5-request blip)."""
    availability_target: float = 0.999
    p99_target_s: float = 1.0
    windows: tuple = (60.0, 300.0)
    fast_burn: float = 14.4
    min_volume: int = 32
    recover_burn: float = 1.0


class SloMonitor:
    """Rolling multi-window SLI tracker with an optional breaker hook.

    ``record(ok, latency_s)`` is the single write path; gauges update on
    every record so a scrape between records always sees a consistent
    (if slightly stale) picture. ``on_fast_burn`` / ``on_recover`` fire
    edge-triggered from inside ``record`` on the caller's thread."""

    def __init__(self, policy: SloPolicy | None = None,
                 provider: MetricsProvider | None = None,
                 clock=time.monotonic,
                 on_fast_burn=None, on_recover=None):
        self.policy = policy or SloPolicy()
        self.provider = provider or GLOBAL
        self.clock = clock
        self.on_fast_burn = on_fast_burn
        self.on_recover = on_recover
        self.fast_burn_active = False
        self.trips = 0
        self._events: deque = deque(maxlen=_EVENT_KEEP)
        self._lock = threading.Lock()
        for fam, help_text in _SLO_FAMILIES.items():
            self.provider.describe(fam, help_text)

    # ------------------------------------------------------------ wiring
    def bind_breaker(self, breaker) -> None:
        """Wire fast-burn to the circuit breaker's kill switch: trip ->
        ``force_open`` (serve degrades to host fallback), recovery ->
        ``force_close``. Replaces any previously-set hooks."""
        self.on_fast_burn = breaker.force_open
        self.on_recover = breaker.force_close

    # ----------------------------------------------------------- updates
    def record(self, ok: bool, latency_s: float | None = None) -> None:
        now = self.clock()
        with self._lock:
            self._events.append((now, bool(ok), latency_s))
            horizon = now - max(self.policy.windows)
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()
            stats = [self._window_stats(now, w)
                     for w in self.policy.windows]
        self._publish(stats)
        self._check_burn(stats)

    def _window_stats(self, now: float, window: float) -> dict:
        """Caller holds the lock."""
        cutoff = now - window
        n = ok_n = 0
        lat: list[float] = []
        for t, ok, latency in self._events:
            if t < cutoff:
                continue
            n += 1
            if ok:
                ok_n += 1
                if latency is not None:
                    lat.append(latency)
        availability = ok_n / n if n else 1.0
        budget = 1.0 - self.policy.availability_target
        burn = ((1.0 - availability) / budget) if budget > 0 else 0.0
        lat.sort()
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))] if lat else 0.0
        return {"window": f"{int(window)}s", "requests": n,
                "availability": availability, "burn": burn, "p99": p99}

    def _publish(self, stats: list[dict]) -> None:
        for st in stats:
            w = st["window"]
            self.provider.gauge("slo_availability_ratio", window=w).set(
                st["availability"])
            self.provider.gauge("slo_p99_seconds", window=w).set(st["p99"])
            self.provider.gauge("slo_error_budget_burn_rate",
                                window=w).set(st["burn"])
            self.provider.gauge("slo_window_requests", window=w).set(
                st["requests"])

    def _check_burn(self, stats: list[dict]) -> None:
        volume_ok = all(st["requests"] >= self.policy.min_volume
                        for st in stats)
        burning = volume_ok and all(st["burn"] >= self.policy.fast_burn
                                    for st in stats)
        recovered = all(st["burn"] <= self.policy.recover_burn
                        for st in stats)
        if burning and not self.fast_burn_active:
            self.fast_burn_active = True
            self.trips += 1
            self.provider.counter("slo_fast_burn_trips_total").add()
            self.provider.gauge("slo_fast_burn_active").set(1)
            JOURNAL.record(EVENT_SLO_BURN, phase="trip",
                           burn=[round(st["burn"], 3) for st in stats],
                           availability=[round(st["availability"], 6)
                                         for st in stats])
            JOURNAL.incident(
                "slo_fast_burn",
                reason="burn rate >= {:.1f} on all windows: {}".format(
                    self.policy.fast_burn,
                    [round(st["burn"], 2) for st in stats]))
            if self.on_fast_burn is not None:
                self.on_fast_burn()
        elif self.fast_burn_active and recovered:
            self.fast_burn_active = False
            self.provider.gauge("slo_fast_burn_active").set(0)
            JOURNAL.record(EVENT_SLO_BURN, phase="recover",
                           burn=[round(st["burn"], 3) for st in stats])
            if self.on_recover is not None:
                self.on_recover()
        else:
            self.provider.gauge("slo_fast_burn_active").set(
                1 if self.fast_burn_active else 0)

    # ----------------------------------------------------------- reading
    def summary(self) -> dict:
        """Point-in-time view for /statusz and the BENCH report."""
        now = self.clock()
        with self._lock:
            stats = [self._window_stats(now, w)
                     for w in self.policy.windows]
        return {
            "availability_target": self.policy.availability_target,
            "p99_target_s": self.policy.p99_target_s,
            "fast_burn_active": self.fast_burn_active,
            "trips": self.trips,
            "windows": {st["window"]: {
                "requests": st["requests"],
                "availability": round(st["availability"], 6),
                "burn_rate": round(st["burn"], 3),
                "p99_s": round(st["p99"], 6),
            } for st in stats},
        }
