"""Live telemetry plane: stdlib HTTP exposition of metrics, health,
status, and traces.

The obs/ stack so far was harvestable only post-mortem (in-process
snapshots, BENCH_OBS_OUT artifacts). This module puts a scrape surface
on a running node with zero new dependencies — ``http.server`` on a
daemon thread, in the spirit of the reference SDK's operational
services (auditor/logging) and the Prometheus exposition conventions:

  /metrics   Prometheus text format (``MetricsProvider.prometheus_text``)
  /healthz   liveness: 200 unless a registered health check fails
             (e.g. circuit breaker open) -> 503
  /readyz    readiness: 200 once registered ready checks pass
             (serve frontend running, prewarm complete) -> 503
  /statusz   JSON snapshot from registered status sources (queue depths,
             prewarm, breaker, pipeline records, SLO, profiler)
  /tenantz   JSON per-tenant SLO table (burn, budget, deficit, drains,
             sheds, in-flight) from the serve frontend's TenantSloMonitor
  /tracez    Chrome-trace JSON of the tracer's completed span buffer

Scrapes observe themselves: ``telemetry_scrapes_total{endpoint}`` is
incremented BEFORE rendering so a /metrics response already contains its
own scrape, and ``telemetry_scrape_seconds{endpoint}`` times rendering.

Thread model: ``ThreadingHTTPServer`` handles each scrape on its own
thread; every data source consulted (metrics registry, tracer root
buffer, SLO monitor, profiler) takes its own lock, and status sources
are individually guarded so one failing subsystem degrades to an
``{"error": ...}`` entry instead of a 500.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .export import spans_to_chrome_trace
from .metrics import GLOBAL, MetricsProvider
from .tracing import TRACER, Tracer

_TELEMETRY_FAMILIES = {
    "telemetry_scrapes_total":
        "Telemetry HTTP requests served, by endpoint (incremented "
        "before rendering so /metrics includes its own scrape).",
    "telemetry_scrape_seconds":
        "Telemetry endpoint render latency.",
}

_ENDPOINTS = ("/metrics", "/healthz", "/readyz", "/statusz", "/tenantz",
              "/tracez", "/fleetz")


@dataclass(frozen=True)
class TelemetryConfig:
    """Where the telemetry plane listens. ``port=0`` binds an ephemeral
    port (tests); production passes a fixed scrape port."""
    host: str = "127.0.0.1"
    port: int = 0


class _TelemetryHTTPServer(ThreadingHTTPServer):
    # SO_REUSEADDR, explicitly: a supervisor-restarted process must
    # rebind its fixed scrape port immediately, not EADDRINUSE through
    # the predecessor's TIME_WAIT window. (http.server defaults this to
    # 1 today, but the crash-recovery layer depends on it — pin it.)
    allow_reuse_address = True
    daemon_threads = True


class _Handler(BaseHTTPRequestHandler):
    server_version = "fts-telemetry/1"
    protocol_version = "HTTP/1.1"
    # socket-level read deadline: a slow-loris scraper (or a wedged
    # peer) cannot pin a handler thread forever
    timeout = 30.0

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # scrape traffic must not spam the node's stdout

    def do_GET(self):
        telemetry: TelemetryServer = self.server.telemetry
        path = self.path.split("?", 1)[0]
        t0 = time.perf_counter()
        try:
            code, ctype, body = telemetry.render(path)
        except Exception as exc:  # defensive: a scrape must never crash
            code, ctype = 500, "text/plain; charset=utf-8"
            body = f"internal error: {exc!r}\n".encode()
        telemetry.provider.histogram(
            "telemetry_scrape_seconds", endpoint=path).observe(
            time.perf_counter() - t0)
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TelemetryServer:
    """Daemon-thread HTTP server over the obs/ registries.

    Checks and status sources are registered as callables so the server
    stays decoupled from serve/resilience: ``add_health_check(name, fn)``
    where ``fn() -> (ok, detail)`` or a plain bool; ``add_status_source``
    registers a ``fn() -> JSON-serializable`` snapshot."""

    def __init__(self, config: TelemetryConfig | None = None,
                 provider: MetricsProvider | None = None,
                 tracer: Tracer | None = None):
        self.config = config or TelemetryConfig()
        self.provider = provider or GLOBAL
        self.tracer = tracer or TRACER
        self._health: dict[str, object] = {}
        self._ready: dict[str, object] = {}
        self._status: dict[str, object] = {}
        self._federator = None
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._started_at: float | None = None
        for fam, help_text in _TELEMETRY_FAMILIES.items():
            self.provider.describe(fam, help_text)

    # ---------------------------------------------------------- wiring
    def add_health_check(self, name: str, fn) -> None:
        self._health[name] = fn

    def add_ready_check(self, name: str, fn) -> None:
        self._ready[name] = fn

    def add_status_source(self, name: str, fn) -> None:
        self._status[name] = fn

    def attach_federator(self, aggregator) -> None:
        """Serve federated fleet metrics: /metrics becomes the
        aggregator's merged exposition (parent registry + every spool
        node, ``node``-labelled) and /fleetz serves its JSON summary.
        ``aggregator`` duck-types obs.aggregate.FleetAggregator
        (``collect() -> str``, ``summary() -> dict``)."""
        self._federator = aggregator

    # -------------------------------------------------------- lifecycle
    def start(self) -> str:
        """Bind and serve on a daemon thread; returns the base URL
        (resolves the ephemeral port)."""
        if self._httpd is not None:
            return self.url
        httpd = _TelemetryHTTPServer(
            (self.config.host, self.config.port), _Handler)
        httpd.telemetry = self
        self._httpd = httpd
        self._started_at = time.time()
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="fts-telemetry", daemon=True)
        self._thread.start()
        return self.url

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int | None:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    @property
    def url(self) -> str:
        host = self.config.host
        return f"http://{host}:{self.port}"

    def trace_summary(self) -> dict:
        """Fleet-wide traces keyed by trace_id (hex): the federator's
        spool-assembled cross-node view when one is attached, else the
        local tracer's completed roots grouped the same way. Never
        raises — /statusz and incident snapshots embed this."""
        try:
            if self._federator is not None \
                    and hasattr(self._federator, "traces"):
                return self._federator.traces()
            from .tracing import assemble_traces
            records = []
            for root in self.tracer.root_snapshot():
                for sp in root.walk():
                    records.append({
                        "node": self.tracer.node, "name": sp.name,
                        "trace_id": f"{sp.trace_id:016x}",
                        "span_id": f"{sp.span_id:016x}",
                        "parent_id": (f"{sp.parent_id:016x}"
                                      if sp.parent_id else None),
                        "duration": sp.duration,
                        "wall_end": 0.0,
                    })
            return assemble_traces(records)
        except Exception as exc:  # a scrape must never crash
            return {"error": repr(exc)}

    # -------------------------------------------------------- rendering
    @staticmethod
    def _run_checks(checks: dict) -> dict[str, str]:
        """Normalize check callables -> {name: failure detail} (empty
        when healthy). A check may return bool or (ok, detail); raising
        counts as failing."""
        failures: dict[str, str] = {}
        for name, fn in checks.items():
            try:
                res = fn()
            except Exception as exc:
                failures[name] = f"raised {exc!r}"
                continue
            if isinstance(res, tuple):
                ok, detail = res
            else:
                ok, detail = bool(res), "check returned false"
            if not ok:
                failures[name] = str(detail)
        return failures

    def _check_body(self, checks: dict) -> tuple[int, str, bytes]:
        failures = self._run_checks(checks)
        if not failures:
            return 200, "text/plain; charset=utf-8", b"ok\n"
        body = json.dumps({"status": "unavailable",
                           "failures": failures}).encode()
        return 503, "application/json", body

    def render(self, path: str) -> tuple[int, str, bytes]:
        """(status code, content type, body) for one endpoint."""
        if path in _ENDPOINTS:
            # count before rendering: a /metrics scrape reports itself
            self.provider.counter("telemetry_scrapes_total",
                                  endpoint=path).add()
        if path == "/metrics":
            text = (self._federator.collect()
                    if self._federator is not None
                    else self.provider.prometheus_text())
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    text.encode())
        if path == "/healthz":
            return self._check_body(self._health)
        if path == "/readyz":
            return self._check_body(self._ready)
        if path == "/statusz":
            status: dict = {"uptime_s": (
                round(time.time() - self._started_at, 3)
                if self._started_at is not None else None)}
            for name, fn in self._status.items():
                try:
                    status[name] = fn()
                except Exception as exc:
                    status[name] = {"error": repr(exc)}
            return (200, "application/json",
                    json.dumps(status, default=str).encode())
        if path == "/tenantz":
            src = self._status.get("tenants")
            if src is None:
                doc = {"enabled": False}
            else:
                try:
                    doc = src()
                except Exception as exc:
                    doc = {"enabled": True, "error": repr(exc)}
            return (200, "application/json",
                    json.dumps(doc, default=str).encode())
        if path == "/fleetz":
            if self._federator is None:
                doc: dict = {"enabled": False}
            else:
                doc = {"enabled": True, **self._federator.summary()}
            return (200, "application/json",
                    json.dumps(doc, default=str).encode())
        if path == "/tracez":
            doc = spans_to_chrome_trace(self.tracer.root_snapshot())
            doc["node"] = self.tracer.node
            # cross-node assembly: with a federator attached, every
            # fleet member's spool-exported spans are grouped by
            # trace_id so one request's rpc.call / rpc.serve /
            # serve.request spans read as a single distributed trace
            doc["traces"] = self.trace_summary()
            return 200, "application/json", json.dumps(doc).encode()
        if path == "/":
            body = ("fabric_token_sdk_tpu telemetry\n"
                    + "".join(f"  {e}\n" for e in _ENDPOINTS)).encode()
            return 200, "text/plain; charset=utf-8", body
        return 404, "text/plain; charset=utf-8", b"not found\n"


def serve_telemetry(service, config: TelemetryConfig | None = None,
                    provider: MetricsProvider | None = None,
                    tracer: Tracer | None = None, *,
                    supervisor=None, rpc_server=None) -> TelemetryServer:
    """Wire a TelemetryServer to a serve ``VerificationService``
    (duck-typed) and start it.

    healthz fails while the circuit breaker is OPEN (forced or tripped):
    the node is alive but actively degrading, which is what a load
    balancer should route around. readyz fails until the frontend is
    running and prewarm compiled every bucket.

    ``supervisor`` (anything with a ``status()``) and the service's WAL
    are surfaced as ``/statusz`` sources so supervised restarts and WAL
    segment state are visible to the ops plane, not just to metrics;
    ``rpc_server`` likewise exposes the network front door's
    connection/credit accounting.
    """
    server = TelemetryServer(config=config, provider=provider,
                             tracer=tracer)
    breaker = getattr(service, "breaker", None)
    if breaker is not None:
        server.add_health_check(
            "breaker",
            lambda: (breaker.state != "open",
                     f"breaker {breaker.state} "
                     f"(failure_rate={breaker.failure_rate:.3f})"))
    server.add_ready_check(
        "running", lambda: (bool(getattr(service, "_running", False)),
                            "frontend not running"))
    prewarm = getattr(service, "prewarm", None)
    if prewarm is not None:
        server.add_ready_check(
            "prewarm",
            lambda: (set(service.config.buckets) <= set(prewarm.ready),
                     f"prewarmed {sorted(prewarm.ready)} of "
                     f"{sorted(service.config.buckets)}"))
    if hasattr(service, "status"):
        server.add_status_source("serve", service.status)

    from .journal import JOURNAL
    from .pipeline import RECORDS
    from .profiling import PROFILER
    server.add_status_source("pipeline", RECORDS.summary)
    server.add_status_source("profile", PROFILER.summary)
    server.add_status_source("journal", JOURNAL.summary)
    slo = getattr(service, "slo", None)
    if slo is not None:
        server.add_status_source("slo", slo.summary)
    # the per-tenant table backs BOTH /tenantz and the "tenants" key of
    # /statusz (and, via the copy below, incident snapshots)
    if getattr(service, "tenant_slo", None) is not None \
            and hasattr(service, "tenant_status"):
        server.add_status_source("tenants", service.tenant_status)
    if supervisor is not None and hasattr(supervisor, "status"):
        server.add_status_source("supervisor", supervisor.status)
    wal = getattr(service, "wal", None)
    if wal is not None and hasattr(wal, "summary"):
        server.add_status_source("wal", wal.summary)
    if rpc_server is not None and hasattr(rpc_server, "status"):
        server.add_status_source("rpc", rpc_server.status)
    # cross-node trace assembly rides /statusz (and, mirrored below,
    # incident snapshots) so an incident artifact carries the traces
    # that were in flight, not just this node's spans
    server.add_status_source("traces", server.trace_summary)
    # incident snapshots embed the same operational views /statusz serves
    for name, fn in server._status.items():
        if name != "journal":
            JOURNAL.add_status_source(name, fn)
    server.start()
    return server
