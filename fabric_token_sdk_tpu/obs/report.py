"""Bench reporter: roll the metrics registry + pipeline records into a
BENCH-style JSON snapshot.

Gives bench.py and harness/txgen.py one comparable artifact per round —
throughput, latency percentiles (steady-state only), pad waste, compile
counts — so every future perf PR is measurable against the previous
round's snapshot instead of ad-hoc profiling scripts. The metric family
names emitted here are a stable interface (see the ROADMAP open item).
"""

from __future__ import annotations

import json
import platform
from typing import Any

from .metrics import GLOBAL, MetricsProvider
from .pipeline import RECORDS, PipelineRecorder


def _labels_dict(labels: tuple) -> dict:
    return {k: v for k, v in labels}


def bench_snapshot(provider: MetricsProvider | None = None,
                   recorder: PipelineRecorder | None = None,
                   extra: dict | None = None) -> dict:
    """One BENCH-style dict: counters, histogram stats (count/sum/mean +
    p50/p95/p99 from the bounded reservoirs), and the pipeline roll-up."""
    provider = provider or GLOBAL
    recorder = recorder or RECORDS
    counters: dict[str, list] = {}
    gauges: dict[str, list] = {}
    histograms: dict[str, list] = {}
    with provider._lock:
        counter_items = list(provider._counters.items())
        gauge_items = list(provider._gauges.items())
        hist_items = list(provider._histograms.items())
    for (name, labels), c in counter_items:
        counters.setdefault(name, []).append(
            {"labels": _labels_dict(labels), "value": c.value})
    for (name, labels), g in gauge_items:
        gauges.setdefault(name, []).append(
            {"labels": _labels_dict(labels), "value": g.value})
    for (name, labels), h in hist_items:
        histograms.setdefault(name, []).append({
            "labels": _labels_dict(labels),
            "count": h.n, "sum": round(h.total, 6),
            "mean": round(h.mean, 6),
            "p50": round(h.percentile(50), 6),
            "p95": round(h.percentile(95), 6),
            "p99": round(h.percentile(99), 6),
        })
    out: dict[str, Any] = {
        "schema": "fts-obs-bench-v1",
        "host": platform.node(),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "pipeline": recorder.summary(),
    }
    if extra:
        out.update(extra)
    return out


def write_bench_report(path: str, provider: MetricsProvider | None = None,
                       recorder: PipelineRecorder | None = None,
                       extra: dict | None = None) -> str:
    snap = bench_snapshot(provider=provider, recorder=recorder, extra=extra)
    with open(path, "w") as fh:
        json.dump(snap, fh, indent=1, sort_keys=True)
    return path
