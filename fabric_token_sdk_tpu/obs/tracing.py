"""Hierarchical span tracer with device-profiler coupling.

Behavioral mirror of token/core/common/tracing/tracing.go:18-26 — spans
threaded through validator/auditor calls (OpenTelemetry in the reference)
— upgraded from the old flat ``Tracer.finished`` list to a real tree:
every span carries a trace-id / span-id / parent-id, attributes, and
events; nesting is tracked with a contextvar so layers that never see
each other (node -> chaincode -> validator -> batch verifier) still
produce one connected tree per request.

Exporters: Chrome/Perfetto trace-event JSON (obs/export.py) and optional
JAX profiler coupling — with ``profile_dir`` set each ROOT span wraps the
work in jax.profiler.start_trace/stop_trace so xprof captures the device
timeline (SURVEY.md §5), and with ``annotate_device=True`` every span
also enters a jax.profiler.TraceAnnotation so host spans line up with
device ops in the xprof view.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from .metrics import GLOBAL, MetricsProvider, sanitize_metric_name

_ids = itertools.count(1)
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "fts_current_span", default=None)


def _next_id() -> int:
    return next(_ids)


@dataclass
class Span:
    name: str
    start: float                      # perf_counter, phase arithmetic
    trace_id: int = 0
    span_id: int = 0
    parent_id: int | None = None
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)
    children: list = field(default_factory=list)
    links: list = field(default_factory=list)
    duration: float | None = None

    def add_event(self, name: str, **attributes) -> None:
        """tracing span AddEvent (audit/auditor.go:143-171 pattern)."""
        self.events.append((name, time.perf_counter() - self.start,
                            attributes or None))

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_link(self, other: "Span", **attributes) -> None:
        """OpenTelemetry-style span link: a causal reference to a span in
        a DIFFERENT trace (a shared batch-dispatch span references each
        member request's span and vice versa). Links carry enough identity
        to join the two traces in an export."""
        link = {"trace_id": other.trace_id, "span_id": other.span_id,
                "name": other.name}
        if attributes:
            link.update(attributes)
        self.links.append(link)

    def walk(self):
        """Depth-first iteration over this span and its descendants."""
        yield self
        for child in self.children:
            yield from child.walk()


class Tracer:
    """Span tracer: tree-building spans, durations into histograms,
    optional JAX device-trace coupling.

    ``finished`` keeps the last ``keep_spans`` COMPLETED spans (flat,
    oldest first) for cheap "what just ran" inspection; ``roots`` keeps
    the last completed ROOT spans with their full child trees — the unit
    the Chrome-trace exporter consumes.
    """

    def __init__(self, provider: MetricsProvider | None = None,
                 profile_dir: str | None = None, keep_spans: int = 256,
                 annotate_device: bool = False):
        self.provider = provider or GLOBAL
        self.profile_dir = profile_dir
        self.annotate_device = annotate_device
        self.finished: list[Span] = []
        self.roots: list[Span] = []
        self._active: dict[int, Span] = {}
        self._keep = keep_spans
        self._lock = threading.Lock()

    def _make_span(self, name: str, parent: Span | None,
                   attributes: dict, start: float | None = None) -> Span:
        sp = Span(name=name,
                  start=time.perf_counter() if start is None else start,
                  span_id=_next_id(),
                  trace_id=(parent.trace_id if parent is not None
                            else _next_id()),
                  parent_id=(parent.span_id if parent is not None
                             else None),
                  attributes=dict(attributes))
        if parent is not None:
            parent.children.append(sp)
        with self._lock:
            self._active[sp.span_id] = sp
        return sp

    def _finish(self, sp: Span, end: float | None = None) -> None:
        sp.duration = ((time.perf_counter() if end is None else end)
                       - sp.start)
        self.provider.histogram(
            sanitize_metric_name(f"span_{sp.name}_seconds")).observe(
            sp.duration)
        with self._lock:
            self._active.pop(sp.span_id, None)
            self.finished.append(sp)
            if len(self.finished) > self._keep:
                self.finished.pop(0)
            if sp.parent_id is None:
                self.roots.append(sp)
                if len(self.roots) > self._keep:
                    self.roots.pop(0)

    def start_span(self, name: str, parent: Span | None = None,
                   **attributes) -> Span:
        """Explicitly-parented span for flows a ``with`` block cannot
        scope: a serve request whose lifetime spans admission -> queue ->
        dispatch -> verdict across coroutines and executor threads (the
        contextvar does not propagate through ``run_in_executor``). Pair
        with :meth:`end_span`; ``parent=None`` starts a new trace."""
        return self._make_span(name, parent, attributes)

    def end_span(self, span: Span) -> None:
        """Finish a span obtained from :meth:`start_span`. Idempotent so
        late completions (deadline expiry racing dispatch) cannot
        double-observe the duration histogram."""
        if span.duration is not None:
            return
        self._finish(span)

    def record_span(self, name: str, start: float, end: float,
                    parent: Span | None = None, **attributes) -> Span:
        """Record an already-elapsed interval as a completed span
        (e.g. queue wait reconstructed at dispatch time from the request's
        enqueue timestamp). ``start``/``end`` are perf_counter values."""
        sp = self._make_span(name, parent, attributes, start=start)
        self._finish(sp, end=end)
        return sp

    @contextmanager
    def span(self, name: str, parent: Span | None = None, **attributes):
        if parent is None:
            parent = _CURRENT.get()
        sp = self._make_span(name, parent, attributes)
        token = _CURRENT.set(sp)
        profiling = False
        annotation = None
        if self.profile_dir is not None and parent is None:
            import jax

            try:
                jax.profiler.start_trace(self.profile_dir)
                profiling = True
            except RuntimeError:
                pass  # a trace is already running
        if self.annotate_device:
            try:
                import jax

                annotation = jax.profiler.TraceAnnotation(name)
                annotation.__enter__()
            except Exception:
                annotation = None
        try:
            yield sp
        finally:
            if annotation is not None:
                annotation.__exit__(None, None, None)
            if profiling:
                import jax

                jax.profiler.stop_trace()
            _CURRENT.reset(token)
            self._finish(sp)

    def current(self) -> Span | None:
        """The innermost open span on this execution context, if any."""
        return _CURRENT.get()

    def root_snapshot(self) -> list[Span]:
        """Copy of the completed-root list, taken under the lock — the
        safe input for exporters running on scrape threads."""
        with self._lock:
            return list(self.roots)

    def active_snapshot(self) -> list[Span]:
        """Every span that has STARTED but not finished, oldest first —
        the incident-snapshot view of what the process was doing when it
        stopped making progress (a wedged dispatch is an open
        ``serve.dispatch`` span with a large age). The Span objects are
        live; callers must only read them."""
        with self._lock:
            return sorted(self._active.values(), key=lambda s: s.start)

    def last_root(self, name: str | None = None) -> Span | None:
        """Most recent completed root span (optionally by name)."""
        with self._lock:
            for sp in reversed(self.roots):
                if name is None or sp.name == name:
                    return sp
        return None

    def clear(self) -> None:
        with self._lock:
            self.finished.clear()
            self.roots.clear()
            self._active.clear()


#: Process-global default tracer: the one the verification pipeline
#: (models / core / services layers) threads its spans through.
TRACER = Tracer()
