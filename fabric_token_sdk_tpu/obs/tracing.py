"""Hierarchical span tracer with device-profiler coupling.

Behavioral mirror of token/core/common/tracing/tracing.go:18-26 — spans
threaded through validator/auditor calls (OpenTelemetry in the reference)
— upgraded from the old flat ``Tracer.finished`` list to a real tree:
every span carries a trace-id / span-id / parent-id, attributes, and
events; nesting is tracked with a contextvar so layers that never see
each other (node -> chaincode -> validator -> batch verifier) still
produce one connected tree per request.

Cross-process propagation (Dapper-style): :class:`SpanContext` is the
compact identity of one span — ``(trace_id, span_id, sampled)`` — with
a fixed 17-byte wire encoding (``>QQB``) carried in RPC frames and pipe
messages. A span created with ``remote_parent=ctx`` joins the CALLER's
trace: it inherits ``ctx.trace_id`` and parents under ``ctx.span_id``
even though the parent Span object lives in another process. Span and
trace ids are seeded from ``os.urandom`` per process so two processes
can never mint the same trace id. :func:`extract_wire_context` is the
tolerant decode half: poisoned or missing context bytes NEVER raise —
they count under ``trace_drops_total{reason}`` and return ``None``, so
a bad trace header can never fail a frame.

Exporters: Chrome/Perfetto trace-event JSON (obs/export.py), the
spool-based :class:`SpanSpoolExporter` (the tracing twin of
``obs.aggregate.SpoolPublisher``: each process appends its finished
spans to ``<spool>/<node>.spans.jsonl`` so a parent can assemble
fleet-wide traces), and optional JAX profiler coupling — with
``profile_dir`` set each ROOT span wraps the work in
jax.profiler.start_trace/stop_trace so xprof captures the device
timeline (SURVEY.md §5), and with ``annotate_device=True`` every span
also enters a jax.profiler.TraceAnnotation so host spans line up with
device ops in the xprof view.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import struct
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from .metrics import GLOBAL, MetricsProvider, sanitize_metric_name

#: Family metadata for the cross-process trace plane (stable inventory;
#: HELP-linted via scripts/check_metric_help.py like every other block).
_TRACE_FAMILIES = {
    "trace_spans_total":
        "Finished spans accepted by the span spool exporter, by node.",
    "trace_drops_total":
        "Spans or trace contexts dropped, by reason: buffer (export "
        "ring full), unsampled (span's trace not sampled), spool_io "
        "(exporter publish failed), invalid_context (poisoned wire "
        "context bytes ignored), missing (frame carried no context).",
    "span_exemplars_total":
        "Trace exemplars attached to latency histograms, by family.",
}

#: Wire layout of one SpanContext: trace_id u64 | span_id u64 | sampled
#: u8 — 17 bytes, big-endian, version-free (the RPC layer negotiates).
_CTX_STRUCT = struct.Struct(">QQB")
CONTEXT_WIRE_SIZE = _CTX_STRUCT.size

# Span/trace ids must be unique ACROSS processes (fleet trace assembly
# keys on trace_id), so the per-process counter rides on a random epoch:
# 40 random bits shifted past a 24-bit counter space keeps ids monotonic
# in-process and collision-free (w.h.p.) between processes, while
# staying under 2**64 for the wire encoding.
_ID_EPOCH = int.from_bytes(os.urandom(5), "big") << 24
_ids = itertools.count(1)
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "fts_current_span", default=None)


def _next_id() -> int:
    return _ID_EPOCH + next(_ids)


def _default_node() -> str:
    """Node identity stamped into exports/snapshots: ``FTS_NODE`` when
    the deployment names its processes, else pid-derived."""
    return os.environ.get("FTS_NODE") or f"pid{os.getpid()}"


@dataclass(frozen=True)
class SpanContext:
    """Compact cross-process span identity (trace_id, span_id, sampled).

    The inject half of Dapper-style propagation: a client serializes
    the context of its open ``rpc.call`` span into a frame, the server
    extracts it and opens its ``rpc.serve`` span with
    ``remote_parent=ctx`` — one trace id across the process hop."""

    trace_id: int
    span_id: int
    sampled: bool = True

    def to_bytes(self) -> bytes:
        """17-byte wire form (``>QQB``)."""
        return _CTX_STRUCT.pack(self.trace_id & 0xFFFFFFFFFFFFFFFF,
                                self.span_id & 0xFFFFFFFFFFFFFFFF,
                                1 if self.sampled else 0)

    @classmethod
    def from_bytes(cls, data: bytes) -> "SpanContext":
        """Strict decode; raises ``ValueError`` on truncated bytes or a
        zero trace id (use :func:`extract_wire_context` on wire input —
        it counts and returns None instead of raising)."""
        if not isinstance(data, (bytes, bytearray, memoryview)) \
                or len(data) != CONTEXT_WIRE_SIZE:
            raise ValueError(
                f"trace context must be {CONTEXT_WIRE_SIZE} bytes, got "
                f"{type(data).__name__} of length "
                f"{len(data) if hasattr(data, '__len__') else '?'}")
        trace_id, span_id, sampled = _CTX_STRUCT.unpack(bytes(data))
        if trace_id == 0 or span_id == 0:
            raise ValueError("zero trace/span id")
        return cls(trace_id=trace_id, span_id=span_id,
                   sampled=bool(sampled))


def extract_wire_context(data,
                         provider: MetricsProvider | None = None
                         ) -> SpanContext | None:
    """Tolerant wire decode: the server-side extract half.

    ``None`` input (a v1/v2 peer that sent no context) counts under
    ``trace_drops_total{reason="missing"}``; poisoned bytes (truncated,
    wrong type, zero trace id) count under ``reason="invalid_context"``.
    Either way the caller gets ``None`` and serves the frame — missing
    or poisoned context is NEVER a frame error."""
    provider = provider or GLOBAL
    if data is None:
        provider.counter("trace_drops_total", reason="missing").add()
        return None
    try:
        return SpanContext.from_bytes(data)
    except ValueError:
        provider.counter("trace_drops_total",
                         reason="invalid_context").add()
        return None


@dataclass
class Span:
    name: str
    start: float                      # perf_counter, phase arithmetic
    trace_id: int = 0
    span_id: int = 0
    parent_id: int | None = None
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)
    children: list = field(default_factory=list)
    links: list = field(default_factory=list)
    duration: float | None = None
    sampled: bool = True

    def context(self) -> SpanContext:
        """This span's cross-process identity — inject it into an
        outbound frame so the callee can parent under it remotely."""
        return SpanContext(trace_id=self.trace_id, span_id=self.span_id,
                           sampled=self.sampled)

    def add_event(self, name: str, **attributes) -> None:
        """tracing span AddEvent (audit/auditor.go:143-171 pattern)."""
        self.events.append((name, time.perf_counter() - self.start,
                            attributes or None))

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_link(self, other: "Span", **attributes) -> None:
        """OpenTelemetry-style span link: a causal reference to a span in
        a DIFFERENT trace (a shared batch-dispatch span references each
        member request's span and vice versa). Links carry enough identity
        to join the two traces in an export."""
        link = {"trace_id": other.trace_id, "span_id": other.span_id,
                "name": other.name}
        if attributes:
            link.update(attributes)
        self.links.append(link)

    def walk(self):
        """Depth-first iteration over this span and its descendants."""
        yield self
        for child in self.children:
            yield from child.walk()


class Tracer:
    """Span tracer: tree-building spans, durations into histograms,
    optional JAX device-trace coupling.

    ``finished`` keeps the last ``keep_spans`` COMPLETED spans (flat,
    oldest first) for cheap "what just ran" inspection; ``roots`` keeps
    the last completed ROOT spans with their full child trees — the unit
    the Chrome-trace exporter consumes.
    """

    def __init__(self, provider: MetricsProvider | None = None,
                 profile_dir: str | None = None, keep_spans: int = 256,
                 annotate_device: bool = False, node: str | None = None):
        self.provider = provider or GLOBAL
        for fam, help_text in _TRACE_FAMILIES.items():
            self.provider.describe(fam, help_text)
        self.profile_dir = profile_dir
        self.annotate_device = annotate_device
        self.node = node or _default_node()
        self.finished: list[Span] = []
        self.roots: list[Span] = []
        self._active: dict[int, Span] = {}
        self._keep = keep_spans
        self._lock = threading.Lock()
        self._finish_hooks: list = []

    def add_finish_hook(self, fn) -> None:
        """Register ``fn(span)`` to run on every span completion — the
        exporter attachment point. Hooks must not raise (a broken
        exporter must not fail the traced work); exceptions are
        swallowed."""
        with self._lock:
            self._finish_hooks.append(fn)

    def remove_finish_hook(self, fn) -> None:
        with self._lock:
            try:
                self._finish_hooks.remove(fn)
            except ValueError:
                pass

    def _make_span(self, name: str, parent: Span | None,
                   attributes: dict, start: float | None = None,
                   remote_parent: SpanContext | None = None) -> Span:
        if parent is not None:
            trace_id, parent_id, sampled = (
                parent.trace_id, parent.span_id, parent.sampled)
        elif remote_parent is not None:
            # join the caller's trace across the process hop: same
            # trace id, parented under a span that lives elsewhere
            trace_id, parent_id, sampled = (
                remote_parent.trace_id, remote_parent.span_id,
                remote_parent.sampled)
        else:
            trace_id, parent_id, sampled = _next_id(), None, True
        sp = Span(name=name,
                  start=time.perf_counter() if start is None else start,
                  span_id=_next_id(),
                  trace_id=trace_id,
                  parent_id=parent_id,
                  attributes=dict(attributes),
                  sampled=sampled)
        if remote_parent is not None and parent is None:
            sp.attributes.setdefault("remote_parent", True)
        if parent is not None:
            parent.children.append(sp)
        with self._lock:
            self._active[sp.span_id] = sp
        return sp

    def _finish(self, sp: Span, end: float | None = None) -> None:
        sp.duration = ((time.perf_counter() if end is None else end)
                       - sp.start)
        self.provider.histogram(
            sanitize_metric_name(f"span_{sp.name}_seconds")).observe(
            sp.duration)
        with self._lock:
            self._active.pop(sp.span_id, None)
            self.finished.append(sp)
            if len(self.finished) > self._keep:
                self.finished.pop(0)
            # a remotely-parented span is a local root (its parent span
            # object lives in another process), so it belongs in roots
            # for the Chrome exporter and /tracez
            if sp.parent_id is None or sp.attributes.get("remote_parent"):
                self.roots.append(sp)
                if len(self.roots) > self._keep:
                    self.roots.pop(0)
            hooks = list(self._finish_hooks)
        for hook in hooks:
            try:
                hook(sp)
            except Exception:
                pass

    def start_span(self, name: str, parent: Span | None = None,
                   remote_parent: SpanContext | None = None,
                   **attributes) -> Span:
        """Explicitly-parented span for flows a ``with`` block cannot
        scope: a serve request whose lifetime spans admission -> queue ->
        dispatch -> verdict across coroutines and executor threads (the
        contextvar does not propagate through ``run_in_executor``). Pair
        with :meth:`end_span`; ``parent=None`` starts a new trace, and
        ``remote_parent=ctx`` joins the trace of a caller in another
        process."""
        return self._make_span(name, parent, attributes,
                               remote_parent=remote_parent)

    def end_span(self, span: Span) -> None:
        """Finish a span obtained from :meth:`start_span`. Idempotent so
        late completions (deadline expiry racing dispatch) cannot
        double-observe the duration histogram."""
        if span.duration is not None:
            return
        self._finish(span)

    def record_span(self, name: str, start: float, end: float,
                    parent: Span | None = None, **attributes) -> Span:
        """Record an already-elapsed interval as a completed span
        (e.g. queue wait reconstructed at dispatch time from the request's
        enqueue timestamp). ``start``/``end`` are perf_counter values."""
        sp = self._make_span(name, parent, attributes, start=start)
        self._finish(sp, end=end)
        return sp

    @contextmanager
    def span(self, name: str, parent: Span | None = None,
             remote_parent: SpanContext | None = None, **attributes):
        if parent is None:
            parent = _CURRENT.get()
        sp = self._make_span(name, parent, attributes,
                             remote_parent=remote_parent)
        token = _CURRENT.set(sp)
        profiling = False
        annotation = None
        if self.profile_dir is not None and parent is None:
            import jax

            try:
                jax.profiler.start_trace(self.profile_dir)
                profiling = True
            except RuntimeError:
                pass  # a trace is already running
        if self.annotate_device:
            try:
                import jax

                annotation = jax.profiler.TraceAnnotation(name)
                annotation.__enter__()
            except Exception:
                annotation = None
        try:
            yield sp
        finally:
            if annotation is not None:
                annotation.__exit__(None, None, None)
            if profiling:
                import jax

                jax.profiler.stop_trace()
            _CURRENT.reset(token)
            self._finish(sp)

    def current(self) -> Span | None:
        """The innermost open span on this execution context, if any."""
        return _CURRENT.get()

    def root_snapshot(self) -> list[Span]:
        """Copy of the completed-root list, taken under the lock — the
        safe input for exporters running on scrape threads."""
        with self._lock:
            return list(self.roots)

    def active_snapshot(self) -> list[Span]:
        """Every span that has STARTED but not finished, oldest first —
        the incident-snapshot view of what the process was doing when it
        stopped making progress (a wedged dispatch is an open
        ``serve.dispatch`` span with a large age). The Span objects are
        live; callers must only read them."""
        with self._lock:
            return sorted(self._active.values(), key=lambda s: s.start)

    def last_root(self, name: str | None = None) -> Span | None:
        """Most recent completed root span (optionally by name)."""
        with self._lock:
            for sp in reversed(self.roots):
                if name is None or sp.name == name:
                    return sp
        return None

    def clear(self) -> None:
        with self._lock:
            self.finished.clear()
            self.roots.clear()
            self._active.clear()


class SpanSpoolExporter:
    """Publish finished spans to ``<spool_dir>/<node>.spans.jsonl`` —
    the tracing twin of :class:`obs.aggregate.SpoolPublisher`.

    Each process in the fleet (parent, sidecars) attaches one exporter
    to its tracer; a finish hook copies completed spans into a BOUNDED
    ring (overflow counts ``trace_drops_total{reason="buffer"}``,
    unsampled spans count ``reason="unsampled"`` — no unbounded growth
    under ``trace_every=1`` storms). ``publish()`` atomically rewrites
    the node's spool file (tmp + rename, same torn-read discipline as
    the metrics spool) with one JSON record per span carrying the node
    stamp, ids, timing, and attributes; ``assemble_traces`` on the
    reading side groups records from every node by trace_id.

    Wall-clock anchoring: span ``start`` is perf_counter (process-
    relative), so each record also carries ``wall_end`` (time.time() at
    finish) and ``duration`` — enough to order spans across processes
    to NTP accuracy without trusting perf_counter epochs to align.
    """

    def __init__(self, spool_dir, node: str | None = None,
                 tracer: Tracer | None = None,
                 provider: MetricsProvider | None = None,
                 keep_spans: int = 2048, interval_s: float = 2.0):
        import pathlib

        self.spool_dir = pathlib.Path(spool_dir)
        self.spool_dir.mkdir(parents=True, exist_ok=True)
        self.tracer = tracer or TRACER
        self.node = node or self.tracer.node
        self.provider = provider or self.tracer.provider
        self.interval_s = interval_s
        self.path = self.spool_dir / f"{self.node}.spans.jsonl"
        self._buf: deque = deque(maxlen=keep_spans)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._attached = False

    # -- collection ----------------------------------------------------
    def on_finish(self, sp: Span) -> None:
        """Finish hook: copy one completed span into the export ring."""
        if not sp.sampled:
            self.provider.counter("trace_drops_total",
                                  reason="unsampled").add()
            return
        rec = {
            "node": self.node,
            "name": sp.name,
            "trace_id": f"{sp.trace_id:016x}",
            "span_id": f"{sp.span_id:016x}",
            "parent_id": (f"{sp.parent_id:016x}"
                          if sp.parent_id else None),
            "duration": sp.duration,
            "wall_end": time.time(),
            "attributes": {k: v for k, v in sp.attributes.items()
                           if isinstance(v, (str, int, float, bool,
                                             type(None)))},
        }
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                # deque drops the oldest on append; surface that
                self.provider.counter("trace_drops_total",
                                      reason="buffer").add()
            self._buf.append(rec)
        self.provider.counter("trace_spans_total",
                              node=self.node).add()

    def attach(self) -> "SpanSpoolExporter":
        if not self._attached:
            self.tracer.add_finish_hook(self.on_finish)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            self.tracer.remove_finish_hook(self.on_finish)
            self._attached = False

    # -- publication ---------------------------------------------------
    def publish(self) -> int:
        """Atomically rewrite this node's span spool file from the
        current ring; returns the number of records written. IO errors
        count ``trace_drops_total{reason="spool_io"}`` and are
        swallowed — a full disk must not fail the traced work."""
        with self._lock:
            records = list(self._buf)
        tmp = self.path.with_suffix(".tmp")
        try:
            with tmp.open("w") as fh:
                for rec in records:
                    fh.write(json.dumps(rec) + "\n")
            os.replace(tmp, self.path)
        except OSError:
            self.provider.counter("trace_drops_total",
                                  reason="spool_io").add()
            return 0
        return len(records)

    def start(self) -> "SpanSpoolExporter":
        """Attach the finish hook and publish on a daemon-thread
        cadence (mirrors ``SpoolPublisher.start``)."""
        self.attach()
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"span-spool-{self.node}",
                daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.publish()

    def stop(self, final_publish: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.detach()
        if final_publish:
            self.publish()


def read_span_spool(spool_dir) -> list[dict]:
    """Read every ``*.spans.jsonl`` file under ``spool_dir`` into a
    flat record list. Torn/garbage lines are skipped (atomic rename
    makes them rare; a crashed writer must not poison the fleet
    view)."""
    import pathlib

    records: list[dict] = []
    spool = pathlib.Path(spool_dir)
    if not spool.is_dir():
        return records
    for path in sorted(spool.glob("*.spans.jsonl")):
        try:
            text = path.read_text()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("trace_id"):
                records.append(rec)
    return records


def assemble_traces(records: list[dict]) -> dict[str, list[dict]]:
    """Group span records (from any number of nodes) by trace_id —
    the fleet-wide trace view. Within each trace, spans are ordered
    parent-before-child where the parent is present, then by wall_end;
    each trace's list therefore reads as the request's path through
    the fleet (client ``rpc.call`` -> sidecar ``rpc.serve`` ->
    ``serve.request``)."""
    by_trace: dict[str, list[dict]] = {}
    for rec in records:
        by_trace.setdefault(rec["trace_id"], []).append(rec)
    for spans in by_trace.values():
        by_id = {sp.get("span_id"): sp for sp in spans
                 if sp.get("span_id")}
        # depths are precomputed — list.sort() swaps the list contents
        # out while it runs, so a key function must not read ``spans``
        depths: dict[int, int] = {}
        for i, sp in enumerate(spans):
            depth, seen, cur = 0, set(), sp
            while True:
                sid = cur.get("span_id")
                if sid is not None:
                    if sid in seen:
                        break  # cycle in poisoned records: stop here
                    seen.add(sid)
                parent = cur.get("parent_id")
                nxt = by_id.get(parent) if parent is not None else None
                if nxt is None:
                    break
                depth += 1
                cur = nxt
            depths[i] = depth
        order = sorted(range(len(spans)),
                       key=lambda i: (depths[i],
                                      spans[i].get("wall_end") or 0))
        spans[:] = [spans[i] for i in order]
    return by_trace


#: Process-global default tracer: the one the verification pipeline
#: (models / core / services layers) threads its spans through.
TRACER = Tracer()
