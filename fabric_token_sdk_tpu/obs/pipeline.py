"""Per-batch device-pipeline records for the zkatdlog verification path.

Every batched verify produces one ``BatchRecord``: batch size, the pow-2
row bucket(s) the batch padded into, the pad-waste ratio, and the
host-prep / device-execute / result-fetch wall split (device time is
fenced at the pipeline's blocking sync — the combined-pass finalize /
exact-pass collection, where ``block_until_ready`` semantics apply; host
work dispatched asynchronously before the fence is charged to
host_prep, which is exactly the overlap the pipeline buys).

Compile-vs-steady-state detection: the first record for a given
(kind, shape-bucket) key in this process is labelled ``cold_compile`` and
kept OUT of the steady-state latency percentiles, so a prewarm or first
verify cannot poison p99.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field

from .metrics import GLOBAL, MetricsProvider

#: Family metadata for the pipeline_* instruments (HELP lines must not
#: depend on call-site order; scripts/check_metric_help.py enforces).
_PIPELINE_FAMILIES = {
    "pipeline_batches_total":
        "Batched device verifies, by kind and cold/steady state",
    "pipeline_rows_total": "Live (non-padding) rows verified, by kind",
    "pipeline_pad_rows_total": "Padding rows added for bucketing, by kind",
    "pipeline_batch_seconds": "Batch wall seconds, by kind and state",
    "pipeline_steady_seconds":
        "Steady-state batch wall seconds (cold compiles excluded)",
    "pipeline_phase_seconds":
        "Host-prep / device-execute / result-fetch wall split per batch",
    "pipeline_pad_waste_ratio":
        "Fraction of padded device rows carrying no real proof",
}


@dataclass
class BatchRecord:
    """One batched device verify through the pipeline."""

    kind: str                 # "range_verify" | "sigma_tas" | ...
    batch: int                # rows requested
    live: int                 # structurally valid rows actually verified
    bucket: int               # largest padded row bucket used
    padded_rows: int          # total rows after bucket padding
    host_prep_s: float = 0.0
    device_execute_s: float = 0.0
    result_fetch_s: float = 0.0
    total_s: float = 0.0
    path: str = ""            # combined | exact | structure-only | ...
    chunks: int = 1
    cold_compile: bool = False
    attrs: dict = field(default_factory=dict)

    @property
    def pad_waste(self) -> float:
        """Fraction of padded device rows that carry no real proof."""
        if self.padded_rows <= 0:
            return 0.0
        return 1.0 - self.live / self.padded_rows

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "batch": self.batch, "live": self.live,
            "bucket": self.bucket, "padded_rows": self.padded_rows,
            "pad_waste": round(self.pad_waste, 4),
            "host_prep_s": round(self.host_prep_s, 6),
            "device_execute_s": round(self.device_execute_s, 6),
            "result_fetch_s": round(self.result_fetch_s, 6),
            "total_s": round(self.total_s, 6),
            "path": self.path, "chunks": self.chunks,
            "cold_compile": self.cold_compile, **self.attrs,
        }


class PhaseTimer:
    """Accumulates named phase durations as child spans of the current
    trace context. A phase may be entered several times (the reject path
    re-enters device_execute for the bisect + exact passes); totals sum.
    """

    def __init__(self, tracer=None):
        if tracer is None:
            from .tracing import TRACER

            tracer = TRACER
        self.tracer = tracer
        self.totals: dict[str, float] = defaultdict(float)

    @contextmanager
    def phase(self, name: str, **attributes):
        with self.tracer.span(name, **attributes) as sp:
            yield sp
        self.totals[name] += sp.duration


class PipelineRecorder:
    """Bounded ring of BatchRecords + registry fan-out.

    Metric families fed per record (stable interface for BENCH
    comparisons, see ROADMAP):
      - pipeline_batches_total{kind, state}   (state: cold|steady)
      - pipeline_rows_total{kind} / pipeline_pad_rows_total{kind}
      - pipeline_batch_seconds{kind, state}
      - pipeline_steady_seconds{kind}     (steady-state only — the family
        latency percentiles are computed from)
      - pipeline_phase_seconds{kind, phase}
      - pipeline_pad_waste_ratio{kind}
    """

    def __init__(self, provider: MetricsProvider | None = None,
                 keep: int = 512):
        self.provider = provider or GLOBAL
        self.records: list[BatchRecord] = []
        self._keep = keep
        self._seen_shapes: set = set()
        self._lock = threading.Lock()
        for fam, help_text in _PIPELINE_FAMILIES.items():
            self.provider.describe(fam, help_text)

    def is_cold(self, kind: str, shape_key) -> bool:
        """True (and marks seen) when this process has not run `kind` at
        `shape_key` before — i.e. this batch likely pays XLA compiles."""
        key = (kind, shape_key)
        with self._lock:
            if key in self._seen_shapes:
                return False
            self._seen_shapes.add(key)
            return True

    def record(self, rec: BatchRecord) -> BatchRecord:
        with self._lock:
            self.records.append(rec)
            if len(self.records) > self._keep:
                self.records.pop(0)
        p = self.provider
        state = "cold" if rec.cold_compile else "steady"
        p.counter("pipeline_batches_total", kind=rec.kind, state=state).add()
        p.counter("pipeline_rows_total", kind=rec.kind).add(rec.live)
        p.counter("pipeline_pad_rows_total", kind=rec.kind).add(
            max(0, rec.padded_rows - rec.live))
        p.histogram("pipeline_batch_seconds", kind=rec.kind,
                    state=state).observe(rec.total_s)
        if not rec.cold_compile:
            p.histogram("pipeline_steady_seconds",
                        kind=rec.kind).observe(rec.total_s)
        for phase, secs in (("host_prep", rec.host_prep_s),
                            ("device_execute", rec.device_execute_s),
                            ("result_fetch", rec.result_fetch_s)):
            if secs:
                p.histogram("pipeline_phase_seconds", kind=rec.kind,
                            phase=phase).observe(secs)
        p.histogram("pipeline_pad_waste_ratio",
                    kind=rec.kind).observe(rec.pad_waste)
        return rec

    def last(self, kind: str | None = None) -> BatchRecord | None:
        with self._lock:
            for rec in reversed(self.records):
                if kind is None or rec.kind == kind:
                    return rec
        return None

    def summary(self) -> dict:
        """Roll-up for the bench reporter."""
        with self._lock:
            recs = list(self.records)
        steady = [r for r in recs if not r.cold_compile]
        out: dict = {
            "batches": len(recs),
            "cold_compiles": sum(1 for r in recs if r.cold_compile),
            "rows": sum(r.live for r in recs),
            "padded_rows": sum(r.padded_rows for r in recs),
        }
        if out["padded_rows"]:
            out["pad_waste"] = round(
                1.0 - out["rows"] / out["padded_rows"], 4)
        if steady:
            lat = sorted(r.total_s for r in steady)

            def pct(p):
                return round(lat[min(len(lat) - 1,
                                     int(p / 100.0 * len(lat)))], 6)

            wall = sum(lat)
            rows = sum(r.live for r in steady)
            out["steady"] = {
                "batches": len(steady),
                "p50_s": pct(50), "p95_s": pct(95), "p99_s": pct(99),
                "rows_per_sec": round(rows / wall, 2) if wall else 0.0,
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self.records.clear()
            self._seen_shapes.clear()


#: Process-global recorder the batched verifiers feed.
RECORDS = PipelineRecorder()
