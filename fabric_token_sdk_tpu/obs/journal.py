"""Flight recorder: a bounded, thread-safe journal of typed events with
JSONL spill and automatic incident snapshots.

The telemetry plane (obs/telemetry.py) answers "how is the node doing
right now"; the journal answers the question that matters once a run has
*stopped making progress*: "what was the last thing every subsystem did,
and what was it waiting on when it died". Every MULTICHIP_r0*.json so far
reads ``rc=124, tail=""`` — a hang with zero diagnostic output — which is
exactly the failure mode a flight recorder exists for.

Event model: one process-global :class:`Journal` (``JOURNAL``) holds the
last ``capacity`` events in a ring. An event is a small dict —
``{"seq", "ts", "kind", ...attrs}`` — with ``kind`` drawn from the typed
inventory below (admission, batching, dispatch, compiles, breaker
transitions, SLO burns, fallbacks, heartbeats, watchdog abandons).
Recording is cheap (one deque append + one counter bump under a lock) so
the ring is always on; configuring a directory additionally spills every
event as a JSON line (``journal.jsonl``) and enables incident snapshots.

Incident snapshots: ``incident(trigger, ...)`` writes one self-contained
JSON artifact with the journal tail, a ``faulthandler`` dump of every
thread's stack, the tracer's still-open ("active") spans — a stalled
dispatch is an open ``serve.dispatch`` span — and the outputs of any
registered status sources. Triggers wired in this codebase: circuit
breaker ``force_open``, SLO fast-burn, watchdog abandon, heartbeat
stall. Snapshots are rate-limited (``min_interval_s``) so a flapping
trigger cannot fill the disk.

Stable families: ``journal_events_total{kind}``,
``journal_dropped_total``, ``journal_incidents_total{trigger}``.
"""

from __future__ import annotations

import faulthandler
import json
import os
import tempfile
import threading
import time
from collections import deque

from .metrics import GLOBAL, MetricsProvider

# ------------------------------------------------------------ event kinds
EVENT_REQUEST_ADMITTED = "request_admitted"
EVENT_REQUEST_SHED = "request_shed"
EVENT_BATCH_FORMED = "batch_formed"
EVENT_DISPATCH_START = "dispatch_start"
EVENT_DISPATCH_END = "dispatch_end"
EVENT_COMPILE_START = "compile_start"
EVENT_COMPILE_END = "compile_end"
EVENT_BREAKER_TRANSITION = "breaker_transition"
EVENT_SLO_BURN = "slo_burn"
EVENT_FALLBACK = "fallback"
EVENT_HEARTBEAT = "heartbeat"
EVENT_WATCHDOG_ABANDON = "watchdog_abandon"
EVENT_INCIDENT = "incident"
#: A request resolved with terminal ``shutdown`` status during drain —
#: journaled so a post-mortem can account for every admitted request.
EVENT_REQUEST_SHUTDOWN = "request_shutdown"
#: WAL lifecycle: recovery scan finished / one entry replayed.
EVENT_WAL_RECOVERED = "wal_recovered"
EVENT_WAL_REPLAY = "wal_replay"
#: Supervisor lifecycle: child failure detected / child (re)started.
EVENT_CHILD_FAILURE = "child_failure"
EVENT_CHILD_RESTART = "child_restart"
#: One columnar SUBMIT_BATCH frame admitted as a single decision (the
#: per-row counterpart is EVENT_REQUEST_ADMITTED).
EVENT_BATCH_ADMITTED = "batch_admitted"
#: A single tenant's error-budget burn tripped the fast-burn rule (the
#: tenant-scoped counterpart of EVENT_SLO_BURN); attrs name the tms_id
#: so an incident snapshot identifies the offending tenant directly.
EVENT_TENANT_FAST_BURN = "tenant_fast_burn"
#: New work from a fast-burning tenant was shed by the TenantShedPolicy
#: (terminal status ``shed_tenant_slo``) while other tenants proceed.
EVENT_TENANT_SHED = "tenant_shed"

EVENT_KINDS = (
    EVENT_REQUEST_ADMITTED, EVENT_REQUEST_SHED, EVENT_BATCH_FORMED,
    EVENT_DISPATCH_START, EVENT_DISPATCH_END, EVENT_COMPILE_START,
    EVENT_COMPILE_END, EVENT_BREAKER_TRANSITION, EVENT_SLO_BURN,
    EVENT_FALLBACK, EVENT_HEARTBEAT, EVENT_WATCHDOG_ABANDON,
    EVENT_INCIDENT, EVENT_REQUEST_SHUTDOWN, EVENT_WAL_RECOVERED,
    EVENT_WAL_REPLAY, EVENT_CHILD_FAILURE, EVENT_CHILD_RESTART,
    EVENT_BATCH_ADMITTED, EVENT_TENANT_FAST_BURN, EVENT_TENANT_SHED,
)

_JOURNAL_FAMILIES = {
    "journal_events_total": "Flight-recorder events recorded, by kind.",
    "journal_dropped_total":
        "Events evicted from the bounded journal ring (oldest-first).",
    "journal_incidents_total":
        "Incident snapshots written, by trigger.",
}

#: Events included in an incident snapshot's journal tail.
_SNAPSHOT_TAIL = 512


def _dump_all_thread_stacks() -> str:
    """Every thread's Python stack via ``faulthandler`` (it walks the
    interpreter's thread states directly, so it sees threads that are
    blocked in C — a dispatch wedged inside an XLA call included, which
    a pure-`traceback` walk can misattribute). faulthandler needs a real
    file descriptor, so dump through an unlinked temp file."""
    with tempfile.TemporaryFile(mode="w+") as f:
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.seek(0)
        return f.read()


class Journal:
    """Bounded ring of typed events + spill + incident snapshots.

    ``record`` is the single write path and is safe from any thread
    (serve event loop, executor threads, scrape threads, stall-detector
    threads). ``configure(dir)`` turns on the JSONL spill and gives
    incident snapshots a home; without it the ring still records and
    ``incident`` degrades to an :data:`EVENT_INCIDENT` ring entry (tests
    and library users stay hermetic by default).
    """

    def __init__(self, capacity: int = 4096,
                 provider: MetricsProvider | None = None,
                 clock=time.time, min_interval_s: float = 30.0):
        self.capacity = capacity
        self.provider = provider or GLOBAL
        self.clock = clock
        self.min_interval_s = min_interval_s
        self.dropped = 0
        self.incidents = 0
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()
        self._spill_path: str | None = None
        self._spill_file = None
        self._incident_dir: str | None = None
        self._last_incident_t: float | None = None
        self._status_sources: dict[str, object] = {}
        for fam, help_text in _JOURNAL_FAMILIES.items():
            self.provider.describe(fam, help_text)

    # ------------------------------------------------------------- wiring
    def configure(self, directory: str | os.PathLike,
                  spill: bool = True) -> None:
        """Point the journal at a directory: events spill to
        ``journal.jsonl`` (append) and incident snapshots land as
        ``incident_<trigger>_<seq>.json``. Idempotent; re-configuring
        switches directories (the old spill file is closed)."""
        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            if self._spill_file is not None:
                self._spill_file.close()
                self._spill_file = None
            self._incident_dir = directory
            self._spill_path = (os.path.join(directory, "journal.jsonl")
                                if spill else None)

    def add_status_source(self, name: str, fn) -> None:
        """Register a ``fn() -> JSON-serializable`` snapshot to embed in
        every incident (same contract as TelemetryServer /statusz)."""
        self._status_sources[name] = fn

    @property
    def spill_path(self) -> str | None:
        return self._spill_path

    @property
    def incident_dir(self) -> str | None:
        return self._incident_dir

    # ------------------------------------------------------------ writing
    def record(self, kind: str, **attrs) -> dict:
        """Append one typed event; returns the event dict."""
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq, "ts": round(self.clock(), 6),
                     "kind": kind}
            event.update(attrs)
            if len(self._ring) == self.capacity:
                self.dropped += 1
                self.provider.counter("journal_dropped_total").add()
            self._ring.append(event)
            spill = self._spill_file
            if spill is None and self._spill_path is not None:
                spill = self._spill_file = open(self._spill_path, "a")
        self.provider.counter("journal_events_total", kind=kind).add()
        if spill is not None:
            # the file object's own lock serializes concurrent writers;
            # flush per event — the spill exists for post-mortems, and a
            # buffered tail lost to a SIGKILL defeats the point
            try:
                spill.write(json.dumps(event, default=str) + "\n")
                spill.flush()
            except ValueError:
                pass  # closed mid-reconfigure: the ring still has it
        return event

    # ------------------------------------------------------------ reading
    def tail(self, n: int | None = None) -> list[dict]:
        """The most recent ``n`` events (all retained when ``n=None``),
        oldest first."""
        with self._lock:
            events = list(self._ring)
        return events if n is None else events[-n:]

    def summary(self) -> dict:
        """Point-in-time view for /statusz."""
        with self._lock:
            n = len(self._ring)
            last = self._ring[-1] if self._ring else None
        return {"events": n, "seq": self._seq, "dropped": self.dropped,
                "incidents": self.incidents, "spill": self._spill_path,
                "last": last}

    # ---------------------------------------------------------- incidents
    def incident(self, trigger: str, reason: str = "",
                 force: bool = False, extra: dict | None = None
                 ) -> str | None:
        """Write one incident snapshot; returns its path (None when no
        incident directory is configured or the rate limit suppressed
        it). Never raises: an incident writer that can crash its caller
        turns one failure into two."""
        now = self.clock()
        with self._lock:
            limited = (not force
                       and self._last_incident_t is not None
                       and now - self._last_incident_t
                       < self.min_interval_s)
            if not limited:
                self._last_incident_t = now
        self.record(EVENT_INCIDENT, trigger=trigger, reason=reason,
                    rate_limited=limited)
        if limited or self._incident_dir is None:
            return None
        self.incidents += 1
        self.provider.counter("journal_incidents_total",
                              trigger=trigger).add()
        snapshot = {
            "schema": "fts-incident-v1",
            "trigger": trigger,
            "reason": reason,
            "ts": now,
            "journal_tail": self.tail(_SNAPSHOT_TAIL),
            "threads": _dump_all_thread_stacks(),
        }
        try:
            from .tracing import TRACER

            # node identity on the artifact AND on every active span:
            # once snapshots from several processes land in one incident
            # directory, each span must say which process it belongs to
            snapshot["node"] = TRACER.node
            snapshot["active_spans"] = [
                {"name": sp.name, "node": TRACER.node,
                 "span_id": sp.span_id,
                 "trace_id": sp.trace_id, "parent_id": sp.parent_id,
                 "age_s": round(time.perf_counter() - sp.start, 6),
                 "attributes": dict(sp.attributes)}
                for sp in TRACER.active_snapshot()]
        except Exception as exc:  # pragma: no cover - defensive
            snapshot["active_spans"] = [{"error": repr(exc)}]
        status: dict = {}
        for name, fn in self._status_sources.items():
            try:
                status[name] = fn()
            except Exception as exc:
                status[name] = {"error": repr(exc)}
        snapshot["status"] = status
        if extra:
            snapshot["extra"] = extra
        path = os.path.join(
            self._incident_dir,
            f"incident_{trigger}_{int(now)}_{self.incidents}.json")
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        return path

    def reset(self) -> None:
        """Drop ring + counters (test-fixture hook, like GLOBAL.reset).
        Spill/incident configuration is kept."""
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self.dropped = 0
            self.incidents = 0
            self._last_incident_t = None


def configure_from_env(journal: "Journal | None" = None) -> str | None:
    """Opt-in wiring used by bench.py and the multichip dryrun: with
    ``FTS_JOURNAL_DIR`` (or ``BENCH_JOURNAL_DIR``) set, spill the global
    journal there and enable incident snapshots. Returns the directory
    (or None)."""
    directory = (os.environ.get("FTS_JOURNAL_DIR")
                 or os.environ.get("BENCH_JOURNAL_DIR"))
    if not directory:
        return None
    (journal or JOURNAL).configure(directory)
    return directory


#: Process-global flight recorder (GLOBAL / TRACER sibling).
JOURNAL = Journal()
