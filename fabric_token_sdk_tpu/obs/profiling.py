"""Device profiling telemetry: compile cost, kernel roofline inputs,
memory watermarks.

Turns the one-off ROOFLINE.md study into continuously measured
quantities: per-bucket XLA cost analysis (FLOPs / bytes accessed, taken
from the *lowered* module so capturing it never triggers a compile) and
compile seconds at prewarm, device memory watermarks and compile-cache
hit/miss counters at dispatch. All capture paths are guarded — a JAX
version that lacks ``cost_analysis`` keys, or a CPU backend whose
``memory_stats()`` is ``None``, degrades to "metric absent", never to an
exception on the serving path.

Exported families (stable names, see ROADMAP):
  profile_compile_seconds{kind,bucket}     compile wall time
  profile_bucket_flops{kind,bucket}        lowered-module FLOP estimate
  profile_bucket_bytes{kind,bucket}        lowered-module bytes accessed
  profile_device_bytes_in_use{device}      allocator watermark (live)
  profile_device_peak_bytes{device}        allocator watermark (peak)
  profile_compile_cache_total{kind,event}  hit/miss at dispatch

The fused device programs report on the same families under their own
``kind`` label values — never as new families (the exposition names are
a stable contract): ``pass12_fused`` is the merged single-program chunk
pipeline (pass-1 + weighted var-MSM partial, one dispatch; available on
every backend since the CPU flavor runs the same program with XLA kernel
bodies), while the individual Pallas kernels (mixed-affine ``fb_msm_t``,
``msm_var_fused``) lower on the TPU path only.
"""

from __future__ import annotations

import threading
import time

from .metrics import GLOBAL, MetricsProvider

_PROFILE_FAMILIES = {
    "profile_compile_seconds":
        "Wall-clock compile/warm-up seconds per kernel kind and batch "
        "bucket.",
    "profile_bucket_flops":
        "XLA cost-analysis FLOP estimate for the dominant kernel at a "
        "batch bucket (lowering only, never compiles).",
    "profile_bucket_bytes":
        "XLA cost-analysis bytes-accessed estimate for the dominant "
        "kernel at a batch bucket.",
    "profile_device_bytes_in_use":
        "Device allocator bytes currently in use (absent on backends "
        "without memory_stats).",
    "profile_device_peak_bytes":
        "Device allocator peak bytes in use since process start.",
    "profile_compile_cache_total":
        "Dispatch-time compile-cache events: event=hit rows whose "
        "(kind, bucket) shape was already compiled, event=miss first "
        "sightings.",
}


def _normalize_cost(cost) -> dict | None:
    """``cost_analysis()`` shape-shifts across JAX versions: a dict on
    some backends, a list of per-computation dicts on others. Reduce to
    one flat dict or None."""
    if cost is None:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    return cost


class DeviceProfiler:
    """Process-wide sink for device profiling telemetry.

    Thread-safe: prewarm writes from the executor thread, the dispatcher
    writes cache events from the event loop, /statusz reads from scrape
    threads."""

    def __init__(self, provider: MetricsProvider | None = None):
        self.provider = provider or GLOBAL
        self._costs: dict = {}
        self._compiles: dict = {}
        self._lock = threading.Lock()
        for fam, help_text in _PROFILE_FAMILIES.items():
            self.provider.describe(fam, help_text)

    # ------------------------------------------------------------ compile
    def record_compile(self, kind: str, bucket: int,
                       seconds: float) -> None:
        self.provider.histogram("profile_compile_seconds", kind=kind,
                                bucket=bucket).observe(seconds)
        with self._lock:
            self._compiles[(kind, int(bucket))] = seconds

    def record_cache_event(self, kind: str, hit: bool) -> None:
        self.provider.counter("profile_compile_cache_total", kind=kind,
                              event="hit" if hit else "miss").add()

    # ----------------------------------------------------------- roofline
    def set_bucket_cost(self, kind: str, bucket: int,
                        cost: dict | None) -> None:
        """Publish a normalized cost dict (``flops`` / ``bytes_accessed``
        keys, extras kept for the summary)."""
        if not cost:
            return
        flops = cost.get("flops")
        nbytes = cost.get("bytes_accessed", cost.get("bytes accessed"))
        if flops is not None:
            self.provider.gauge("profile_bucket_flops", kind=kind,
                                bucket=bucket).set(float(flops))
        if nbytes is not None:
            self.provider.gauge("profile_bucket_bytes", kind=kind,
                                bucket=bucket).set(float(nbytes))
        with self._lock:
            self._costs[(kind, int(bucket))] = dict(cost)

    def capture_bucket_cost(self, zk, bucket: int,
                            kind: str = "range") -> dict | None:
        """Ask a verifier for its dominant kernel's AOT cost at a bucket
        (duck-typed ``kernel_cost`` — the FaultyZK shim passes it
        through) and publish it. Any failure returns None."""
        fn = getattr(zk, "kernel_cost", None)
        if not callable(fn):
            return None
        try:
            cost = _normalize_cost(fn(bucket))
        except Exception:
            return None
        self.set_bucket_cost(kind, bucket, cost)
        return cost

    def capture_fused_costs(self, zk, bucket: int) -> dict | None:
        """Capture the fused device-program estimates at a bucket
        (duck-typed ``kernel_cost_fused``). Each program publishes on the
        SAME stable ``profile_bucket_*`` families as the standalone
        kernels, under its own kind label — new label values, not new
        families: ``kind="pass12_fused"`` (the merged single-program
        chunk pipeline; published on EVERY backend, the CPU flavor runs
        the same program structure with XLA kernel bodies) plus
        ``kind="fb_msm_t"`` / ``kind="msm_var_fused"`` where the Pallas
        path is on (TPU). None only on shims without the hook."""
        fn = getattr(zk, "kernel_cost_fused", None)
        if not callable(fn):
            return None
        try:
            return fn(bucket)
        except Exception:
            return None

    def capture_kernel_cost(self, kind: str, bucket: int, fn, *args,
                            **kwargs) -> dict | None:
        """Lower ``fn(*args)`` (jit-wrapping if needed) and publish its
        cost analysis. Lowering is trace-only — safe to call on the
        serving path for kernels that were never compiled."""
        try:
            import jax

            jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
            cost = _normalize_cost(
                jitted.lower(*args, **kwargs).cost_analysis())
        except Exception:
            return None
        self.set_bucket_cost(kind, bucket, cost)
        return cost

    # ------------------------------------------------------------- memory
    def record_memory_watermark(self) -> dict:
        """Sample every local device's allocator stats. Backends without
        ``memory_stats`` (CPU) contribute nothing."""
        out = {}
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return out
        for dev in devices:
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            label = f"{dev.platform}:{dev.id}"
            in_use = stats.get("bytes_in_use")
            peak = stats.get("peak_bytes_in_use")
            if in_use is not None:
                self.provider.gauge("profile_device_bytes_in_use",
                                    device=label).set(float(in_use))
            if peak is not None:
                self.provider.gauge("profile_device_peak_bytes",
                                    device=label).set(float(peak))
            out[label] = {"bytes_in_use": in_use, "peak_bytes": peak}
        return out

    # ------------------------------------------------------------ reading
    def summary(self) -> dict:
        """Point-in-time view for /statusz and the BENCH report."""
        with self._lock:
            costs = {f"{kind}:{bucket}": {
                "flops": cost.get("flops"),
                "bytes_accessed": cost.get(
                    "bytes_accessed", cost.get("bytes accessed")),
            } for (kind, bucket), cost in sorted(self._costs.items())}
            compiles = {f"{kind}:{bucket}": round(s, 3)
                        for (kind, bucket), s in
                        sorted(self._compiles.items())}
        return {"bucket_costs": costs, "compile_seconds": compiles,
                "memory": self.record_memory_watermark(),
                "sampled_at": time.time()}


#: Process-global profiler (mirrors obs.metrics.GLOBAL / obs.tracing.TRACER).
PROFILER = DeviceProfiler()
