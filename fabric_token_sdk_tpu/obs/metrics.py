"""Label-namespaced metrics registry with conformant Prometheus exposition.

Behavioral mirror of the reference's observability stack:
  - token/core/common/metrics/provider.go:26-75 — a metrics provider that
    namespaces every instrument with TMS labels;
  - token/core/zkatdlog/nogh/v1/metrics.go:14-40 — per-driver duration
    histograms around zk issue/transfer.

TPU-native additions over the old services/metrics.py stub:
  - exposition-format conformance: ``# HELP``/``# TYPE`` lines, metric and
    label name sanitization (span names contain dots, which are invalid
    Prometheus identifiers), label-value escaping;
  - bounded sample reservoirs on histograms so the bench reporter can
    publish p50/p95/p99 without a separate latency store;
  - ``reset()`` so test fixtures can stop GLOBAL state leaking between
    tests.
"""

from __future__ import annotations

import bisect
import re
import threading
from collections import deque
from dataclasses import dataclass, field

_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_metric_name(name: str) -> str:
    """Map an arbitrary string onto a valid Prometheus metric name
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``). Span names contain dots; label-ish
    suffixes may contain anything."""
    out = _METRIC_NAME_RE.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def sanitize_label_name(name: str) -> str:
    """Valid Prometheus label name (``[a-zA-Z_][a-zA-Z0-9_]*``)."""
    out = _LABEL_NAME_RE.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value) -> str:
    """Escape a label value per the exposition format: backslash, double
    quote, and line feed must be escaped inside the quoted value."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help_text(value) -> str:
    """Escape HELP text per the exposition format. Unlike label values,
    HELP lines are unquoted: only backslash and line feed are escaped —
    a double quote must pass through verbatim."""
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


@dataclass
class Counter:
    value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta


@dataclass
class Gauge:
    value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta


#: Histogram bucket boundaries (seconds) tuned for proof verification:
#: sub-ms host ops up to multi-second cold batches.
_DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                    30.0)

#: Per-histogram sample reservoir size: enough for stable p99 estimates at
#: bench scale while bounding memory for long-running nodes.
_SAMPLE_KEEP = 4096


@dataclass
class Histogram:
    buckets: tuple = _DEFAULT_BUCKETS
    counts: list = None
    total: float = 0.0
    n: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _samples: deque = field(
        default_factory=lambda: deque(maxlen=_SAMPLE_KEEP))
    #: OpenMetrics-style exemplars: at most ONE slot per bucket (the
    #: most recent exemplar-bearing observation that fell in it), so
    #: storage is bounded by the bucket count no matter the traffic.
    #: Kept out of ``prometheus_text`` — the fleet merge parser speaks
    #: plain exposition; exemplars travel via ``exemplar_snapshot()``.
    _exemplars: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.counts is None:
            self.counts = [0] * (len(self.buckets) + 1)

    def observe(self, value: float, exemplar: dict | None = None) -> None:
        """Record one observation; ``exemplar`` optionally attaches
        trace identity (e.g. ``{"trace_id": "4f2a..."}``) to the bucket
        the value lands in, overwriting that bucket's previous slot."""
        with self._lock:
            idx = bisect.bisect_left(self.buckets, value)
            self.counts[idx] += 1
            self.total += value
            self.n += 1
            self._samples.append(value)
            if exemplar:
                self._exemplars[idx] = {"value": value,
                                        "labels": dict(exemplar)}

    def exemplar_snapshot(self) -> dict:
        """Copy of the per-bucket exemplar slots, keyed by upper bound
        (``+Inf`` for the overflow bucket)."""
        with self._lock:
            slots = dict(self._exemplars)
        out = {}
        for idx, ex in slots.items():
            bound = (self.buckets[idx] if idx < len(self.buckets)
                     else float("inf"))
            out[bound] = ex
        return out

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def percentile(self, p: float) -> float:
        """Approximate percentile from the bounded sample reservoir (the
        last ``_SAMPLE_KEEP`` observations). Exact while fewer than that
        many samples have been observed."""
        with self._lock:
            samples = sorted(self._samples)
        if not samples:
            return 0.0
        idx = min(len(samples) - 1, int(p / 100.0 * len(samples)))
        return samples[idx]


def _key(name: str, labels: dict | None) -> tuple:
    return (name, tuple(sorted((labels or {}).items())))


class MetricsProvider:
    """Label-namespaced metrics registry (metrics/provider.go:26-75)."""

    def __init__(self, namespace_labels: dict | None = None):
        self.namespace_labels = dict(namespace_labels or {})
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._histograms: dict[tuple, Histogram] = {}
        self._help: dict[str, str] = {}
        self._lock = threading.Lock()

    def with_labels(self, **labels) -> "MetricsProvider":
        """Derived provider with extra namespace labels (TMS-id labelling
        in the reference). Shares the registry AND its lock — parent and
        children registering the same instrument concurrently must
        serialize on one lock or increments race away."""
        child = MetricsProvider({**self.namespace_labels, **labels})
        child._counters = self._counters
        child._gauges = self._gauges
        child._histograms = self._histograms
        child._help = self._help
        child._lock = self._lock
        return child

    def describe(self, name: str, help: str) -> None:
        """Register a family's HELP text without creating an instrument.

        Lets a subsystem hoist all its family metadata to one place
        (first-registration-wins otherwise makes the HELP line depend on
        which call site runs first). Idempotent; an existing description
        is kept."""
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        key = _key(name, {**self.namespace_labels, **labels})
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            if key not in self._counters:
                self._counters[key] = Counter()
            return self._counters[key]

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        key = _key(name, {**self.namespace_labels, **labels})
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            if key not in self._gauges:
                self._gauges[key] = Gauge()
            return self._gauges[key]

    def histogram(self, name: str, help: str = "", **labels) -> Histogram:
        key = _key(name, {**self.namespace_labels, **labels})
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            if key not in self._histograms:
                self._histograms[key] = Histogram()
            return self._histograms[key]

    def remove_series(self, name: str | None = None, **labels) -> int:
        """Delete registered series whose labels include every given
        ``labels`` pair (and whose family is ``name``, when given).
        Returns the number of series removed.

        This is the eviction half of bounded-cardinality labelling: a
        per-tenant gauge registered for a departed ``tms_id`` would
        otherwise live in the registry (and every exposition) forever.
        Family HELP text is kept — the family still exists, it just has
        fewer series."""
        want = tuple(sorted(labels.items()))

        def _match(key: tuple) -> bool:
            fam, lbls = key
            if name is not None and fam != name:
                return False
            return all(pair in lbls for pair in want)

        removed = 0
        with self._lock:
            for reg in (self._counters, self._gauges, self._histograms):
                for key in [k for k in reg if _match(k)]:
                    del reg[key]
                    removed += 1
        return removed

    def reset(self) -> None:
        """Drop every registered instrument. Shared-registry children see
        the reset too (they alias the same dicts). Test-fixture hook so
        GLOBAL state cannot leak between tests."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._help.clear()

    def exemplars(self, name: str | None = None) -> list[dict]:
        """Exemplar slots across registered histograms (optionally one
        family): ``{"family", "labels", "bucket_le", "value",
        "exemplar"}`` per slot. This is the scrape surface for trace
        exemplars — they are deliberately NOT rendered into
        ``prometheus_text`` (the fleet merge parser treats unknown
        sample syntax as a document-level conflict)."""
        with self._lock:
            hists = [(fam, labels, h)
                     for (fam, labels), h in self._histograms.items()
                     if name is None or fam == name]
        out = []
        for fam, labels, h in hists:
            for bound, ex in sorted(h.exemplar_snapshot().items()):
                out.append({"family": fam, "labels": dict(labels),
                            "bucket_le": bound, "value": ex["value"],
                            "exemplar": ex["labels"]})
        return out

    # ------------------------------------------------------------- scraping
    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            for (name, labels), c in self._counters.items():
                out[(name, labels)] = c.value
            for (name, labels), g in self._gauges.items():
                out[(name, labels)] = g.value
            for (name, labels), h in self._histograms.items():
                out[(name, labels)] = {"count": h.n, "sum": h.total,
                                       "mean": h.mean}
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition format (what the reference's provider
        ultimately serves), conformant: one ``# HELP``/``# TYPE`` block
        per family, sanitized metric/label names, escaped label values."""
        lines = []

        def fmt_labels(labels):
            if not labels:
                return ""
            inner = ",".join(
                f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
                for k, v in labels)
            return "{" + inner + "}"

        def fmt_num(v) -> str:
            v = float(v)
            if v != v:
                return "NaN"
            if v == float("inf"):
                return "+Inf"
            if v == float("-inf"):
                return "-Inf"
            return repr(v)

        with self._lock:
            by_family: dict[str, list] = {}
            for (name, labels), c in self._counters.items():
                by_family.setdefault(name, []).append(("counter", labels, c))
            for (name, labels), g in self._gauges.items():
                by_family.setdefault(name, []).append(("gauge", labels, g))
            for (name, labels), h in self._histograms.items():
                by_family.setdefault(name, []).append(
                    ("histogram", labels, h))
            for name in sorted(by_family):
                fam = sanitize_metric_name(name)
                kind = by_family[name][0][0]
                help_text = self._help.get(name, "") or name
                lines.append(f"# HELP {fam} "
                             f"{escape_help_text(help_text)}")
                lines.append(f"# TYPE {fam} {kind}")
                for _, labels, inst in sorted(
                        by_family[name], key=lambda t: t[1]):
                    if isinstance(inst, (Counter, Gauge)):
                        lines.append(
                            f"{fam}{fmt_labels(labels)} "
                            f"{fmt_num(inst.value)}")
                    else:
                        cum = 0
                        for bound, cnt in zip(inst.buckets, inst.counts):
                            cum += cnt
                            lbl = fmt_labels(
                                labels + (("le", fmt_num(bound)),))
                            lines.append(f"{fam}_bucket{lbl} {cum}")
                        lines.append(
                            f"{fam}_bucket"
                            f"{fmt_labels(labels + (('le', '+Inf'),))} "
                            f"{inst.n}")
                        lines.append(
                            f"{fam}_sum{fmt_labels(labels)} "
                            f"{fmt_num(inst.total)}")
                        lines.append(
                            f"{fam}_count{fmt_labels(labels)} {inst.n}")
        return "\n".join(lines) + "\n"


#: Process-global default provider (sdk/dig singleton equivalent).
GLOBAL = MetricsProvider()
