"""Unified observability plane: metrics, hierarchical tracing, pipeline
records, exporters, and the bench reporter.

Absorbs and extends the old ``services/metrics.py`` stub (which remains as
a compatibility shim). One process-global registry (``GLOBAL``) and one
process-global tracer (``TRACER``) are threaded through the verification
pipeline — the models layer (BatchRangeVerifier / BatchSigmaVerifier /
adjust), the zkatdlog verifier/validator, the node/ttx lifecycle, the
selector, the DBs, and the chaincode — so a single request produces a
span tree (exportable to Chrome/Perfetto trace-event JSON) plus counter
and histogram families scrapeable in Prometheus exposition format.

Layer map vs the reference SDK:
  - obs/metrics.py  ~ token/core/common/metrics (label-namespaced provider)
  - obs/tracing.py  ~ token/core/common/tracing (OpenTelemetry spans)
  - obs/pipeline.py — TPU-native extension: per-batch device pipeline
    records (bucket/pad-waste/phase split/compile detection)
  - obs/export.py   — Chrome trace-event JSON (chrome://tracing, Perfetto)
  - obs/report.py   — BENCH-style JSON snapshots for round-over-round
    comparison (bench.py / harness/txgen.py)
"""

from .metrics import (Counter, Gauge, Histogram, MetricsProvider, GLOBAL,
                      escape_help_text, escape_label_value,
                      sanitize_label_name, sanitize_metric_name)
from .tracing import (CONTEXT_WIRE_SIZE, Span, SpanContext,
                      SpanSpoolExporter, Tracer, TRACER, assemble_traces,
                      extract_wire_context, read_span_spool)
from .pipeline import BatchRecord, PhaseTimer, PipelineRecorder, RECORDS
from .export import spans_to_chrome_trace, write_chrome_trace
from .report import bench_snapshot, write_bench_report
from .slo import (SloMonitor, SloPolicy, TenantSloMonitor, TenantSloPolicy,
                  jain_index)
from .profiling import DeviceProfiler, PROFILER
from .telemetry import TelemetryConfig, TelemetryServer, serve_telemetry
from .journal import (EVENT_KINDS, JOURNAL, Journal,
                      configure_from_env as configure_journal_from_env)
from .heartbeat import (FileHeartbeatReader, Heartbeat, StallDetector,
                        incident_on_stall, read_last as read_last_heartbeat)
from .aggregate import (FleetAggregator, SpoolPublisher, merge_expositions,
                        parse_exposition)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsProvider", "GLOBAL",
    "sanitize_metric_name", "sanitize_label_name", "escape_label_value",
    "escape_help_text",
    "Span", "Tracer", "TRACER",
    "SpanContext", "SpanSpoolExporter", "CONTEXT_WIRE_SIZE",
    "extract_wire_context", "read_span_spool", "assemble_traces",
    "BatchRecord", "PhaseTimer", "PipelineRecorder", "RECORDS",
    "spans_to_chrome_trace", "write_chrome_trace",
    "bench_snapshot", "write_bench_report",
    "SloMonitor", "SloPolicy", "TenantSloMonitor", "TenantSloPolicy",
    "jain_index",
    "DeviceProfiler", "PROFILER",
    "TelemetryConfig", "TelemetryServer", "serve_telemetry",
    "Journal", "JOURNAL", "EVENT_KINDS", "configure_journal_from_env",
    "Heartbeat", "StallDetector", "FileHeartbeatReader",
    "incident_on_stall", "read_last_heartbeat",
    "FleetAggregator", "SpoolPublisher", "merge_expositions",
    "parse_exposition",
]
