"""Per-phase heartbeat stamps + a stall detector with phase deadlines.

The multichip dryrun has timed out five driver rounds in a row with
``rc=124, tail=""`` — the process died silently somewhere between "jax
initialized" and "verify done" and nothing recorded where. A heartbeat
file turns that class of failure into a phase-attributed artifact: the
running process stamps every phase transition (append-only, flushed per
line, so a SIGKILL loses at most nothing), and any OTHER process — or a
watchdog thread in the same one — can read the last stamp and say which
phase the victim was in and for how long.

Two halves, both clock-injectable:

- :class:`Heartbeat` — the writer. ``beat(phase, detail)`` appends one
  JSON line ``{"t": wall, "phase", "detail", "pid"}`` to the heartbeat
  file (when one is configured), mirrors the event into the flight
  recorder (kind ``heartbeat``), and bumps ``hb_beats_total{phase}``.
- :class:`StallDetector` — the reader. ``check()`` compares the age of
  the last beat against the current phase's deadline
  (``deadlines[phase]``, else ``default_deadline_s``) and fires
  ``on_stall(phase, age_s)`` edge-triggered (latched until the
  heartbeat advances past the stalled stamp). ``start()`` runs it on a
  daemon thread; tests drive ``check()`` with a fake clock instead.

Cross-process use (the dryrun monitor): the child writes with
:class:`Heartbeat`, the parent constructs ``StallDetector(reader=
FileHeartbeatReader(path))`` — wall-clock timestamps are the shared
timebase.

Stable families: ``hb_beats_total{phase}``, ``hb_last_age_seconds``,
``hb_stalls_total{phase}``.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .journal import EVENT_HEARTBEAT, JOURNAL, Journal
from .metrics import GLOBAL, MetricsProvider

_HB_FAMILIES = {
    "hb_beats_total": "Heartbeat stamps written, by phase.",
    "hb_last_age_seconds":
        "Seconds since the most recent heartbeat stamp (set on beat and "
        "by the stall detector on every check).",
    "hb_stalls_total":
        "Stall-detector trips (heartbeat older than the phase deadline), "
        "by phase.",
}


class Heartbeat:
    """Append-only phase progress stamps.

    ``path=None`` keeps the heartbeat purely in-memory (journal + metrics
    still see every beat). The file is opened lazily and every line is
    flushed: the whole point is surviving an external SIGKILL.
    """

    def __init__(self, path: str | os.PathLike | None = None,
                 provider: MetricsProvider | None = None,
                 journal: Journal | None = None, clock=time.time):
        self.path = None if path is None else os.fspath(path)
        self.provider = provider or GLOBAL
        self.journal = journal if journal is not None else JOURNAL
        self.clock = clock
        self._file = None
        self._lock = threading.Lock()
        self._last: dict | None = None
        for fam, help_text in _HB_FAMILIES.items():
            self.provider.describe(fam, help_text)

    def beat(self, phase: str, detail: str = "") -> dict:
        """Stamp a phase transition (or intra-phase progress)."""
        stamp = {"t": round(self.clock(), 6), "phase": phase,
                 "detail": detail, "pid": os.getpid()}
        with self._lock:
            self._last = stamp
            if self.path is not None:
                if self._file is None:
                    self._file = open(self.path, "a")
                self._file.write(json.dumps(stamp) + "\n")
                self._file.flush()
        self.provider.counter("hb_beats_total", phase=phase).add()
        self.provider.gauge("hb_last_age_seconds").set(0.0)
        if self.journal is not None:
            self.journal.record(EVENT_HEARTBEAT, phase=phase,
                                detail=detail)
        return stamp

    def last(self) -> dict | None:
        """The most recent stamp written by THIS object (None before the
        first beat)."""
        with self._lock:
            return self._last

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def read_last(path: str | os.PathLike) -> dict | None:
    """Last complete stamp in a heartbeat file, from any process.

    Tolerates a torn final line (the writer died mid-write): scans back
    for the last line that parses. Returns None for a missing/empty
    file."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    for line in reversed(data.decode(errors="replace").splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


class FileHeartbeatReader:
    """StallDetector reader over a heartbeat file written by another
    process (the dryrun monitor's view of its child)."""

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)

    def __call__(self) -> dict | None:
        return read_last(self.path)


class StallDetector:
    """Edge-triggered per-phase deadline watch over a heartbeat source.

    ``reader`` is any ``() -> stamp-dict-or-None`` (a
    :class:`Heartbeat`'s ``last`` method, or a
    :class:`FileHeartbeatReader`). A phase whose last beat is older than
    its deadline trips ``on_stall(phase, age_s)`` once; the latch clears
    when a NEWER stamp appears (any phase), so a recovered run can trip
    again later. ``None`` from the reader before ``grace_s`` has elapsed
    is "not started yet", after it, a stall of phase ``"(no
    heartbeat)"``.
    """

    NO_HEARTBEAT = "(no heartbeat)"

    def __init__(self, reader, deadlines: dict[str, float] | None = None,
                 default_deadline_s: float = 120.0,
                 grace_s: float = 60.0, on_stall=None,
                 provider: MetricsProvider | None = None,
                 clock=time.time, poll_s: float = 1.0):
        self.reader = reader
        self.deadlines = dict(deadlines or {})
        self.default_deadline_s = default_deadline_s
        self.grace_s = grace_s
        self.on_stall = on_stall
        self.provider = provider or GLOBAL
        self.clock = clock
        self.poll_s = poll_s
        self.stalls = 0
        self._started_t: float | None = None
        self._latched_t: float | None = None   # stamp time already fired on
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        for fam, help_text in _HB_FAMILIES.items():
            self.provider.describe(fam, help_text)

    def deadline_for(self, phase: str) -> float:
        return self.deadlines.get(phase, self.default_deadline_s)

    def check(self) -> tuple[str, float] | None:
        """One detection pass; returns ``(phase, age_s)`` when it fires
        (and calls ``on_stall``), else None. Pure given ``clock`` and
        ``reader`` — the fake-clock test surface."""
        now = self.clock()
        if self._started_t is None:
            self._started_t = now
        stamp = self.reader()
        if stamp is None:
            if now - self._started_t < self.grace_s:
                return None
            phase, age, stamp_t = (self.NO_HEARTBEAT,
                                   now - self._started_t, self._started_t)
            if self._latched_t == stamp_t:
                return None
        else:
            phase = stamp.get("phase", "?")
            stamp_t = float(stamp.get("t", 0.0))
            age = max(0.0, now - stamp_t)
            self.provider.gauge("hb_last_age_seconds").set(round(age, 3))
            if self._latched_t is not None and stamp_t > self._latched_t:
                self._latched_t = None   # progress since the last trip
            if age < self.deadline_for(phase) or self._latched_t is not None:
                return None
        self._latched_t = stamp_t
        self.stalls += 1
        self.provider.counter("hb_stalls_total", phase=phase).add()
        if self.on_stall is not None:
            self.on_stall(phase, age)
        return phase, age

    # ------------------------------------------------------ thread runner
    def start(self) -> "StallDetector":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="fts-stall-detector", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:  # a broken reader must not kill the watch
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def incident_on_stall(journal: Journal | None = None,
                      trigger: str = "heartbeat_stall"):
    """An ``on_stall`` callback that dumps an incident snapshot — the
    default wiring for in-process stall watching (the dryrun monitor
    builds a richer report instead)."""
    j = journal if journal is not None else JOURNAL

    def _on_stall(phase: str, age_s: float) -> None:
        j.incident(trigger,
                   reason=f"phase {phase!r} heartbeat {age_s:.1f}s old")

    return _on_stall
