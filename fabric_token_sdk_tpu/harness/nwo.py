"""Multiprocess platform: ledger server process + node processes.

Behavioral mirror of reference integration/nwo/token/platform.go:112-246:
  1. GENERATE phase — every node process generates its crypto material and
     reports its public identity;
  2. SETUP phase — the orchestrator builds the public parameters (with the
     collected issuer/auditor identities) and boots the ledger process
     hosting the token chaincode (the ordering + validation plane);
  3. RUN phase — nodes build their driver bundle from the pp bytes and
     serve views; the orchestrator drives initiator views and asserts.

Planes (SURVEY.md §2.5):
  - session plane: per-node IPC inbox queues (paired initiator/responder
    calls — the websockets/libp2p substitute);
  - consensus plane: the ledger manager process (Broadcast ==
    process_request RPC; finality == block polling via DeliveryService).
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import threading
import time
import uuid
from dataclasses import dataclass
from multiprocessing.managers import BaseManager

from ..services.network.rws import KeyTranslator


# ---------------------------------------------------------------------------
# ledger server process
# ---------------------------------------------------------------------------

class _LedgerService:
    """The shared ledger + chaincode, hosted in its own process."""

    def __init__(self):
        self._cc = None
        self._lock = threading.Lock()

    def boot(self, pp_raw: bytes, driver_label: str) -> None:
        """SETUP phase: build validator + chaincode from pp bytes."""
        from ..core.registry import default_registry
        from ..services.network.tcc import MemoryLedger, TokenChaincode

        bundle = default_registry(device=False).new_bundle(pp_raw)
        with self._lock:
            self._cc = TokenChaincode(bundle.validator, MemoryLedger(),
                                      pp_raw)

    def process_request(self, tx_id: str, request_raw: bytes):
        return self._cc.process_request(tx_id, request_raw)

    def get_state(self, key: str):
        return self._cc.ledger.get_state(key)

    def blocks_since(self, cursor: int):
        """Delivery service: commit events from `cursor` on."""
        blocks = self._cc.ledger.blocks
        return list(blocks[cursor:]), len(blocks)

    def query_public_params(self):
        return self._cc.query_public_params()


class LedgerManager(BaseManager):
    pass


LedgerManager.register("ledger", callable=None)


def _serve_ledger(address, authkey):
    service = _LedgerService()
    mgr = LedgerManager(address=address, authkey=authkey)
    LedgerManager.register("ledger", callable=lambda: service)
    server = mgr.get_server()
    server.serve_forever()


# ---------------------------------------------------------------------------
# client-side ledger facade (per node process)
# ---------------------------------------------------------------------------

class DeliveryService(threading.Thread):
    """Polls the ledger for new blocks and dispatches commit events to the
    local finality listeners (network/common/finality.go manager role)."""

    def __init__(self, proxy, poll: float = 0.02):
        super().__init__(daemon=True)
        self.proxy = proxy
        self.poll = poll
        self.listeners: list = []
        self.cursor = 0
        self._stop = threading.Event()
        self._lock = threading.Lock()

    def add_finality_listener(self, listener) -> None:
        with self._lock:
            self.listeners.append(listener)

    def remove_finality_listener(self, listener) -> None:
        with self._lock:
            if listener in self.listeners:
                self.listeners.remove(listener)

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                events, new_cursor = self.proxy.blocks_since(self.cursor)
            except (EOFError, ConnectionError, BrokenPipeError):
                return  # ledger gone: shut down quietly
            self.cursor = new_cursor
            for ev in events:
                with self._lock:
                    listeners = list(self.listeners)
                for listener in listeners:
                    try:
                        listener(ev)
                    except Exception:  # listener isolation
                        import logging

                        logging.getLogger(
                            "fabric_token_sdk_tpu.harness").exception(
                            "finality listener failed [%s]", ev.tx_id)
            self._stop.wait(self.poll)

    def stop(self) -> None:
        self._stop.set()


class RemoteLedger:
    """MemoryLedger facade over the manager proxy + delivery thread."""

    def __init__(self, proxy, delivery: DeliveryService):
        self.proxy = proxy
        self.delivery = delivery

    def get_state(self, key: str):
        return self.proxy.get_state(key)

    def add_finality_listener(self, listener) -> None:
        self.delivery.add_finality_listener(listener)

    def remove_finality_listener(self, listener) -> None:
        self.delivery.remove_finality_listener(listener)


class RemoteChaincode:
    """TokenChaincode facade: validation/ordering RPC + local key scheme.

    unmarshal_actions runs on the LOCAL validator (nodes hold the pp);
    process_request is the Broadcast RPC to the ledger process.
    """

    def __init__(self, proxy, validator, delivery: DeliveryService):
        self.keys = KeyTranslator()
        self.validator = validator
        self.ledger = RemoteLedger(proxy, delivery)
        self._proxy = proxy

    def process_request(self, tx_id: str, request_raw: bytes):
        return self._proxy.process_request(tx_id, request_raw)


# ---------------------------------------------------------------------------
# session plane: IPC queue bus
# ---------------------------------------------------------------------------

class QueueBus:
    """SessionBus over per-node inbox queues.

    A call is (reply_queue, method, args, kwargs); the responder node's
    dispatcher thread executes it on the real node object and posts
    (ok, result_or_error) on the reply queue — the paired initiator/
    responder view shape of ttx over a process boundary.
    """

    def __init__(self, inboxes: dict, my_name: str, reply_queue):
        self.inboxes = inboxes
        self.my_name = my_name
        self.reply_queue = reply_queue
        self.local: dict[str, object] = {}

    def register(self, name: str, node) -> None:
        self.local[name] = node

    def node(self, name: str):
        if name in self.local:
            return self.local[name]
        if name not in self.inboxes:
            from ..services.ttx import TtxError

            raise TtxError(f"unknown node [{name}]")
        return _RemoteNodeStub(self, name)


class _RemoteNodeStub:
    """Initiator-side proxy for a responder view on another node."""

    _METHODS = ("sign_transfer", "sign_issue", "audit", "receive_opening",
                "recipient_identity", "issuer_public_identity",
                "owns_identity", "sign_as_co_owner")

    def __init__(self, bus: QueueBus, name: str):
        self._bus = bus
        self._name = name

    def __getattr__(self, method):
        if method not in self._METHODS:
            raise AttributeError(method)

        def call(*args, **kwargs):
            self._bus.inboxes[self._name].put(
                (self._bus.reply_queue, method, args, kwargs))
            ok, payload = self._bus.reply_queue.get(timeout=60)
            if not ok:
                raise RuntimeError(
                    f"view [{method}] on [{self._name}] failed: {payload}")
            return payload

        return call


def _dispatch_loop(node, inbox, stop_event):
    """Responder thread: serve session-plane calls on the real node."""
    while not stop_event.is_set():
        try:
            msg = inbox.get(timeout=0.1)
        except Exception:
            continue
        if msg is None:
            return
        reply_queue, method, args, kwargs = msg
        try:
            result = getattr(node, method)(*args, **kwargs)
            reply_queue.put((True, result))
        except Exception as e:
            reply_queue.put((False, f"{type(e).__name__}: {e}"))


# ---------------------------------------------------------------------------
# node process
# ---------------------------------------------------------------------------

@dataclass
class NodeSpec:
    name: str
    role: str = "owner"          # "owner" | "issuer" | "auditor"
    idemix: bool = False         # pseudonymous owner wallet
    key_pem: str = ""            # path to a pre-generated sk.pem (tokengen
    #                              artifacts); empty -> fresh key at boot


def _sidecar_zk_factory(pp_raw: bytes, driver: str):
    """Picklable verification backend for the shared TCP sidecar.

    zkatdlog gets the real host verifier over the platform's public
    params; fabtoken (no zk proofs to verify) gets the crypto-free
    ``StubZK``, which keeps the network plane — framing, credits,
    deadlines, reconnects — fully exercisable under every driver.
    """
    if driver == "zkatdlog":
        from ..core.zkatdlog.verifier import ZKVerifier
        from ..crypto import setup

        pp = setup.PublicParams.deserialize(pp_raw)
        return ZKVerifier(pp, device=False)
    from ..serve.worker import StubZK

    return StubZK()


def _node_main(spec_dict, ledger_address, authkey, inboxes, control, replies,
               fleet_spool_dir=None, state_dir=None):
    """Entry point of one node process."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    # fleet observability: publish this process's exposition into the
    # platform spool (obs/aggregate.py) and stamp lifecycle phases into a
    # per-node heartbeat file, so the orchestrator can federate N node
    # registries on one /metrics and see which node stopped progressing
    publisher = hb = None
    if fleet_spool_dir:
        from ..obs.aggregate import SpoolPublisher
        from ..obs.heartbeat import Heartbeat

        publisher = SpoolPublisher(fleet_spool_dir, spec_dict["name"],
                                   interval_s=0.5).start()
        hb = Heartbeat(os.path.join(fleet_spool_dir,
                                    f"{spec_dict['name']}.hb.jsonl"))
        hb.beat("generate")

    from ..core.registry import default_registry
    from ..services.auditor import AuditorNode
    from ..services.identity.x509 import new_signing_identity
    from ..services.node import TokenNode
    from ..services.ttx import Transaction

    spec = NodeSpec(**spec_dict)
    if spec.key_pem:
        from pathlib import Path

        from ..services.identity.x509 import keypair_from_pem

        keys = keypair_from_pem(Path(spec.key_pem).read_bytes())
    elif state_dir:
        # durable identity: persist the signing key on first boot so a
        # supervised RESTART of this node is the same logical party —
        # its on-ledger tokens stay recognizable and balances
        # reconstruct from block replay (the reference Restart(...)
        # contract)
        from pathlib import Path

        from ..services.identity.x509 import (keypair_from_pem,
                                              keypair_to_pem)

        os.makedirs(state_dir, exist_ok=True)
        key_path = Path(state_dir) / f"{spec.name}.sk.pem"
        if key_path.exists():
            keys = keypair_from_pem(key_path.read_bytes())
        else:
            keys = new_signing_identity()
            priv_pem, _pub_pem = keypair_to_pem(keys)
            key_path.write_bytes(priv_pem)
    else:
        keys = new_signing_identity()

    # GENERATE phase: report identity material
    control["out"].put(("identity", spec.name, bytes(keys.identity)))

    # wait for SETUP: pp bytes + go signal. A restarted node can find
    # stale commands queued ahead of its release (sent while the old
    # process was dead) — skip them; their callers already timed out.
    if hb is not None:
        hb.beat("setup_wait")
    while True:
        msg = control["in"].get()
        if msg[0] == "start":
            _cmd, pp_raw, extra = msg
            break

    bundle = default_registry(device=False).new_bundle(pp_raw)
    mgr = LedgerManager(address=tuple(ledger_address)
                        if isinstance(ledger_address, list)
                        else ledger_address, authkey=authkey)
    mgr.connect()
    proxy = mgr.ledger()
    delivery = DeliveryService(proxy)
    cc = RemoteChaincode(proxy, bundle.validator, delivery)

    bus = QueueBus(inboxes, spec.name, replies[spec.name])
    owner_wallet = None
    if spec.idemix:
        from ..services.identity.idemix import (EnrollmentAuthority,
                                                IdemixKeyManager)
        from ..services.identity.wallet import IdemixOwnerWallet

        # extra carries the pickled enrollment authority keys? Out of scope:
        # each idemix node enrolls with a process-local authority here;
        # cross-process CA distribution is exercised in-process instead.
        ca = EnrollmentAuthority()
        owner_wallet = IdemixOwnerWallet(
            IdemixKeyManager(f"{spec.name}@org", ca))

    cls = AuditorNode if spec.role == "auditor" else TokenNode
    node = cls(spec.name, keys, bus, cc,
               precision=extra["precision"],
               auditor_name=extra.get("auditor"),
               driver=bundle.services, owner_wallet=owner_wallet)
    delivery.start()

    # shared verification sidecar: every node process dials the ONE
    # TCP front door, multi-tenant by node name — the "millions of
    # users" topology in miniature (N clients, one Validator SPI)
    rpc_client = None
    if extra.get("sidecar_addr"):
        from ..serve.rpc_client import RpcClient

        rpc_client = RpcClient(tuple(extra["sidecar_addr"]),
                               tms_id=spec.name,
                               name=f"rpc-{spec.name}")
        rpc_client.wait_ready(timeout_s=120.0)

    stop_event = threading.Event()
    dispatcher = threading.Thread(
        target=_dispatch_loop, args=(node, inboxes[spec.name], stop_event),
        daemon=True)
    dispatcher.start()

    # RUN phase: command loop from the orchestrator
    if hb is not None:
        hb.beat("run")
    while True:
        cmd, *args = control["in"].get()
        try:
            if cmd == "stop":
                stop_event.set()
                delivery.stop()
                if rpc_client is not None:
                    rpc_client.close()
                if hb is not None:
                    hb.beat("stopped")
                if publisher is not None:
                    publisher.stop()  # final publish: exit totals land
                control["out"].put(("stopped", spec.name, None))
                return
            elif cmd == "issue":
                issuer_node, to_node, token_type, amount_hex = args
                tx = node.issue(issuer_node, to_node, token_type, amount_hex)
                ev = node.execute(tx)
                control["out"].put(("result", spec.name,
                                    (ev.status, ev.message, tx.tx_id)))
            elif cmd == "transfer":
                token_type, amount_hex, to_node, redeem = args
                tx = node.transfer(token_type, amount_hex, to_node,
                                   redeem=redeem)
                ev = node.execute(tx)
                control["out"].put(("result", spec.name,
                                    (ev.status, ev.message, tx.tx_id)))
            elif cmd == "balance":
                token_type, = args
                control["out"].put(("result", spec.name,
                                    node.balance(token_type)))
            elif cmd == "verify_range":
                # offload a range-proof batch through the SHARED TCP
                # sidecar (transport failures surface as transient
                # WorkerUnavailable and are reported, not crashes)
                proofs, coms = args
                if rpc_client is None:
                    control["out"].put(("error", spec.name,
                                        "no sidecar configured"))
                else:
                    verdicts = rpc_client.submit_range(proofs, coms)
                    control["out"].put(("result", spec.name,
                                        [bool(v) for v in verdicts]))
            elif cmd == "wait_tx":
                tx_id, timeout = args
                deadline = time.time() + timeout
                status = None
                while time.time() < deadline:
                    status = node.ttxdb.get_status(tx_id)
                    if status in ("Confirmed", "Deleted"):
                        break
                    time.sleep(0.02)
                control["out"].put(("result", spec.name, status))
            else:
                control["out"].put(("error", spec.name,
                                    f"unknown command [{cmd}]"))
        except Exception as e:
            control["out"].put(("error", spec.name,
                                f"{type(e).__name__}: {e}"))


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

class Platform:
    """Boots the topology and drives it (platform.go:112-246 role)."""

    def __init__(self, specs: list[NodeSpec], precision: int = 64,
                 driver: str = "fabtoken", bit_length: int = 16,
                 pp_raw: bytes | None = None,
                 fleet_spool_dir: str | None = None,
                 state_dir: str | None = None,
                 supervise: bool = False, supervisor_policy=None,
                 sidecar: str | None = None, sidecar_factory=None):
        if sidecar not in (None, "tcp"):
            raise ValueError(f"unknown sidecar transport {sidecar!r}")
        self.specs = specs
        self.precision = precision
        #: "tcp" boots one shared verification sidecar (serve/sidecar.py)
        #: that every node process dials; None keeps verification
        #: in-process per node.
        self.sidecar_mode = sidecar
        self.sidecar_factory = sidecar_factory
        self.sidecar = None
        self.driver = driver
        self.bit_length = bit_length
        self._pp_override = pp_raw   # tokengen-artifacts pp, if any
        self.fleet_spool_dir = fleet_spool_dir
        #: durable per-node state (signing keys) — required for a
        #: restarted node to come back as the same logical party
        self.state_dir = state_dir
        self.supervise = supervise
        self.supervisor_policy = supervisor_policy
        self.supervisor = None
        self._ctx = mp.get_context("spawn")
        self._mgr = self._ctx.Manager()
        self._procs: dict[str, mp.Process] = {}
        self._controls: dict[str, dict] = {}
        self._events = self._mgr.Queue()
        self._ledger_proc = None
        self._ledger_mgr = None
        self._authkey = uuid.uuid4().hex.encode()
        self._address = ("127.0.0.1", 0)
        self._pp_raw: bytes | None = None
        self._extra: dict | None = None

    # ------------------------------------------------------------------ boot
    def start(self) -> None:
        # keep proxy references alive on self: if the orchestrator drops
        # them, the manager decrefs and deletes the queues server-side,
        # stranding the children's proxies (RebuildProxy KeyError)
        inboxes = self._inboxes = \
            {s.name: self._mgr.Queue() for s in self.specs}
        replies = self._replies = \
            {s.name: self._mgr.Queue() for s in self.specs}

        # 1. GENERATE: spawn nodes, collect identities
        for s in self.specs:
            self._controls[s.name] = {"in": self._mgr.Queue(),
                                      "out": self._events}

        # pick a free port for the ledger manager
        import socket

        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        self._address = sock.getsockname()
        sock.close()

        if self.fleet_spool_dir:
            import os

            os.makedirs(self.fleet_spool_dir, exist_ok=True)
        for s in self.specs:
            self._procs[s.name] = self._ctx.Process(
                target=_node_main,
                args=(s.__dict__, list(self._address), self._authkey,
                      inboxes, self._controls[s.name], replies,
                      self.fleet_spool_dir, self.state_dir),
                daemon=True)
            self._procs[s.name].start()

        identities = {}
        for _ in self.specs:
            kind, name, ident = self._events.get(timeout=60)
            assert kind == "identity"
            identities[name] = ident

        # 2. SETUP: build pp with collected material, boot the ledger
        pp_raw = self._make_pp(identities)
        self._ledger_proc = self._ctx.Process(
            target=_serve_ledger, args=(self._address, self._authkey),
            daemon=True)
        self._ledger_proc.start()
        mgr = LedgerManager(address=self._address, authkey=self._authkey)
        for _ in range(100):
            try:
                mgr.connect()
                break
            except ConnectionRefusedError:
                time.sleep(0.05)
        self._ledger_mgr = mgr
        mgr.ledger().boot(pp_raw, self.driver)

        # 3. RUN: release the nodes. pp bytes + extras are kept so a
        # supervised restart can re-run the handshake for one node.
        auditor = next((s.name for s in self.specs if s.role == "auditor"),
                       None)
        self._pp_raw = pp_raw
        self._extra = {"precision": self.precision
                       if self.driver == "fabtoken" else self.bit_length,
                       "auditor": auditor}
        if self.sidecar_mode == "tcp":
            self._extra["sidecar_addr"] = list(
                self._start_sidecar(pp_raw).address)
        for s in self.specs:
            self._controls[s.name]["in"].put(
                ("start", pp_raw, self._extra))
        if self.supervise:
            self._start_supervisor()

    def _start_sidecar(self, pp_raw: bytes):
        """Boot the ONE shared verification sidecar all nodes dial.

        WAL and heartbeat land under ``state_dir``/``fleet_spool_dir``
        when available, so a supervised respawn replays open requests
        and the supervisor's stall watch sees sidecar phases.
        """
        import functools
        import os

        from ..serve.sidecar import RpcSidecar

        factory = self.sidecar_factory or functools.partial(
            _sidecar_zk_factory, pp_raw, self.driver)
        base = self.state_dir or self.fleet_spool_dir
        wal_dir = hb_path = None
        if base:
            os.makedirs(base, exist_ok=True)
            wal_dir = os.path.join(base, "sidecar_wal")
            hb_path = os.path.join(base, "rpc-sidecar.hb.jsonl")
        self.sidecar = RpcSidecar(
            factory, heartbeat_path=hb_path, wal_dir=wal_dir,
            prewarm=False, name="rpc-sidecar")
        self.sidecar.spawn()
        return self.sidecar

    def _respawn_sidecar(self, ctx=None):
        """ChildSpec.start for the sidecar: clear the dead pid's stale
        heartbeat stamps first, then spawn the replacement (which
        recovers + replays the shared WAL before serving)."""
        import os

        hb = self.sidecar.heartbeat_path
        if hb is not None:
            try:
                os.remove(hb)
            except OSError:
                pass
        return self.sidecar.spawn(ctx)

    def _start_supervisor(self) -> None:
        """Put every node process under the resilience supervisor: exit
        detection + respawn-with-handshake. Node heartbeats stamp phase
        *transitions* only (not a steady cadence), so the stall watch
        is disarmed via an unreachable deadline — exit detection and
        the fresh-beat RTO measurement are what supervision buys here.
        """
        import os

        from ..resilience.supervisor import ChildSpec, Supervisor

        self.supervisor = Supervisor(policy=self.supervisor_policy,
                                     poll_s=0.1)
        for s in self.specs:
            hb_file = (os.path.join(self.fleet_spool_dir,
                                    f"{s.name}.hb.jsonl")
                       if self.fleet_spool_dir else None)
            self.supervisor.add_child(
                ChildSpec(
                    name=s.name,
                    start=(lambda ctx, _name=s.name:
                           self._respawn_node(_name, cold=ctx.cold)),
                    heartbeat_file=hb_file,
                    default_deadline_s=1e9, grace_s=1e9),
                handle=self._procs[s.name])
        if self.sidecar is not None:
            # the sidecar DOES beat at a steady cadence, so its stall
            # watch is armed for real (SIGSTOP -> stall -> restart)
            self.supervisor.add_child(
                ChildSpec(name="rpc-sidecar",
                          start=self._respawn_sidecar,
                          heartbeat_file=self.sidecar.heartbeat_path,
                          default_deadline_s=15.0, grace_s=300.0),
                handle=self.sidecar._proc)
        self.supervisor.start()

    # ------------------------------------------------------------- restart
    def _respawn_node(self, name: str, cold: bool = False):
        """Boot a replacement process for ``name`` and re-run its
        GENERATE -> RUN handshake.

        The replacement reloads its persisted signing key (``state_dir``)
        so it is the same logical party, re-announces its identity (the
        event is ignored by any in-flight ``call`` loop), and is released
        immediately with the original pp bytes; its DeliveryService then
        replays the ledger from block 0, reconstructing token state —
        the reference ``Restart(...)`` semantics. The node's manager
        queues survive the process, so session-plane calls queued while
        it was down are served by the replacement."""
        del cold   # nodes hold no process-local warm caches today
        if self._pp_raw is None:
            raise RuntimeError("Platform not started")
        spec = next(s for s in self.specs if s.name == name)
        proc = self._ctx.Process(
            target=_node_main,
            args=(spec.__dict__, list(self._address), self._authkey,
                  self._inboxes, self._controls[name], self._replies,
                  self.fleet_spool_dir, self.state_dir),
            daemon=True)
        proc.start()
        self._procs[name] = proc
        self._controls[name]["in"].put(("start", self._pp_raw,
                                        self._extra))
        return proc

    def restart_node(self, name: str, timeout_s: float = 5.0):
        """Hard-kill one node process and boot its replacement (direct,
        unsupervised restart — the supervised path goes through
        :class:`~fabric_token_sdk_tpu.resilience.supervisor.Supervisor`
        detecting the death instead)."""
        proc = self._procs[name]
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=timeout_s)
        return self._respawn_node(name)

    @classmethod
    def from_artifacts(cls, artifacts_dir) -> "Platform":
        """Boot a topology from `tokengen artifacts gen` output: node keys
        and the pp come from disk instead of being generated at start
        (the reference flow: artifactgen writes, NWO consumes)."""
        import json
        from pathlib import Path

        root = Path(artifacts_dir)
        manifest = json.loads((root / "manifest.json").read_text())
        specs = [NodeSpec(name=n["name"], role=n.get("role", "owner"),
                          idemix=bool(n.get("idemix", False)),
                          key_pem=str(root / manifest["crypto_dir"]
                                      / n["name"] / "sk.pem"))
                 for n in manifest["nodes"]]
        return cls(specs,
                   precision=int(manifest.get("precision", 64)),
                   driver=manifest.get("driver", "fabtoken"),
                   bit_length=int(manifest.get("bit_length", 16)),
                   pp_raw=(root / manifest["pp"]).read_bytes())

    def _make_pp(self, identities: dict) -> bytes:
        if self._pp_override is not None:
            return self._pp_override
        issuers = [identities[s.name] for s in self.specs
                   if s.role == "issuer"]
        auditors = [identities[s.name] for s in self.specs
                    if s.role == "auditor"]
        if self.driver == "fabtoken":
            from ..core import fabtoken
            from ..driver.identity import Identity

            pp = fabtoken.setup(self.precision)
            pp.issuer_ids = [Identity(i) for i in issuers]
            if auditors:
                pp.auditor = auditors[0]
            return pp.serialize()
        from ..crypto import setup as zk_setup
        from ..driver.identity import Identity

        pp = zk_setup.setup(self.bit_length)
        pp.issuer_ids = [Identity(i) for i in issuers]
        if auditors:
            pp.auditor = auditors[0]
        return pp.serialize()

    # ----------------------------------------------------------------- views
    def call(self, node: str, command: str, *args, timeout: float = 120):
        """Drive one initiator view on `node` and wait for its result."""
        self._controls[node]["in"].put((command, *args))
        while True:
            kind, name, payload = self._events.get(timeout=timeout)
            if kind == "error":
                raise RuntimeError(f"[{name}] {payload}")
            if kind == "result":
                return payload

    def issue(self, via: str, issuer: str, to: str, token_type: str,
              amount: int):
        status, message, tx_id = self.call(
            via, "issue", issuer, to, token_type, hex(amount))
        if status != "VALID":
            raise RuntimeError(f"issue failed: {message}")
        return tx_id

    def transfer(self, via: str, token_type: str, amount: int, to: str,
                 redeem: bool = False):
        status, message, tx_id = self.call(
            via, "transfer", token_type, hex(amount), to, redeem)
        if status != "VALID":
            raise RuntimeError(f"transfer failed: {message}")
        return tx_id

    def balance(self, node: str, token_type: str) -> int:
        return self.call(node, "balance", token_type)

    def verify_range(self, node: str, proofs, coms=None) -> list[bool]:
        """Drive a range-proof batch from ``node`` through the shared
        TCP sidecar (requires ``sidecar="tcp"``)."""
        coms = list(coms) if coms is not None else [None] * len(proofs)
        return self.call(node, "verify_range", list(proofs), coms)

    # ------------------------------------------------------------ fleet obs
    def fleet_aggregator(self, provider=None):
        """A FleetAggregator over the platform spool (requires
        ``fleet_spool_dir``)."""
        if not self.fleet_spool_dir:
            raise RuntimeError("Platform started without fleet_spool_dir")
        from ..obs.aggregate import FleetAggregator

        return FleetAggregator(self.fleet_spool_dir, provider=provider)

    def fleet_telemetry(self, config=None, provider=None):
        """Start a TelemetryServer whose /metrics federates every node
        process's exposition (``node``-labelled) and whose /fleetz shows
        per-node spool freshness. Caller stops it."""
        from ..obs.telemetry import TelemetryConfig, TelemetryServer

        server = TelemetryServer(config or TelemetryConfig(port=0),
                                 provider=provider)
        server.attach_federator(self.fleet_aggregator(provider=provider))
        server.start()
        return server

    def wait_tx(self, node: str, tx_id: str, timeout: float = 10.0) -> str:
        return self.call(node, "wait_tx", tx_id, timeout)

    # ------------------------------------------------------------------ stop
    def stop(self, timeout_s: float = 5.0,
             raise_on_error: bool = True) -> dict:
        """Shut the topology down and surface how each child died.

        Joins every node with a shared bounded deadline, escalates
        terminate -> kill for stragglers, and returns ``{name:
        exitcode}``. A node that exited nonzero on its own (crashed
        rather than acked the stop) is logged — and raised, under
        ``raise_on_error`` — instead of being silently reaped;
        escalated stragglers are logged but never raised (the negative
        exit code is this method's own doing)."""
        import logging

        log = logging.getLogger("fabric_token_sdk_tpu.harness")
        if self.supervisor is not None:
            # first: a supervisor that outlives the stop commands would
            # dutifully "recover" every cleanly-exiting node
            self.supervisor.stop()
            self.supervisor = None
        for s in self.specs:
            try:
                self._controls[s.name]["in"].put(("stop",))
            except Exception:
                pass
        deadline = time.time() + timeout_s
        exit_codes: dict[str, int | None] = {}
        escalated: dict[str, str] = {}
        for name, p in self._procs.items():
            p.join(timeout=max(0.1, deadline - time.time()))
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
                escalated[name] = "terminate"
                if p.is_alive():
                    p.kill()
                    p.join(timeout=2.0)
                    escalated[name] = "kill"
            exit_codes[name] = p.exitcode
        if self.sidecar is not None:
            # after the nodes: their stop path closes RPC clients first
            self.sidecar.stop(timeout_s=max(2.0, timeout_s))
            self.sidecar = None
        if self._ledger_proc is not None:
            self._ledger_proc.terminate()
            self._ledger_proc.join(timeout=2.0)
        self._mgr.shutdown()
        for name, how in escalated.items():
            log.warning("node [%s] missed the %.1fs stop deadline; "
                        "escalated to %s (exitcode %s)",
                        name, timeout_s, how, exit_codes[name])
        unexpected = {n: c for n, c in exit_codes.items()
                      if c not in (0, None) and n not in escalated}
        if unexpected:
            detail = ", ".join(f"{n}={c}"
                               for n, c in sorted(unexpected.items()))
            log.error("node processes exited nonzero: %s", detail)
            if raise_on_error:
                raise RuntimeError(
                    f"node processes exited nonzero: {detail}")
        return exit_codes
