"""txgen: configurable transaction load generator + metrics collection.

Behavioral mirror of reference integration/nwo/txgen ({model,service,
executor}: user/issuer APIs, a configurable transaction-mix distribution,
concurrent execution, per-request metrics). Drives any set of TokenNode
facades — the in-process SessionBus net or the NWO multiprocess platform's
node handles — through the same issue/transfer/redeem initiator views the
applications use, and reports throughput/latency/error statistics.

Determinism: the mix is drawn from a seeded RNG so a load profile replays
identically (txgen's distribution model), which also makes failure counts
assertable in tests.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from ..obs import GLOBAL as _METRICS
from ..obs import bench_snapshot

_METRICS.describe("txgen_ops_total",
                  "Load-generator operations executed, by op and outcome")
_METRICS.describe("txgen_op_seconds",
                  "End-to-end wall per load-generator operation")


def open_loop_arrivals(rate_hz: float, duration_s: float,
                       seed: int = 0) -> list[float]:
    """Deterministic open-loop arrival schedule: Poisson-process offsets
    (seconds from t0, ascending) at ``rate_hz`` for ``duration_s``.

    Open loop means the schedule is fixed before the run: a slow server
    does not slow the arrival process down, so queueing/shedding behaviour
    under overload is actually exercised (closed-loop generators
    self-throttle and hide it). Seeded, so a bench replays the identical
    arrival sequence run-over-run (the txgen determinism contract).
    """
    if rate_hz <= 0 or duration_s <= 0:
        return []
    rng = random.Random(seed)
    out, t = [], 0.0
    while True:
        t += rng.expovariate(rate_hz)
        if t >= duration_s:
            return out
        out.append(t)


@dataclass
class TxProfile:
    """The transaction-mix model (txgen model.go equivalents): weights of
    each op plus the value range drawn for it."""

    issue_weight: float = 0.2
    transfer_weight: float = 0.7
    redeem_weight: float = 0.1
    min_value: int = 1
    max_value: int = 50
    token_type: str = "USD"


@dataclass
class TxOutcome:
    op: str
    ok: bool
    seconds: float
    error: str = ""


@dataclass
class LoadReport:
    outcomes: list[TxOutcome] = field(default_factory=list)
    wall_seconds: float = 0.0

    # ------------------------------------------------------------- metrics
    def _lat(self, ok_only=True) -> list[float]:
        return sorted(o.seconds for o in self.outcomes
                      if o.ok or not ok_only)

    @property
    def succeeded(self) -> int:
        return sum(1 for o in self.outcomes if o.ok)

    @property
    def failed(self) -> int:
        return sum(1 for o in self.outcomes if not o.ok)

    def failures_by_error(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for o in self.outcomes:
            if not o.ok:
                out[o.error] = out.get(o.error, 0) + 1
        return out

    def throughput(self) -> float:
        return self.succeeded / self.wall_seconds if self.wall_seconds else 0.0

    def percentile_latency(self, p: float) -> float:
        lat = self._lat()
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(p / 100.0 * len(lat)))]

    def summary(self) -> dict:
        return {
            "total": len(self.outcomes),
            "succeeded": self.succeeded,
            "failed": self.failed,
            "tx_per_sec": round(self.throughput(), 2),
            "p50_latency_s": round(self.percentile_latency(50), 4),
            "p95_latency_s": round(self.percentile_latency(95), 4),
            "p99_latency_s": round(self.percentile_latency(99), 4),
        }

    def bench_report(self, extra: dict | None = None) -> dict:
        """Roll this run's report together with the process-global
        observability registry (pipeline records, node counters) into one
        BENCH-style dict."""
        return bench_snapshot(extra={"txgen": self.summary(),
                                     **(extra or {})})


class LoadGenerator:
    """txgen service/executor: drive a transaction mix over live nodes.

    `users` are payer nodes; each op picks a payer and a distinct payee.
    Issues go through `issuer_name` to the payer (the user-API Withdraw);
    transfers move payer->payee; redeems burn at the payer.
    """

    def __init__(self, users: list, issuer_name: str,
                 profile: TxProfile | None = None, seed: int = 7):
        if not users:
            raise ValueError("txgen needs at least one user node")
        self.users = users
        self.issuer_name = issuer_name
        self.profile = profile or TxProfile()
        self.rng = random.Random(seed)

    # ------------------------------------------------------------ one op
    def _pick_op(self) -> str:
        p = self.profile
        return self.rng.choices(
            ["issue", "transfer", "redeem"],
            weights=[p.issue_weight, p.transfer_weight, p.redeem_weight])[0]

    def _run_one(self, op: str) -> TxOutcome:
        p = self.profile
        value = self.rng.randint(p.min_value, p.max_value)
        payer = self.rng.choice(self.users)
        t0 = time.perf_counter()
        try:
            if op == "issue":
                tx = payer.issue(self.issuer_name, payer.name, p.token_type,
                                 hex(value))
            elif op == "transfer":
                others = [u for u in self.users if u is not payer]
                payee = self.rng.choice(others) if others else payer
                tx = payer.transfer(p.token_type, hex(value), payee.name)
            else:
                tx = payer.transfer(p.token_type, hex(value), "", redeem=True)
            ev = payer.execute(tx)
            ok = ev.status == "VALID"
            err = "" if ok else ev.message
        except Exception as e:
            ok, err = False, type(e).__name__
        dt = time.perf_counter() - t0
        _METRICS.counter("txgen_ops_total", op=op,
                         ok=str(ok).lower()).add()
        _METRICS.histogram("txgen_op_seconds", op=op).observe(dt)
        return TxOutcome(op, ok, dt, err)

    # ---------------------------------------------------------------- run
    def run(self, n_txs: int, parallelism: int = 1,
            bootstrap_value: int | None = None) -> LoadReport:
        """Execute n_txs drawn from the profile. `parallelism` worker
        threads share the stream (txgen's concurrent executors —
        contention on the selector/locks is part of the workload).
        `bootstrap_value`: optional initial issue to every user so
        transfers don't all fail on empty wallets."""
        report = LoadReport()
        t_start = time.perf_counter()
        if bootstrap_value:
            for u in self.users:
                out = self._bootstrap(u, bootstrap_value)
                report.outcomes.append(out)
        ops = [self._pick_op() for _ in range(n_txs)]
        if parallelism <= 1:
            report.outcomes.extend(self._run_one(op) for op in ops)
        else:
            mu = threading.Lock()
            cursor = iter(ops)

            def worker():
                while True:
                    with mu:
                        op = next(cursor, None)
                    if op is None:
                        return
                    out = self._run_one(op)
                    with mu:
                        report.outcomes.append(out)

            threads = [threading.Thread(target=worker)
                       for _ in range(parallelism)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        report.wall_seconds = time.perf_counter() - t_start
        return report

    def _bootstrap(self, user, value: int) -> TxOutcome:
        t0 = time.perf_counter()
        try:
            tx = user.issue(self.issuer_name, user.name,
                            self.profile.token_type, hex(value))
            ev = user.execute(tx)
            out = TxOutcome("issue", ev.status == "VALID",
                            time.perf_counter() - t0, ev.message
                            if ev.status != "VALID" else "")
        except Exception as e:
            out = TxOutcome("issue", False, time.perf_counter() - t0,
                            type(e).__name__)
        _METRICS.counter("txgen_ops_total", op="issue",
                         ok=str(out.ok).lower()).add()
        _METRICS.histogram("txgen_op_seconds", op="issue").observe(
            out.seconds)
        return out
