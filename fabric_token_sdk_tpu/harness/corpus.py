"""ProofCorpus: seeded range-proof corpora for benches and harnesses.

The replay bench (bench.py BENCH_MODE=replay) historically tiled four
pre-generated benchdata proofs; a prover-fed corpus replaces that with a
stream of DISTINCT proofs — diverse values (the 0 and 2^n - 1 edges are
always pinned in), seeded blinding draws so a corpus replays
byte-identically run-over-run (the txgen determinism contract), and a
deliberately forged out-of-range witness every ``forge_every`` rows so
the reject path is exercised at a known cadence.

Sources:
  * ``device`` — ``prover.DeviceRangeProver`` synthesizes the corpus in
    fused on-device chunks (the BENCH_REPLAY_SOURCE=prover arm);
  * ``host``   — ``crypto.rp.range_prove`` row by row (slow; the parity
    oracle and the CPU-only tier-1 tests).

Both sources share one seeded witness plan, so a device corpus and a
host corpus from the same seed are byte-identical proof-for-proof.
``provenance()`` reports the generation parameters for the BENCH report.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from ..crypto import bn254, rp
from ..obs import GLOBAL as _METRICS
from .txgen import open_loop_arrivals

R = bn254.R

#: Corpus metric family metadata (stable name, HELP-linted).
_CORPUS_FAMILIES = {
    "prover_corpus_proofs_total":
        "Corpus range proofs generated, by source, bits and forged",
}
for _fam, _help in _CORPUS_FAMILIES.items():
    _METRICS.describe(_fam, _help)


@dataclass
class CorpusEntry:
    proof: rp.RangeProof
    commitment: bn254.G1
    value: int
    forged: bool


def _seeded_draws(rng: random.Random, bit_length: int) -> rp.RangeProverDraws:
    return rp.RangeProverDraws(
        rho=rng.randrange(1, R), eta=rng.randrange(1, R),
        random_left=[rng.randrange(1, R) for _ in range(bit_length)],
        random_right=[rng.randrange(1, R) for _ in range(bit_length)],
        tau1=rng.randrange(1, R), tau2=rng.randrange(1, R))


class ProofCorpus:
    """Deterministic range-proof corpus for one PublicParams set.

    ``forge_every=N`` plants an out-of-range witness at every index with
    ``i % N == N - 1`` (never displacing the pinned edge values at
    indices 0 and 1); ``forge_every=0`` disables forgeries. Entries
    carry their ground-truth ``forged`` flag so a replay harness can
    assert every verdict.
    """

    def __init__(self, pp, source: str = "device", seed: int = 17,
                 forge_every: int = 0, chunk_rows: int | None = None):
        if source not in ("device", "host"):
            raise ValueError(f"unknown corpus source: {source!r}")
        self.pp = pp
        self.source = source
        self.seed = seed
        self.forge_every = forge_every
        self.chunk_rows = chunk_rows
        self.bit_length = pp.range_proof_params.bit_length

    # ------------------------------------------------------- witness plan
    def _plan(self, count: int):
        """Seeded (values, bfs, draws, forged_flags): indices 0/1 pin
        the range edges, every forge_every-th row is out of range."""
        n = self.bit_length
        rng = random.Random(self.seed)
        values, forged = [], []
        for i in range(count):
            forge = (self.forge_every > 0
                     and i % self.forge_every == self.forge_every - 1)
            if forge:
                v = (1 << n) + rng.randrange(1, 1 << n)
            elif i == 0:
                v = 0
            elif i == 1:
                v = (1 << n) - 1
            else:
                v = rng.randrange(1 << n)
            values.append(v)
            forged.append(forge)
        bfs = [rng.randrange(1, R) for _ in range(count)]
        draws = [_seeded_draws(rng, n) for _ in range(count)]
        return values, bfs, draws, forged

    # --------------------------------------------------------- generation
    def generate(self, count: int) -> list[CorpusEntry]:
        values, bfs, draws, forged = self._plan(count)
        if self.source == "device":
            proofs, coms = self._device_rows(values, bfs, draws, forged)
        else:
            proofs, coms = self._host_rows(values, bfs, draws)
        n_forged = sum(forged)
        bits = str(self.bit_length)
        _METRICS.counter("prover_corpus_proofs_total", source=self.source,
                         bits=bits, forged="false").add(count - n_forged)
        if n_forged:
            _METRICS.counter("prover_corpus_proofs_total",
                             source=self.source, bits=bits,
                             forged="true").add(n_forged)
        return [CorpusEntry(p, c, v, f) for p, c, v, f in
                zip(proofs, coms, values, forged)]

    def _device_rows(self, values, bfs, draws, forged):
        from ..prover import DeviceRangeProver

        prover = DeviceRangeProver(self.pp, chunk_rows=self.chunk_rows)
        # valid and forged rows go through separate prove() calls (the
        # forge=True contract stays per-call), then re-interleave
        ok_idx = [i for i, f in enumerate(forged) if not f]
        bad_idx = [i for i, f in enumerate(forged) if f]
        proofs = [None] * len(values)
        coms = [None] * len(values)
        for idxs, forge in ((ok_idx, False), (bad_idx, True)):
            if not idxs:
                continue
            ps, cs = prover.prove([values[i] for i in idxs],
                                  [bfs[i] for i in idxs],
                                  draws=[draws[i] for i in idxs],
                                  forge=forge)
            for j, i in enumerate(idxs):
                proofs[i], coms[i] = ps[j], cs[j]
        return proofs, coms

    def _host_rows(self, values, bfs, draws):
        pp = self.pp
        rpp = pp.range_proof_params
        cg = pp.pedersen_generators[1:3]
        proofs, coms = [], []
        for v, bf, d in zip(values, bfs, draws):
            com = bn254.g1_add(bn254.g1_mul(cg[0], v),
                               bn254.g1_mul(cg[1], bf))
            proofs.append(rp.range_prove(
                com, v, cg, bf, rpp.left_generators, rpp.right_generators,
                rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length,
                draws=d))
            coms.append(com)
        return proofs, coms

    def columnar_cells(self, entries: list[CorpusEntry]):
        """``(proof_cells, com_cells, bits, flags)`` for one FMT_RANGE
        SUBMIT_BATCH frame over ``entries`` — the bridge between a
        generated corpus and the columnar front door (``flags`` bit 0
        carries each row's ground-truth forged marker, so the server
        side of a bench can assert verdict parity per row)."""
        from ..serve.columnar import range_cells

        proof_cells, com_cells = range_cells(
            [e.proof for e in entries], [e.commitment for e in entries])
        bits = [self.bit_length] * len(entries)
        flags = [1 if e.forged else 0 for e in entries]
        return proof_cells, com_cells, bits, flags

    # ----------------------------------------------------------- plumbing
    def provenance(self) -> dict:
        """Generation parameters for the BENCH report (config 5 replay
        records where its corpus came from)."""
        return {
            "generator": "harness.corpus.ProofCorpus",
            "source": self.source,
            "bits": self.bit_length,
            "seed": self.seed,
            "forge_every": self.forge_every,
            "edge_values": [0, (1 << self.bit_length) - 1],
        }

    def arrival_schedule(self, count: int, rate_hz: float,
                         seed: int = 11) -> list[float]:
        """Open-loop Poisson offsets for replaying ``count`` corpus
        entries at ``rate_hz`` (txgen.open_loop_arrivals, topped up to
        exactly ``count`` arrivals)."""
        duration = count / rate_hz
        out = open_loop_arrivals(rate_hz, duration * 1.1, seed=seed)[:count]
        while len(out) < count:
            out.append((out[-1] if out else 0.0) + 1.0 / rate_hz)
        return out
