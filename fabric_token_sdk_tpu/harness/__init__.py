"""NWO-style multiprocess test harness (reference integration/nwo/token).

Boots N real OS processes — one per token node — over a shared ledger
process, with the session plane (sign/audit/distribute views) running over
IPC queues and finality flowing through a polling delivery service, the
same planes the reference runs over websockets + Fabric delivery
(SURVEY.md §2.5).
"""

from .corpus import CorpusEntry, ProofCorpus  # noqa: F401
from .nwo import Platform, NodeSpec  # noqa: F401
