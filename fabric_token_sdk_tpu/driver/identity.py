"""Identity: an opaque serialized identity (reference token/driver/identity.go).

Identities are raw bytes (serialized MSP/X.509/Idemix material or a script
wrapper); equality and hashing are by content. `UniqueID` mirrors the
reference's base64-of-SHA256 short form used for logging/keys.
"""

from __future__ import annotations

import base64
import hashlib


class Identity(bytes):
    """Opaque identity bytes with convenience helpers."""

    def is_none(self) -> bool:
        return len(self) == 0

    def unique_id(self) -> str:
        if len(self) == 0:
            return ""
        return base64.b64encode(hashlib.sha256(self).digest()).decode("ascii")

    def __repr__(self) -> str:  # keep logs short
        return f"Identity({self.unique_id()[:12]}…)" if self else "Identity(∅)"


NONE = Identity(b"")
