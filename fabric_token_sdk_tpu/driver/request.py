"""TokenRequest wire format.

Byte-compatible with reference token/driver/protos/request.proto +
token/driver/request.go:26-104: a proto3 TokenRequest{version, actions,
signatures, auditor_signatures} and the ASN.1 message-to-sign
(Go asn1.Marshal of the 4-slice struct with only Issues/Transfers populated,
with the anchor appended).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import serialization as ser
from ..utils import protowire as pw

VERSION = 1

ACTION_ISSUE = 0
ACTION_TRANSFER = 1


class RequestError(ValueError):
    pass


@dataclass
class TokenRequest:
    """Collection of independent actions + witnesses (request.go:26-36).

    Actions in one request are independent: an action cannot spend tokens
    created by another action in the same request.
    """

    issues: list[bytes] = field(default_factory=list)
    transfers: list[bytes] = field(default_factory=list)
    signatures: list[bytes] = field(default_factory=list)
    auditor_signatures: list[bytes] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        """proto3 request.TokenRequest (request.go:38-66)."""
        out = [pw.uint64_field(1, VERSION)]
        for raw in self.issues:
            body = pw.uint64_field(1, ACTION_ISSUE) + pw.bytes_field(2, raw)
            out.append(pw.message_field(2, body))
        for raw in self.transfers:
            body = pw.uint64_field(1, ACTION_TRANSFER) + pw.bytes_field(2, raw)
            out.append(pw.message_field(2, body))
        for sig in self.signatures:
            out.append(pw.message_field(3, pw.bytes_field(1, sig)))
        for sig in self.auditor_signatures:
            out.append(pw.message_field(4, pw.bytes_field(1, sig)))
        return b"".join(out)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TokenRequest":
        """request.go:46-53,68-96 (nil/empty signature rejection included)."""
        req = cls()
        for num, _, value in pw.iter_fields(raw):
            if num == 2:
                fields = pw.parse_fields(value)
                a_type = fields.get(1, [0])[0]
                a_raw = bytes(fields.get(2, [b""])[0])
                if a_type == ACTION_ISSUE:
                    req.issues.append(a_raw)
                elif a_type == ACTION_TRANSFER:
                    req.transfers.append(a_raw)
                else:
                    raise RequestError(f"unknown action type [{a_type}]")
            elif num in (3, 4):
                fields = pw.parse_fields(value)
                sig = bytes(fields.get(1, [b""])[0])
                if len(sig) == 0:
                    which = "signature" if num == 3 else "auditor signature"
                    raise RequestError(f"nil {which} found")
                if num == 3:
                    req.signatures.append(sig)
                else:
                    req.auditor_signatures.append(sig)
        return req

    def message_to_sign(self, anchor: bytes) -> bytes:
        """ASN.1 of {Issues, Transfers, [], []} + anchor (request.go:98-104).

        Go asn1.Marshal of the driver.TokenRequest struct: SEQUENCE of four
        SEQUENCE OF OCTET STRING (signatures empty at signing time).
        """
        body = ser.der_sequence(
            ser.der_sequence(*[ser.der_octet_string(b) for b in self.issues]),
            ser.der_sequence(*[ser.der_octet_string(b) for b in self.transfers]),
            ser.der_sequence(),
            ser.der_sequence(),
        )
        return body + anchor
