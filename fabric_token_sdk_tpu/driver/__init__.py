"""Driver SPI — the plugin boundary between the Token API and drivers.

Mirrors the capability surface of reference token/driver/*.go (SURVEY.md
§2.1): token request wire format, validator/ledger/signature interfaces, and
the identity type. Drivers (fabtoken, zkatdlog) implement these contracts;
the TPU batch verifier plugs in behind `Validator` exactly as the north star
requires (BASELINE.json).
"""

from .identity import Identity  # noqa: F401
from .request import TokenRequest  # noqa: F401
from .api import (  # noqa: F401
    Ledger,
    SignatureProvider,
    Validator,
    Verifier,
    ValidationAttributes,
)
