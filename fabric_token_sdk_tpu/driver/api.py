"""Driver SPI contracts.

Python Protocols standing in for the reference's Go interfaces (reference
token/driver/driver.go, validator.go, tms.go — SURVEY.md §1 "Driver API").
Only behavior-bearing members are modeled; Go's context plumbing is dropped.
"""

from __future__ import annotations

from typing import Callable, Protocol, runtime_checkable

from ..token.model import ID
from .identity import Identity

# Attributes generated during validation (driver/validator.go:15-18).
ValidationAttributes = dict[str, bytes]

# GetStateFnc returns the ledger value for a token ID (validator.go:21-22).
GetStateFnc = Callable[[ID], bytes | None]


@runtime_checkable
class Ledger(Protocol):
    """Read-only ledger (validator.go:24-28)."""

    def get_state(self, token_id: ID) -> bytes | None: ...


@runtime_checkable
class Verifier(Protocol):
    """Signature verifier bound to one identity's key material."""

    def verify(self, message: bytes, signature: bytes) -> None:
        """Raises on invalid signature."""


@runtime_checkable
class SignatureProvider(Protocol):
    """validator.go:30-35."""

    def has_been_signed_by(self, identity: Identity, verifier: Verifier) -> bytes:
        """Returns the verified signature or raises."""

    def sigs(self) -> list[bytes]: ...


@runtime_checkable
class Validator(Protocol):
    """Token request validator (validator.go:44-52) — the TPU plugin boundary."""

    def unmarshal_actions(self, raw: bytes) -> list: ...

    def verify_token_request_from_raw(
        self, get_state: GetStateFnc, anchor: str, raw: bytes
    ) -> tuple[list, ValidationAttributes]: ...


@runtime_checkable
class Deserializer(Protocol):
    """Identity-to-verifier resolution (driver/deserializer.go)."""

    def get_owner_verifier(self, identity: Identity) -> Verifier: ...

    def get_issuer_verifier(self, identity: Identity) -> Verifier: ...

    def get_auditor_verifier(self, identity: Identity) -> Verifier: ...


class TransferAction(Protocol):
    """driver/action.go transfer surface."""

    def get_inputs(self) -> list[ID]: ...

    def get_serialized_outputs(self) -> list[bytes]: ...

    def get_metadata(self) -> dict[str, bytes]: ...

    def serialize(self) -> bytes: ...


class IssueAction(Protocol):
    def get_serialized_outputs(self) -> list[bytes]: ...

    def get_metadata(self) -> dict[str, bytes]: ...

    def serialize(self) -> bytes: ...


# --------------------------------------------------------------------------
# TokenManagerService SPI (reference token/driver/tms.go:31-46)
#
# The reference's plugin architecture: a driver is anything that can build
# the services below for one PublicParams set; everything above the SPI
# (token API, services tier) talks only to these contracts. The two shipped
# drivers (core/fabtoken, core/zkatdlog) are declared against them in
# tests/test_registry_tms.py::TestDriverSPIConformance.
# --------------------------------------------------------------------------


@runtime_checkable
class PublicParameters(Protocol):
    """What the registry/TMS require of a driver's pp object
    (driver/publicparams.go: Identifier/Precision/Validate/Serialize)."""

    def serialize(self) -> bytes: ...

    def validate(self) -> None: ...


@runtime_checkable
class PublicParamsManager(Protocol):
    """driver/publicparams.go PublicParamsManager + token/ppm.go facade."""

    def public_parameters(self) -> PublicParameters: ...

    def serialize(self) -> bytes: ...

    def validate(self) -> None: ...

    def precision(self) -> int: ...

    def auditors(self) -> list[bytes]: ...

    def issuers(self) -> list[bytes]: ...


@runtime_checkable
class IssueService(Protocol):
    """driver/issue.go:36-50 — builds an IssueAction + per-output
    metadata (this build: crypto proof generation inside assemble_issue)."""

    def assemble_issue(self, issuer_identity: bytes,
                       outputs: list) -> tuple: ...


@runtime_checkable
class TransferService(Protocol):
    """driver/transfer.go:24-37 — builds a TransferAction + metadata from
    loaded input rows (openings) and output specs."""

    def assemble_transfer(self, input_rows, outputs: list,
                          wallet=None, sender_audit_info=None) -> tuple: ...


@runtime_checkable
class TokensService(Protocol):
    """driver/tokens.go:34-50 — Deobfuscate equivalents: recover clear
    tokens from committed outputs + openings at ingestion time."""

    def extract_outputs(self, action, openings=None) -> list: ...

    def parse_ledger_output(self, raw: bytes, opening: bytes | None = None): ...


@runtime_checkable
class AuditorService(Protocol):
    """driver/auditor.go:12-15 — request well-formedness check against
    audit metadata (zkatdlog: commitment re-opening + NymEID match)."""

    def audit_check(self, request, metadata, input_tokens,
                    tx_id: str) -> None: ...


@runtime_checkable
class DriverService(IssueService, TransferService, TokensService,
                    AuditorService, Protocol):
    """The consolidated per-driver service bundle member: one object
    providing the reference's Issue/Transfer/Tokens/Auditor services
    (tms.go:32-36 accessors). `label` identifies the driver and doubles
    as the ledger token format it writes (token.Format)."""

    label: str


@runtime_checkable
class WalletService(Protocol):
    """driver/wallet.go:157-203 — role-scoped wallet directory."""

    def owner_wallet(self, lookup=None): ...

    def issuer_wallet(self, lookup=None): ...

    def auditor_wallet(self, lookup=None): ...

    def certifier_wallet(self, lookup=None): ...

    def wallet_ids(self, role: str) -> list[str]: ...


@runtime_checkable
class Wallet(Protocol):
    """driver/wallet.go:36-49 — one wallet's signing surface."""

    def recipient_identity(self) -> tuple[bytes, bytes]: ...

    def owns(self, owner_raw: bytes) -> bool: ...

    def sign(self, owner_raw: bytes, message: bytes) -> bytes: ...


@runtime_checkable
class Authorization(Protocol):
    """driver/wallet.go:138-155 — is an owner identity recognized, and
    which local wallets may spend it (TMS + HTLC script + multisig escrow
    multiplexer in the reference, core/common/authrorization.go:123)."""

    def is_mine(self, tok) -> tuple[list[str], bool]: ...

    def am_i_an_auditor(self) -> bool: ...


@runtime_checkable
class Configuration(Protocol):
    """driver/config.go:10-25 — typed access to one TMS's config tree."""

    def id(self): ...

    def is_set(self, key: str) -> bool: ...

    def get_string(self, key: str) -> str: ...

    def get_bool(self, key: str) -> bool: ...


@runtime_checkable
class TokenManagerService(Protocol):
    """driver/tms.go:31-46 — the SPI entry point: access to every driver
    service for one TMS. Satisfied by token/tms.py TokenManagementService
    (services/validator/deserializer accessors) once node-scoped components
    are bound."""

    def public_parameters_manager(self) -> PublicParamsManager: ...

    def validator(self) -> Validator: ...

    def deserializer(self) -> Deserializer: ...

    def driver_services(self) -> DriverService: ...

    def wallet_manager(self) -> WalletService: ...


@runtime_checkable
class Driver(Protocol):
    """driver/driver.go:16 — a named factory turning serialized public
    parameters into a full service bundle (label + services + validator +
    deserializer). Register with core.registry.DriverRegistry."""

    def __call__(self, pp_raw: bytes): ...
