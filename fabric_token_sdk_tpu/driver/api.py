"""Driver SPI contracts.

Python Protocols standing in for the reference's Go interfaces (reference
token/driver/driver.go, validator.go, tms.go — SURVEY.md §1 "Driver API").
Only behavior-bearing members are modeled; Go's context plumbing is dropped.
"""

from __future__ import annotations

from typing import Callable, Protocol, runtime_checkable

from ..token.model import ID
from .identity import Identity

# Attributes generated during validation (driver/validator.go:15-18).
ValidationAttributes = dict[str, bytes]

# GetStateFnc returns the ledger value for a token ID (validator.go:21-22).
GetStateFnc = Callable[[ID], bytes | None]


@runtime_checkable
class Ledger(Protocol):
    """Read-only ledger (validator.go:24-28)."""

    def get_state(self, token_id: ID) -> bytes | None: ...


@runtime_checkable
class Verifier(Protocol):
    """Signature verifier bound to one identity's key material."""

    def verify(self, message: bytes, signature: bytes) -> None:
        """Raises on invalid signature."""


@runtime_checkable
class SignatureProvider(Protocol):
    """validator.go:30-35."""

    def has_been_signed_by(self, identity: Identity, verifier: Verifier) -> bytes:
        """Returns the verified signature or raises."""

    def sigs(self) -> list[bytes]: ...


@runtime_checkable
class Validator(Protocol):
    """Token request validator (validator.go:44-52) — the TPU plugin boundary."""

    def unmarshal_actions(self, raw: bytes) -> list: ...

    def verify_token_request_from_raw(
        self, get_state: GetStateFnc, anchor: str, raw: bytes
    ) -> tuple[list, ValidationAttributes]: ...


@runtime_checkable
class Deserializer(Protocol):
    """Identity-to-verifier resolution (driver/deserializer.go)."""

    def get_owner_verifier(self, identity: Identity) -> Verifier: ...

    def get_issuer_verifier(self, identity: Identity) -> Verifier: ...

    def get_auditor_verifier(self, identity: Identity) -> Verifier: ...


class TransferAction(Protocol):
    """driver/action.go transfer surface."""

    def get_inputs(self) -> list[ID]: ...

    def get_serialized_outputs(self) -> list[bytes]: ...

    def get_metadata(self) -> dict[str, bytes]: ...

    def serialize(self) -> bytes: ...


class IssueAction(Protocol):
    def get_serialized_outputs(self) -> list[bytes]: ...

    def get_metadata(self) -> dict[str, bytes]: ...

    def serialize(self) -> bytes: ...
