"""Deterministic startup prewarm: compile every shape the scheduler can emit.

The scheduler's closed world (ServeConfig.buckets) makes warm-up a
bounded, enumerable phase instead of an ad-hoc cost smeared over the
first real traffic (the round-5 driver bench measured 321.7 s of warm-up
convergence). At service start the manager walks the bucket ladder
ascending and drives one synthetic verify per bucket through the SAME
entry points real batches use — ``FTS_PREWARM`` semantics (network/tcc.py
pp-install prewarm), lifted from an env-var side channel into an explicit
startup stage with per-shape accounting:

  - ``serve_prewarm_seconds{bucket}`` records each shape's compile wall,
    so a driver can see exactly which executable is expensive;
  - ``compile_s`` / ``total_s`` let the bench report prewarm wall time
    separately from steady-state throughput;
  - ``ready`` is the set of compiled buckets — the smoke test asserts
    every configured bucket is in it BEFORE the first dispatch.

Deterministic by construction: fixed bucket order, fixed synthetic
inputs (the all-generators fake proof the verifier's own ``prewarm``
uses); nothing depends on arrival timing.
"""

from __future__ import annotations

import os
import time

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..obs.journal import (EVENT_COMPILE_END, EVENT_COMPILE_START,
                           JOURNAL)
from ..obs.profiling import PROFILER
from .config import ServeConfig


class PrewarmManager:
    """Compiles the configured bucket ladder through a ZKVerifier.

    One manager per DEVICE dispatch lane (``lane`` is the lane index):
    each lane keeps its own ``ready`` inventory and per-bucket compile
    accounting, so a multi-lane service can assert every lane compiled
    every emittable bucket before its first dispatch. Lanes sharing one
    in-process verifier still pay each compile only once (the jit cache
    is per-executable, not per-manager); lanes holding per-device
    verifiers each warm their own device."""

    def __init__(self, zk, config: ServeConfig, lane: int = 0):
        self.zk = zk
        self.config = config
        self.lane = lane
        self.compile_s: dict[int, float] = {}
        self.ready: set[int] = set()
        self.total_s: float = 0.0

    def run(self) -> float:
        """Compile every bucket shape; returns total wall seconds.

        Idempotent: already-ready buckets are skipped, so a restart of
        the dispatch loop never re-pays compiles.
        """
        t0 = time.perf_counter()
        # Opt-in persistent compile cache: with BENCH_COMPILE_CACHE_DIR
        # set, executables compiled here land in a directory that outlives
        # the process, so a service restart's prewarm is mostly cache
        # reads. Same entry point bench.py uses; no-op otherwise.
        if os.environ.get("BENCH_COMPILE_CACHE_DIR"):
            try:
                from ..utils.jaxcfg import configure_jax_cache

                configure_jax_cache()
            except Exception:
                pass  # cache is an optimization, never a startup failure
        with _TRACER.span("serve.prewarm",
                          buckets=tuple(self.config.buckets),
                          lane=self.lane,
                          block=self.config.prewarm_block):
            for bucket in self.config.buckets:
                if bucket in self.ready:
                    continue
                JOURNAL.record(EVENT_COMPILE_START, what="serve_prewarm",
                               bucket=bucket, lane=self.lane)
                per_shape = self.zk.prewarm_shapes(
                    (bucket,), include_block=self.config.prewarm_block)
                elapsed = per_shape[bucket]
                JOURNAL.record(EVENT_COMPILE_END, what="serve_prewarm",
                               bucket=bucket, lane=self.lane,
                               elapsed_s=round(elapsed, 3))
                self.compile_s[bucket] = elapsed
                self.ready.add(bucket)
                _METRICS.histogram(
                    "serve_prewarm_seconds",
                    help="Per-bucket prewarm compile wall at service start",
                    bucket=str(bucket),
                    lane=str(self.lane)).observe(elapsed)
                # profiling telemetry: compile wall + AOT cost analysis of
                # the dominant kernel at this bucket (lowering only; a
                # backend without kernel_cost contributes nothing)
                PROFILER.record_compile("serve_prewarm", bucket, elapsed)
                PROFILER.capture_bucket_cost(self.zk, bucket)
                # fused device programs: same families, own kinds —
                # pass12_fused (merged chunk pipeline, every backend)
                # plus the Pallas kernels on TPU
                PROFILER.capture_fused_costs(self.zk, bucket)
            PROFILER.record_memory_watermark()
        self.total_s += time.perf_counter() - t0
        return self.total_s
