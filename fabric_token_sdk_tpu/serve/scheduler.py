"""Deadline-aware pow-2 bucket scheduler (continuous batch assembly).

The scheduling unit is a (group, lane) FIFO: range rows and block
actions never mix into one device call (they take different backend
paths), and within a group the interactive lane drains before bulk so
adversarial/bulk backlog cannot starve latency-sensitive traffic.

Dispatch policy per group — evaluated continuously by the service loop:

  - FULL:     queued rows reach ``max(buckets)`` -> dispatch a full
              bucket immediately (throughput mode);
  - WAIT-DUE: the oldest request has waited ``max_wait_s`` and at least
              ``min_batch`` rows are queued -> dispatch everything;
  - DEADLINE: the oldest request's ``deadline - service_estimate_s``
              instant has passed -> dispatch everything queued even
              below ``min_batch`` (a request is never held into a
              guaranteed miss to improve batch fill);
  - otherwise wait until ``next_event()``.

Deadline expiry is handled here too: ``expire()`` removes requests whose
deadline passed while queued so they complete with ``deadline_miss``
instead of occupying batch rows a verdict can no longer use.

All state is single-threaded by construction: only the service's event
loop touches the queues (the device call runs in an executor thread but
never sees the scheduler).
"""

from __future__ import annotations

import time
from collections import deque

from ..obs import GLOBAL as _METRICS
from .config import ServeConfig
from .request import KIND_RANGE, VerifyRequest

#: Batching groups, in priority order at assembly time: action batches
#: carry interactive HTLC/validate traffic more often than bulk ranges.
GROUPS = ("action", KIND_RANGE)


class BucketScheduler:
    """Per-(group, lane) queues + the batch assembly decision."""

    def __init__(self, config: ServeConfig):
        self.config = config
        self._queues: dict[tuple, deque] = {
            (g, lane): deque() for g in GROUPS for lane in config.lanes}
        # device-lane assignment state: last-emission stamp per dispatch
        # lane index (pick_lane round-robins over the idle ones)
        self._lane_stamp: dict[int, int] = {}
        self._stamp = 0

    # ------------------------------------------------------- device lanes
    def pick_lane(self, idle: list[int]) -> int | None:
        """Device dispatch lane for the next emitted bucket: the least-
        recently-used of the currently idle lanes (round-robin when all
        are fresh), so consecutive batches spread across every device
        instead of re-feeding lane 0. Returns None when no lane is idle
        — the service then sleeps until a lane completes."""
        if not idle:
            return None
        lane = min(idle, key=lambda i: (self._lane_stamp.get(i, -1), i))
        self._stamp += 1
        self._lane_stamp[lane] = self._stamp
        return lane

    # ------------------------------------------------------------- queues
    def push(self, req: VerifyRequest) -> None:
        self._queues[(req.group, req.lane)].append(req)
        self._gauge(req.lane)

    def lane_depth(self, lane: str) -> int:
        return sum(len(q) for (g, ln), q in self._queues.items()
                   if ln == lane)

    def depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _gauge(self, lane: str) -> None:
        _METRICS.gauge(
            "serve_queue_depth",
            help="Queued requests per lane (admitted, not yet dispatched)",
            lane=lane).set(self.lane_depth(lane))

    # ------------------------------------------------------------- expiry
    def expire(self, now: float | None = None) -> list[VerifyRequest]:
        """Pop every queued request whose deadline has already passed."""
        now = time.perf_counter() if now is None else now
        out: list[VerifyRequest] = []
        for (g, lane), q in self._queues.items():
            if not q or all(r.deadline > now for r in q):
                continue
            out.extend(r for r in q if r.deadline <= now)
            live = [r for r in q if r.deadline > now]
            q.clear()
            q.extend(live)
            self._gauge(lane)
        return out

    # ----------------------------------------------------------- assembly
    def _group_rows(self, group: str) -> int:
        return sum(len(self._queues[(group, lane)])
                   for lane in self.config.lanes)

    def _due_instants(self, group: str) -> tuple[float, float] | None:
        """(wait_due, deadline_due) over the group's queue heads, or
        None when the group is empty. wait_due is the max-wait horizon
        (gated by min_batch at decision time); deadline_due is the
        instant deadline pressure forces dispatch regardless of fill."""
        cfg = self.config
        heads = [q[0] for lane in cfg.lanes
                 for q in (self._queues[(group, lane)],) if q]
        if not heads:
            return None
        return (min(r.enqueue_t + cfg.max_wait_s for r in heads),
                min(r.deadline - cfg.service_estimate_s for r in heads))

    def next_event(self, now: float | None = None,
                   include_dispatch: bool = True) -> float | None:
        """Earliest future instant a dispatch or expiry becomes due, or
        None when nothing is queued (the service sleeps until a push).

        ``include_dispatch=False`` restricts the horizon to deadline
        EXPIRY instants only — what the service needs while every
        dispatch lane is busy (a dispatch-due instant in the past would
        otherwise hot-spin the loop until a lane frees)."""
        instants = []
        for g in GROUPS if include_dispatch else ():
            due = self._due_instants(g)
            if due is None:
                continue
            wait_due, deadline_due = due
            if self._group_rows(g) >= self.config.min_batch:
                instants.append(min(wait_due, deadline_due))
            else:
                instants.append(deadline_due)
        for q in self._queues.values():
            if q:
                instants.append(min(r.deadline for r in q))
        return min(instants) if instants else None

    def assemble(self, now: float | None = None) -> list[VerifyRequest]:
        """Pop the next due batch (possibly empty when nothing is due).

        Priority lanes drain first; the batch never exceeds
        ``max(buckets)`` rows and never mixes groups.
        """
        now = time.perf_counter() if now is None else now
        cfg = self.config
        for group in GROUPS:
            rows = self._group_rows(group)
            if rows == 0:
                continue
            wait_due, deadline_due = self._due_instants(group)
            full = rows >= cfg.max_batch
            waited = rows >= cfg.min_batch and now >= wait_due
            forced = now >= deadline_due
            if not (full or waited or forced):
                continue
            batch: list[VerifyRequest] = []
            for lane in cfg.lanes:           # interactive first
                q = self._queues[(group, lane)]
                while q and len(batch) < cfg.max_batch:
                    batch.append(q.popleft())
                self._gauge(lane)
            bucket = cfg.bucket_for(len(batch))
            _METRICS.histogram(
                "serve_batch_fill_ratio",
                help="Live rows / covering bucket, per dispatched batch",
                group=group).observe(len(batch) / bucket)
            _METRICS.histogram(
                "serve_batch_rows",
                help="Live rows per dispatched batch",
                group=group).observe(len(batch))
            return batch
        return []
