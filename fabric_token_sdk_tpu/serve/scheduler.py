"""Deadline-aware pow-2 bucket scheduler (continuous batch assembly).

The scheduling unit is a (group, lane) queue: range rows and block
actions never mix into one device call (they take different backend
paths), and within a group the interactive lane drains before bulk so
adversarial/bulk backlog cannot starve latency-sensitive traffic.

Within one (group, lane) queue, tenants drain by DEFICIT ROUND-ROBIN
(:class:`_TenantDrrQueue`) instead of a global FIFO: each ``tms_id``
owns a FIFO sub-queue and earns ``tenant_quantum * weight`` rows of
service per rotation, so one hot tenant can no longer starve the rest
(SURVEY §3.2 — many TMS instances share one validator). A single
tenant degenerates to exact FIFO, preserving every historical ordering
guarantee. Exposed as ``serve_tenant_drains_total{tms_id}`` and the
``rpc_tenant_deficit`` gauge.

Dispatch policy per group — evaluated continuously by the service loop:

  - FULL:     queued rows reach ``max(buckets)`` -> dispatch a full
              bucket immediately (throughput mode);
  - WAIT-DUE: the oldest request has waited ``max_wait_s`` and at least
              ``min_batch`` rows are queued -> dispatch everything;
  - DEADLINE: the oldest request's ``deadline - service_estimate_s``
              instant has passed -> dispatch everything queued even
              below ``min_batch`` (a request is never held into a
              guaranteed miss to improve batch fill);
  - otherwise wait until ``next_event()``.

Deadline expiry is handled here too: ``expire()`` removes requests whose
deadline passed while queued so they complete with ``deadline_miss``
instead of occupying batch rows a verdict can no longer use.

All state is single-threaded by construction: only the service's event
loop touches the queues (the device call runs in an executor thread but
never sees the scheduler).
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque

from ..obs import GLOBAL as _METRICS
from .config import ServeConfig
from .request import KIND_RANGE, VerifyRequest

#: Batching groups, in priority order at assembly time: action batches
#: carry interactive HTLC/validate traffic more often than bulk ranges.
GROUPS = ("action", KIND_RANGE)


class _TenantDrrQueue:
    """Deficit-round-robin queue over per-tenant FIFOs.

    Deque-compatible for everything the scheduler and service do with a
    queue — ``append`` / ``extend`` / ``clear`` / ``len`` / iteration /
    ``q[0]`` / ``popleft`` — but ``popleft`` serves tenants by DRR:
    every rotation to the front of the ring grants a tenant
    ``tenant_quantum * weight`` rows of deficit; rows are served while
    the deficit lasts, then the drain rotates. A tenant whose sub-queue
    empties retires (deficit resets — the classic DRR rule that keeps
    idle tenants from banking service).

    Iteration and ``q[0]`` present rows in global arrival order
    (``enqueue_t``, then ``req_id``), so deadline horizons and the
    expiry sweep see the true oldest row regardless of drain order,
    and a single tenant is byte-for-byte the old FIFO.
    """

    def __init__(self, config: ServeConfig):
        self._quantum = float(config.tenant_quantum)
        self._weights = dict(config.tenant_weights)
        self._max_tenants = config.max_tenants
        self._qs: dict[str, deque] = {}
        self._ring: deque = deque()          # tenant rotation order
        self._deficit: dict[str, float] = {}
        self._granted: set = set()           # granted this front residence
        self._seen: OrderedDict[str, None] = OrderedDict()  # drain LRU
        self._len = 0

    # --------------------------------------------------- deque duck-type
    def append(self, req) -> None:
        tenant = getattr(req, "tenant", "default") or "default"
        q = self._qs.get(tenant)
        if q is None:
            q = self._qs[tenant] = deque()
            self._ring.append(tenant)
            self._deficit[tenant] = 0.0
        q.append(req)
        self._len += 1

    def extend(self, reqs) -> None:
        for req in reqs:
            self.append(req)

    def clear(self) -> None:
        self._qs.clear()
        self._ring.clear()
        self._deficit.clear()
        self._granted.clear()
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def __iter__(self):
        rows = [r for q in self._qs.values() for r in q]
        rows.sort(key=lambda r: (r.enqueue_t, r.req_id))
        return iter(rows)

    def __getitem__(self, idx: int):
        if idx != 0:
            raise IndexError("only the head (q[0]) is addressable")
        heads = [q[0] for q in self._qs.values() if q]
        if not heads:
            raise IndexError("head of empty queue")
        return min(heads, key=lambda r: (r.enqueue_t, r.req_id))

    # ------------------------------------------------------------- DRR
    def _retire(self, tenant: str) -> None:
        self._ring.remove(tenant)
        self._qs.pop(tenant, None)
        self._deficit.pop(tenant, None)
        self._granted.discard(tenant)
        # a retired tenant has nothing left to drain: its deficit gauge
        # would otherwise read a stale residue forever (the cardinality
        # leak this fixes) — drop the series; it re-registers on the
        # tenant's next drain
        _METRICS.remove_series("rpc_tenant_deficit", tms_id=tenant)

    def _note_drain(self, tenant: str) -> None:
        """LRU ledger of tenants with live ``serve_tenant_drains_total``
        series, bounded by ``ServeConfig.max_tenants``: past the bound
        the least-recently-drained tenant's series is evicted from the
        registry (a Prometheus counter reset if it ever returns)."""
        self._seen[tenant] = None
        self._seen.move_to_end(tenant)
        while len(self._seen) > self._max_tenants:
            gone, _ = self._seen.popitem(last=False)
            _METRICS.remove_series("serve_tenant_drains_total", tms_id=gone)
            _METRICS.remove_series("rpc_tenant_deficit", tms_id=gone)

    def popleft(self):
        if self._len == 0:
            raise IndexError("pop from empty queue")
        while True:
            tenant = self._ring[0]
            q = self._qs.get(tenant)
            if not q:
                self._retire(tenant)
                continue
            if self._deficit[tenant] >= 1.0:
                self._deficit[tenant] -= 1.0
                self._len -= 1
                req = q.popleft()
                if not q:
                    self._retire(tenant)
                else:
                    # tenant-bounded: removed on _retire and LRU-evicted
                    # past ServeConfig.max_tenants in _note_drain
                    _METRICS.gauge(
                        "rpc_tenant_deficit",
                        help="Deficit-round-robin rows a tenant may still "
                             "drain before rotating",
                        tms_id=tenant).set(self._deficit[tenant])
                # tenant-bounded: LRU-evicted past ServeConfig.max_tenants
                # in _note_drain
                _METRICS.counter(
                    "serve_tenant_drains_total",
                    help="Rows drained from the admission queues, by "
                         "tenant tms id (the DRR fairness ledger)",
                    tms_id=tenant).add()
                self._note_drain(tenant)
                return req
            if tenant in self._granted:
                # quantum exhausted this residence: rotate, keep residue
                self._granted.discard(tenant)
                self._ring.rotate(-1)
                continue
            self._granted.add(tenant)
            self._deficit[tenant] += (
                self._quantum * self._weights.get(tenant, 1.0))


class BucketScheduler:
    """Per-(group, lane) DRR tenant queues + the batch assembly decision."""

    def __init__(self, config: ServeConfig):
        self.config = config
        self._queues: dict[tuple, _TenantDrrQueue] = {
            (g, lane): _TenantDrrQueue(config)
            for g in GROUPS for lane in config.lanes}
        # device-lane assignment state: last-emission stamp per dispatch
        # lane index (pick_lane round-robins over the idle ones)
        self._lane_stamp: dict[int, int] = {}
        self._stamp = 0

    # ------------------------------------------------------- device lanes
    def pick_lane(self, idle: list[int]) -> int | None:
        """Device dispatch lane for the next emitted bucket: the least-
        recently-used of the currently idle lanes (round-robin when all
        are fresh), so consecutive batches spread across every device
        instead of re-feeding lane 0. Returns None when no lane is idle
        — the service then sleeps until a lane completes."""
        if not idle:
            return None
        lane = min(idle, key=lambda i: (self._lane_stamp.get(i, -1), i))
        self._stamp += 1
        self._lane_stamp[lane] = self._stamp
        return lane

    # ------------------------------------------------------------- queues
    def push(self, req: VerifyRequest) -> None:
        self._queues[(req.group, req.lane)].append(req)
        self._gauge(req.lane)

    def lane_depth(self, lane: str) -> int:
        return sum(len(q) for (g, ln), q in self._queues.items()
                   if ln == lane)

    def depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def tenant_status(self) -> dict:
        """Per-tenant queue view for /tenantz: rows currently queued and
        DRR deficit residue, summed over every (group, lane) queue."""
        out: dict[str, dict] = {}
        for q in self._queues.values():
            for tenant, sub in q._qs.items():
                row = out.setdefault(tenant, {"queued": 0, "deficit": 0.0})
                row["queued"] += len(sub)
                row["deficit"] += q._deficit.get(tenant, 0.0)
        return out

    def _gauge(self, lane: str) -> None:
        _METRICS.gauge(
            "serve_queue_depth",
            help="Queued requests per lane (admitted, not yet dispatched)",
            lane=lane).set(self.lane_depth(lane))

    # ------------------------------------------------------------- expiry
    def expire(self, now: float | None = None) -> list[VerifyRequest]:
        """Pop every queued request whose deadline has already passed."""
        now = time.perf_counter() if now is None else now
        out: list[VerifyRequest] = []
        for (g, lane), q in self._queues.items():
            if not q or all(r.deadline > now for r in q):
                continue
            out.extend(r for r in q if r.deadline <= now)
            live = [r for r in q if r.deadline > now]
            q.clear()
            q.extend(live)
            self._gauge(lane)
        return out

    # ----------------------------------------------------------- assembly
    def _group_rows(self, group: str) -> int:
        return sum(len(self._queues[(group, lane)])
                   for lane in self.config.lanes)

    def _due_instants(self, group: str) -> tuple[float, float] | None:
        """(wait_due, deadline_due) over the group's queue heads, or
        None when the group is empty. wait_due is the max-wait horizon
        (gated by min_batch at decision time); deadline_due is the
        instant deadline pressure forces dispatch regardless of fill."""
        cfg = self.config
        heads = [q[0] for lane in cfg.lanes
                 for q in (self._queues[(group, lane)],) if q]
        if not heads:
            return None
        return (min(r.enqueue_t + cfg.max_wait_s for r in heads),
                min(r.deadline - cfg.service_estimate_s for r in heads))

    def next_event(self, now: float | None = None,
                   include_dispatch: bool = True) -> float | None:
        """Earliest future instant a dispatch or expiry becomes due, or
        None when nothing is queued (the service sleeps until a push).

        ``include_dispatch=False`` restricts the horizon to deadline
        EXPIRY instants only — what the service needs while every
        dispatch lane is busy (a dispatch-due instant in the past would
        otherwise hot-spin the loop until a lane frees)."""
        instants = []
        for g in GROUPS if include_dispatch else ():
            due = self._due_instants(g)
            if due is None:
                continue
            wait_due, deadline_due = due
            if self._group_rows(g) >= self.config.min_batch:
                instants.append(min(wait_due, deadline_due))
            else:
                instants.append(deadline_due)
        for q in self._queues.values():
            if q:
                instants.append(min(r.deadline for r in q))
        return min(instants) if instants else None

    def assemble(self, now: float | None = None) -> list[VerifyRequest]:
        """Pop the next due batch (possibly empty when nothing is due).

        Priority lanes drain first; the batch never exceeds
        ``max(buckets)`` rows and never mixes groups.
        """
        now = time.perf_counter() if now is None else now
        cfg = self.config
        for group in GROUPS:
            rows = self._group_rows(group)
            if rows == 0:
                continue
            wait_due, deadline_due = self._due_instants(group)
            full = rows >= cfg.max_batch
            waited = rows >= cfg.min_batch and now >= wait_due
            forced = now >= deadline_due
            if not (full or waited or forced):
                continue
            batch: list[VerifyRequest] = []
            for lane in cfg.lanes:           # interactive first
                q = self._queues[(group, lane)]
                while q and len(batch) < cfg.max_batch:
                    batch.append(q.popleft())
                self._gauge(lane)
            bucket = cfg.bucket_for(len(batch))
            _METRICS.histogram(
                "serve_batch_fill_ratio",
                help="Live rows / covering bucket, per dispatched batch",
                group=group).observe(len(batch) / bucket)
            _METRICS.histogram(
                "serve_batch_rows",
                help="Live rows per dispatched batch",
                group=group).observe(len(batch))
            return batch
        return []
