"""serve/ — continuous-batching verification service.

Inference-serving techniques applied to ZK verification: an async
frontend accepts individual proof/action verification requests, an
admission controller bounds the queues, a deadline-aware scheduler
assembles pow-2-bucketed batches (priority lanes, max-wait and deadline
triggers), a deterministic prewarm manager compiles every emittable
bucket shape at startup, and the dispatcher demultiplexes per-request
verdicts bit-identically to the unbatched path. See README "Serving".
"""

from .admission import AdmissionController, TenantShedPolicy
from .columnar import (FMT_OPAQUE, FMT_RANGE, ColumnarBatch, ColumnarError,
                       ResultBatch, decode_result_batch,
                       decode_submit_batch, encode_result_batch,
                       encode_submit_batch, materialize_rows)
from .config import LANE_BULK, LANE_INTERACTIVE, LANES, ServeConfig
from .prewarm import PrewarmManager
from .request import (ACTION_KINDS, KIND_ISSUE, KIND_RANGE, KIND_TRANSFER,
                      SERVED_BY_DEVICE, SERVED_BY_HOST,
                      STATUS_DEADLINE_MISS, STATUS_ERROR, STATUS_OK,
                      STATUS_SHED_DEADLINE, STATUS_SHED_QUEUE_FULL,
                      STATUS_SHED_TENANT_SLO, STATUS_SHUTDOWN,
                      VerifyRequest, VerifyResult)
from .rpc import FrameError, RpcConfig, RpcServer, ScratchPool
from .rpc_client import BatchSubmitBuffer, RpcClient
from .scheduler import GROUPS, BucketScheduler
from .service import VerificationService
from .sidecar import RpcSidecar, pick_free_port, sidecar_main
from .wal import WalConfig, WalEntry, WriteAheadLog
from .worker import StubZK, WorkerClient, WorkerUnavailable, worker_main

__all__ = [
    "AdmissionController",
    "ACTION_KINDS",
    "BatchSubmitBuffer",
    "BucketScheduler",
    "ColumnarBatch",
    "ColumnarError",
    "FMT_OPAQUE",
    "FMT_RANGE",
    "FrameError",
    "GROUPS",
    "KIND_ISSUE",
    "KIND_RANGE",
    "KIND_TRANSFER",
    "LANE_BULK",
    "LANE_INTERACTIVE",
    "LANES",
    "PrewarmManager",
    "ResultBatch",
    "RpcClient",
    "RpcConfig",
    "RpcServer",
    "RpcSidecar",
    "SERVED_BY_DEVICE",
    "SERVED_BY_HOST",
    "ServeConfig",
    "STATUS_DEADLINE_MISS",
    "STATUS_ERROR",
    "STATUS_OK",
    "STATUS_SHED_DEADLINE",
    "STATUS_SHED_QUEUE_FULL",
    "STATUS_SHED_TENANT_SLO",
    "STATUS_SHUTDOWN",
    "ScratchPool",
    "StubZK",
    "TenantShedPolicy",
    "VerificationService",
    "VerifyRequest",
    "VerifyResult",
    "WalConfig",
    "WalEntry",
    "WorkerClient",
    "WorkerUnavailable",
    "WriteAheadLog",
    "decode_result_batch",
    "decode_submit_batch",
    "encode_result_batch",
    "encode_submit_batch",
    "materialize_rows",
    "pick_free_port",
    "sidecar_main",
    "worker_main",
]
