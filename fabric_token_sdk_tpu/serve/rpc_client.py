"""Reconnecting TCP client for the RPC sidecar (``serve/rpc.py``).

Matches the ``WorkerClient`` duck-type — ``_range.verify``,
``verify_block``, ``prewarm_shapes``, ``wait_ready``, ``stop``, ``pp``
— so a ``VerificationService`` (or the crash bench) can point at a
network sidecar instead of a pipe worker without changing anything
else. Every transport failure surfaces as
``WorkerUnavailable(TransientError)``, so traffic degrades onto the
existing retry→breaker→watchdog→``HostFallbackVerifier`` ladder while
the ``Supervisor`` respawns the sidecar process.

Mechanics:

  - **Reconnect under crash**: dialing rides
    ``resilience.RetryPolicy`` — decorrelated-jitter redial with a
    bounded attempt ladder, counted by ``rpc_redials_total{outcome}``.
    A dead or GOAWAY'd connection is replaced on the next call.
  - **Pipelined, not single-flight**: a background reader thread
    demultiplexes RESULT frames to per-request slots by ``req_id``, so
    concurrent callers share one connection without serializing behind
    one slow reply (the pipe ``WorkerClient`` is single-flight; see
    its ``_call`` docstring).
  - **Credit flow control**: SUBMITs spend row credits granted by the
    server (WELCOME + CREDIT frames). When the sidecar's lanes fill,
    credits dry up and callers stall here — counted by
    ``rpc_credit_waits_total`` — instead of stuffing the socket.
  - **Deadline propagation**: the HELLO/WELCOME (and PING/PONG)
    exchange measures RTT and a clock offset; each SUBMIT carries an
    absolute server-clock deadline of ``now + budget - RTT/2``, so the
    server sheds already-expired work at decode.
  - **Hedged sends** (optional): with ``hedge_after_s`` set,
    interactive-lane calls that wait longer than the hedge threshold
    send a duplicate SUBMIT under a fresh req_id; first reply wins
    (verdicts are deterministic, so duplicates are parity-safe).
  - **Columnar batch submit**: against a v2 server (WELCOME advertises
    ``batch: true``) ``submit_range_batch`` ships N rows as ONE
    SUBMIT_BATCH frame — contiguous limb planes, no per-row pickling —
    answered by one RESULT. ``prefer_batch=True`` routes the
    ``_range.verify`` duck-type through it automatically, and
    :class:`BatchSubmitBuffer` coalesces single-row submits into
    frames under row/byte/delay flush triggers. Credits account in
    rows either way, so backpressure is format-blind; a v1 server
    silently keeps the legacy per-request path (wire-compatible).
  - **Columnar result demux**: a v4 server answers flat range verdicts
    with columnar RESULT_BATCH frames that may interleave rows from
    many in-flight requests. The reader thread decodes each frame once
    (numpy views, zero per-row pickle) and accumulates rows per
    ``req_id`` until a request's full row count arrived, then resolves
    its slot with a legacy-shaped reply — callers cannot tell the
    formats apart. Non-OK replies and block verdicts still arrive as
    pickled RESULT frames from every server version.
"""

from __future__ import annotations

import concurrent.futures
import itertools
import socket
import threading
import time

import numpy as np

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..resilience import RetryPolicy
from .columnar import (FMT_OPAQUE, FMT_RANGE, ColumnarError,
                       decode_result_batch, encode_submit_batch,
                       opaque_cells, range_cells)
from .config import LANE_BULK, LANE_INTERACTIVE
from .rpc import (CREDIT, DEFAULT_MAX_FRAME, FLAG_TRACE_CONTEXT,
                  FRAME_NAMES, GOAWAY, HELLO, PING, PONG, RESULT,
                  RESULT_BATCH, RPC_OK, RPC_VERSION, SUBMIT, SUBMIT_BATCH,
                  WELCOME, FrameError, _describe, recv_frame_sock,
                  send_frame_sock, send_raw_frame_sock)
from .worker import _REMOTE_TRANSIENT_NAMES, WorkerUnavailable


class _Slot:
    """One pending request: first RESULT (of possibly hedged pair) wins."""

    __slots__ = ("event", "reply")

    def __init__(self):
        self.event = threading.Event()
        self.reply = None

    def resolve(self, body: dict) -> None:
        if self.reply is None:
            self.reply = body
        self.event.set()


class _BatchAcc:
    """Accumulates RESULT_BATCH rows for one req_id.

    A request's verdict rows may arrive split across several frames
    (the server coalesces per drain cycle, not per request); rows for
    OTHER requests may share each frame. ``absorb`` returns True once
    all ``n`` distinct rows landed; duplicate rows (hedged sends) are
    idempotent — the status cell doubles as the fill marker, since row
    statuses are never None while verdicts legitimately are."""

    __slots__ = ("n", "statuses", "verdicts", "served", "got", "tc")

    def __init__(self, n: int):
        self.n = n
        self.statuses: list = [None] * n
        self.verdicts: list = [None] * n
        self.served: set = set()
        self.got = 0
        self.tc = None

    def absorb(self, row_idx: int, status: str, verdict, served: str,
               tc) -> bool:
        if 0 <= row_idx < self.n and self.statuses[row_idx] is None:
            self.statuses[row_idx] = status
            self.verdicts[row_idx] = verdict
            if served:
                self.served.add(served)
            if tc is not None and self.tc is None:
                self.tc = tc
            self.got += 1
        return self.got >= self.n

    def reply(self, req_id: int) -> dict:
        """Legacy-shaped reply dict — ``_classify`` can't tell it from
        a pickled RESULT body."""
        body = {"req_id": req_id, "status": RPC_OK,
                "statuses": self.statuses, "verdicts": self.verdicts,
                "served_by": sorted(self.served)}
        if self.tc is not None:
            body["tc"] = self.tc
        return body


class _RpcRange:
    """``zk._range.verify`` facade over the wire."""

    def __init__(self, client: "RpcClient"):
        self._client = client

    def verify(self, proofs, coms):
        return self._client.submit_range(proofs, coms)


class RpcClient:
    """Reconnecting, pipelined client for one RPC sidecar address."""

    def __init__(self, address, *, pp=None, tms_id: str = "default",
                 call_timeout_s: float = 120.0,
                 connect_timeout_s: float = 5.0,
                 tick_s: float = 0.25,
                 frame_timeout_s: float = 30.0,
                 credit_wait_s: float = 30.0,
                 hedge_after_s: float | None = None,
                 redial_attempts: int = 4,
                 redial_base_s: float = 0.05,
                 redial_cap_s: float = 1.0,
                 seed: int = 0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME,
                 name: str = "rpc-client",
                 prefer_batch: bool = False,
                 provider=None, tracer=None):
        self.address = (str(address[0]), int(address[1]))
        self.pp = pp
        self.tms_id = tms_id
        self.call_timeout_s = call_timeout_s
        self.connect_timeout_s = connect_timeout_s
        self.tick_s = tick_s
        self.frame_timeout_s = frame_timeout_s
        self.credit_wait_s = credit_wait_s
        self.hedge_after_s = hedge_after_s
        self.max_frame_bytes = max_frame_bytes
        self.name = name
        #: Route ``submit_range`` through the columnar SUBMIT_BATCH path
        #: whenever the server advertises it (v1 servers keep legacy).
        self.prefer_batch = prefer_batch
        #: WELCOME capabilities of the current connection.
        self.server_version = 1
        self.server_batch = False
        self.server_trace = False
        self.provider = provider or _METRICS
        self.tracer = tracer or _TRACER
        _describe(self.provider)
        self._redial = RetryPolicy(
            max_attempts=redial_attempts, base_s=redial_base_s,
            cap_s=redial_cap_s, seed=seed, op=f"rpc_dial_{name}")
        self._range = _RpcRange(self)
        self._dial_lock = threading.Lock()   # one redial ladder at a time
        self._send_lock = threading.Lock()   # frame writes are atomic
        self._cv = threading.Condition()     # credits + pending + liveness
        self._pending: dict[int, _Slot] = {}
        # RESULT_BATCH demux (v4 servers): expected row count per
        # req_id, registered at submit; row accumulators, created on
        # first row — both guarded by _cv alongside _pending
        self._expected_rows: dict[int, int] = {}
        self._accs: dict[int, _BatchAcc] = {}
        self._pong_waiters: list[threading.Event] = []
        self._req_ids = itertools.count(1)
        self._sock = None
        self._reader: threading.Thread | None = None
        self._gen = 0                        # invalidates stale readers
        self._dead = True
        self._goaway = False
        self._closed = False
        self._credits = 0
        self.rtt_s = 0.0
        self.clock_offset_s = 0.0            # server clock minus ours

    # ----------------------------------------------------------- transport
    def _dial(self) -> None:
        """One connect + HELLO/WELCOME handshake (RTT + clock offset)."""
        self._gen += 1
        gen = self._gen
        old = self._sock
        self._sock = None
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        sock = socket.create_connection(
            self.address, timeout=self.connect_timeout_s)
        try:
            sock.settimeout(self.tick_s)
            t0 = time.time()
            send_frame_sock(sock, HELLO,
                            {"tms_id": self.tms_id, "t": t0,
                             "v": RPC_VERSION},
                            self.max_frame_bytes)
            deadline = time.monotonic() + self.connect_timeout_s
            while True:
                if time.monotonic() >= deadline:
                    raise FrameError("slow_frame", "WELCOME never arrived")
                try:
                    frame = recv_frame_sock(
                        sock, max_frame_bytes=self.max_frame_bytes,
                        body_timeout_s=self.connect_timeout_s)
                except TimeoutError:
                    continue
                break
            if frame is None or frame[0] != WELCOME:
                raise FrameError("protocol", "expected WELCOME")
        except BaseException:
            sock.close()
            raise
        welcome = frame[1]
        t1 = time.time()
        # capability negotiation: a v1 server omits these keys and the
        # client keeps the legacy per-request SUBMIT path; only a v3
        # server (``trace: true``) receives trace-context bytes
        self.server_version = int(welcome.get("v", 1))
        self.server_batch = bool(welcome.get("batch", False))
        self.server_trace = bool(welcome.get("trace", False))
        self.rtt_s = max(0.0, t1 - t0)
        self.clock_offset_s = welcome.get("t_srv", t1) - (
            t0 + self.rtt_s / 2.0)
        with self._cv:
            self._sock = sock
            self._dead = False
            self._goaway = False
            self._credits = int(welcome.get("credits", 0))
            self._cv.notify_all()
        self._count_frame("sent", HELLO)
        self._count_frame("recv", WELCOME)
        reader = threading.Thread(
            target=self._read_loop, args=(sock, gen),
            name=f"{self.name}-reader", daemon=True)
        self._reader = reader
        reader.start()

    def _ensure_conn(self) -> None:
        """Redial ladder (decorrelated jitter) until connected or out of
        attempts; raises ``WorkerUnavailable`` so the resilience ladder
        takes over."""
        with self._dial_lock:
            if self._closed:
                raise WorkerUnavailable(f"{self.name} is closed")
            if self._sock is not None and not self._dead \
                    and not self._goaway:
                return
            last: Exception | None = None
            delays = self._redial.delays()
            for attempt in range(self._redial.max_attempts):
                if attempt:
                    self._redial.pause(next(delays))
                try:
                    self._dial()
                    self.provider.counter(
                        "rpc_redials_total", outcome="ok").add()
                    return
                except (OSError, ConnectionError, TimeoutError,
                        FrameError) as exc:
                    last = exc
                    self.provider.counter(
                        "rpc_redials_total", outcome="error").add()
            raise WorkerUnavailable(
                f"rpc dial {self.address[0]}:{self.address[1]} failed "
                f"after {self._redial.max_attempts} attempts: {last!r}")

    def _conn_lost(self, gen: int, why: str) -> None:
        """Fail every pending call on this generation — callers raise
        ``WorkerUnavailable`` and the parent ladder retries/falls back."""
        with self._cv:
            if gen != self._gen and not self._closed:
                return  # a newer dial already superseded this conn
            self._dead = True
            pending, self._pending = self._pending, {}
            self._expected_rows.clear()
            self._accs.clear()
            self._cv.notify_all()
        for slot in pending.values():
            slot.resolve({"status": "transport", "error": why})

    def _read_loop(self, sock, gen: int) -> None:
        while not self._closed and gen == self._gen:
            try:
                frame = recv_frame_sock(
                    sock, max_frame_bytes=self.max_frame_bytes,
                    body_timeout_s=self.frame_timeout_s)
            except TimeoutError:
                continue  # idle tick: re-check stop/generation flags
            except (FrameError, OSError, ConnectionError) as exc:
                if isinstance(exc, FrameError):
                    self.provider.counter(
                        "rpc_frame_errors_total", kind=exc.kind).add()
                self._conn_lost(gen, repr(exc))
                return
            if frame is None:
                self._conn_lost(gen, "server closed connection")
                return
            ftype, body, _flags = frame
            self._count_frame("recv", ftype)
            if ftype == RESULT:
                with self._cv:
                    slot = self._pending.pop(body.get("req_id"), None)
                if slot is not None:
                    slot.resolve(body)
            elif ftype == RESULT_BATCH:
                if not self._absorb_result_batch(body):
                    self._conn_lost(gen, "undecodable RESULT_BATCH")
                    return
            elif ftype == CREDIT:
                with self._cv:
                    self._credits += int(body.get("grant", 0))
                    self._cv.notify_all()
            elif ftype == GOAWAY:
                self.provider.counter(
                    "rpc_goaways_total", role="client").add()
                with self._cv:
                    self._goaway = True
                    self._cv.notify_all()
            elif ftype == PONG:
                t0 = body.get("t")
                if isinstance(t0, float):
                    t1 = time.time()
                    self.rtt_s = max(0.0, t1 - t0)
                    self.clock_offset_s = body.get("t_srv", t1) - (
                        t0 + self.rtt_s / 2.0)
                with self._cv:
                    waiters, self._pong_waiters = self._pong_waiters, []
                for ev in waiters:
                    ev.set()

    def _absorb_result_batch(self, payload: bytes) -> bool:
        """Demux one columnar RESULT_BATCH frame into pending slots.

        One decode per frame — every column is a numpy view, zero
        per-row pickle. Rows whose req_id is unknown (stale generation,
        already-resolved hedge twin) are dropped silently, same as an
        unknown-req_id RESULT. Returns False only on an undecodable
        frame, which poisons the connection like a torn pickled frame.
        """
        try:
            batch = decode_result_batch(payload)
        except ColumnarError as exc:
            self.provider.counter(
                "rpc_frame_errors_total", kind=exc.kind).add()
            return False
        self.provider.counter("rpc_result_batch_frames_total",
                              role="client").add()
        self.provider.counter("rpc_result_batch_rows_total",
                              role="client").add(batch.n_rows)
        self.provider.counter("rpc_result_batch_bytes_total",
                              role="client").add(batch.nbytes)
        done = []
        with self._cv:
            for i in range(batch.n_rows):
                req_id = int(batch.req_id[i])
                acc = self._accs.get(req_id)
                if acc is None:
                    n = self._expected_rows.get(req_id)
                    if n is None:
                        continue
                    acc = self._accs[req_id] = _BatchAcc(n)
                if acc.absorb(int(batch.row_idx[i]), batch.status(i),
                              batch.verdict_value(i), batch.served(i),
                              batch.trace_cell(i)):
                    self._accs.pop(req_id, None)
                    self._expected_rows.pop(req_id, None)
                    slot = self._pending.pop(req_id, None)
                    if slot is not None:
                        done.append((slot, acc.reply(req_id)))
        for slot, reply in done:
            slot.resolve(reply)
        return True

    def _count_frame(self, direction: str, ftype: int) -> None:
        self.provider.counter(
            "rpc_frames_total", role="client", dir=direction,
            type=FRAME_NAMES.get(ftype, str(ftype))).add()

    # ------------------------------------------------------------- credits
    def _acquire_credits(self, rows: int, deadline_mono: float) -> None:
        cap = time.monotonic() + self.credit_wait_s
        credit_deadline = min(deadline_mono, cap)
        with self._cv:
            if self._credits >= rows:
                self._credits -= rows
                return
            self.provider.counter("rpc_credit_waits_total").add()
            while True:
                remaining = credit_deadline - time.monotonic()
                if remaining <= 0:
                    raise WorkerUnavailable(
                        f"rpc backpressure: {rows} credits not granted "
                        f"within budget (held {self._credits})")
                self._cv.wait(timeout=min(remaining, self.tick_s))
                if self._dead or self._goaway or self._closed:
                    raise WorkerUnavailable(
                        "connection lost while awaiting credits")
                if self._credits >= rows:
                    self._credits -= rows
                    return

    def _try_acquire_credits(self, rows: int) -> bool:
        with self._cv:
            if self._credits >= rows:
                self._credits -= rows
                return True
            return False

    # ---------------------------------------------------------------- call
    def _wire_deadline(self, budget_s: float) -> float:
        """Absolute server-clock deadline: now + budget - RTT/2."""
        return time.time() + budget_s - self.rtt_s / 2.0 \
            + self.clock_offset_s

    def _send_submit(self, body: dict) -> None:
        with self._cv:
            sock = self._sock
            dead = self._dead
        if sock is None or dead:
            raise WorkerUnavailable("rpc connection lost before send")
        try:
            with self._send_lock:
                send_frame_sock(sock, SUBMIT, body, self.max_frame_bytes)
        except (OSError, ConnectionError, FrameError) as exc:
            self._conn_lost(self._gen, repr(exc))
            raise WorkerUnavailable(f"rpc send failed: {exc!r}") from exc
        self._count_frame("sent", SUBMIT)

    def _send_batch(self, payload: bytes, rows: int) -> None:
        with self._cv:
            sock = self._sock
            dead = self._dead
        if sock is None or dead:
            raise WorkerUnavailable("rpc connection lost before send")
        # columnar payloads are raw bytes, so the trace context rides as
        # a flagged 17-byte prefix instead of a dict key
        flags = 0
        sp = self.tracer.current()
        if sp is not None and self.server_trace:
            payload = sp.context().to_bytes() + payload
            flags = FLAG_TRACE_CONTEXT
        try:
            with self._send_lock:
                send_raw_frame_sock(sock, SUBMIT_BATCH, payload,
                                    self.max_frame_bytes, flags)
        except (OSError, ConnectionError, FrameError) as exc:
            self._conn_lost(self._gen, repr(exc))
            raise WorkerUnavailable(f"rpc send failed: {exc!r}") from exc
        self._count_frame("sent", SUBMIT_BATCH)
        self.provider.counter("rpc_batch_frames_total", role="client",
                              tms=self.tms_id).add()
        self.provider.counter("rpc_batch_rows_total", role="client",
                              tms=self.tms_id).add(rows)
        self.provider.counter("rpc_batch_bytes_total", role="client",
                              tms=self.tms_id).add(len(payload))

    def _observe_call(self, kind: str, seconds: float, span=None) -> None:
        """Observe ``rpc_call_seconds`` with the call span's trace id
        attached as an exemplar, so a slow bucket resolves to a concrete
        fleet trace (``span_exemplars_total`` counts the attachments)."""
        exemplar = None
        if span is not None and span.sampled:
            exemplar = {"trace_id": f"{span.trace_id:016x}"}
            self.provider.counter("span_exemplars_total",
                                  family="rpc_call_seconds").add()
        self.provider.histogram("rpc_call_seconds", kind=kind).observe(
            seconds, exemplar=exemplar)

    def _call(self, kind: str, payload, rows: int, *,
              lane: str = LANE_BULK, deadline_s: float | None = None):
        budget = deadline_s if deadline_s is not None else self.call_timeout_s
        t_start = time.perf_counter()
        with self.tracer.span("rpc.call", kind=kind, rows=rows,
                              lane=lane) as sp:
            try:
                return self._call_once(kind, payload, rows, lane, budget)
            finally:
                self._observe_call(kind, time.perf_counter() - t_start,
                                   span=sp)

    def _call_once(self, kind, payload, rows, lane, budget):
        self._ensure_conn()
        deadline_mono = time.monotonic() + budget
        self._acquire_credits(rows, deadline_mono)
        slot = _Slot()
        req_id = next(self._req_ids)
        body = {"req_id": req_id, "kind": kind, "lane": lane,
                "tms_id": self.tms_id, "rows": rows,
                "deadline": self._wire_deadline(budget),
                "payload": payload}
        # inject the open rpc.call span's context so the sidecar's
        # rpc.serve / serve.request spans join this trace (v3 servers
        # only; older servers never see the key)
        sp = self.tracer.current()
        if sp is not None and self.server_trace:
            body["tc"] = sp.context().to_bytes()
        hedge_id = None
        # flat range verdicts may come back columnar from a v4 server:
        # pre-register the expected row count so the reader can tell
        # when the request's rows are complete
        demux = kind == "range" and self.server_version >= 4
        with self._cv:
            self._pending[req_id] = slot
            if demux:
                self._expected_rows[req_id] = rows
        try:
            self._send_submit(body)
            hedge = (self.hedge_after_s is not None
                     and lane == LANE_INTERACTIVE)
            if hedge:
                first_wait = min(self.hedge_after_s,
                                 deadline_mono - time.monotonic())
                if not slot.event.wait(timeout=max(0.0, first_wait)) \
                        and self._try_acquire_credits(rows):
                    hedge_id = next(self._req_ids)
                    with self._cv:
                        self._pending[hedge_id] = slot
                        if demux:
                            self._expected_rows[hedge_id] = rows
                    self.provider.counter("rpc_hedges_total").add()
                    self._send_submit(dict(body, req_id=hedge_id))
            remaining = deadline_mono - time.monotonic()
            if not slot.event.wait(timeout=max(0.0, remaining)):
                raise WorkerUnavailable(
                    f"rpc {kind} call timed out after {budget:.3f}s")
        finally:
            with self._cv:
                for rid in (req_id, hedge_id):
                    if rid is not None:
                        self._pending.pop(rid, None)
                        self._expected_rows.pop(rid, None)
                        self._accs.pop(rid, None)
        return self._classify(kind, slot.reply)

    def _classify(self, kind: str, reply: dict):
        status = reply.get("status")
        if status == RPC_OK:
            return self._unpack(kind, reply)
        error = reply.get("error", "")
        if status == "error":
            # same split the pipe WorkerClient applies to remote errors
            type_name = reply.get("error_type", "")
            if type_name in _REMOTE_TRANSIENT_NAMES \
                    or type_name.endswith("TransientError"):
                raise WorkerUnavailable(
                    f"sidecar error ({type_name}): {error}")
            raise RuntimeError(f"sidecar {type_name}: {error}")
        # expired / goaway / transport — all transient by construction
        raise WorkerUnavailable(f"rpc {kind} {status}: {error}")

    def _unpack(self, kind: str, reply: dict):
        if kind == "range":
            verdicts = reply["verdicts"]
            if any(v is None for v in verdicts):
                raise WorkerUnavailable(
                    "sidecar shed rows: "
                    f"{sorted(set(reply['statuses']))}")
            return np.asarray(verdicts, dtype=bool)
        t_v, i_v = reply["verdicts"]
        if any(v is None for v in t_v) or any(v is None for v in i_v):
            t_st, i_st = reply["statuses"]
            raise WorkerUnavailable(
                f"sidecar shed rows: {sorted(set(t_st) | set(i_st))}")
        return (np.asarray(t_v, dtype=bool), np.asarray(i_v, dtype=bool))

    # ------------------------------------------------------ batch submit
    def submit_range_batch(self, proofs, coms, *, lane: str = LANE_BULK,
                           deadline_s: float | None = None,
                           bits=None, flags=None, deadline_off_us=None,
                           fmt: int | None = None):
        """Ship N rows as ONE columnar SUBMIT_BATCH frame.

        ``fmt`` defaults to :data:`FMT_RANGE` when the proofs carry a
        ``serialize`` method (real RangeProof objects) and
        :data:`FMT_OPAQUE` otherwise (stub truth values). Against a v1
        server the call transparently degrades to the legacy pickled
        SUBMIT — same verdict vector, N-row frame cost.
        """
        proofs = list(proofs)
        coms = list(coms)
        n = len(proofs)
        budget = (deadline_s if deadline_s is not None
                  else self.call_timeout_s)
        t_start = time.perf_counter()
        with self.tracer.span("rpc.call", kind="range_batch", rows=n,
                              lane=lane) as sp:
            try:
                return self._call_batch_once(
                    proofs, coms, n, lane, budget, bits, flags,
                    deadline_off_us, fmt)
            finally:
                self._observe_call("range_batch",
                                   time.perf_counter() - t_start, span=sp)

    def _call_batch_once(self, proofs, coms, n, lane, budget, bits,
                         flags, deadline_off_us, fmt):
        self._ensure_conn()
        if not self.server_batch:
            return self._call_once("range", (proofs, coms), n, lane,
                                   budget)
        if fmt is None:
            fmt = (FMT_RANGE if n and hasattr(proofs[0], "serialize")
                   else FMT_OPAQUE)
        if fmt == FMT_RANGE:
            proof_cells, com_cells = range_cells(proofs, coms)
        else:
            proof_cells, com_cells = opaque_cells(proofs), None
        deadline_mono = time.monotonic() + budget
        # one frame debits n row credits — backpressure is format-blind
        self._acquire_credits(n, deadline_mono)
        slot = _Slot()
        req_id = next(self._req_ids)
        payload = encode_submit_batch(
            fmt=fmt, lane=lane, req_id_base=req_id,
            deadline=self._wire_deadline(budget),
            proof_cells=proof_cells, com_cells=com_cells, bits=bits,
            flags=flags, deadline_off_us=deadline_off_us)
        with self._cv:
            self._pending[req_id] = slot
            if self.server_version >= 4:
                self._expected_rows[req_id] = n
        try:
            self._send_batch(payload, n)
            remaining = deadline_mono - time.monotonic()
            if not slot.event.wait(timeout=max(0.0, remaining)):
                raise WorkerUnavailable(
                    f"rpc range_batch call timed out after {budget:.3f}s")
        finally:
            with self._cv:
                self._pending.pop(req_id, None)
                self._expected_rows.pop(req_id, None)
                self._accs.pop(req_id, None)
        return self._classify("range", slot.reply)

    # ------------------------------------------------------- zk duck-type
    def submit_range(self, proofs, coms, *, lane: str = LANE_BULK,
                     deadline_s: float | None = None):
        proofs = list(proofs)
        coms = list(coms)
        if self.prefer_batch:
            self._ensure_conn()
            if self.server_batch:
                return self.submit_range_batch(proofs, coms, lane=lane,
                                               deadline_s=deadline_s)
        return self._call("range", (proofs, coms), len(proofs),
                          lane=lane, deadline_s=deadline_s)

    def verify_block(self, transfers, issues, *, lane: str = LANE_BULK,
                     deadline_s: float | None = None):
        transfers = [tuple(t) for t in transfers]
        issues = [tuple(i) for i in issues]
        rows = max(1, len(transfers) + len(issues))
        return self._call("block", (transfers, issues), rows,
                          lane=lane, deadline_s=deadline_s)

    def prewarm_shapes(self, buckets, include_block: bool = False):
        """The sidecar prewarms its own shapes at boot; here this is a
        readiness gate: one ping round-trip per call."""
        self.wait_ready(timeout_s=self.call_timeout_s)
        return {int(b): 0.0 for b in buckets}

    # ----------------------------------------------------------- liveness
    def ping(self, timeout_s: float = 5.0) -> bool:
        """One PING/PONG round-trip on the current connection."""
        ev = threading.Event()
        with self._cv:
            sock = self._sock
            if sock is None or self._dead:
                return False
            self._pong_waiters.append(ev)
        try:
            with self._send_lock:
                send_frame_sock(sock, PING, {"t": time.time()},
                                self.max_frame_bytes)
        except (OSError, ConnectionError, FrameError):
            return False
        self._count_frame("sent", PING)
        return ev.wait(timeout=timeout_s)

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        """Block until a dial + ping round-trip succeeds."""
        deadline = time.monotonic() + timeout_s
        last = "never attempted"
        while time.monotonic() < deadline:
            try:
                self._ensure_conn()
                if self.ping(timeout_s=min(
                        5.0, max(0.1, deadline - time.monotonic()))):
                    return
                last = "ping timed out"
            except WorkerUnavailable as exc:
                last = str(exc)
            time.sleep(min(0.2, self.tick_s))
        raise WorkerUnavailable(
            f"rpc sidecar not ready within {timeout_s}s: {last}")

    def alive(self) -> bool:
        with self._cv:
            return self._sock is not None and not self._dead

    # -------------------------------------------------------------- close
    def close(self) -> None:
        self._closed = True
        with self._cv:
            sock = self._sock
            self._sock = None
            self._gen += 1
            self._cv.notify_all()
        if sock is not None:
            try:
                with self._send_lock:
                    send_frame_sock(sock, GOAWAY, {"reason": "client close"},
                                    self.max_frame_bytes)
            except (OSError, ConnectionError, FrameError):
                pass
            try:
                sock.close()
            except OSError:
                pass
        reader = self._reader
        if reader is not None and reader.is_alive():
            reader.join(timeout=2 * self.tick_s)
        self._conn_lost(self._gen, "client closed")

    def stop(self, timeout_s: float = 5.0) -> None:
        """``WorkerClient.stop`` duck-type alias."""
        del timeout_s
        self.close()


class BatchSubmitBuffer:
    """Client-side coalescing buffer: single-row submits accumulate and
    leave as ONE columnar SUBMIT_BATCH frame.

    ``add(proof, com)`` returns a ``concurrent.futures.Future`` that
    resolves to the row's bool verdict. A flush fires when any trigger
    trips: ``max_rows`` rows buffered, ``max_bytes`` of estimated
    payload, or ``max_delay_s`` since the oldest buffered row (a timer,
    so a trickle of single rows still ships promptly). Flushes run on a
    small private pool so ``add`` never blocks on the wire; row order
    within a frame is arrival order.

    This is how corpus replay and bench traffic ride batch frames
    without restructuring their per-proof loops.
    """

    def __init__(self, client: RpcClient, *, max_rows: int = 256,
                 max_bytes: int = 1 << 20, max_delay_s: float = 0.005,
                 lane: str = LANE_BULK, deadline_s: float | None = None):
        self.client = client
        self.max_rows = max_rows
        self.max_bytes = max_bytes
        self.max_delay_s = max_delay_s
        self.lane = lane
        self.deadline_s = deadline_s
        self._lock = threading.Lock()
        self._rows: list[tuple] = []
        self._bytes = 0
        self._timer: threading.Timer | None = None
        self._closed = False
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="batch-flush")

    @staticmethod
    def _row_cost(proof) -> int:
        """Payload-size estimate for the byte trigger: serialized
        proofs dominate the frame; metadata columns add 16B/row."""
        if isinstance(proof, (bytes, bytearray)):
            return 16 + len(proof)
        return 16 + (256 if hasattr(proof, "serialize") else 4)

    def add(self, proof, com=None, *, bits: int = 0,
            forge_expected: bool = False) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("BatchSubmitBuffer is closed")
            self._rows.append((proof, com, bits, forge_expected, fut))
            self._bytes += self._row_cost(proof)
            if self._timer is None:
                t = threading.Timer(self.max_delay_s, self._flush_due)
                t.daemon = True
                self._timer = t
                t.start()
            rows = (self._take()
                    if len(self._rows) >= self.max_rows
                    or self._bytes >= self.max_bytes else None)
        if rows:
            self._pool.submit(self._flush_rows, rows)
        return fut

    def _take(self) -> list[tuple]:
        """Detach the buffered rows (caller holds the lock)."""
        rows, self._rows = self._rows, []
        self._bytes = 0
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return rows

    def _flush_due(self) -> None:
        with self._lock:
            rows = self._take()
        if rows:
            self._flush_rows(rows)

    def _flush_rows(self, rows: list[tuple]) -> None:
        proofs = [r[0] for r in rows]
        coms = [r[1] for r in rows]
        bits = [int(r[2]) for r in rows]
        flags = [1 if r[3] else 0 for r in rows]
        futures = [r[4] for r in rows]
        try:
            verdicts = self.client.submit_range_batch(
                proofs, coms, lane=self.lane, deadline_s=self.deadline_s,
                bits=bits, flags=flags)
        except Exception as exc:  # noqa: BLE001 — fan the failure out
            for fut in futures:
                if not fut.done():
                    fut.set_exception(exc)
            return
        for fut, verdict in zip(futures, verdicts):
            if not fut.done():
                fut.set_result(bool(verdict))

    def flush(self) -> None:
        """Ship whatever is buffered now (synchronously)."""
        with self._lock:
            rows = self._take()
        if rows:
            self._flush_rows(rows)

    def close(self) -> None:
        """Final flush, then reject further adds."""
        with self._lock:
            self._closed = True
            rows = self._take()
        if rows:
            self._flush_rows(rows)
        self._pool.shutdown(wait=True)
