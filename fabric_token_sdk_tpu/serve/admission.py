"""Admission control: bounded queues + load shedding + deadline triage.

Sits in front of the bucket scheduler. Every decision is counted in the
``serve_*`` family so overload shows up as shed counters and queue-depth
gauges, never as unbounded memory growth or hung callers:

  - a lane at ``queue_capacity`` sheds new arrivals
    (``serve_shed_total{reason="queue_full"}``);
  - a request whose remaining deadline is already below the service
    estimate is shed on arrival (``reason="deadline"``) rather than
    queued to miss deterministically;
  - a request from a tenant whose per-tenant fast-burn has tripped is
    shed with the distinct ``shed_tenant_slo`` status
    (``reason="tenant_slo"``) while every other tenant is untouched —
    the SLO-aware isolation arm of the noisy-neighbor story.

Admission never blocks: the verdict is immediate and the caller's future
resolves with a terminal status.
"""

from __future__ import annotations

import os
import time

from ..obs import GLOBAL as _METRICS
from ..obs.journal import EVENT_TENANT_SHED, JOURNAL
from .config import ServeConfig
from .request import (STATUS_SHED_DEADLINE, STATUS_SHED_QUEUE_FULL,
                      STATUS_SHED_TENANT_SLO, VerifyRequest)


class TenantShedPolicy:
    """SLO-aware per-tenant shed: isolate a tenant in fast-burn.

    Consults a ``TenantSloMonitor``'s edge-triggered fast-burn state at
    admission time: while a tenant's burn rate is >= ``fast_burn`` on
    all windows (min-volume gated, same rule as the global monitor),
    NEW work from that tenant sheds with ``shed_tenant_slo``; it
    un-sheds automatically when the tenant's windows recover. Decisions
    are journaled (``tenant_shed`` events) and counted
    (``serve_tenant_sheds_total{tms_id}``).

    Sheds are reported back to the monitor via ``note_shed`` — NOT as
    window errors — so the policy cannot sustain the very burn that
    tripped it. ``FTS_NO_TENANT_SHED=1`` (read once, at construction)
    disables the policy: the monitor still observes and trips, but
    admission ignores it — the bench's control arm.
    """

    def __init__(self, monitor, enabled: bool | None = None):
        self.monitor = monitor
        if enabled is None:
            enabled = os.environ.get("FTS_NO_TENANT_SHED", "") != "1"
        self.enabled = enabled

    def should_shed(self, tenant: str) -> bool:
        return (self.enabled and self.monitor is not None
                and self.monitor.shedding(tenant))

    def shed(self, tenant: str, lane: str, rows: int = 1) -> str:
        """Account one shed decision; returns the terminal status."""
        # tenant-bounded: serve_tenant_sheds_total rides the
        # TenantSloMonitor LRU table — its series are removed by the
        # service's on_evict hook above TenantSloPolicy.max_tenants
        _METRICS.counter(
            "serve_tenant_sheds_total",
            help="Rows shed by the per-tenant SLO policy, by tms id",
            tms_id=tenant).add(rows)
        _METRICS.counter("serve_shed_total", reason="tenant_slo",
                         lane=lane).add(rows)
        if self.monitor is not None:
            self.monitor.note_shed(tenant, rows)
        JOURNAL.record(EVENT_TENANT_SHED, tms_id=tenant, lane=lane,
                       rows=rows)
        return STATUS_SHED_TENANT_SLO


class AdmissionController:
    """Stateless policy over the scheduler's queue depths (plus the
    optional stateful per-tenant SLO shed)."""

    def __init__(self, config: ServeConfig, tenant_shed=None):
        self.config = config
        self.tenant_shed = tenant_shed

    def admit(self, req: VerifyRequest, lane_depth: int) -> str | None:
        """None admits; otherwise the terminal shed status.

        ``lane_depth`` is the current depth of the request's lane queue.
        """
        now = time.perf_counter()
        if (self.tenant_shed is not None
                and self.tenant_shed.should_shed(req.tenant)):
            return self.tenant_shed.shed(req.tenant, req.lane)
        if lane_depth >= self.config.queue_capacity:
            _METRICS.counter(
                "serve_shed_total",
                help="Requests refused at admission, by reason",
                reason="queue_full", lane=req.lane).add()
            return STATUS_SHED_QUEUE_FULL
        if req.deadline - now < self.config.service_estimate_s:
            _METRICS.counter("serve_shed_total", reason="deadline",
                             lane=req.lane).add()
            return STATUS_SHED_DEADLINE
        _METRICS.counter(
            "serve_requests_total",
            help="Admitted verification requests",
            kind=req.kind, lane=req.lane).add()
        return None

    def admit_batch(self, kind: str, lane: str, rows: int,
                    lane_depth: int, deadline: float,
                    tenant: str = "default") -> str | None:
        """ONE admission decision for a whole columnar frame.

        The frame admits or sheds atomically — queue_full when the lane
        cannot absorb every row (partial admission would break the
        one-WAL-append-per-frame durability contract), deadline when
        even the frame's latest row cannot be served in time. Counters
        advance by ``rows`` so shed/request rates stay row-denominated.
        A frame is single-tenant, so the per-tenant SLO shed also
        applies whole-frame.
        """
        now = time.perf_counter()
        if (self.tenant_shed is not None
                and self.tenant_shed.should_shed(tenant)):
            return self.tenant_shed.shed(tenant, lane, rows)
        if lane_depth + rows > self.config.queue_capacity:
            _METRICS.counter(
                "serve_shed_total",
                help="Requests refused at admission, by reason",
                reason="queue_full", lane=lane).add(rows)
            return STATUS_SHED_QUEUE_FULL
        if deadline - now < self.config.service_estimate_s:
            _METRICS.counter("serve_shed_total", reason="deadline",
                             lane=lane).add(rows)
            return STATUS_SHED_DEADLINE
        _METRICS.counter(
            "serve_requests_total",
            help="Admitted verification requests",
            kind=kind, lane=lane).add(rows)
        return None
