"""Admission control: bounded queues + load shedding + deadline triage.

Sits in front of the bucket scheduler. Every decision is counted in the
``serve_*`` family so overload shows up as shed counters and queue-depth
gauges, never as unbounded memory growth or hung callers:

  - a lane at ``queue_capacity`` sheds new arrivals
    (``serve_shed_total{reason="queue_full"}``);
  - a request whose remaining deadline is already below the service
    estimate is shed on arrival (``reason="deadline"``) rather than
    queued to miss deterministically.

Admission never blocks: the verdict is immediate and the caller's future
resolves with a terminal status.
"""

from __future__ import annotations

import time

from ..obs import GLOBAL as _METRICS
from .config import ServeConfig
from .request import (STATUS_SHED_DEADLINE, STATUS_SHED_QUEUE_FULL,
                      VerifyRequest)


class AdmissionController:
    """Stateless policy over the scheduler's queue depths."""

    def __init__(self, config: ServeConfig):
        self.config = config

    def admit(self, req: VerifyRequest, lane_depth: int) -> str | None:
        """None admits; otherwise the terminal shed status.

        ``lane_depth`` is the current depth of the request's lane queue.
        """
        now = time.perf_counter()
        if lane_depth >= self.config.queue_capacity:
            _METRICS.counter(
                "serve_shed_total",
                help="Requests refused at admission, by reason",
                reason="queue_full", lane=req.lane).add()
            return STATUS_SHED_QUEUE_FULL
        if req.deadline - now < self.config.service_estimate_s:
            _METRICS.counter("serve_shed_total", reason="deadline",
                             lane=req.lane).add()
            return STATUS_SHED_DEADLINE
        _METRICS.counter(
            "serve_requests_total",
            help="Admitted verification requests",
            kind=req.kind, lane=req.lane).add()
        return None

    def admit_batch(self, kind: str, lane: str, rows: int,
                    lane_depth: int, deadline: float) -> str | None:
        """ONE admission decision for a whole columnar frame.

        The frame admits or sheds atomically — queue_full when the lane
        cannot absorb every row (partial admission would break the
        one-WAL-append-per-frame durability contract), deadline when
        even the frame's latest row cannot be served in time. Counters
        advance by ``rows`` so shed/request rates stay row-denominated.
        """
        now = time.perf_counter()
        if lane_depth + rows > self.config.queue_capacity:
            _METRICS.counter(
                "serve_shed_total",
                help="Requests refused at admission, by reason",
                reason="queue_full", lane=lane).add(rows)
            return STATUS_SHED_QUEUE_FULL
        if deadline - now < self.config.service_estimate_s:
            _METRICS.counter("serve_shed_total", reason="deadline",
                             lane=lane).add(rows)
            return STATUS_SHED_DEADLINE
        _METRICS.counter(
            "serve_requests_total",
            help="Admitted verification requests",
            kind=kind, lane=lane).add(rows)
        return None
