"""Async verification frontend: continuous batching over the ZK backends.

``VerificationService`` accepts individual verification requests
(``submit_range`` / ``submit_transfer`` / ``submit_issue``), assembles
them into pow-2-bucketed batches under the ``ServeConfig`` policy, runs
each batch through the SAME entry points the unbatched path uses
(``BatchRangeVerifier.verify`` for range rows, ``ZKVerifier.verify_block``
for transfer/issue actions), and demultiplexes the per-row verdicts back
to each caller's future — bit-identically to what a direct call on the
same payload would return.

Threading model: all scheduler/queue state lives on the event loop; the
blocking device call runs on a dedicated single-thread executor via
``run_in_executor``, so exactly one batch is in flight at a time and
arrivals keep queueing while the device works (continuous batching).
Futures resolve on the event loop after the executor returns — no
cross-thread future writes.

Every stage is observable: admission counts, queue-depth gauges,
wait/dispatch histograms, shed/deadline-miss counters (all under the
stable ``serve_*`` family), plus a ``serve.dispatch`` span per device
batch.
"""

from __future__ import annotations

import asyncio
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from .admission import AdmissionController
from .config import LANE_BULK, ServeConfig
from .prewarm import PrewarmManager
from .request import (KIND_ISSUE, KIND_RANGE, KIND_TRANSFER, STATUS_DEADLINE_MISS,
                      STATUS_ERROR, STATUS_OK, VerifyRequest, VerifyResult)
from .scheduler import BucketScheduler


class VerificationService:
    """Continuous-batching frontend over a ``ZKVerifier``.

    Lifecycle::

        svc = VerificationService(zk=zk, config=ServeConfig(...))
        prewarm_s = await svc.start()      # compiles every bucket shape
        res = await svc.submit_range(proof, com, deadline_s=0.5)
        assert res.ok and res.accepted
        await svc.stop()                   # drains, then stops the loop
    """

    def __init__(self, zk, config: ServeConfig | None = None):
        self.zk = zk
        self.config = config or ServeConfig()
        self.scheduler = BucketScheduler(self.config)
        self.admission = AdmissionController(self.config)
        self.prewarm = PrewarmManager(zk, self.config)
        self.prewarm_s: float | None = None
        self.first_dispatch_t: float | None = None
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-dispatch")
        self._task: asyncio.Task | None = None
        self._wake: asyncio.Event | None = None
        self._running = False

    # ---------------------------------------------------------- lifecycle
    async def start(self, prewarm: bool = True) -> float:
        """Prewarm every configured bucket, then start the dispatch loop.

        Returns the prewarm wall seconds (0.0 when ``prewarm=False``) so
        callers can report startup cost separately from steady state.
        """
        if self._running:
            return self.prewarm_s or 0.0
        loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        if prewarm:
            self.prewarm_s = await loop.run_in_executor(
                self._executor, self.prewarm.run)
        self._running = True
        self._task = asyncio.create_task(self._dispatch_loop())
        return self.prewarm_s or 0.0

    async def stop(self, drain: bool = True) -> None:
        """Stop the dispatch loop; with ``drain`` every queued request is
        served (or expires) first, without it the queued requests complete
        with ``error``."""
        if not self._running:
            return
        self._running = False
        if not drain:
            for req in self._drain_queues():
                self._resolve(req, VerifyResult(
                    status=STATUS_ERROR, error="service stopped"))
        self._wake.set()
        await self._task
        self._task = None

    def _drain_queues(self) -> list[VerifyRequest]:
        out = []
        for q in self.scheduler._queues.values():
            out.extend(q)
            q.clear()
        return out

    # ------------------------------------------------------------- submit
    async def submit_range(self, proof, commitment, *, deadline_s=None,
                           lane: str = LANE_BULK) -> VerifyResult:
        """Verify one range proof against its commitment."""
        return await self._submit(KIND_RANGE, (proof, commitment),
                                  deadline_s, lane)

    async def submit_transfer(self, proof_raw, inputs, outputs, *,
                              deadline_s=None,
                              lane: str = LANE_BULK) -> VerifyResult:
        """Verify one transfer action (serialized proof + token vectors)."""
        return await self._submit(KIND_TRANSFER, (proof_raw, inputs, outputs),
                                  deadline_s, lane)

    async def submit_issue(self, proof_raw, outputs, *, deadline_s=None,
                           lane: str = LANE_BULK) -> VerifyResult:
        """Verify one issue action (serialized proof + output tokens)."""
        return await self._submit(KIND_ISSUE, (proof_raw, outputs),
                                  deadline_s, lane)

    async def _submit(self, kind, payload, deadline_s, lane) -> VerifyResult:
        if not self._running:
            raise RuntimeError("VerificationService is not started")
        now = time.perf_counter()
        deadline_s = (self.config.default_deadline_s
                      if deadline_s is None else deadline_s)
        req = VerifyRequest(kind=kind, payload=payload, lane=lane,
                            deadline=now + deadline_s, enqueue_t=now,
                            future=asyncio.get_running_loop().create_future())
        shed = self.admission.admit(req, self.scheduler.lane_depth(lane))
        if shed is not None:
            return VerifyResult(status=shed)
        self.scheduler.push(req)
        self._wake.set()
        return await req.future

    # ------------------------------------------------------ dispatch loop
    async def _dispatch_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            now = time.perf_counter()
            for req in self.scheduler.expire(now):
                self._complete_expired(req, now)
            batch = self.scheduler.assemble(now)
            if batch:
                if self.first_dispatch_t is None:
                    self.first_dispatch_t = now
                try:
                    verdicts = await loop.run_in_executor(
                        self._executor, self._run_batch, batch)
                except Exception as exc:  # noqa: BLE001 — fail the batch, not the loop
                    msg = f"{type(exc).__name__}: {exc}"
                    for req in batch:
                        self._resolve(req, VerifyResult(
                            status=STATUS_ERROR, error=msg))
                else:
                    self._demux(batch, verdicts, dispatch_t=now)
                continue
            if not self._running and self.scheduler.depth() == 0:
                return
            nxt = self.scheduler.next_event(time.perf_counter())
            self._wake.clear()
            # Re-check after clear: a push between assemble() and clear()
            # would otherwise sleep through its max-wait window.
            if self.scheduler.depth() and nxt is None:
                continue
            try:
                if nxt is None:
                    await self._wake.wait()
                else:
                    delay = max(0.0, nxt - time.perf_counter())
                    await asyncio.wait_for(self._wake.wait(), delay)
            except asyncio.TimeoutError:
                pass

    # ----------------------------------------------------- device batches
    def _run_batch(self, batch: list[VerifyRequest]) -> np.ndarray:
        """Runs on the executor thread: one blocking device call.

        Returns a bool vector aligned with ``batch`` order.
        """
        group = batch[0].group
        t0 = time.perf_counter()
        with _TRACER.span("serve.dispatch", group=group, rows=len(batch),
                          bucket=self.config.bucket_for(len(batch))):
            if group == KIND_RANGE:
                proofs = [r.payload[0] for r in batch]
                coms = [r.payload[1] for r in batch]
                verdicts = np.asarray(
                    self.zk._range.verify(proofs, coms), dtype=bool)
            else:
                transfers, issues, slots = [], [], []
                for r in batch:
                    if r.kind == KIND_TRANSFER:
                        slots.append((0, len(transfers)))
                        transfers.append(r.payload)
                    else:
                        slots.append((1, len(issues)))
                        issues.append(r.payload)
                t_ok, i_ok = self.zk.verify_block(transfers, issues)
                t_ok = np.asarray(t_ok, dtype=bool).reshape(-1)
                i_ok = np.asarray(i_ok, dtype=bool).reshape(-1)
                verdicts = np.asarray(
                    [(i_ok if which else t_ok)[idx] for which, idx in slots],
                    dtype=bool)
        _METRICS.counter("serve_batches_total",
                         help="Device batches dispatched",
                         group=group).add()
        _METRICS.histogram("serve_dispatch_seconds",
                           help="Blocking device-call wall per batch",
                           group=group).observe(time.perf_counter() - t0)
        return verdicts

    # -------------------------------------------------------- completion
    def _demux(self, batch, verdicts, dispatch_t: float) -> None:
        now = time.perf_counter()
        rows = len(batch)
        bucket = self.config.bucket_for(rows)
        for req, acc in zip(batch, verdicts):
            miss = now > req.deadline
            status = STATUS_DEADLINE_MISS if miss else STATUS_OK
            if miss:
                _METRICS.counter(
                    "serve_deadline_miss_total",
                    help="Requests whose deadline passed, by where",
                    where="served").add()
            _METRICS.histogram(
                "serve_wait_seconds",
                help="Enqueue -> dispatch wait per request",
                lane=req.lane).observe(dispatch_t - req.enqueue_t)
            self._resolve(req, VerifyResult(
                status=status, accepted=bool(acc),
                wait_s=dispatch_t - req.enqueue_t,
                total_s=now - req.enqueue_t,
                bucket=bucket, batch_rows=rows))

    def _complete_expired(self, req: VerifyRequest, now: float) -> None:
        _METRICS.counter("serve_deadline_miss_total",
                         where="queued").add()
        self._resolve(req, VerifyResult(
            status=STATUS_DEADLINE_MISS,
            total_s=now - req.enqueue_t))

    def _resolve(self, req: VerifyRequest, result: VerifyResult) -> None:
        _METRICS.counter("serve_results_total",
                         help="Completed requests by terminal status",
                         status=result.status).add()
        if req.future is not None and not req.future.done():
            req.future.set_result(result)
