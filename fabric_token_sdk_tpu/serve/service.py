"""Async verification frontend: continuous batching over the ZK backends.

``VerificationService`` accepts individual verification requests
(``submit_range`` / ``submit_transfer`` / ``submit_issue``) and whole
columnar frames (``submit_batch`` — ONE admission decision, ONE journal
event, and ONE WAL append for N rows; the front-door fast path for
SUBMIT_BATCH frames), assembles
them into pow-2-bucketed batches under the ``ServeConfig`` policy, runs
each batch through the SAME entry points the unbatched path uses
(``BatchRangeVerifier.verify`` for range rows, ``ZKVerifier.verify_block``
for transfer/issue actions), and demultiplexes the per-row verdicts back
to each caller's future — bit-identically to what a direct call on the
same payload would return.

Threading model: all scheduler/queue state lives on the event loop; each
blocking device call runs on a DISPATCH LANE's dedicated single-thread
executor (owned by that lane's resilience watchdog) via
``run_in_executor``. A lane owns one device or mesh shard
(``lane_verifiers``) with its own prewarm inventory; exactly one batch
is in flight per lane, and up to ``ServeConfig.n_lanes`` lanes serve
concurrently, so the continuous-batching frontend feeds every device
instead of serializing on one dispatcher thread (``n_lanes=1``, the
default, preserves the historical single-dispatcher behaviour exactly).
Futures resolve on the event loop after the executor returns — no
cross-thread future writes.

Failure handling (resilience/): with a :class:`ResilienceConfig` the
dispatch is wrapped in retry (transient errors, seeded decorrelated
jitter), a circuit breaker (failure-rate window, half-open probes), a
watchdog that abandons hung device calls on a fresh executor thread, and
a host fallback that routes exhausted/broken-open batches through the
pure-host proof verifiers for bit-identical verdicts. Results carry
``served_by="device"`` or ``"host"``; a batch only terminates in
``error`` when every layer is out of options.

Every stage is observable: admission counts, queue-depth gauges,
wait/dispatch histograms, shed/deadline-miss counters (all under the
stable ``serve_*`` family), retry/breaker/fallback/watchdog counters
(``resil_*``), plus ``serve.dispatch`` / ``resil.retry`` /
``resil.fallback`` spans.

Trace propagation (``ServeConfig.trace_every``): every Nth admitted
request opens a ``serve.request`` root span with its own trace id, which
the service carries across the whole lifetime the contextvar cannot
(coroutine -> scheduler queue -> executor thread): queue wait lands as a
``serve.queue_wait`` child at dispatch, the shared per-batch
``serve.batch`` span cross-links with every member request's span, and
``serve.dispatch`` / ``resil.retry`` / ``resil.fallback`` spans parent
under the batch span — so one request's admission/wait/dispatch/retry
history is a connected chain in the Chrome-trace export. An optional
:class:`~fabric_token_sdk_tpu.obs.slo.SloMonitor` receives every
terminal result, and the device profiler records compile-cache hit/miss
and memory watermarks per dispatch.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..obs.journal import (EVENT_BATCH_ADMITTED, EVENT_BATCH_FORMED,
                           EVENT_DISPATCH_END, EVENT_DISPATCH_START,
                           EVENT_FALLBACK, EVENT_REQUEST_ADMITTED,
                           EVENT_REQUEST_SHED, EVENT_REQUEST_SHUTDOWN,
                           EVENT_WAL_REPLAY, JOURNAL)
from ..obs.profiling import PROFILER
from ..resilience import DispatchWatchdog, HostFallbackVerifier, \
    ResilienceConfig
from .admission import AdmissionController, TenantShedPolicy
from .config import LANE_BULK, ServeConfig
from .prewarm import PrewarmManager
from .request import (KIND_ISSUE, KIND_RANGE, KIND_TRANSFER,
                      SERVED_BY_DEVICE, SERVED_BY_HOST,
                      STATUS_DEADLINE_MISS, STATUS_ERROR, STATUS_OK,
                      STATUS_SHED_TENANT_SLO, STATUS_SHUTDOWN,
                      VerifyRequest, VerifyResult)
from .scheduler import BucketScheduler
from .wal import RECORD_ADMIT_BATCH

#: Family metadata for every serve_* instrument this module touches,
#: hoisted so the HELP line cannot depend on which call site registers a
#: family first (``_complete_expired`` vs ``_demux`` used to race on
#: ``serve_deadline_miss_total``). Registered via ``describe`` at service
#: construction — call-order independent by construction.
_SERVE_FAMILIES = {
    "serve_batches_total": "Device batches dispatched",
    "serve_dispatch_seconds": "Blocking device-call wall per batch",
    "serve_deadline_miss_total": "Requests whose deadline passed, by where",
    "serve_wait_seconds": "Enqueue -> dispatch wait per request",
    "serve_results_total": "Completed requests by terminal status",
    "resil_fallback_batches_total":
        "Batches served by the host fallback path, by group",
}

#: Per-tenant serve families (the ``tms_id``-labelled latency pipeline).
#: Only recorded while a :class:`TenantSloMonitor` is attached — its
#: ``max_tenants`` LRU table is the cardinality bound, and its eviction
#: hook removes these series alongside the ``slo_tenant_*`` gauges.
_TENANT_SERVE_FAMILIES = {
    "serve_tenant_queue_seconds":
        "Enqueue -> dispatch wait per request, by tenant tms id",
    "serve_tenant_e2e_seconds":
        "Enqueue -> terminal verdict wall per request, by tenant tms id",
    "serve_tenant_sheds_total":
        "Rows shed by the per-tenant SLO policy, by tms id",
}

#: Per-device dispatch-lane families (ServeConfig.n_lanes > 1 feeds all
#: devices concurrently); new stable families, never renamed.
_LANE_FAMILIES = {
    "lane_dispatch_total": "Batches dispatched per device dispatch lane",
    "lane_rows_total": "Live rows dispatched per device dispatch lane",
    "lane_busy_seconds":
        "Wall seconds a device dispatch lane spent serving batches",
    "lane_inflight": "Batches in flight per device dispatch lane (0/1)",
}


class _DispatchLane:
    """One device dispatch lane: its own executor thread (the watchdog
    owns it), its own verifier handle (one device or mesh shard when the
    caller passes ``lane_verifiers``), its own prewarm inventory, and
    its dispatch accounting. Exactly one batch is in flight per lane;
    ``VerificationService`` runs up to ``n_lanes`` lanes concurrently."""

    def __init__(self, index: int, zk, config: ServeConfig,
                 resilience: ResilienceConfig | None):
        self.index = index
        self.zk = zk
        self.watchdog = DispatchWatchdog(
            timeout_s=(resilience.watchdog_timeout_s
                       if resilience is not None else None),
            thread_name_prefix=f"serve-lane{index}")
        self.prewarm = PrewarmManager(zk, config, lane=index)
        self.busy = False
        self.inflight: list[VerifyRequest] = []
        self.dispatches = 0
        self.rows = 0
        self.busy_s = 0.0


class VerificationService:
    """Continuous-batching frontend over a ``ZKVerifier``.

    Lifecycle::

        svc = VerificationService(zk, config=ServeConfig(...),
                                  resilience=ResilienceConfig(...))
        prewarm_s = await svc.start()      # compiles every bucket shape
        res = await svc.submit_range(proof, com, deadline_s=0.5)
        assert res.ok and res.accepted and res.served_by == "device"
        await svc.stop(timeout_s=30.0)     # bounded drain, then stop

    ``resilience=None`` (the default) preserves the bare dispatch
    behaviour: one attempt, no breaker, no watchdog, no fallback —
    failures complete the batch with ``status="error"``.

    ``slo`` optionally attaches an :class:`SloMonitor` that receives
    every terminal result (``slo.bind_breaker(svc.breaker)`` wires
    fast-burn to the breaker's kill switch).
    """

    def __init__(self, zk, config: ServeConfig | None = None,
                 resilience: ResilienceConfig | None = None,
                 fallback=None, slo=None, wal=None,
                 lane_verifiers: list | None = None, tenant_slo=None):
        self.zk = zk
        self.wal = wal
        #: (wal_id, VerifyResult) pairs replayed at the last ``start()``.
        self.replayed: list[tuple[int, VerifyResult]] = []
        # batch WAL countdown: wal_id -> rows not yet terminal. A frame
        # admitted via submit_batch shares one wal_id across its rows;
        # append_resolve fires exactly once, when the LAST row resolves.
        self._wal_batch_open: dict[int, int] = {}
        self.config = config or ServeConfig()
        self.resilience = resilience
        self.slo = slo
        # per-tenant SLO plane: a TenantSloMonitor attaches the tenant-
        # labelled latency pipeline AND arms the SLO-aware shed policy
        # (FTS_NO_TENANT_SHED=1 keeps the monitor observing but disables
        # the shed — the bench's control arm)
        self.tenant_slo = tenant_slo
        if tenant_slo is not None and tenant_slo.on_evict is None:
            tenant_slo.on_evict = self._evict_tenant_series
        self.scheduler = BucketScheduler(self.config)
        self.admission = AdmissionController(
            self.config,
            tenant_shed=(TenantShedPolicy(tenant_slo)
                         if tenant_slo is not None else None))
        for fam, help_text in {**_SERVE_FAMILIES, **_LANE_FAMILIES,
                               **_TENANT_SERVE_FAMILIES}.items():
            _METRICS.describe(fam, help_text)
        # device dispatch lanes: lane i serves lane_verifiers[i] (a
        # per-device / per-mesh-shard verifier) or the shared zk when the
        # caller passes none — each lane still gets its OWN executor
        # thread, so batches overlap even on one shared backend handle
        n_lanes = self.config.n_lanes
        if lane_verifiers is not None and len(lane_verifiers) != n_lanes:
            raise ValueError(
                f"lane_verifiers has {len(lane_verifiers)} entries, "
                f"config.n_lanes is {n_lanes}")
        zks = (list(lane_verifiers) if lane_verifiers is not None
               else [zk] * n_lanes)
        self._lanes = [_DispatchLane(i, zks[i], self.config, resilience)
                       for i in range(n_lanes)]
        self._lane_tasks: set[asyncio.Task] = set()
        # single-lane compat surfaces (tests, statusz, bench): lane 0's
        # prewarm inventory and watchdog keep their historical names
        self.prewarm = self._lanes[0].prewarm
        self._watchdog = self._lanes[0].watchdog
        self.prewarm_s: float | None = None
        self.first_dispatch_t: float | None = None
        if resilience is not None:
            self._retry = resilience.build_retry_policy(op="serve_dispatch")
            self._breaker = resilience.build_breaker(name="device")
            if fallback is None and resilience.fallback \
                    and getattr(zk, "pp", None) is not None:
                fallback = HostFallbackVerifier(zk.pp)
        else:
            self._retry = None
            self._breaker = None
        self._fallback = fallback
        self._task: asyncio.Task | None = None
        self._wake: asyncio.Event | None = None
        self._running = False
        #: The event loop the service started on — submits must run
        #: here; the RPC server's loop shards hand off to it.
        self.loop: asyncio.AbstractEventLoop | None = None
        # (group, bucket) shapes already dispatched/prewarmed — the basis
        # of the profile_compile_cache_total hit/miss classification
        self._warm_shapes: set[tuple] = set()

    @property
    def _inflight(self) -> list:
        """Every in-flight request across all dispatch lanes."""
        return [r for lane in self._lanes for r in lane.inflight]

    @property
    def breaker(self):
        """The dispatch circuit breaker (None without resilience)."""
        return self._breaker

    # ---------------------------------------------------------- lifecycle
    async def start(self, prewarm: bool = True) -> float:
        """Prewarm every configured bucket, then start the dispatch loop.

        Returns the prewarm wall seconds (0.0 when ``prewarm=False``) so
        callers can report startup cost separately from steady state.
        """
        if self._running:
            return self.prewarm_s or 0.0
        loop = asyncio.get_running_loop()
        self.loop = loop
        self._wake = asyncio.Event()
        if prewarm:
            # no watchdog here: first-compile legitimately takes minutes.
            # Lanes warm SEQUENTIALLY: concurrent first-compiles of the
            # same shapes just contend (same jit cache on a shared
            # verifier; one compiler on the gate host either way), and
            # lanes past 0 on a shared verifier hit the warm cache.
            total = 0.0
            for lane in self._lanes:
                total += await loop.run_in_executor(
                    lane.watchdog.executor, lane.prewarm.run)
            self.prewarm_s = total
        self._running = True
        self._task = asyncio.create_task(self._dispatch_loop())
        if self.wal is not None:
            await self._replay_wal()
        return self.prewarm_s or 0.0

    async def _replay_wal(self) -> None:
        """Crash recovery: push every admitted-but-unresolved WAL entry
        back through the normal dispatch path (same scheduler, same
        device call — bit-identical verdicts) and wait for their
        terminal verdicts. Replays bypass admission: each entry was
        already admitted once, and shedding it now would turn a durable
        promise into a loss. Results land in :attr:`replayed` and each
        resolution is logged to the WAL exactly once under the
        original id."""
        entries = self.wal.recover()
        self.replayed = []
        if not entries:
            return
        loop = asyncio.get_running_loop()
        now = time.perf_counter()
        reqs = []
        for e in entries:
            # deadline is re-based on the replay instant: the original
            # wall deadline is long past, and expiring a recovered
            # request unexamined would defeat the replay
            deadline_s = max(e.deadline_s, self.config.default_deadline_s)
            # a batch record expands back into per-row requests sharing
            # the frame's wal_id; the countdown keeps resolution at one
            # RECORD_RESOLVE per frame, mirroring the admit side
            row_payloads = (list(e.payload)
                            if e.record == RECORD_ADMIT_BATCH
                            else [e.payload])
            if e.record == RECORD_ADMIT_BATCH:
                self._wal_batch_open[e.wal_id] = len(row_payloads)
            JOURNAL.record(EVENT_WAL_REPLAY, req_kind=e.kind, lane=e.lane,
                           wal_id=e.wal_id, rows=len(row_payloads))
            _METRICS.counter("wal_replayed_total").add(len(row_payloads))
            for payload in row_payloads:
                req = VerifyRequest(kind=e.kind, payload=payload,
                                    lane=e.lane, deadline=now + deadline_s,
                                    enqueue_t=now,
                                    future=loop.create_future(),
                                    wal_id=e.wal_id)
                self.scheduler.push(req)
                reqs.append(req)
        self._wake.set()
        results = await asyncio.gather(*(r.future for r in reqs))
        self.replayed = [(r.wal_id, res) for r, res in zip(reqs, results)]

    async def abort(self) -> None:
        """Simulate a crash: cancel the dispatch loop WITHOUT resolving
        queued or in-flight requests. Their futures never resolve (as
        in a real SIGKILL — callers must not await them past this) and
        the WAL keeps their admit records unresolved, so a successor
        service constructed over the same WAL directory replays them.
        Test/drill hook for the crash-recovery contract."""
        if not self._running:
            return
        self._running = False
        for t in list(self._lane_tasks):
            t.cancel()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    async def stop(self, drain: bool = True,
                   timeout_s: float | None = None) -> None:
        """Stop the dispatch loop.

        With ``drain`` every queued request is served (or expires) first;
        without it the queued requests complete with ``error``. A
        ``timeout_s`` bounds the drain: past it, still-queued and
        in-flight requests resolve with the terminal ``shutdown`` status
        and the loop is cancelled — ``stop`` can no longer block forever
        behind a hung device call.
        """
        if not self._running:
            return
        self._running = False
        if not drain:
            for req in self._drain_queues():
                self._resolve(req, VerifyResult(
                    status=STATUS_ERROR, error="service stopped"))
        self._wake.set()
        if timeout_s is None:
            await self._task
        else:
            try:
                await asyncio.wait_for(asyncio.shield(self._task),
                                       timeout_s)
            except asyncio.TimeoutError:
                for req in self._drain_queues() + list(self._inflight):
                    self._resolve(req, VerifyResult(
                        status=STATUS_SHUTDOWN,
                        error=f"service stopped after {timeout_s}s drain "
                              "timeout"))
                for t in list(self._lane_tasks):
                    t.cancel()
                self._task.cancel()
                try:
                    await self._task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        self._task = None

    def _drain_queues(self) -> list[VerifyRequest]:
        out = []
        for q in self.scheduler._queues.values():
            out.extend(q)
            q.clear()
        return out

    def _record_shed_slo(self, tenant: str, status: str,
                         rows: int) -> None:
        """SLO accounting for an admission shed. Capacity/deadline sheds
        are genuine failures and feed both the global and the tenant
        windows. A ``shed_tenant_slo`` verdict is the tenant policy
        ACTING, not the service failing: feeding it back into either
        window would sustain the burn that tripped it (the tenant could
        never recover) — the TenantShedPolicy already accounted it via
        ``note_shed``."""
        if status == STATUS_SHED_TENANT_SLO:
            return
        for _ in range(rows):
            if self.slo is not None:
                self.slo.record(False)
            if self.tenant_slo is not None:
                self.tenant_slo.record(tenant, False)

    def _evict_tenant_series(self, tenant: str) -> None:
        """TenantSloMonitor eviction hook: when the bounded tenant table
        drops a tms_id, its serve-layer series go with it — the other
        half of the per-tenant cardinality bound. The scheduler's DRR
        ledger series are included so a departed tenant disappears from
        the exposition in one step (they re-register, as a counter
        reset, if the tenant returns)."""
        for fam in (*_TENANT_SERVE_FAMILIES, "serve_tenant_drains_total",
                    "rpc_tenant_deficit"):
            _METRICS.remove_series(fam, tms_id=tenant)

    # ------------------------------------------------------------- submit
    async def submit_range(self, proof, commitment, *, deadline_s=None,
                           lane: str = LANE_BULK,
                           tenant: str = "default",
                           trace_ctx=None) -> VerifyResult:
        """Verify one range proof against its commitment."""
        return await self._submit(KIND_RANGE, (proof, commitment),
                                  deadline_s, lane, tenant, trace_ctx)

    async def submit_transfer(self, proof_raw, inputs, outputs, *,
                              deadline_s=None, lane: str = LANE_BULK,
                              tenant: str = "default",
                              trace_ctx=None) -> VerifyResult:
        """Verify one transfer action (serialized proof + token vectors)."""
        return await self._submit(KIND_TRANSFER, (proof_raw, inputs, outputs),
                                  deadline_s, lane, tenant, trace_ctx)

    async def submit_issue(self, proof_raw, outputs, *, deadline_s=None,
                           lane: str = LANE_BULK,
                           tenant: str = "default",
                           trace_ctx=None) -> VerifyResult:
        """Verify one issue action (serialized proof + output tokens)."""
        return await self._submit(KIND_ISSUE, (proof_raw, outputs),
                                  deadline_s, lane, tenant, trace_ctx)

    async def _submit(self, kind, payload, deadline_s, lane,
                      tenant: str = "default",
                      trace_ctx=None) -> VerifyResult:
        if not self._running:
            raise RuntimeError("VerificationService is not started")
        now = time.perf_counter()
        deadline_s = (self.config.default_deadline_s
                      if deadline_s is None else deadline_s)
        req = VerifyRequest(kind=kind, payload=payload, lane=lane,
                            deadline=now + deadline_s, enqueue_t=now,
                            future=asyncio.get_running_loop().create_future(),
                            tenant=tenant)
        # a caller-propagated trace context (``trace_ctx``, from the RPC
        # front door) always gets a serve.request span joined to the
        # caller's trace — the caller made the sampling decision; the
        # local trace_every sampler only governs untraced entry points
        if trace_ctx is not None:
            req.span = _TRACER.start_span(
                "serve.request", remote_parent=trace_ctx, kind=kind,
                lane=lane, req_id=req.req_id,
                deadline_s=round(deadline_s, 6), tenant=tenant)
        elif self.config.trace_every \
                and req.req_id % self.config.trace_every == 0:
            req.span = _TRACER.start_span(
                "serve.request", kind=kind, lane=lane, req_id=req.req_id,
                deadline_s=round(deadline_s, 6), tenant=tenant)
        trace_id = (f"{req.span.trace_id:016x}"
                    if req.span is not None else None)
        shed = self.admission.admit(req, self.scheduler.lane_depth(lane))
        if shed is not None:
            result = VerifyResult(status=shed)
            JOURNAL.record(EVENT_REQUEST_SHED, req_kind=kind, lane=lane,
                           req_id=req.req_id, status=shed, tenant=tenant,
                           trace_id=trace_id)
            self._record_shed_slo(tenant, shed, rows=1)
            self._finish_request_span(req, result)
            return result
        JOURNAL.record(EVENT_REQUEST_ADMITTED, req_kind=kind, lane=lane,
                       req_id=req.req_id,
                       depth=self.scheduler.lane_depth(lane),
                       trace_id=trace_id)
        if self.wal is not None:
            # durability point: once this line is flushed the request
            # survives a SIGKILL — a successor service replays it
            req.wal_id = self.wal.append_admit(
                kind=kind, lane=lane, deadline_s=deadline_s,
                payload=payload)
        if req.span is not None:
            req.span.add_event(
                "admitted", depth=self.scheduler.lane_depth(lane))
        self.scheduler.push(req)
        self._wake.set()
        return await req.future

    async def submit_batch(self, kind, payloads, *, deadline_s=None,
                           deadline_offsets_s=None, lane: str = LANE_BULK,
                           tenant: str = "default",
                           trace_ctx=None) -> list[VerifyResult]:
        """Admit one columnar frame of ``len(payloads)`` rows at once.

        The front-door fast path for SUBMIT_BATCH frames: the whole
        frame admits or sheds with ONE admission decision, ONE journal
        event (:data:`EVENT_BATCH_ADMITTED`), and ONE WAL append
        (``append_admit_batch``), then its rows fan into the normal
        bucket scheduler — same batch assembly, same device call,
        bit-identical verdicts to N individual submits.

        ``deadline_s`` is the base budget (config default when None);
        ``deadline_offsets_s`` optionally adds a per-row offset (the
        frame's ``deadline_off_us`` column). ``tenant`` is the DRR
        drain key. Returns one :class:`VerifyResult` per row, in row
        order.
        """
        if not self._running:
            raise RuntimeError("VerificationService is not started")
        n = len(payloads)
        if n == 0:
            return []
        now = time.perf_counter()
        base = (self.config.default_deadline_s
                if deadline_s is None else deadline_s)
        if deadline_offsets_s is not None:
            row_deadline_s = [base + float(deadline_offsets_s[i])
                              for i in range(n)]
        else:
            row_deadline_s = [base] * n
        trace_id = (f"{trace_ctx.trace_id:016x}"
                    if trace_ctx is not None else None)
        # triage on the frame's LATEST row: if even that one cannot be
        # served in time, the whole frame is a deterministic miss
        shed = self.admission.admit_batch(
            kind, lane, n, self.scheduler.lane_depth(lane),
            now + max(row_deadline_s), tenant=tenant)
        if shed is not None:
            JOURNAL.record(EVENT_REQUEST_SHED, req_kind=kind, lane=lane,
                           rows=n, tenant=tenant, status=shed,
                           trace_id=trace_id)
            self._record_shed_slo(tenant, shed, rows=n)
            return [VerifyResult(status=shed) for _ in range(n)]
        JOURNAL.record(EVENT_BATCH_ADMITTED, req_kind=kind, lane=lane,
                       rows=n, tenant=tenant,
                       depth=self.scheduler.lane_depth(lane),
                       trace_id=trace_id)
        wal_id = None
        if self.wal is not None:
            # durability point for the WHOLE frame: one flushed line
            wal_id = self.wal.append_admit_batch(
                kind=kind, lane=lane, deadline_s=base, payloads=payloads)
            self._wal_batch_open[wal_id] = n
        loop = asyncio.get_running_loop()
        reqs = []
        for i, payload in enumerate(payloads):
            req = VerifyRequest(kind=kind, payload=payload, lane=lane,
                                deadline=now + row_deadline_s[i],
                                enqueue_t=now, future=loop.create_future(),
                                wal_id=wal_id, tenant=tenant)
            self.scheduler.push(req)
            reqs.append(req)
        self._wake.set()
        return list(await asyncio.gather(*(r.future for r in reqs)))

    # ------------------------------------------------------ dispatch loop
    async def _dispatch_loop(self) -> None:
        while True:
            now = time.perf_counter()
            for req in self.scheduler.expire(now):
                self._complete_expired(req, now)
            # Feed every idle device lane: each assembled batch launches
            # as its own task on the least-recently-used idle lane, so up
            # to n_lanes batches overlap (continuous batching across all
            # devices). The loop itself never blocks on a device call.
            launched = False
            while True:
                idle = [lane for lane in self._lanes if not lane.busy]
                if not idle:
                    break
                batch = self.scheduler.assemble(now)
                if not batch:
                    break
                if self.first_dispatch_t is None:
                    self.first_dispatch_t = now
                lane_idx = self.scheduler.pick_lane(
                    [lane.index for lane in idle])
                lane = self._lanes[lane_idx]
                lane.busy = True
                lane.inflight = list(batch)
                task = asyncio.create_task(
                    self._run_lane(lane, batch, now))
                self._lane_tasks.add(task)
                task.add_done_callback(self._lane_tasks.discard)
                launched = True
            if launched:
                continue
            if not self._running and self.scheduler.depth() == 0 \
                    and not any(lane.busy for lane in self._lanes):
                return
            # With every lane busy, only EXPIRY instants matter: a
            # dispatch-due instant in the past would hot-spin the loop
            # until a lane frees (the lane's completion sets _wake).
            idle_any = any(not lane.busy for lane in self._lanes)
            nxt = self.scheduler.next_event(time.perf_counter(),
                                            include_dispatch=idle_any)
            self._wake.clear()
            # Re-check after clear: a push between assemble() and clear()
            # would otherwise sleep through its max-wait window.
            if self.scheduler.depth() and nxt is None and idle_any:
                continue
            try:
                if nxt is None:
                    await self._wake.wait()
                else:
                    delay = max(0.0, nxt - time.perf_counter())
                    await asyncio.wait_for(self._wake.wait(), delay)
            except asyncio.TimeoutError:
                pass

    async def _run_lane(self, lane: _DispatchLane,
                        batch: list[VerifyRequest], now: float) -> None:
        """One batch through one device dispatch lane, as its own task:
        dispatch, demux, lane accounting, then wake the loop so the
        freed lane is refilled immediately."""
        lane_lbl = str(lane.index)
        _METRICS.gauge("lane_inflight", lane=lane_lbl).set(1)
        t0 = time.perf_counter()
        try:
            verdicts, served_by = await self._dispatch(batch, lane)
        except Exception as exc:  # noqa: BLE001 — fail the batch, not the lane
            msg = f"{type(exc).__name__}: {exc}"
            for req in batch:
                self._resolve(req, VerifyResult(
                    status=STATUS_ERROR, error=msg))
        else:
            self._demux(batch, verdicts, dispatch_t=now,
                        served_by=served_by, lane=lane.index)
        finally:
            busy_s = time.perf_counter() - t0
            lane.busy = False
            lane.inflight = []
            lane.dispatches += 1
            lane.rows += len(batch)
            lane.busy_s += busy_s
            _METRICS.counter("lane_dispatch_total", lane=lane_lbl).add()
            _METRICS.counter("lane_rows_total",
                             lane=lane_lbl).add(len(batch))
            _METRICS.counter("lane_busy_seconds",
                             lane=lane_lbl).add(busy_s)
            _METRICS.gauge("lane_inflight", lane=lane_lbl).set(0)
            if self._wake is not None:
                self._wake.set()

    async def _dispatch(self, batch: list[VerifyRequest],
                        lane: _DispatchLane | None = None):
        """One batch through the resilient device path, under a shared
        ``serve.batch`` span cross-linked with every member request's
        span (the OpenTelemetry link pattern for fan-in: N request traces
        reference one batch span and vice versa).

        Returns ``(verdicts, served_by)``.
        """
        if lane is None:
            lane = self._lanes[0]
        group = batch[0].group
        bucket = self.config.bucket_for(len(batch))
        warm_key = (group, bucket)
        # compile-cache classification: prewarm covers range buckets (and
        # block shapes when prewarm_block); anything else is warm only
        # after its first dispatch
        prewarmed = bucket in lane.prewarm.ready and (
            group == KIND_RANGE or self.config.prewarm_block)
        PROFILER.record_cache_event(
            "serve_dispatch", hit=prewarmed
            or warm_key in self._warm_shapes)
        self._warm_shapes.add(warm_key)
        bspan = _TRACER.start_span("serve.batch", group=group,
                                   rows=len(batch), bucket=bucket,
                                   lane=lane.index)
        for req in batch:
            if req.span is not None:
                bspan.add_link(req.span, role="member")
                req.span.add_link(bspan, role="batch")
        JOURNAL.record(EVENT_BATCH_FORMED, group=group, rows=len(batch),
                       bucket=bucket, span_id=bspan.span_id,
                       trace_id=f"{bspan.trace_id:016x}")
        JOURNAL.record(EVENT_DISPATCH_START, group=group,
                       rows=len(batch), bucket=bucket, lane=lane.index,
                       span_id=bspan.span_id,
                       trace_id=f"{bspan.trace_id:016x}")
        outcome = "error"
        try:
            verdicts, served_by = await self._dispatch_resilient(
                batch, bspan, lane)
            bspan.set_attribute("served_by", served_by)
            outcome = served_by
            return verdicts, served_by
        except Exception as exc:
            bspan.set_attribute("error", f"{type(exc).__name__}: {exc}")
            outcome = f"error: {type(exc).__name__}"
            raise
        finally:
            JOURNAL.record(EVENT_DISPATCH_END, group=group,
                           rows=len(batch), span_id=bspan.span_id,
                           outcome=outcome,
                           trace_id=f"{bspan.trace_id:016x}")
            _TRACER.end_span(bspan)
            PROFILER.record_memory_watermark()

    async def _dispatch_resilient(self, batch: list[VerifyRequest],
                                  bspan, lane: _DispatchLane):
        """Attempt order: device call (watchdog-bounded, on the LANE's
        executor thread against the lane's verifier) with retry on
        transient errors while the breaker admits traffic; then the host
        fallback; then raise the last error (the batch completes with
        ``status="error"``)."""
        if self.resilience is None:
            return (await lane.watchdog.run(self._run_batch, batch,
                                            bspan, lane),
                    SERVED_BY_DEVICE)
        last_exc: Exception | None = None
        delays = self._retry.delays()
        for attempt in range(self._retry.max_attempts):
            if not self._breaker.allow():
                break
            try:
                verdicts = await lane.watchdog.run(self._run_batch, batch,
                                                   bspan, lane)
            except Exception as exc:  # noqa: BLE001 — classified below
                self._breaker.record_failure()
                last_exc = exc
                if not self._retry.is_transient(exc):
                    break
                if attempt + 1 < self._retry.max_attempts:
                    delay = next(delays)
                    # pause() does the resil_retries_total / resil.retry
                    # bookkeeping; the actual wait must be async.
                    self._retry.pause(delay, sleep=lambda _s: None,
                                      parent=bspan)
                    await asyncio.sleep(delay)
                continue
            self._breaker.record_success()
            return verdicts, SERVED_BY_DEVICE
        if self._fallback is not None:
            group = batch[0].group
            JOURNAL.record(
                EVENT_FALLBACK, group=group, rows=len(batch),
                why=(f"{type(last_exc).__name__}" if last_exc is not None
                     else f"breaker {self._breaker.state}"),
                trace_id=f"{bspan.trace_id:016x}")
            with _TRACER.span("resil.fallback", parent=bspan, group=group,
                              rows=len(batch)):
                verdicts = await asyncio.get_running_loop().run_in_executor(
                    lane.watchdog.executor,
                    self._fallback.verify_batch, batch)
            _METRICS.counter("resil_fallback_batches_total",
                             group=group).add()
            return verdicts, SERVED_BY_HOST
        if last_exc is not None:
            raise last_exc
        raise RuntimeError(
            "circuit breaker open and no host fallback configured")

    # ----------------------------------------------------- device batches
    def _run_batch(self, batch: list[VerifyRequest], bspan,
                   lane: _DispatchLane) -> np.ndarray:
        """Runs on the lane's executor thread: one blocking device call
        against the lane's verifier.

        Returns a bool vector aligned with ``batch`` order.
        """
        group = batch[0].group
        t0 = time.perf_counter()
        # explicit parent: contextvars do not cross run_in_executor, so
        # the batch span is threaded through as an argument
        with _TRACER.span("serve.dispatch", parent=bspan,
                          group=group, rows=len(batch),
                          bucket=self.config.bucket_for(len(batch))):
            if group == KIND_RANGE:
                proofs = [r.payload[0] for r in batch]
                coms = [r.payload[1] for r in batch]
                verdicts = np.asarray(
                    lane.zk._range.verify(proofs, coms), dtype=bool)
            else:
                transfers, issues, slots = [], [], []
                for r in batch:
                    if r.kind == KIND_TRANSFER:
                        slots.append((0, len(transfers)))
                        transfers.append(r.payload)
                    else:
                        slots.append((1, len(issues)))
                        issues.append(r.payload)
                t_ok, i_ok = lane.zk.verify_block(transfers, issues)
                t_ok = np.asarray(t_ok, dtype=bool).reshape(-1)
                i_ok = np.asarray(i_ok, dtype=bool).reshape(-1)
                verdicts = np.asarray(
                    [(i_ok if which else t_ok)[idx] for which, idx in slots],
                    dtype=bool)
        _METRICS.counter("serve_batches_total", group=group).add()
        _METRICS.histogram("serve_dispatch_seconds",
                           group=group).observe(time.perf_counter() - t0)
        return verdicts

    # -------------------------------------------------------- completion
    def _demux(self, batch, verdicts, dispatch_t: float,
               served_by: str = SERVED_BY_DEVICE, lane: int = 0) -> None:
        now = time.perf_counter()
        rows = len(batch)
        bucket = self.config.bucket_for(rows)
        for req, acc in zip(batch, verdicts):
            miss = now > req.deadline
            status = STATUS_DEADLINE_MISS if miss else STATUS_OK
            if miss:
                _METRICS.counter("serve_deadline_miss_total",
                                 where="served").add()
            exemplar = None
            if req.span is not None:
                # bounded exemplar slot: the traced request's id rides
                # on the bucket its wait time lands in
                exemplar = {"trace_id": f"{req.span.trace_id:016x}"}
                _METRICS.counter("span_exemplars_total",
                                 family="serve_wait_seconds").add()
            _METRICS.histogram(
                "serve_wait_seconds",
                lane=req.lane).observe(dispatch_t - req.enqueue_t,
                                       exemplar=exemplar)
            if self.tenant_slo is not None:
                # tenant-bounded: only recorded while a TenantSloMonitor
                # is attached; its max_tenants LRU eviction removes these
                # series via _evict_tenant_series
                _METRICS.histogram(
                    "serve_tenant_queue_seconds",
                    tms_id=req.tenant).observe(dispatch_t - req.enqueue_t)
            if req.span is not None:
                _TRACER.record_span("serve.queue_wait", req.enqueue_t,
                                    dispatch_t, parent=req.span,
                                    lane=req.lane, tenant=req.tenant)
            self._resolve(req, VerifyResult(
                status=status, accepted=bool(acc),
                wait_s=dispatch_t - req.enqueue_t,
                total_s=now - req.enqueue_t,
                bucket=bucket, batch_rows=rows, served_by=served_by,
                device_lane=lane))

    def _complete_expired(self, req: VerifyRequest, now: float) -> None:
        _METRICS.counter("serve_deadline_miss_total",
                         where="queued").add()
        if req.span is not None:
            _TRACER.record_span("serve.queue_wait", req.enqueue_t, now,
                                parent=req.span, lane=req.lane)
        self._resolve(req, VerifyResult(
            status=STATUS_DEADLINE_MISS,
            total_s=now - req.enqueue_t))

    def _finish_request_span(self, req: VerifyRequest,
                             result: VerifyResult) -> None:
        sp = req.span
        if sp is None:
            return
        req.span = None
        sp.set_attribute("status", result.status)
        if result.served_by:
            sp.set_attribute("served_by", result.served_by)
        if result.accepted is not None:
            sp.add_event("verdict", accepted=bool(result.accepted))
        _TRACER.end_span(sp)

    def _resolve(self, req: VerifyRequest, result: VerifyResult) -> None:
        # exactly-once: the drain-timeout path and a late demux can both
        # reach a request; only the first resolution counts anywhere
        # (metrics, SLO, WAL, future)
        if req.terminal:
            return
        req.terminal = True
        _METRICS.counter("serve_results_total",
                         status=result.status).add()
        if result.status == STATUS_SHUTDOWN:
            JOURNAL.record(EVENT_REQUEST_SHUTDOWN, req_kind=req.kind,
                           lane=req.lane, req_id=req.req_id,
                           error=result.error)
        ok = result.status == STATUS_OK
        if self.slo is not None:
            self.slo.record(ok, result.total_s if ok else None)
        if self.tenant_slo is not None:
            self.tenant_slo.record(req.tenant, ok,
                                   result.total_s if ok else None)
            exemplar = None
            if req.span is not None:
                exemplar = {"trace_id": f"{req.span.trace_id:016x}"}
                _METRICS.counter(
                    "span_exemplars_total",
                    family="serve_tenant_e2e_seconds").add()
            # tenant-bounded: recorded only with a TenantSloMonitor
            # attached; evicted via _evict_tenant_series
            _METRICS.histogram(
                "serve_tenant_e2e_seconds",
                tms_id=req.tenant).observe(result.total_s,
                                           exemplar=exemplar)
        if self.wal is not None and req.wal_id is not None:
            open_rows = self._wal_batch_open.get(req.wal_id)
            if open_rows is None:
                self.wal.append_resolve(req.wal_id, status=result.status,
                                        accepted=result.accepted,
                                        served_by=result.served_by)
            elif open_rows <= 1:
                # last row of a batch frame: the single resolve record
                del self._wal_batch_open[req.wal_id]
                self.wal.append_resolve(req.wal_id, status=result.status,
                                        accepted=result.accepted,
                                        served_by=result.served_by)
            else:
                self._wal_batch_open[req.wal_id] = open_rows - 1
        self._finish_request_span(req, result)
        if req.future is not None and not req.future.done():
            req.future.set_result(result)

    # ----------------------------------------------------------- statusz
    def status(self) -> dict:
        """JSON-serializable point-in-time snapshot for /statusz."""
        out = {
            "running": self._running,
            "queue_depth": {lane: self.scheduler.lane_depth(lane)
                            for lane in self.config.lanes},
            "inflight_rows": len(self._inflight),
            "lanes": [{
                "index": lane.index,
                "busy": lane.busy,
                "dispatches": lane.dispatches,
                "rows": lane.rows,
                "busy_s": round(lane.busy_s, 3),
                "prewarm_ready": sorted(lane.prewarm.ready),
            } for lane in self._lanes],
            "prewarm": {
                "ready": sorted(self.prewarm.ready),
                "compile_s": {str(b): round(s, 3) for b, s in
                              sorted(self.prewarm.compile_s.items())},
                "total_s": round(self.prewarm.total_s, 3),
            },
            "config": {
                "buckets": list(self.config.buckets),
                "max_wait_s": self.config.max_wait_s,
                "queue_capacity": self.config.queue_capacity,
                "default_deadline_s": self.config.default_deadline_s,
                "trace_every": self.config.trace_every,
            },
        }
        if self._breaker is not None:
            out["breaker"] = {
                "state": self._breaker.state,
                "failure_rate": round(self._breaker.failure_rate, 4),
            }
        if self.slo is not None:
            out["slo"] = self.slo.summary()
        if self.tenant_slo is not None:
            out["tenants"] = self.tenant_status()
        return out

    def tenant_status(self) -> dict:
        """Per-tenant operator table for /tenantz: the TenantSloMonitor
        summary (burn, budget, sheds, trips) joined with the scheduler's
        live queue view (queued rows, DRR deficit) and in-flight rows.
        ``{"enabled": False}`` without a monitor."""
        if self.tenant_slo is None:
            return {"enabled": False}
        out = self.tenant_slo.summary()
        out["enabled"] = True
        out["shed_policy_enabled"] = (
            self.admission.tenant_shed is not None
            and self.admission.tenant_shed.enabled)
        queued = self.scheduler.tenant_status()
        inflight: dict[str, int] = {}
        for req in self._inflight:
            inflight[req.tenant] = inflight.get(req.tenant, 0) + 1
        for tenant, row in out["tenants"].items():
            sched = queued.get(tenant, {})
            row["queued"] = sched.get("queued", 0)
            row["deficit"] = round(sched.get("deficit", 0.0), 3)
            row["inflight"] = inflight.get(tenant, 0)
        return out
