"""Async verification frontend: continuous batching over the ZK backends.

``VerificationService`` accepts individual verification requests
(``submit_range`` / ``submit_transfer`` / ``submit_issue``), assembles
them into pow-2-bucketed batches under the ``ServeConfig`` policy, runs
each batch through the SAME entry points the unbatched path uses
(``BatchRangeVerifier.verify`` for range rows, ``ZKVerifier.verify_block``
for transfer/issue actions), and demultiplexes the per-row verdicts back
to each caller's future — bit-identically to what a direct call on the
same payload would return.

Threading model: all scheduler/queue state lives on the event loop; the
blocking device call runs on a dedicated single-thread executor (owned by
the resilience watchdog) via ``run_in_executor``, so exactly one batch is
in flight at a time and arrivals keep queueing while the device works
(continuous batching). Futures resolve on the event loop after the
executor returns — no cross-thread future writes.

Failure handling (resilience/): with a :class:`ResilienceConfig` the
dispatch is wrapped in retry (transient errors, seeded decorrelated
jitter), a circuit breaker (failure-rate window, half-open probes), a
watchdog that abandons hung device calls on a fresh executor thread, and
a host fallback that routes exhausted/broken-open batches through the
pure-host proof verifiers for bit-identical verdicts. Results carry
``served_by="device"`` or ``"host"``; a batch only terminates in
``error`` when every layer is out of options.

Every stage is observable: admission counts, queue-depth gauges,
wait/dispatch histograms, shed/deadline-miss counters (all under the
stable ``serve_*`` family), retry/breaker/fallback/watchdog counters
(``resil_*``), plus ``serve.dispatch`` / ``resil.retry`` /
``resil.fallback`` spans.

Trace propagation (``ServeConfig.trace_every``): every Nth admitted
request opens a ``serve.request`` root span with its own trace id, which
the service carries across the whole lifetime the contextvar cannot
(coroutine -> scheduler queue -> executor thread): queue wait lands as a
``serve.queue_wait`` child at dispatch, the shared per-batch
``serve.batch`` span cross-links with every member request's span, and
``serve.dispatch`` / ``resil.retry`` / ``resil.fallback`` spans parent
under the batch span — so one request's admission/wait/dispatch/retry
history is a connected chain in the Chrome-trace export. An optional
:class:`~fabric_token_sdk_tpu.obs.slo.SloMonitor` receives every
terminal result, and the device profiler records compile-cache hit/miss
and memory watermarks per dispatch.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..obs.journal import (EVENT_BATCH_FORMED, EVENT_DISPATCH_END,
                           EVENT_DISPATCH_START, EVENT_FALLBACK,
                           EVENT_REQUEST_ADMITTED, EVENT_REQUEST_SHED,
                           EVENT_REQUEST_SHUTDOWN, EVENT_WAL_REPLAY,
                           JOURNAL)
from ..obs.profiling import PROFILER
from ..resilience import DispatchWatchdog, HostFallbackVerifier, \
    ResilienceConfig
from .admission import AdmissionController
from .config import LANE_BULK, ServeConfig
from .prewarm import PrewarmManager
from .request import (KIND_ISSUE, KIND_RANGE, KIND_TRANSFER,
                      SERVED_BY_DEVICE, SERVED_BY_HOST,
                      STATUS_DEADLINE_MISS, STATUS_ERROR, STATUS_OK,
                      STATUS_SHUTDOWN, VerifyRequest, VerifyResult)
from .scheduler import BucketScheduler

#: Family metadata for every serve_* instrument this module touches,
#: hoisted so the HELP line cannot depend on which call site registers a
#: family first (``_complete_expired`` vs ``_demux`` used to race on
#: ``serve_deadline_miss_total``). Registered via ``describe`` at service
#: construction — call-order independent by construction.
_SERVE_FAMILIES = {
    "serve_batches_total": "Device batches dispatched",
    "serve_dispatch_seconds": "Blocking device-call wall per batch",
    "serve_deadline_miss_total": "Requests whose deadline passed, by where",
    "serve_wait_seconds": "Enqueue -> dispatch wait per request",
    "serve_results_total": "Completed requests by terminal status",
    "resil_fallback_batches_total":
        "Batches served by the host fallback path, by group",
}


class VerificationService:
    """Continuous-batching frontend over a ``ZKVerifier``.

    Lifecycle::

        svc = VerificationService(zk, config=ServeConfig(...),
                                  resilience=ResilienceConfig(...))
        prewarm_s = await svc.start()      # compiles every bucket shape
        res = await svc.submit_range(proof, com, deadline_s=0.5)
        assert res.ok and res.accepted and res.served_by == "device"
        await svc.stop(timeout_s=30.0)     # bounded drain, then stop

    ``resilience=None`` (the default) preserves the bare dispatch
    behaviour: one attempt, no breaker, no watchdog, no fallback —
    failures complete the batch with ``status="error"``.

    ``slo`` optionally attaches an :class:`SloMonitor` that receives
    every terminal result (``slo.bind_breaker(svc.breaker)`` wires
    fast-burn to the breaker's kill switch).
    """

    def __init__(self, zk, config: ServeConfig | None = None,
                 resilience: ResilienceConfig | None = None,
                 fallback=None, slo=None, wal=None):
        self.zk = zk
        self.wal = wal
        #: (wal_id, VerifyResult) pairs replayed at the last ``start()``.
        self.replayed: list[tuple[int, VerifyResult]] = []
        self.config = config or ServeConfig()
        self.resilience = resilience
        self.slo = slo
        self.scheduler = BucketScheduler(self.config)
        self.admission = AdmissionController(self.config)
        self.prewarm = PrewarmManager(zk, self.config)
        self.prewarm_s: float | None = None
        self.first_dispatch_t: float | None = None
        for fam, help_text in _SERVE_FAMILIES.items():
            _METRICS.describe(fam, help_text)
        self._watchdog = DispatchWatchdog(
            timeout_s=(resilience.watchdog_timeout_s
                       if resilience is not None else None))
        if resilience is not None:
            self._retry = resilience.build_retry_policy(op="serve_dispatch")
            self._breaker = resilience.build_breaker(name="device")
            if fallback is None and resilience.fallback \
                    and getattr(zk, "pp", None) is not None:
                fallback = HostFallbackVerifier(zk.pp)
        else:
            self._retry = None
            self._breaker = None
        self._fallback = fallback
        self._inflight: list[VerifyRequest] = []
        self._task: asyncio.Task | None = None
        self._wake: asyncio.Event | None = None
        self._running = False
        # (group, bucket) shapes already dispatched/prewarmed — the basis
        # of the profile_compile_cache_total hit/miss classification
        self._warm_shapes: set[tuple] = set()
        # the in-flight batch's span: exactly one batch is in flight at a
        # time, and the executor thread cannot see the event loop's
        # contextvars, so explicit hand-off is both safe and required
        self._batch_span = None

    @property
    def breaker(self):
        """The dispatch circuit breaker (None without resilience)."""
        return self._breaker

    # ---------------------------------------------------------- lifecycle
    async def start(self, prewarm: bool = True) -> float:
        """Prewarm every configured bucket, then start the dispatch loop.

        Returns the prewarm wall seconds (0.0 when ``prewarm=False``) so
        callers can report startup cost separately from steady state.
        """
        if self._running:
            return self.prewarm_s or 0.0
        loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        if prewarm:
            # no watchdog here: first-compile legitimately takes minutes
            self.prewarm_s = await loop.run_in_executor(
                self._watchdog.executor, self.prewarm.run)
        self._running = True
        self._task = asyncio.create_task(self._dispatch_loop())
        if self.wal is not None:
            await self._replay_wal()
        return self.prewarm_s or 0.0

    async def _replay_wal(self) -> None:
        """Crash recovery: push every admitted-but-unresolved WAL entry
        back through the normal dispatch path (same scheduler, same
        device call — bit-identical verdicts) and wait for their
        terminal verdicts. Replays bypass admission: each entry was
        already admitted once, and shedding it now would turn a durable
        promise into a loss. Results land in :attr:`replayed` and each
        resolution is logged to the WAL exactly once under the
        original id."""
        entries = self.wal.recover()
        self.replayed = []
        if not entries:
            return
        loop = asyncio.get_running_loop()
        now = time.perf_counter()
        reqs = []
        for e in entries:
            # deadline is re-based on the replay instant: the original
            # wall deadline is long past, and expiring a recovered
            # request unexamined would defeat the replay
            deadline_s = max(e.deadline_s, self.config.default_deadline_s)
            req = VerifyRequest(kind=e.kind, payload=e.payload,
                                lane=e.lane, deadline=now + deadline_s,
                                enqueue_t=now, future=loop.create_future(),
                                wal_id=e.wal_id)
            JOURNAL.record(EVENT_WAL_REPLAY, req_kind=e.kind, lane=e.lane,
                           wal_id=e.wal_id)
            _METRICS.counter("wal_replayed_total").add()
            self.scheduler.push(req)
            reqs.append(req)
        self._wake.set()
        results = await asyncio.gather(*(r.future for r in reqs))
        self.replayed = [(r.wal_id, res) for r, res in zip(reqs, results)]

    async def abort(self) -> None:
        """Simulate a crash: cancel the dispatch loop WITHOUT resolving
        queued or in-flight requests. Their futures never resolve (as
        in a real SIGKILL — callers must not await them past this) and
        the WAL keeps their admit records unresolved, so a successor
        service constructed over the same WAL directory replays them.
        Test/drill hook for the crash-recovery contract."""
        if not self._running:
            return
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    async def stop(self, drain: bool = True,
                   timeout_s: float | None = None) -> None:
        """Stop the dispatch loop.

        With ``drain`` every queued request is served (or expires) first;
        without it the queued requests complete with ``error``. A
        ``timeout_s`` bounds the drain: past it, still-queued and
        in-flight requests resolve with the terminal ``shutdown`` status
        and the loop is cancelled — ``stop`` can no longer block forever
        behind a hung device call.
        """
        if not self._running:
            return
        self._running = False
        if not drain:
            for req in self._drain_queues():
                self._resolve(req, VerifyResult(
                    status=STATUS_ERROR, error="service stopped"))
        self._wake.set()
        if timeout_s is None:
            await self._task
        else:
            try:
                await asyncio.wait_for(asyncio.shield(self._task),
                                       timeout_s)
            except asyncio.TimeoutError:
                for req in self._drain_queues() + list(self._inflight):
                    self._resolve(req, VerifyResult(
                        status=STATUS_SHUTDOWN,
                        error=f"service stopped after {timeout_s}s drain "
                              "timeout"))
                self._task.cancel()
                try:
                    await self._task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        self._task = None

    def _drain_queues(self) -> list[VerifyRequest]:
        out = []
        for q in self.scheduler._queues.values():
            out.extend(q)
            q.clear()
        return out

    # ------------------------------------------------------------- submit
    async def submit_range(self, proof, commitment, *, deadline_s=None,
                           lane: str = LANE_BULK) -> VerifyResult:
        """Verify one range proof against its commitment."""
        return await self._submit(KIND_RANGE, (proof, commitment),
                                  deadline_s, lane)

    async def submit_transfer(self, proof_raw, inputs, outputs, *,
                              deadline_s=None,
                              lane: str = LANE_BULK) -> VerifyResult:
        """Verify one transfer action (serialized proof + token vectors)."""
        return await self._submit(KIND_TRANSFER, (proof_raw, inputs, outputs),
                                  deadline_s, lane)

    async def submit_issue(self, proof_raw, outputs, *, deadline_s=None,
                           lane: str = LANE_BULK) -> VerifyResult:
        """Verify one issue action (serialized proof + output tokens)."""
        return await self._submit(KIND_ISSUE, (proof_raw, outputs),
                                  deadline_s, lane)

    async def _submit(self, kind, payload, deadline_s, lane) -> VerifyResult:
        if not self._running:
            raise RuntimeError("VerificationService is not started")
        now = time.perf_counter()
        deadline_s = (self.config.default_deadline_s
                      if deadline_s is None else deadline_s)
        req = VerifyRequest(kind=kind, payload=payload, lane=lane,
                            deadline=now + deadline_s, enqueue_t=now,
                            future=asyncio.get_running_loop().create_future())
        if self.config.trace_every \
                and req.req_id % self.config.trace_every == 0:
            req.span = _TRACER.start_span(
                "serve.request", kind=kind, lane=lane, req_id=req.req_id,
                deadline_s=round(deadline_s, 6))
        shed = self.admission.admit(req, self.scheduler.lane_depth(lane))
        if shed is not None:
            result = VerifyResult(status=shed)
            JOURNAL.record(EVENT_REQUEST_SHED, req_kind=kind, lane=lane,
                           req_id=req.req_id, status=shed)
            if self.slo is not None:
                self.slo.record(False)
            self._finish_request_span(req, result)
            return result
        JOURNAL.record(EVENT_REQUEST_ADMITTED, req_kind=kind, lane=lane,
                       req_id=req.req_id,
                       depth=self.scheduler.lane_depth(lane))
        if self.wal is not None:
            # durability point: once this line is flushed the request
            # survives a SIGKILL — a successor service replays it
            req.wal_id = self.wal.append_admit(
                kind=kind, lane=lane, deadline_s=deadline_s,
                payload=payload)
        if req.span is not None:
            req.span.add_event(
                "admitted", depth=self.scheduler.lane_depth(lane))
        self.scheduler.push(req)
        self._wake.set()
        return await req.future

    # ------------------------------------------------------ dispatch loop
    async def _dispatch_loop(self) -> None:
        while True:
            now = time.perf_counter()
            for req in self.scheduler.expire(now):
                self._complete_expired(req, now)
            batch = self.scheduler.assemble(now)
            if batch:
                if self.first_dispatch_t is None:
                    self.first_dispatch_t = now
                self._inflight = batch
                try:
                    verdicts, served_by = await self._dispatch(batch)
                except Exception as exc:  # noqa: BLE001 — fail the batch, not the loop
                    msg = f"{type(exc).__name__}: {exc}"
                    for req in batch:
                        self._resolve(req, VerifyResult(
                            status=STATUS_ERROR, error=msg))
                else:
                    self._demux(batch, verdicts, dispatch_t=now,
                                served_by=served_by)
                finally:
                    self._inflight = []
                continue
            if not self._running and self.scheduler.depth() == 0:
                return
            nxt = self.scheduler.next_event(time.perf_counter())
            self._wake.clear()
            # Re-check after clear: a push between assemble() and clear()
            # would otherwise sleep through its max-wait window.
            if self.scheduler.depth() and nxt is None:
                continue
            try:
                if nxt is None:
                    await self._wake.wait()
                else:
                    delay = max(0.0, nxt - time.perf_counter())
                    await asyncio.wait_for(self._wake.wait(), delay)
            except asyncio.TimeoutError:
                pass

    async def _dispatch(self, batch: list[VerifyRequest]):
        """One batch through the resilient device path, under a shared
        ``serve.batch`` span cross-linked with every member request's
        span (the OpenTelemetry link pattern for fan-in: N request traces
        reference one batch span and vice versa).

        Returns ``(verdicts, served_by)``.
        """
        group = batch[0].group
        bucket = self.config.bucket_for(len(batch))
        warm_key = (group, bucket)
        # compile-cache classification: prewarm covers range buckets (and
        # block shapes when prewarm_block); anything else is warm only
        # after its first dispatch
        prewarmed = bucket in self.prewarm.ready and (
            group == KIND_RANGE or self.config.prewarm_block)
        PROFILER.record_cache_event(
            "serve_dispatch", hit=prewarmed
            or warm_key in self._warm_shapes)
        self._warm_shapes.add(warm_key)
        bspan = _TRACER.start_span("serve.batch", group=group,
                                   rows=len(batch), bucket=bucket)
        for req in batch:
            if req.span is not None:
                bspan.add_link(req.span, role="member")
                req.span.add_link(bspan, role="batch")
        self._batch_span = bspan
        JOURNAL.record(EVENT_BATCH_FORMED, group=group, rows=len(batch),
                       bucket=bucket, span_id=bspan.span_id)
        JOURNAL.record(EVENT_DISPATCH_START, group=group,
                       rows=len(batch), bucket=bucket,
                       span_id=bspan.span_id)
        outcome = "error"
        try:
            verdicts, served_by = await self._dispatch_resilient(batch,
                                                                 bspan)
            bspan.set_attribute("served_by", served_by)
            outcome = served_by
            return verdicts, served_by
        except Exception as exc:
            bspan.set_attribute("error", f"{type(exc).__name__}: {exc}")
            outcome = f"error: {type(exc).__name__}"
            raise
        finally:
            JOURNAL.record(EVENT_DISPATCH_END, group=group,
                           rows=len(batch), span_id=bspan.span_id,
                           outcome=outcome)
            self._batch_span = None
            _TRACER.end_span(bspan)
            PROFILER.record_memory_watermark()

    async def _dispatch_resilient(self, batch: list[VerifyRequest],
                                  bspan):
        """Attempt order: device call (watchdog-bounded) with retry on
        transient errors while the breaker admits traffic; then the host
        fallback; then raise the last error (the batch completes with
        ``status="error"``)."""
        if self.resilience is None:
            return (await self._watchdog.run(self._run_batch, batch),
                    SERVED_BY_DEVICE)
        last_exc: Exception | None = None
        delays = self._retry.delays()
        for attempt in range(self._retry.max_attempts):
            if not self._breaker.allow():
                break
            try:
                verdicts = await self._watchdog.run(self._run_batch, batch)
            except Exception as exc:  # noqa: BLE001 — classified below
                self._breaker.record_failure()
                last_exc = exc
                if not self._retry.is_transient(exc):
                    break
                if attempt + 1 < self._retry.max_attempts:
                    delay = next(delays)
                    # pause() does the resil_retries_total / resil.retry
                    # bookkeeping; the actual wait must be async.
                    self._retry.pause(delay, sleep=lambda _s: None,
                                      parent=bspan)
                    await asyncio.sleep(delay)
                continue
            self._breaker.record_success()
            return verdicts, SERVED_BY_DEVICE
        if self._fallback is not None:
            group = batch[0].group
            JOURNAL.record(
                EVENT_FALLBACK, group=group, rows=len(batch),
                why=(f"{type(last_exc).__name__}" if last_exc is not None
                     else f"breaker {self._breaker.state}"))
            with _TRACER.span("resil.fallback", parent=bspan, group=group,
                              rows=len(batch)):
                verdicts = await asyncio.get_running_loop().run_in_executor(
                    self._watchdog.executor,
                    self._fallback.verify_batch, batch)
            _METRICS.counter("resil_fallback_batches_total",
                             group=group).add()
            return verdicts, SERVED_BY_HOST
        if last_exc is not None:
            raise last_exc
        raise RuntimeError(
            "circuit breaker open and no host fallback configured")

    # ----------------------------------------------------- device batches
    def _run_batch(self, batch: list[VerifyRequest]) -> np.ndarray:
        """Runs on the executor thread: one blocking device call.

        Returns a bool vector aligned with ``batch`` order.
        """
        group = batch[0].group
        t0 = time.perf_counter()
        # explicit parent: contextvars do not cross run_in_executor, and
        # exactly one batch is in flight, so _batch_span is unambiguous
        with _TRACER.span("serve.dispatch", parent=self._batch_span,
                          group=group, rows=len(batch),
                          bucket=self.config.bucket_for(len(batch))):
            if group == KIND_RANGE:
                proofs = [r.payload[0] for r in batch]
                coms = [r.payload[1] for r in batch]
                verdicts = np.asarray(
                    self.zk._range.verify(proofs, coms), dtype=bool)
            else:
                transfers, issues, slots = [], [], []
                for r in batch:
                    if r.kind == KIND_TRANSFER:
                        slots.append((0, len(transfers)))
                        transfers.append(r.payload)
                    else:
                        slots.append((1, len(issues)))
                        issues.append(r.payload)
                t_ok, i_ok = self.zk.verify_block(transfers, issues)
                t_ok = np.asarray(t_ok, dtype=bool).reshape(-1)
                i_ok = np.asarray(i_ok, dtype=bool).reshape(-1)
                verdicts = np.asarray(
                    [(i_ok if which else t_ok)[idx] for which, idx in slots],
                    dtype=bool)
        _METRICS.counter("serve_batches_total", group=group).add()
        _METRICS.histogram("serve_dispatch_seconds",
                           group=group).observe(time.perf_counter() - t0)
        return verdicts

    # -------------------------------------------------------- completion
    def _demux(self, batch, verdicts, dispatch_t: float,
               served_by: str = SERVED_BY_DEVICE) -> None:
        now = time.perf_counter()
        rows = len(batch)
        bucket = self.config.bucket_for(rows)
        for req, acc in zip(batch, verdicts):
            miss = now > req.deadline
            status = STATUS_DEADLINE_MISS if miss else STATUS_OK
            if miss:
                _METRICS.counter("serve_deadline_miss_total",
                                 where="served").add()
            _METRICS.histogram(
                "serve_wait_seconds",
                lane=req.lane).observe(dispatch_t - req.enqueue_t)
            if req.span is not None:
                _TRACER.record_span("serve.queue_wait", req.enqueue_t,
                                    dispatch_t, parent=req.span,
                                    lane=req.lane)
            self._resolve(req, VerifyResult(
                status=status, accepted=bool(acc),
                wait_s=dispatch_t - req.enqueue_t,
                total_s=now - req.enqueue_t,
                bucket=bucket, batch_rows=rows, served_by=served_by))

    def _complete_expired(self, req: VerifyRequest, now: float) -> None:
        _METRICS.counter("serve_deadline_miss_total",
                         where="queued").add()
        if req.span is not None:
            _TRACER.record_span("serve.queue_wait", req.enqueue_t, now,
                                parent=req.span, lane=req.lane)
        self._resolve(req, VerifyResult(
            status=STATUS_DEADLINE_MISS,
            total_s=now - req.enqueue_t))

    def _finish_request_span(self, req: VerifyRequest,
                             result: VerifyResult) -> None:
        sp = req.span
        if sp is None:
            return
        req.span = None
        sp.set_attribute("status", result.status)
        if result.served_by:
            sp.set_attribute("served_by", result.served_by)
        if result.accepted is not None:
            sp.add_event("verdict", accepted=bool(result.accepted))
        _TRACER.end_span(sp)

    def _resolve(self, req: VerifyRequest, result: VerifyResult) -> None:
        # exactly-once: the drain-timeout path and a late demux can both
        # reach a request; only the first resolution counts anywhere
        # (metrics, SLO, WAL, future)
        if req.terminal:
            return
        req.terminal = True
        _METRICS.counter("serve_results_total",
                         status=result.status).add()
        if result.status == STATUS_SHUTDOWN:
            JOURNAL.record(EVENT_REQUEST_SHUTDOWN, req_kind=req.kind,
                           lane=req.lane, req_id=req.req_id,
                           error=result.error)
        if self.slo is not None:
            ok = result.status == STATUS_OK
            self.slo.record(ok, result.total_s if ok else None)
        if self.wal is not None and req.wal_id is not None:
            self.wal.append_resolve(req.wal_id, status=result.status,
                                    accepted=result.accepted,
                                    served_by=result.served_by)
        self._finish_request_span(req, result)
        if req.future is not None and not req.future.done():
            req.future.set_result(result)

    # ----------------------------------------------------------- statusz
    def status(self) -> dict:
        """JSON-serializable point-in-time snapshot for /statusz."""
        out = {
            "running": self._running,
            "queue_depth": {lane: self.scheduler.lane_depth(lane)
                            for lane in self.config.lanes},
            "inflight_rows": len(self._inflight),
            "prewarm": {
                "ready": sorted(self.prewarm.ready),
                "compile_s": {str(b): round(s, 3) for b, s in
                              sorted(self.prewarm.compile_s.items())},
                "total_s": round(self.prewarm.total_s, 3),
            },
            "config": {
                "buckets": list(self.config.buckets),
                "max_wait_s": self.config.max_wait_s,
                "queue_capacity": self.config.queue_capacity,
                "default_deadline_s": self.config.default_deadline_s,
                "trace_every": self.config.trace_every,
            },
        }
        if self._breaker is not None:
            out["breaker"] = {
                "state": self._breaker.state,
                "failure_rate": round(self._breaker.failure_rate, 4),
            }
        if self.slo is not None:
            out["slo"] = self.slo.summary()
        return out
