"""Sidecar verification worker: the device backend in a child process.

Running the ZK backend inside the dispatcher's own process means a
device wedge or an OOM kills the whole serving plane. This module moves
the blocking verify calls into a supervised child process:

  - :func:`worker_main` is the child entry point: build the verifier
    from a picklable ``factory``, prewarm, then serve ``range`` /
    ``block`` calls over a ``multiprocessing.Pipe``. A daemon thread
    stamps the current phase (``boot -> prewarm -> ready``) into a
    heartbeat file at a fixed cadence, so a SIGSTOP'd or wedged worker
    is visible to the supervisor as a stall (the beats stop) while a
    SIGKILL'd one is visible as an exit.
  - :class:`WorkerClient` is the parent-side facade with the exact
    duck-type ``VerificationService`` dispatches on (``_range.verify``,
    ``verify_block``, ``pp``): transport failures — dead process,
    closed pipe, reply timeout — raise :class:`WorkerUnavailable`,
    which derives from :class:`TransientError`, so the existing
    resilience chain (retry -> breaker -> ``HostFallbackVerifier``)
    degrades service to the host path while the supervisor respawns
    and re-prewarms the worker. Availability degrades; it never zeroes.

``WorkerClient.spawn`` doubles as a :class:`ChildSpec.start` callable:
the supervisor hands it a :class:`RestartContext` and a cold restart
spawns the child with the warm-cache env cleared.

:class:`StubZK` is the crypto-free backend used by the worker/
supervisor tests and smoke drills: the "proof" object is its own
verdict, so parity across restarts is trivially checkable without jax.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time

import numpy as np

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..obs.heartbeat import Heartbeat, read_last
from ..obs.tracing import extract_wire_context
from ..resilience.retry import TransientError

#: Hard cap on an unconfigured reply wait — "no call timeout" must
#: still mean a *bounded* wait, or a wedged worker hangs the caller
#: with no diagnosis (see scripts/check_socket_timeouts.py).
_MAX_REPLY_WAIT_S = 3600.0

#: Worker heartbeat phases, in boot order.
PHASE_BOOT = "boot"
PHASE_PREWARM = "prewarm"
PHASE_READY = "ready"

#: Remote exception type names re-raised as transient on the parent
#: side (the same classification RetryPolicy applies locally).
_REMOTE_TRANSIENT_NAMES = frozenset(
    {"XlaRuntimeError", "TransientError", "InjectedTransientError",
     "ConnectionError", "TimeoutError"})


class WorkerUnavailable(TransientError):
    """The worker process is dead, unreachable, or silent past the call
    timeout. Transient by construction: the supervisor is (re)starting
    it, and until then the host fallback serves."""


# --------------------------------------------------------------- child
def worker_main(conn, factory, heartbeat_path=None, prewarm_buckets=(),
                include_block: bool = False,
                beat_interval_s: float = 0.25) -> None:
    """Child entry point (spawn context: ``factory`` must pickle).

    The child inherits the parent's env (JAX platform, cache dirs) at
    spawn; a cold restart's cleared cache env is inherited the same
    way."""
    hb = Heartbeat(heartbeat_path)
    phase = {"now": PHASE_BOOT}
    stop_beats = threading.Event()

    def _beater():
        # a separate thread so the beat cadence reflects scheduler
        # liveness: SIGSTOP freezes it (stall), a wedged verify call
        # does not (the GIL is released inside device calls)
        while not stop_beats.wait(beat_interval_s):
            hb.beat(phase["now"])

    hb.beat(PHASE_BOOT)
    threading.Thread(target=_beater, name="fts-worker-beat",
                     daemon=True).start()
    zk = factory()
    if prewarm_buckets and hasattr(zk, "prewarm_shapes"):
        phase["now"] = PHASE_PREWARM
        hb.beat(PHASE_PREWARM)
        try:
            zk.prewarm_shapes(tuple(prewarm_buckets),
                              include_block=include_block)
        except TypeError:
            zk.prewarm_shapes(tuple(prewarm_buckets))
    phase["now"] = PHASE_READY
    hb.beat(PHASE_READY)
    while True:
        try:
            # child idle wait: parent closing the pipe raises EOFError,
            # and the supervisor's kill ladder bounds a wedged child
            # io-deadline: bounded from outside (pipe EOF / supervisor)
            msg = conn.recv()
        except (EOFError, OSError):
            break
        op = msg[0]
        try:
            if op == "stop":
                conn.send(("ok", None))
                break
            if op == "ping":
                conn.send(("ok", os.getpid()))
            elif op == "range":
                # trailing optional element: caller's trace context
                # bytes (absent from old parents — both directions stay
                # pickle-compatible); poisoned bytes are counted and
                # ignored, never an error
                _, proofs, coms, *rest = msg
                ctx = (extract_wire_context(rest[0])
                       if rest and rest[0] is not None else None)
                with _TRACER.span("rpc.serve", remote_parent=ctx,
                                  kind="range", transport="pipe",
                                  rows=len(proofs)):
                    verdicts = np.asarray(
                        zk._range.verify(proofs, coms), dtype=bool)
                conn.send(("ok", verdicts))
            elif op == "block":
                _, transfers, issues, *rest = msg
                ctx = (extract_wire_context(rest[0])
                       if rest and rest[0] is not None else None)
                with _TRACER.span("rpc.serve", remote_parent=ctx,
                                  kind="block", transport="pipe",
                                  rows=len(transfers) + len(issues)):
                    t_ok, i_ok = zk.verify_block(transfers, issues)
                conn.send(("ok", (np.asarray(t_ok, dtype=bool),
                                  np.asarray(i_ok, dtype=bool))))
            else:
                conn.send(("err", "ValueError", f"unknown op {op!r}"))
        except Exception as exc:  # noqa: BLE001 — ship it to the parent
            try:
                conn.send(("err", type(exc).__name__, str(exc)))
            except (OSError, ValueError):
                break
    stop_beats.set()
    hb.close()


# -------------------------------------------------------------- parent
class _WorkerRange:
    """The ``zk._range`` facet of the worker facade."""

    def __init__(self, client: "WorkerClient"):
        self._client = client

    def verify(self, proofs, coms):
        return self._client._call("range", list(proofs), list(coms))


class WorkerClient:
    """Parent-side verifier facade over a supervised worker process.

    ``factory`` builds the real verifier inside the child (it must be
    picklable — a module-level function or ``functools.partial`` over
    one). ``pp`` is held parent-side so ``VerificationService`` can
    auto-build its ``HostFallbackVerifier`` for degraded mode.
    """

    def __init__(self, factory, pp=None, heartbeat_path=None,
                 prewarm_buckets=(), include_block: bool = False,
                 call_timeout_s: float | None = None,
                 name: str = "verify-worker", mp_context: str = "spawn"):
        self.factory = factory
        self.pp = pp
        self.name = name
        self.heartbeat_path = heartbeat_path
        self.prewarm_buckets = tuple(prewarm_buckets)
        self.include_block = include_block
        self.call_timeout_s = call_timeout_s
        self._ctx = mp.get_context(mp_context)
        self._range = _WorkerRange(self)
        self._state_lock = threading.Lock()   # conn/proc swap
        self._io_lock = threading.Lock()      # send/recv pairing
        self._conn = None
        self._proc = None

    # --------------------------------------------------------- lifecycle
    def spawn(self, ctx=None):
        """Spawn a fresh worker (ChildSpec.start-compatible: ``ctx`` is
        an optional RestartContext; cold-cache env is the supervisor's
        job). Returns the process handle; the previous pipe, if any, is
        closed so a blocked call fails over immediately."""
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=worker_main,
            args=(child_conn, self.factory, self.heartbeat_path,
                  self.prewarm_buckets, self.include_block),
            name=self.name, daemon=True)
        proc.start()
        child_conn.close()
        with self._state_lock:
            old_conn, self._conn = self._conn, parent_conn
            self._proc = proc
        if old_conn is not None:
            try:
                old_conn.close()
            except OSError:
                pass
        return proc

    def stop(self, timeout_s: float = 5.0) -> None:
        with self._state_lock:
            conn, proc = self._conn, self._proc
            self._conn = None
            self._proc = None
        if conn is not None:
            try:
                conn.send(("stop",))
                conn.poll(timeout_s)
            except (OSError, ValueError, BrokenPipeError):
                pass
            try:
                conn.close()
            except OSError:
                pass
        if proc is not None and proc.is_alive():
            proc.terminate()
            proc.join(timeout=timeout_s)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=timeout_s)

    # ------------------------------------------------------------- state
    @property
    def pid(self) -> int | None:
        proc = self._proc
        return proc.pid if proc is not None and proc.is_alive() else None

    def alive(self) -> bool:
        proc = self._proc
        return proc is not None and proc.is_alive()

    def phase(self) -> str | None:
        """Last heartbeat phase of the CURRENT worker pid (None before
        its first beat)."""
        if self.heartbeat_path is None:
            return PHASE_READY if self.alive() else None
        stamp = read_last(self.heartbeat_path)
        if stamp is None or stamp.get("pid") != self.pid:
            return None
        return stamp.get("phase")

    def wait_ready(self, timeout_s: float = 60.0) -> int:
        """Block until the worker answers a ping (it only enters the
        serve loop after prewarm); returns the worker pid."""
        deadline = time.monotonic() + timeout_s
        with self._io_lock:
            with self._state_lock:
                conn, proc = self._conn, self._proc
            if conn is None or proc is None:
                raise WorkerUnavailable(f"{self.name}: not spawned")
            try:
                conn.send(("ping",))
                while time.monotonic() < deadline:
                    if conn.poll(0.2):
                        # io-deadline: poll above bounds it
                        tag, payload = conn.recv()
                        if tag == "ok":
                            return payload
                        raise WorkerUnavailable(
                            f"{self.name}: ping failed: {payload}")
                    if not proc.is_alive():
                        raise WorkerUnavailable(
                            f"{self.name}: died during boot "
                            f"(exitcode {proc.exitcode})")
            except (EOFError, BrokenPipeError, OSError) as exc:
                raise WorkerUnavailable(
                    f"{self.name}: pipe failed during boot: "
                    f"{exc}") from exc
        raise WorkerUnavailable(
            f"{self.name}: not ready within {timeout_s}s")

    # -------------------------------------------------------------- calls
    def _call(self, op: str, *args):
        """One pipe round-trip. SINGLE-FLIGHT by design: ``_io_lock``
        is held across the full send/poll/recv pairing because the pipe
        is one stream with no request ids — interleaved sends would
        cross-deliver replies. Concurrent callers therefore serialize
        behind the slowest in-flight call; ``serve_worker_lock_wait_seconds``
        measures that queueing so it is visible, and the TCP
        ``RpcClient`` (serve/rpc_client.py) is the pipelined alternative
        when it matters."""
        with self._state_lock:
            conn, proc = self._conn, self._proc
        if conn is None or proc is None or not proc.is_alive():
            raise WorkerUnavailable(
                f"{self.name}: worker process is not running")
        if op in ("range", "block"):
            # inject the current span's context as a trailing element so
            # the child's rpc.serve span joins this trace across the
            # pipe hop (the child unpacks it via *rest, so a parent
            # that omits it stays compatible)
            sp = _TRACER.current()
            if sp is not None:
                args = (*args, sp.context().to_bytes())
        t_lock = time.perf_counter()
        with self._io_lock:
            _METRICS.histogram(
                "serve_worker_lock_wait_seconds",
                help="Time a WorkerClient call queued behind the "
                     "single-flight pipe lock, by op",
                op=op).observe(time.perf_counter() - t_lock)
            try:
                conn.send((op, *args))
                # the reply wait is ALWAYS bounded: call_timeout_s when
                # configured, else a generous hard cap — an unbounded
                # recv on a wedged worker is a silent rc=124
                timeout_s = (self.call_timeout_s
                             if self.call_timeout_s is not None
                             else _MAX_REPLY_WAIT_S)
                if not conn.poll(timeout_s):
                    raise WorkerUnavailable(
                        f"{self.name}: no reply to {op!r} within "
                        f"{timeout_s}s")
                reply = conn.recv()  # io-deadline: poll above bounds it
            except WorkerUnavailable:
                raise
            except (EOFError, BrokenPipeError, OSError,
                    ValueError) as exc:
                raise WorkerUnavailable(
                    f"{self.name}: pipe failed during {op!r}: "
                    f"{exc}") from exc
        if reply[0] == "ok":
            return reply[1]
        _, type_name, message = reply
        if type_name in _REMOTE_TRANSIENT_NAMES \
                or type_name.endswith("TransientError"):
            raise TransientError(f"worker {type_name}: {message}")
        raise RuntimeError(f"worker {type_name}: {message}")

    def verify_block(self, transfers, issues):
        return self._call("block", list(transfers), list(issues))

    def prewarm_shapes(self, buckets, include_block: bool = False):
        """PrewarmManager compatibility: the worker prewarms at boot,
        so a parent-side prewarm is one ready-wait, not a compile."""
        self.wait_ready()
        return {int(b): 0.0 for b in buckets}


# ------------------------------------------------------- stub backend
class _StubRange:
    def __init__(self, verify_delay_s: float = 0.0):
        self.verify_delay_s = verify_delay_s

    def verify(self, proofs, coms):
        del coms
        if self.verify_delay_s:
            # per-batch service time: lets C10k bench/tests pace the
            # verify stage without a real crypto backend
            time.sleep(self.verify_delay_s)
        return [bool(p) for p in proofs]


class StubZK:
    """Deterministic, dependency-free verifier for worker/supervisor
    tests and drills: each 'proof' is its own verdict (truthiness), so
    bit-identical replay across process kills is directly assertable.
    ``pp`` stays None so the service does not auto-build a fallback.
    ``verify_delay_s`` adds a fixed per-batch service time, modeling a
    busy device for connection-scaling tests."""

    pp = None

    def __init__(self, boot_delay_s: float = 0.0,
                 verify_delay_s: float = 0.0):
        if boot_delay_s:
            time.sleep(boot_delay_s)
        self._range = _StubRange(verify_delay_s)

    def verify_block(self, transfers, issues):
        return ([bool(t[0]) for t in transfers],
                [bool(i[0]) for i in issues])

    def prewarm_shapes(self, buckets, include_block: bool = False):
        del include_block
        return {int(b): 0.0 for b in buckets}


def stub_zk_factory():
    """Picklable worker factory for tests/drills."""
    return StubZK()


class StubHostFallback:
    """Host-fallback twin of :class:`StubZK` (same verdict function),
    for degraded-mode tests: verdicts stay bit-identical whether the
    worker or the 'host' serves them."""

    def verify_batch(self, batch) -> np.ndarray:
        return np.asarray([bool(r.payload[0]) for r in batch],
                          dtype=bool)
