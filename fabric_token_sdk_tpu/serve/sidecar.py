"""Supervised TCP sidecar process: a whole serving plane per child.

Where ``worker_main`` moves only the blocking verify call into a child,
``sidecar_main`` moves the *entire* front door — WAL, admission,
scheduler, dispatch, resilience ladder and the asyncio ``RpcServer`` —
into one supervised process that N clients (node processes, bench
drivers) dial over TCP. The parent-side :class:`RpcSidecar` facade is
``ChildSpec.start``-compatible, so the existing ``Supervisor`` kill
ladder, heartbeat-stall detection and cold-restart escalation apply
unchanged:

  - phase-stamped heartbeats (``boot -> prewarm -> ready``) from a
    daemon thread, same contract as the pipe worker: SIGSTOP shows as
    a stall, SIGKILL as an exit;
  - WAL-backed: the child's ``VerificationService`` appends every
    admit/resolve to a WAL under ``wal_dir``, so a respawned sidecar
    replays admitted-but-unresolved requests before accepting new
    traffic — a killed sidecar loses no acknowledged work;
  - a fixed port chosen once at facade construction (SO_REUSEADDR), so
    clients redial the same address across respawns.
"""

from __future__ import annotations

import asyncio
import multiprocessing as mp
import os
import signal
import socket
import threading
from dataclasses import replace

from ..obs.heartbeat import Heartbeat, read_last
from .config import ServeConfig
from .rpc import RpcConfig, RpcServer
from .wal import WriteAheadLog
from .worker import PHASE_BOOT, PHASE_PREWARM, PHASE_READY


def pick_free_port(host: str = "127.0.0.1") -> int:
    """Ephemeral port reserved long enough to hand to a child.

    SO_REUSEADDR on both ends makes the immediate rebind race-free in
    practice for a single-host harness.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def sidecar_main(factory, host: str, port: int, *,
                 heartbeat_path=None, wal_dir=None,
                 buckets=(64,), prewarm: bool = True,
                 include_block: bool = False,
                 max_wait_s: float = 0.005,
                 default_deadline_s: float = 30.0,
                 resilience=None,
                 rpc: RpcConfig | None = None,
                 rpc_loops: int | None = None,
                 tenant_quantum: int = 8,
                 tenant_weights: tuple = (),
                 beat_interval_s: float = 0.25,
                 obs_spool_dir=None, node: str | None = None) -> None:
    """Child entry point (spawn context: every arg must pickle).

    Builds the ZK backend from ``factory``, stands up a WAL-backed
    ``VerificationService`` (recovering + replaying any WAL left by a
    killed predecessor), prewarms, then serves TCP until SIGTERM/SIGINT
    — at which point it drains: GOAWAY to every client, in-flight
    frames finish, service drains, WAL closes.

    With ``obs_spool_dir`` set the child joins the fleet observability
    plane: its metrics publish via ``SpoolPublisher`` and its finished
    spans via ``SpanSpoolExporter`` under the ``node`` identity
    (default ``sidecar-<pid>``), so a parent ``FleetAggregator`` /
    federated ``/tracez`` can assemble cross-process traces.
    """
    from .service import VerificationService  # deferred: heavy import

    hb = Heartbeat(heartbeat_path)
    phase = {"now": PHASE_BOOT}
    stop_beats = threading.Event()

    def _beater():
        # same contract as worker_main: SIGSTOP freezes the beats
        # (stall), a wedged dispatch does not
        while not stop_beats.wait(beat_interval_s):
            hb.beat(phase["now"])

    hb.beat(PHASE_BOOT)
    threading.Thread(target=_beater, name="fts-sidecar-beat",
                     daemon=True).start()

    zk = factory()
    config = ServeConfig(buckets=tuple(buckets), max_wait_s=max_wait_s,
                         default_deadline_s=default_deadline_s,
                         prewarm_block=include_block,
                         tenant_quantum=tenant_quantum,
                         tenant_weights=tuple(tenant_weights))
    wal = None
    if wal_dir is not None:
        wal = WriteAheadLog(wal_dir)
    service = VerificationService(zk, config, resilience=resilience,
                                  wal=wal)
    rpc_config = replace(rpc or RpcConfig(), host=host, port=port)
    if rpc_loops is not None:
        # loop-shard override without requiring callers to build a full
        # RpcConfig (the C10k bench arm flips just this knob)
        rpc_config = replace(rpc_config, n_loops=int(rpc_loops))
    publisher = None
    span_exporter = None
    if obs_spool_dir is not None:
        from ..obs import GLOBAL, TRACER
        from ..obs.aggregate import SpoolPublisher
        from ..obs.tracing import SpanSpoolExporter

        node_id = node or f"sidecar-{os.getpid()}"
        TRACER.node = node_id  # stamp snapshots/incidents with identity
        publisher = SpoolPublisher(obs_spool_dir, node_id,
                                   GLOBAL).start()
        span_exporter = SpanSpoolExporter(obs_spool_dir, node=node_id,
                                          tracer=TRACER).start()

    async def _amain():
        loop = asyncio.get_running_loop()
        stop_ev = asyncio.Event()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop_ev.set)
        if prewarm:
            phase["now"] = PHASE_PREWARM
            hb.beat(PHASE_PREWARM)
        await service.start(prewarm=prewarm)
        server = RpcServer(service, rpc_config)
        await server.start()
        phase["now"] = PHASE_READY
        hb.beat(PHASE_READY)
        await stop_ev.wait()
        await server.stop(drain=True)
        await service.stop(drain=True, timeout_s=rpc_config.drain_timeout_s)

    try:
        asyncio.run(_amain())
    finally:
        stop_beats.set()
        if span_exporter is not None:
            span_exporter.stop(final_publish=True)
        if publisher is not None:
            publisher.stop(final_publish=True)
        if wal is not None:
            wal.close()
        hb.close()


class RpcSidecar:
    """Parent-side facade: spawn/stop/pid/phase, ChildSpec-compatible.

    ``spawn`` is a valid ``ChildSpec.start`` callable (takes an
    optional ``RestartContext``); ``address`` is fixed for the facade's
    lifetime so clients redial the same endpoint across respawns.
    """

    def __init__(self, factory, *, host: str = "127.0.0.1",
                 port: int | None = None, heartbeat_path=None,
                 wal_dir=None, buckets=(64,), prewarm: bool = True,
                 include_block: bool = False, max_wait_s: float = 0.005,
                 default_deadline_s: float = 30.0, resilience=None,
                 rpc: RpcConfig | None = None,
                 rpc_loops: int | None = None,
                 tenant_quantum: int = 8, tenant_weights: tuple = (),
                 name: str = "rpc-sidecar", mp_context: str = "spawn",
                 obs_spool_dir=None, node: str | None = None):
        self.factory = factory
        self.host = host
        self.port = port if port is not None else pick_free_port(host)
        self.address = (self.host, self.port)
        self.heartbeat_path = heartbeat_path
        self.wal_dir = wal_dir
        self.buckets = tuple(buckets)
        self.prewarm = prewarm
        self.include_block = include_block
        self.max_wait_s = max_wait_s
        self.default_deadline_s = default_deadline_s
        self.resilience = resilience
        self.rpc = rpc
        self.rpc_loops = rpc_loops
        self.tenant_quantum = tenant_quantum
        self.tenant_weights = tuple(tenant_weights)
        self.name = name
        self.obs_spool_dir = obs_spool_dir
        self.node = node
        self._ctx = mp.get_context(mp_context)
        self._proc = None

    # --------------------------------------------------------- lifecycle
    def spawn(self, ctx=None):
        """Spawn a fresh sidecar (``ctx`` is an optional
        RestartContext; cold-cache env is the supervisor's job)."""
        proc = self._ctx.Process(
            target=sidecar_main,
            args=(self.factory, self.host, self.port),
            kwargs={
                "heartbeat_path": self.heartbeat_path,
                "wal_dir": self.wal_dir,
                "buckets": self.buckets,
                "prewarm": self.prewarm,
                "include_block": self.include_block,
                "max_wait_s": self.max_wait_s,
                "default_deadline_s": self.default_deadline_s,
                "resilience": self.resilience,
                "rpc": self.rpc,
                "rpc_loops": self.rpc_loops,
                "tenant_quantum": self.tenant_quantum,
                "tenant_weights": self.tenant_weights,
                "obs_spool_dir": self.obs_spool_dir,
                "node": self.node,
            },
            name=self.name, daemon=True)
        proc.start()
        self._proc = proc
        return proc

    def stop(self, timeout_s: float = 10.0) -> None:
        proc = self._proc
        self._proc = None
        if proc is None or not proc.is_alive():
            return
        proc.terminate()  # SIGTERM -> child drains (GOAWAY, WAL close)
        proc.join(timeout=timeout_s)
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=timeout_s)

    # ------------------------------------------------------------- state
    @property
    def pid(self) -> int | None:
        proc = self._proc
        return proc.pid if proc is not None and proc.is_alive() else None

    def alive(self) -> bool:
        proc = self._proc
        return proc is not None and proc.is_alive()

    def phase(self) -> str | None:
        """Heartbeat phase of the CURRENT sidecar pid (None before its
        first beat)."""
        if self.heartbeat_path is None:
            return PHASE_READY if self.alive() else None
        stamp = read_last(self.heartbeat_path)
        if stamp is None or stamp.get("pid") != self.pid:
            return None
        return stamp.get("phase")


def stale_heartbeat_guard(path) -> None:
    """Remove a previous incarnation's heartbeat file so the supervisor
    never reads a dead pid's last beat as fresh liveness."""
    if path is None:
        return
    try:
        os.remove(path)
    except FileNotFoundError:
        pass
