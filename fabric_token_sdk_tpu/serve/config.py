"""Serving-policy configuration for the continuous-batching frontend.

One ``ServeConfig`` fixes every knob the scheduler, admission controller,
and prewarm manager consult, so a deployment's batching behaviour — and
therefore the exact set of device kernel shapes it can ever request — is
a single declarative object. The prewarm manager compiles precisely
``buckets``; the scheduler can emit no other shape. That closed-world
property is what turns the ad-hoc warm-up story (321.7 s measured in the
round-5 driver bench) into a bounded, observable startup phase.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..models.batching import B_BUCKETS

#: Priority lane for interactive / latency-sensitive traffic (HTLC claims,
#: user-facing validates): drained before ``LANE_BULK`` at every batch
#: assembly, so a backlog of bulk re-verification cannot starve it.
LANE_INTERACTIVE = "interactive"
#: Default lane for throughput traffic (auditor re-verify, backlog replay).
LANE_BULK = "bulk"

LANES = (LANE_INTERACTIVE, LANE_BULK)


@dataclass(frozen=True)
class ServeConfig:
    """Batch-assembly and admission policy.

    buckets: ascending batch-size buckets the scheduler may emit; a batch
        never exceeds ``max(buckets)`` rows and its fill ratio is reported
        against the smallest covering bucket. Defaults to the shared
        device bucket ladder (models/batching.py) up to 1024 — the
        measured single-chip throughput peak; 2048 is deliberately NOT
        emitted by default (round-5 bench: 2,045/s at 1024 vs 1,381/s at
        2048 — the regression the serve_* metrics exist to observe).
    max_wait_s: ceiling on how long the oldest queued request may wait
        before its batch is dispatched regardless of fill.
    min_batch: smallest batch dispatched on a max-wait/deadline trigger
        (full buckets dispatch immediately; a due request always
        dispatches even below min_batch — requests are never held past
        their dispatch-by time to satisfy min_batch).
    queue_capacity: per-lane bound; past it the admission controller
        sheds with ``shed_queue_full`` instead of growing the queue.
    default_deadline_s: per-request deadline when the caller gives none.
    service_estimate_s: rough per-batch service time used for two
        decisions: admission sheds a request whose remaining deadline is
        below it (it cannot possibly be served in time), and the
        scheduler dispatches a batch early when waiting longer would
        push a member past ``deadline - service_estimate_s``.
    prewarm_block: also compile the block path (Σ + adjust kernels) at
        startup; range-only services skip it to keep prewarm minimal.
    trace_every: trace sampling — every Nth admitted request gets a full
        ``serve.request`` span (admission → queue wait → linked batch
        dispatch → verdict) under its own trace id. 1 traces everything,
        0 disables request tracing (batch-level spans remain).
    n_lanes: number of DEVICE dispatch lanes (distinct from the
        ``lanes`` priority lanes): each dispatch lane owns one device or
        mesh shard with its own executor thread and prewarm inventory,
        so up to ``n_lanes`` batches are in flight concurrently — the
        continuous-batching frontend feeds every device instead of
        serializing on one dispatcher thread. 1 (the default) preserves
        the single-dispatcher behaviour exactly.
    tenant_quantum: deficit-round-robin quantum — rows of service a
        tenant's queue earns per DRR visit, so one hot tenant can hold
        a (group, lane) queue for at most ``tenant_quantum * weight``
        rows before the drain rotates to the next tenant. A single
        tenant degenerates to exact FIFO (the historical behaviour).
    tenant_weights: ((tms_id, weight), ...) pairs scaling the quantum
        per tenant; unlisted tenants weigh 1.0. Tuple-of-pairs keeps
        the dataclass frozen/hashable.
    max_tenants: bound on per-tenant metric cardinality in the serve
        layer: the scheduler remembers at most this many departed
        tenants' ``rpc_tenant_deficit`` / ``serve_tenant_drains_total``
        series before LRU-evicting the oldest from the registry. The
        TenantSloMonitor has its own equally-named bound
        (TenantSloPolicy.max_tenants); deployments should keep them
        equal so the two tables evict in step.
    """

    buckets: tuple = tuple(b for b in B_BUCKETS if b <= 1024)
    max_wait_s: float = 0.025
    min_batch: int = 1
    queue_capacity: int = 8192
    default_deadline_s: float = 2.0
    service_estimate_s: float = 0.0
    prewarm_block: bool = False
    lanes: tuple = LANES
    trace_every: int = 1
    n_lanes: int = 1
    tenant_quantum: int = 8
    tenant_weights: tuple = ()
    max_tenants: int = 256

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("ServeConfig.buckets must be non-empty")
        if tuple(sorted(self.buckets)) != tuple(self.buckets):
            raise ValueError("ServeConfig.buckets must be ascending")
        if self.min_batch > self.max_batch:
            raise ValueError("min_batch exceeds max(buckets)")
        if self.n_lanes < 1:
            raise ValueError("ServeConfig.n_lanes must be >= 1")
        if self.tenant_quantum < 1:
            raise ValueError("ServeConfig.tenant_quantum must be >= 1")
        if self.max_tenants < 1:
            raise ValueError("ServeConfig.max_tenants must be >= 1")
        for pair in self.tenant_weights:
            tms_id, weight = pair
            if not isinstance(tms_id, str) or weight <= 0:
                raise ValueError(
                    f"tenant_weights entries must be (tms_id, weight > 0) "
                    f"pairs, got {pair!r}")

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket covering ``n`` rows."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch
