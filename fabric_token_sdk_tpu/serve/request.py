"""Request/result types for the verification service.

A request is one unit the unbatched APIs accept today — a single range
proof + commitment, or a single transfer/issue action — wrapped with the
serving envelope (lane, absolute deadline, enqueue timestamp, completion
future). The service's contract is that the ``accepted`` verdict it
demultiplexes back is bit-identical to what the direct
``BatchRangeVerifier.verify`` / ``ZKVerifier.verify_block`` call on the
same payload would return.

Statuses reject-with-status instead of hanging: a request that cannot be
served (queue full, impossible deadline, deadline expired while queued)
completes with a terminal status and ``accepted=None``.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

#: Verdict delivered within the deadline.
STATUS_OK = "ok"
#: Admission refused: the lane queue is at capacity.
STATUS_SHED_QUEUE_FULL = "shed_queue_full"
#: Admission refused: remaining deadline below the service estimate.
STATUS_SHED_DEADLINE = "shed_deadline"
#: Admission refused: the request's tenant is in an active fast-burn
#: episode and the per-tenant SLO shed policy is isolating it.
STATUS_SHED_TENANT_SLO = "shed_tenant_slo"
#: Deadline expired while queued (never dispatched) or during service;
#: ``accepted`` carries the verdict when service did complete.
STATUS_DEADLINE_MISS = "deadline_miss"
#: The backend raised; ``error`` carries the message.
STATUS_ERROR = "error"
#: The service was stopped (bounded-drain timeout) before this request
#: could be served; terminal, never a hang.
STATUS_SHUTDOWN = "shutdown"

#: ``VerifyResult.served_by`` values: which backend produced the verdict.
SERVED_BY_DEVICE = "device"
SERVED_BY_HOST = "host"

#: Range-proof request kind: payload is (proof, commitment).
KIND_RANGE = "range"
#: Transfer-action kind: payload is (proof_raw, inputs, outputs).
KIND_TRANSFER = "transfer"
#: Issue-action kind: payload is (proof_raw, commitments).
KIND_ISSUE = "issue"

#: Kinds that batch together into one ``verify_block`` call.
ACTION_KINDS = (KIND_TRANSFER, KIND_ISSUE)

_req_ids = itertools.count(1)


@dataclass
class VerifyResult:
    """What the submitter's future resolves to."""

    status: str
    accepted: bool | None = None
    error: str = ""
    wait_s: float = 0.0       # enqueue -> dispatch (0 when never dispatched)
    total_s: float = 0.0      # enqueue -> completion
    bucket: int = 0           # scheduler bucket the serving batch filled
    batch_rows: int = 0       # live rows in the serving batch
    served_by: str = ""       # "device" | "host" (fallback); "" if unserved
    device_lane: int = -1     # dispatch lane that served it; -1 if unserved

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


@dataclass
class VerifyRequest:
    """One queued verification unit."""

    kind: str                 # KIND_RANGE | KIND_TRANSFER | KIND_ISSUE
    payload: tuple
    lane: str
    deadline: float           # absolute time.perf_counter() instant
    enqueue_t: float = field(default_factory=time.perf_counter)
    future: object = None     # asyncio.Future set by the service
    req_id: int = field(default_factory=lambda: next(_req_ids))
    span: object = None       # obs Span opened at admission (sampled)
    wal_id: int | None = None  # durable WAL id (when the service logs)
    terminal: bool = False    # set by _resolve: exactly-once completion
    tenant: str = "default"   # tms_id: the DRR drain key in the scheduler

    @property
    def group(self) -> str:
        """Batching group: range rows and block actions never mix."""
        return KIND_RANGE if self.kind == KIND_RANGE else "action"

    def dispatch_by(self, max_wait_s: float, service_estimate_s: float) -> float:
        """Latest instant this request should leave the queue: its
        max-wait horizon, pulled earlier if the deadline (minus the
        service estimate) is tighter."""
        return min(self.enqueue_t + max_wait_s,
                   self.deadline - service_estimate_s)
