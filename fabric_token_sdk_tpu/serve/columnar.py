"""Columnar zero-copy codec for SUBMIT_BATCH / RESULT_BATCH frames.

The legacy SUBMIT frame pickles a Python dict per request, so at
"millions of users" scale the front door spends its wall on host-side
ser/de — one object graph per row — before the device ever sees a
proof. This module fixes the wire layout instead: one SUBMIT_BATCH
frame carries N proofs as contiguous uint32 limb planes plus per-row
metadata columns, and the server decodes the whole frame into numpy
views over the frame buffer — zero per-row Python objects, zero pickle
calls, one CRC (the frame's own) over everything.

Payload layout (after the standard 12-byte frame header; all integers
little-endian, the native order of every deployment host):

    batch header  struct "<HBBIQdII" (32 bytes)
        version u16 | fmt u8 | lane u8 | n_rows u32 | req_id_base u64 |
        base deadline f64 (absolute server-clock epoch seconds) |
        proof_words u32 | com_words u32
    columns       bits        u16[n]   witness bit-length per row
                  flags       u8[n]    bit0 = forge-expected
                  (zero pad to a 4-byte boundary)
                  deadline_off_us u32[n]  per-row offset past the base
                  proof_len   u32[n]   live bytes in the row's proof cell
                  com_len     u32[n]   live bytes in the row's com cell
    planes        proof       u32[n * proof_words]  row-major cells
                  com         u32[n * com_words]    row-major cells

Row formats:

  * ``FMT_OPAQUE`` — tier-1 / StubZK: word 0 of the proof cell carries
    the row's truth value, the commitment plane is typically empty.
    Crypto-free, so the codec tests run without the pairing stack.
  * ``FMT_RANGE``  — real traffic: the proof cell is
    ``RangeProof.serialize()`` bytes, the com cell is
    ``ser.g1_to_bytes(commitment)``. Materialization imports the crypto
    stack lazily; decode itself never touches it.

Validation is strict and total-size-checked: a payload whose byte count
disagrees with its declared ``n_rows``/plane widths raises
``ColumnarError("row_count")``, a garbage header raises
``ColumnarError("decode")`` — the RPC server maps both onto the
``rpc_frame_errors_total{kind}`` taxonomy and drops the connection,
exactly like a poisoned pickled frame.

RESULT_BATCH (protocol v4) is the egress mirror: one CRC-framed frame
carries N verdict rows — possibly spanning many requests on the same
connection — as numpy-backed columns, with the small, bounded status /
served_by string vocabulary interned once per frame:

    result header  struct "<HBBII" (12 bytes)
        version u16 | flags u8 (bit0 = per-row trace column present) |
        n_strings u8 | n_rows u32 | table_bytes u32
    string table  n_strings entries of (u8 length + raw utf-8),
                  zero-padded to an 8-byte boundary (table_bytes total)
    columns       req_id   u64[n]   owning request id
                  row_idx  u32[n]   row position within that request
                  status   u8[n]    string-table index
                  served   u8[n]    string-table index ("" = unserved)
                  verdict  u8[n]    0=False 1=True 2=None (row shed)
    trace         tc       u8[n,17] only when flags bit0 — per-row
                  SpanContext wire bytes, all-zero = no context

Per-row pickle count on both halves: zero. v1–v3 peers never see the
type (negotiated in HELLO/WELCOME); their verdicts keep riding per-row
pickled RESULT frames unchanged.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from .config import LANES

#: Columnar layout version carried in every batch header.
COLUMNAR_VERSION = 1

#: Row formats (the ``fmt`` header field).
FMT_OPAQUE = 0
FMT_RANGE = 1
FMT_NAMES = {FMT_OPAQUE: "opaque", FMT_RANGE: "range"}

#: ``flags`` column bits.
FLAG_FORGE_EXPECTED = 0x01

_BATCH_HEADER = struct.Struct("<HBBIQdII")
BATCH_HEADER_SIZE = _BATCH_HEADER.size  # 32


class ColumnarError(ValueError):
    """A malformed columnar payload; ``kind`` maps onto the frame-error
    taxonomy (``row_count`` for size/stride disagreements, ``decode``
    for an unparseable or nonsensical header)."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind


def _pad(n_bytes: int) -> int:
    """Zero-fill between the byte columns and the u32 columns."""
    return (-n_bytes) % 4


def batch_nbytes(n_rows: int, proof_words: int, com_words: int) -> int:
    """Exact payload size for a given shape — decode rejects any other."""
    cols = 3 * n_rows                       # bits u16 + flags u8
    return (BATCH_HEADER_SIZE + cols + _pad(cols)
            + 12 * n_rows                   # deadline_off + proof/com len
            + 4 * n_rows * (proof_words + com_words))


@dataclass
class ColumnarBatch:
    """Decoded SUBMIT_BATCH payload: numpy views over the frame buffer.

    Every array is a zero-copy view (read-only, backed by the payload
    bytes); per-row Python objects exist only once :func:`materialize_rows`
    fans the batch into the request-granular scheduler.
    """

    fmt: int
    lane: str
    n_rows: int
    req_id_base: int
    deadline: float                 # absolute server-clock epoch seconds
    bits: np.ndarray                # uint16[n]
    flags: np.ndarray               # uint8[n]
    deadline_off_us: np.ndarray     # uint32[n]
    proof_len: np.ndarray           # uint32[n]
    com_len: np.ndarray             # uint32[n]
    proof_planes: np.ndarray        # uint32[n, proof_words]
    com_planes: np.ndarray          # uint32[n, com_words]
    nbytes: int

    @property
    def fmt_name(self) -> str:
        return FMT_NAMES.get(self.fmt, str(self.fmt))

    @property
    def deadline_offsets_s(self) -> np.ndarray:
        """Per-row deadline offsets past :attr:`deadline`, in seconds."""
        return self.deadline_off_us.astype(np.float64) * 1e-6

    def proof_cell(self, i: int) -> bytes:
        """Row ``i``'s live proof bytes (copies — materialization only)."""
        return self.proof_planes[i].tobytes()[: int(self.proof_len[i])]

    def com_cell(self, i: int) -> bytes:
        return self.com_planes[i].tobytes()[: int(self.com_len[i])]


# -------------------------------------------------------------- encoding
def opaque_cells(proofs) -> list[bytes]:
    """FMT_OPAQUE proof cells: one little-endian word per row carrying
    the row's truth value (all the stub verifier consults)."""
    return [b"\x01\x00\x00\x00" if p else b"\x00\x00\x00\x00"
            for p in proofs]


def range_cells(proofs, coms) -> tuple[list[bytes], list[bytes]]:
    """FMT_RANGE cells: serialized proofs + compressed commitments."""
    from ..crypto import serialization as ser

    return ([p.serialize() for p in proofs],
            [ser.g1_to_bytes(c) for c in coms])


def _plane_words(cells) -> int:
    if not cells:
        return 0
    return max((len(c) + 3) // 4 for c in cells)


def _pack_planes(cells, n_rows: int, words: int) -> bytes:
    plane = np.zeros((n_rows, 4 * words), dtype=np.uint8)
    for i, cell in enumerate(cells):
        if cell:
            plane[i, : len(cell)] = np.frombuffer(cell, dtype=np.uint8)
    return plane.tobytes()


def encode_submit_batch(*, fmt: int, lane: str, req_id_base: int,
                        deadline: float, proof_cells: list[bytes],
                        com_cells: list[bytes] | None = None,
                        bits=None, flags=None,
                        deadline_off_us=None) -> bytes:
    """Pack N rows into one columnar payload (no frame header).

    ``deadline`` is the frame's absolute server-clock base deadline;
    ``deadline_off_us`` optionally staggers rows past it. ``flags`` bit 0
    is the forge-expected marker benches use for ground-truth parity.
    """
    n = len(proof_cells)
    if n == 0:
        raise ColumnarError("row_count", "empty batch")
    if fmt not in FMT_NAMES:
        raise ColumnarError("decode", f"unknown fmt {fmt}")
    if lane not in LANES:
        raise ColumnarError("decode", f"unknown lane {lane!r}")
    com_cells = com_cells if com_cells is not None else [b""] * n
    if len(com_cells) != n:
        raise ColumnarError("row_count",
                            f"{len(com_cells)} com cells for {n} rows")
    pw = _plane_words(proof_cells)
    cw = _plane_words(com_cells)
    bits_col = np.asarray(
        bits if bits is not None else np.zeros(n), dtype="<u2")
    flags_col = np.asarray(
        flags if flags is not None else np.zeros(n), dtype=np.uint8)
    off_col = np.asarray(
        deadline_off_us if deadline_off_us is not None else np.zeros(n),
        dtype="<u4")
    if not (len(bits_col) == len(flags_col) == len(off_col) == n):
        raise ColumnarError("row_count", "metadata columns disagree on n")
    parts = [
        _BATCH_HEADER.pack(COLUMNAR_VERSION, fmt, LANES.index(lane), n,
                           req_id_base, deadline, pw, cw),
        bits_col.tobytes(), flags_col.tobytes(), b"\x00" * _pad(3 * n),
        off_col.tobytes(),
        np.asarray([len(c) for c in proof_cells], dtype="<u4").tobytes(),
        np.asarray([len(c) for c in com_cells], dtype="<u4").tobytes(),
        _pack_planes(proof_cells, n, pw),
        _pack_planes(com_cells, n, cw),
    ]
    return b"".join(parts)


# -------------------------------------------------------------- decoding
def decode_submit_batch(payload, *, max_rows: int = 1 << 20) -> ColumnarBatch:
    """Decode one columnar payload into numpy views — zero per-row
    Python objects, zero pickle calls, O(1) allocations.

    Raises :class:`ColumnarError` (``decode`` / ``row_count``) on any
    disagreement between the header and the actual byte count.
    """
    buf = memoryview(payload)
    if len(buf) < BATCH_HEADER_SIZE:
        raise ColumnarError(
            "decode", f"{len(buf)}B payload below the {BATCH_HEADER_SIZE}B "
            "batch header")
    try:
        (version, fmt, lane_code, n, req_id_base, deadline, pw,
         cw) = _BATCH_HEADER.unpack_from(buf)
    except struct.error as exc:  # pragma: no cover — size checked above
        raise ColumnarError("decode", repr(exc)) from exc
    if version != COLUMNAR_VERSION:
        raise ColumnarError("decode", f"columnar version {version}")
    if fmt not in FMT_NAMES:
        raise ColumnarError("decode", f"unknown fmt {fmt}")
    if lane_code >= len(LANES):
        raise ColumnarError("decode", f"unknown lane code {lane_code}")
    if n == 0 or n > max_rows:
        raise ColumnarError("row_count", f"n_rows={n} outside (0, {max_rows}]")
    expect = batch_nbytes(n, pw, cw)
    if len(buf) != expect:
        raise ColumnarError(
            "row_count",
            f"{len(buf)}B payload, header shape ({n} rows x {pw}+{cw} "
            f"words) needs exactly {expect}B")
    off = BATCH_HEADER_SIZE
    bits = np.frombuffer(buf, dtype="<u2", count=n, offset=off)
    off += 2 * n
    flags = np.frombuffer(buf, dtype=np.uint8, count=n, offset=off)
    off += n + _pad(3 * n)
    dl_off = np.frombuffer(buf, dtype="<u4", count=n, offset=off)
    off += 4 * n
    proof_len = np.frombuffer(buf, dtype="<u4", count=n, offset=off)
    off += 4 * n
    com_len = np.frombuffer(buf, dtype="<u4", count=n, offset=off)
    off += 4 * n
    proof_planes = np.frombuffer(
        buf, dtype="<u4", count=n * pw, offset=off).reshape(n, pw)
    off += 4 * n * pw
    com_planes = np.frombuffer(
        buf, dtype="<u4", count=n * cw, offset=off).reshape(n, cw)
    if int(proof_len.max(initial=0)) > 4 * pw \
            or int(com_len.max(initial=0)) > 4 * cw:
        raise ColumnarError(
            "row_count", "a cell length column overruns its plane stride")
    return ColumnarBatch(
        fmt=fmt, lane=LANES[lane_code], n_rows=n, req_id_base=req_id_base,
        deadline=deadline, bits=bits, flags=flags, deadline_off_us=dl_off,
        proof_len=proof_len, com_len=com_len, proof_planes=proof_planes,
        com_planes=com_planes, nbytes=len(buf))


def materialize_rows(batch: ColumnarBatch) -> tuple[list, list]:
    """(proofs, coms) for fanning into the request-granular scheduler.

    This is the one per-row step of the batch path, deferred past the
    single admission decision. ``FMT_OPAQUE`` stays crypto-free (the
    truth word vectorizes); ``FMT_RANGE`` imports the crypto stack
    lazily and rebuilds the exact objects the per-request path carries.
    """
    if batch.fmt == FMT_OPAQUE:
        if batch.proof_planes.shape[1] == 0:
            raise ColumnarError("row_count", "opaque batch with zero "
                                             "proof words")
        truth = (batch.proof_planes[:, 0] != 0).tolist()
        return truth, [None] * batch.n_rows
    from ..crypto import rp
    from ..crypto import serialization as ser

    proofs = [rp.RangeProof.deserialize(batch.proof_cell(i))
              for i in range(batch.n_rows)]
    coms = [ser.g1_from_bytes(batch.com_cell(i))
            for i in range(batch.n_rows)]
    return proofs, coms


# ----------------------------------------------- RESULT_BATCH (egress)
#: RESULT_BATCH layout version carried in every result header.
RESULT_VERSION = 1

#: Result-header flag bit: a per-row 17-byte trace-context column
#: follows the verdict column (all-zero rows carry no context).
RESULT_FLAG_TRACE = 0x01

#: ``verdict`` column encoding. VERDICT_NONE marks a shed row whose
#: verdict is ``None`` (the client raises WorkerUnavailable, same as
#: the legacy pickled reply).
VERDICT_FALSE = 0
VERDICT_TRUE = 1
VERDICT_NONE = 2

#: Wire size of one SpanContext (mirrors obs.tracing.CONTEXT_WIRE_SIZE
#: without importing the obs stack into the codec).
_TRACE_WIRE = 17

_RESULT_HEADER = struct.Struct("<HBBII")
RESULT_HEADER_SIZE = _RESULT_HEADER.size  # 12


def _pad8(n_bytes: int) -> int:
    """Zero-fill aligning the string table to an 8-byte boundary."""
    return (-n_bytes) % 8


def result_batch_nbytes(n_rows: int, table_bytes: int,
                        traced: bool) -> int:
    """Exact payload size for a given shape — decode rejects any other."""
    return (RESULT_HEADER_SIZE + table_bytes
            + 15 * n_rows                       # u64 + u32 + 3 x u8
            + (_TRACE_WIRE * n_rows if traced else 0))


@dataclass
class ResultBatch:
    """Decoded RESULT_BATCH payload: numpy views over the frame buffer.

    ``table`` is the frame's interned string vocabulary; ``status_idx``
    / ``served_idx`` index into it. ``trace`` is ``None`` unless the
    frame carried the per-row trace column."""

    n_rows: int
    table: tuple[str, ...]
    req_id: np.ndarray              # uint64[n]
    row_idx: np.ndarray             # uint32[n]
    status_idx: np.ndarray          # uint8[n]
    served_idx: np.ndarray          # uint8[n]
    verdict: np.ndarray             # uint8[n] (VERDICT_*)
    trace: np.ndarray | None        # uint8[n, 17] or None
    nbytes: int

    def status(self, i: int) -> str:
        return self.table[int(self.status_idx[i])]

    def served(self, i: int) -> str:
        return self.table[int(self.served_idx[i])]

    def verdict_value(self, i: int):
        v = int(self.verdict[i])
        return None if v == VERDICT_NONE else bool(v)

    def trace_cell(self, i: int) -> bytes | None:
        """Row ``i``'s raw 17 context bytes; None when the frame has no
        trace column or the row's cell is all-zero (no context)."""
        if self.trace is None:
            return None
        cell = self.trace[i]
        if not cell.any():
            return None
        return cell.tobytes()


def encode_result_batch(rows, *, pool=None) -> tuple[bytes, bool]:
    """Pack verdict rows into one RESULT_BATCH payload (no frame header).

    ``rows`` is an iterable of ``(req_id, row_idx, status, verdict,
    served_by, tc)`` tuples — ``verdict`` is ``True``/``False``/``None``,
    ``tc`` is 17 raw SpanContext bytes or ``None``. Returns
    ``(payload, traced)``; ``traced`` mirrors the header flag so the
    caller can count trace-threaded frames. ``pool`` optionally supplies
    the encode scratch buffer (``acquire``/``release`` of bytearrays)
    so steady-state egress reuses one staging allocation per size class.

    Raises :class:`ColumnarError` when the frame's string vocabulary
    overflows the u8 index space (>= 256 unique status/served strings)
    — the server falls back to legacy per-row RESULT frames for that
    drain cycle rather than failing the connection.
    """
    rows = list(rows)
    n = len(rows)
    if n == 0:
        raise ColumnarError("row_count", "empty result batch")
    interned: dict[str, int] = {}

    def intern(s: str) -> int:
        idx = interned.get(s)
        if idx is None:
            if len(interned) >= 256:
                raise ColumnarError(
                    "decode", f"result string table overflow at {s!r}")
            idx = len(interned)
            interned[s] = idx
        return idx

    status_col = np.empty(n, dtype=np.uint8)
    served_col = np.empty(n, dtype=np.uint8)
    verdict_col = np.empty(n, dtype=np.uint8)
    req_col = np.empty(n, dtype="<u8")
    idx_col = np.empty(n, dtype="<u4")
    traced = any(r[5] for r in rows)
    trace_col = np.zeros((n, _TRACE_WIRE), dtype=np.uint8) \
        if traced else None
    for i, (req_id, row_idx, status, verdict, served, tc) in \
            enumerate(rows):
        req_col[i] = int(req_id) & 0xFFFFFFFFFFFFFFFF
        idx_col[i] = int(row_idx)
        status_col[i] = intern(str(status))
        served_col[i] = intern(str(served or ""))
        verdict_col[i] = (VERDICT_NONE if verdict is None
                          else VERDICT_TRUE if verdict else VERDICT_FALSE)
        if tc is not None and trace_col is not None \
                and len(tc) == _TRACE_WIRE:
            trace_col[i] = np.frombuffer(tc, dtype=np.uint8)
    entries = bytearray()
    for s in interned:  # insertion order == index order
        raw = s.encode("utf-8")
        if len(raw) > 255:
            raise ColumnarError("decode", "result table entry > 255B")
        entries.append(len(raw))
        entries += raw
    entries += b"\x00" * _pad8(len(entries))
    table_bytes = len(entries)
    size = result_batch_nbytes(n, table_bytes, traced)
    buf = pool.acquire(size) if pool is not None else bytearray(size)
    try:
        view = memoryview(buf)
        _RESULT_HEADER.pack_into(
            buf, 0, RESULT_VERSION, RESULT_FLAG_TRACE if traced else 0,
            len(interned), n, table_bytes)
        off = RESULT_HEADER_SIZE
        view[off:off + table_bytes] = entries
        off += table_bytes
        for col in (req_col, idx_col, status_col, served_col,
                    verdict_col):
            raw = col.tobytes()
            view[off:off + len(raw)] = raw
            off += len(raw)
        if trace_col is not None:
            raw = trace_col.tobytes()
            view[off:off + len(raw)] = raw
            off += len(raw)
        payload = bytes(view[:size])
    finally:
        if pool is not None:
            pool.release(buf)
    return payload, traced


def decode_result_batch(payload, *, max_rows: int = 1 << 20) -> ResultBatch:
    """Decode one RESULT_BATCH payload into numpy views — zero per-row
    pickle calls, O(table) Python objects however many rows the frame
    carries. Raises :class:`ColumnarError` on any disagreement between
    the header and the actual byte count."""
    buf = memoryview(payload)
    if len(buf) < RESULT_HEADER_SIZE:
        raise ColumnarError(
            "decode", f"{len(buf)}B payload below the "
            f"{RESULT_HEADER_SIZE}B result header")
    version, flags, n_strings, n, table_bytes = \
        _RESULT_HEADER.unpack_from(buf)
    if version != RESULT_VERSION:
        raise ColumnarError("decode", f"result version {version}")
    if n == 0 or n > max_rows:
        raise ColumnarError("row_count",
                            f"n_rows={n} outside (0, {max_rows}]")
    traced = bool(flags & RESULT_FLAG_TRACE)
    expect = result_batch_nbytes(n, table_bytes, traced)
    if len(buf) != expect:
        raise ColumnarError(
            "row_count",
            f"{len(buf)}B payload, header shape ({n} rows, {table_bytes}B "
            f"table, traced={traced}) needs exactly {expect}B")
    off = RESULT_HEADER_SIZE
    table: list[str] = []
    cursor = off
    end = off + table_bytes
    for _ in range(n_strings):
        if cursor >= end:
            raise ColumnarError("decode", "result table truncated")
        length = buf[cursor]
        cursor += 1
        if cursor + length > end:
            raise ColumnarError("decode", "result table entry overruns")
        try:
            table.append(bytes(buf[cursor:cursor + length])
                         .decode("utf-8"))
        except UnicodeDecodeError as exc:
            raise ColumnarError("decode", repr(exc)) from exc
        cursor += length
    off = end
    req_id = np.frombuffer(buf, dtype="<u8", count=n, offset=off)
    off += 8 * n
    row_idx = np.frombuffer(buf, dtype="<u4", count=n, offset=off)
    off += 4 * n
    status_idx = np.frombuffer(buf, dtype=np.uint8, count=n, offset=off)
    off += n
    served_idx = np.frombuffer(buf, dtype=np.uint8, count=n, offset=off)
    off += n
    verdict = np.frombuffer(buf, dtype=np.uint8, count=n, offset=off)
    off += n
    trace = None
    if traced:
        trace = np.frombuffer(buf, dtype=np.uint8, count=n * _TRACE_WIRE,
                              offset=off).reshape(n, _TRACE_WIRE)
    n_table = len(table)
    if int(status_idx.max(initial=0)) >= n_table \
            or int(served_idx.max(initial=0)) >= n_table:
        raise ColumnarError("decode",
                            "a string index column overruns the table")
    if int(verdict.max(initial=0)) > VERDICT_NONE:
        raise ColumnarError("decode", "verdict column holds values > 2")
    return ResultBatch(
        n_rows=n, table=tuple(table), req_id=req_id, row_idx=row_idx,
        status_idx=status_idx, served_idx=served_idx, verdict=verdict,
        trace=trace, nbytes=len(buf))
