"""Columnar zero-copy codec for SUBMIT_BATCH frames.

The legacy SUBMIT frame pickles a Python dict per request, so at
"millions of users" scale the front door spends its wall on host-side
ser/de — one object graph per row — before the device ever sees a
proof. This module fixes the wire layout instead: one SUBMIT_BATCH
frame carries N proofs as contiguous uint32 limb planes plus per-row
metadata columns, and the server decodes the whole frame into numpy
views over the frame buffer — zero per-row Python objects, zero pickle
calls, one CRC (the frame's own) over everything.

Payload layout (after the standard 12-byte frame header; all integers
little-endian, the native order of every deployment host):

    batch header  struct "<HBBIQdII" (32 bytes)
        version u16 | fmt u8 | lane u8 | n_rows u32 | req_id_base u64 |
        base deadline f64 (absolute server-clock epoch seconds) |
        proof_words u32 | com_words u32
    columns       bits        u16[n]   witness bit-length per row
                  flags       u8[n]    bit0 = forge-expected
                  (zero pad to a 4-byte boundary)
                  deadline_off_us u32[n]  per-row offset past the base
                  proof_len   u32[n]   live bytes in the row's proof cell
                  com_len     u32[n]   live bytes in the row's com cell
    planes        proof       u32[n * proof_words]  row-major cells
                  com         u32[n * com_words]    row-major cells

Row formats:

  * ``FMT_OPAQUE`` — tier-1 / StubZK: word 0 of the proof cell carries
    the row's truth value, the commitment plane is typically empty.
    Crypto-free, so the codec tests run without the pairing stack.
  * ``FMT_RANGE``  — real traffic: the proof cell is
    ``RangeProof.serialize()`` bytes, the com cell is
    ``ser.g1_to_bytes(commitment)``. Materialization imports the crypto
    stack lazily; decode itself never touches it.

Validation is strict and total-size-checked: a payload whose byte count
disagrees with its declared ``n_rows``/plane widths raises
``ColumnarError("row_count")``, a garbage header raises
``ColumnarError("decode")`` — the RPC server maps both onto the
``rpc_frame_errors_total{kind}`` taxonomy and drops the connection,
exactly like a poisoned pickled frame.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from .config import LANES

#: Columnar layout version carried in every batch header.
COLUMNAR_VERSION = 1

#: Row formats (the ``fmt`` header field).
FMT_OPAQUE = 0
FMT_RANGE = 1
FMT_NAMES = {FMT_OPAQUE: "opaque", FMT_RANGE: "range"}

#: ``flags`` column bits.
FLAG_FORGE_EXPECTED = 0x01

_BATCH_HEADER = struct.Struct("<HBBIQdII")
BATCH_HEADER_SIZE = _BATCH_HEADER.size  # 32


class ColumnarError(ValueError):
    """A malformed columnar payload; ``kind`` maps onto the frame-error
    taxonomy (``row_count`` for size/stride disagreements, ``decode``
    for an unparseable or nonsensical header)."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind


def _pad(n_bytes: int) -> int:
    """Zero-fill between the byte columns and the u32 columns."""
    return (-n_bytes) % 4


def batch_nbytes(n_rows: int, proof_words: int, com_words: int) -> int:
    """Exact payload size for a given shape — decode rejects any other."""
    cols = 3 * n_rows                       # bits u16 + flags u8
    return (BATCH_HEADER_SIZE + cols + _pad(cols)
            + 12 * n_rows                   # deadline_off + proof/com len
            + 4 * n_rows * (proof_words + com_words))


@dataclass
class ColumnarBatch:
    """Decoded SUBMIT_BATCH payload: numpy views over the frame buffer.

    Every array is a zero-copy view (read-only, backed by the payload
    bytes); per-row Python objects exist only once :func:`materialize_rows`
    fans the batch into the request-granular scheduler.
    """

    fmt: int
    lane: str
    n_rows: int
    req_id_base: int
    deadline: float                 # absolute server-clock epoch seconds
    bits: np.ndarray                # uint16[n]
    flags: np.ndarray               # uint8[n]
    deadline_off_us: np.ndarray     # uint32[n]
    proof_len: np.ndarray           # uint32[n]
    com_len: np.ndarray             # uint32[n]
    proof_planes: np.ndarray        # uint32[n, proof_words]
    com_planes: np.ndarray          # uint32[n, com_words]
    nbytes: int

    @property
    def fmt_name(self) -> str:
        return FMT_NAMES.get(self.fmt, str(self.fmt))

    @property
    def deadline_offsets_s(self) -> np.ndarray:
        """Per-row deadline offsets past :attr:`deadline`, in seconds."""
        return self.deadline_off_us.astype(np.float64) * 1e-6

    def proof_cell(self, i: int) -> bytes:
        """Row ``i``'s live proof bytes (copies — materialization only)."""
        return self.proof_planes[i].tobytes()[: int(self.proof_len[i])]

    def com_cell(self, i: int) -> bytes:
        return self.com_planes[i].tobytes()[: int(self.com_len[i])]


# -------------------------------------------------------------- encoding
def opaque_cells(proofs) -> list[bytes]:
    """FMT_OPAQUE proof cells: one little-endian word per row carrying
    the row's truth value (all the stub verifier consults)."""
    return [b"\x01\x00\x00\x00" if p else b"\x00\x00\x00\x00"
            for p in proofs]


def range_cells(proofs, coms) -> tuple[list[bytes], list[bytes]]:
    """FMT_RANGE cells: serialized proofs + compressed commitments."""
    from ..crypto import serialization as ser

    return ([p.serialize() for p in proofs],
            [ser.g1_to_bytes(c) for c in coms])


def _plane_words(cells) -> int:
    if not cells:
        return 0
    return max((len(c) + 3) // 4 for c in cells)


def _pack_planes(cells, n_rows: int, words: int) -> bytes:
    plane = np.zeros((n_rows, 4 * words), dtype=np.uint8)
    for i, cell in enumerate(cells):
        if cell:
            plane[i, : len(cell)] = np.frombuffer(cell, dtype=np.uint8)
    return plane.tobytes()


def encode_submit_batch(*, fmt: int, lane: str, req_id_base: int,
                        deadline: float, proof_cells: list[bytes],
                        com_cells: list[bytes] | None = None,
                        bits=None, flags=None,
                        deadline_off_us=None) -> bytes:
    """Pack N rows into one columnar payload (no frame header).

    ``deadline`` is the frame's absolute server-clock base deadline;
    ``deadline_off_us`` optionally staggers rows past it. ``flags`` bit 0
    is the forge-expected marker benches use for ground-truth parity.
    """
    n = len(proof_cells)
    if n == 0:
        raise ColumnarError("row_count", "empty batch")
    if fmt not in FMT_NAMES:
        raise ColumnarError("decode", f"unknown fmt {fmt}")
    if lane not in LANES:
        raise ColumnarError("decode", f"unknown lane {lane!r}")
    com_cells = com_cells if com_cells is not None else [b""] * n
    if len(com_cells) != n:
        raise ColumnarError("row_count",
                            f"{len(com_cells)} com cells for {n} rows")
    pw = _plane_words(proof_cells)
    cw = _plane_words(com_cells)
    bits_col = np.asarray(
        bits if bits is not None else np.zeros(n), dtype="<u2")
    flags_col = np.asarray(
        flags if flags is not None else np.zeros(n), dtype=np.uint8)
    off_col = np.asarray(
        deadline_off_us if deadline_off_us is not None else np.zeros(n),
        dtype="<u4")
    if not (len(bits_col) == len(flags_col) == len(off_col) == n):
        raise ColumnarError("row_count", "metadata columns disagree on n")
    parts = [
        _BATCH_HEADER.pack(COLUMNAR_VERSION, fmt, LANES.index(lane), n,
                           req_id_base, deadline, pw, cw),
        bits_col.tobytes(), flags_col.tobytes(), b"\x00" * _pad(3 * n),
        off_col.tobytes(),
        np.asarray([len(c) for c in proof_cells], dtype="<u4").tobytes(),
        np.asarray([len(c) for c in com_cells], dtype="<u4").tobytes(),
        _pack_planes(proof_cells, n, pw),
        _pack_planes(com_cells, n, cw),
    ]
    return b"".join(parts)


# -------------------------------------------------------------- decoding
def decode_submit_batch(payload, *, max_rows: int = 1 << 20) -> ColumnarBatch:
    """Decode one columnar payload into numpy views — zero per-row
    Python objects, zero pickle calls, O(1) allocations.

    Raises :class:`ColumnarError` (``decode`` / ``row_count``) on any
    disagreement between the header and the actual byte count.
    """
    buf = memoryview(payload)
    if len(buf) < BATCH_HEADER_SIZE:
        raise ColumnarError(
            "decode", f"{len(buf)}B payload below the {BATCH_HEADER_SIZE}B "
            "batch header")
    try:
        (version, fmt, lane_code, n, req_id_base, deadline, pw,
         cw) = _BATCH_HEADER.unpack_from(buf)
    except struct.error as exc:  # pragma: no cover — size checked above
        raise ColumnarError("decode", repr(exc)) from exc
    if version != COLUMNAR_VERSION:
        raise ColumnarError("decode", f"columnar version {version}")
    if fmt not in FMT_NAMES:
        raise ColumnarError("decode", f"unknown fmt {fmt}")
    if lane_code >= len(LANES):
        raise ColumnarError("decode", f"unknown lane code {lane_code}")
    if n == 0 or n > max_rows:
        raise ColumnarError("row_count", f"n_rows={n} outside (0, {max_rows}]")
    expect = batch_nbytes(n, pw, cw)
    if len(buf) != expect:
        raise ColumnarError(
            "row_count",
            f"{len(buf)}B payload, header shape ({n} rows x {pw}+{cw} "
            f"words) needs exactly {expect}B")
    off = BATCH_HEADER_SIZE
    bits = np.frombuffer(buf, dtype="<u2", count=n, offset=off)
    off += 2 * n
    flags = np.frombuffer(buf, dtype=np.uint8, count=n, offset=off)
    off += n + _pad(3 * n)
    dl_off = np.frombuffer(buf, dtype="<u4", count=n, offset=off)
    off += 4 * n
    proof_len = np.frombuffer(buf, dtype="<u4", count=n, offset=off)
    off += 4 * n
    com_len = np.frombuffer(buf, dtype="<u4", count=n, offset=off)
    off += 4 * n
    proof_planes = np.frombuffer(
        buf, dtype="<u4", count=n * pw, offset=off).reshape(n, pw)
    off += 4 * n * pw
    com_planes = np.frombuffer(
        buf, dtype="<u4", count=n * cw, offset=off).reshape(n, cw)
    if int(proof_len.max(initial=0)) > 4 * pw \
            or int(com_len.max(initial=0)) > 4 * cw:
        raise ColumnarError(
            "row_count", "a cell length column overruns its plane stride")
    return ColumnarBatch(
        fmt=fmt, lane=LANES[lane_code], n_rows=n, req_id_base=req_id_base,
        deadline=deadline, bits=bits, flags=flags, deadline_off_us=dl_off,
        proof_len=proof_len, com_len=com_len, proof_planes=proof_planes,
        com_planes=com_planes, nbytes=len(buf))


def materialize_rows(batch: ColumnarBatch) -> tuple[list, list]:
    """(proofs, coms) for fanning into the request-granular scheduler.

    This is the one per-row step of the batch path, deferred past the
    single admission decision. ``FMT_OPAQUE`` stays crypto-free (the
    truth word vectorizes); ``FMT_RANGE`` imports the crypto stack
    lazily and rebuilds the exact objects the per-request path carries.
    """
    if batch.fmt == FMT_OPAQUE:
        if batch.proof_planes.shape[1] == 0:
            raise ColumnarError("row_count", "opaque batch with zero "
                                             "proof words")
        truth = (batch.proof_planes[:, 0] != 0).tolist()
        return truth, [None] * batch.n_rows
    from ..crypto import rp
    from ..crypto import serialization as ser

    proofs = [rp.RangeProof.deserialize(batch.proof_cell(i))
              for i in range(batch.n_rows)]
    coms = [ser.g1_from_bytes(batch.com_cell(i))
            for i in range(batch.n_rows)]
    return proofs, coms
