"""Network front door: asyncio TCP RPC server on the Validator SPI.

The reference SDK's whole point is a pluggable ``driver.Validator``
behind a process boundary (SURVEY §3.2). PR 8's sidecar speaks a
same-host ``multiprocessing`` pipe; this module adds the real network
plane — stdlib-only (asyncio TCP, no grpcio — same policy as the
stdlib-HTTP ``TelemetryServer``) so the failure modes of a network
boundary (half-open connections, torn frames, slow peers, reconnect
storms) are exercised and testable.

Wire format — length-prefixed, CRC-checksummed frames (the WAL's
checksum discipline applied to the socket):

    header  = struct ">BBHII" (12 bytes)
              magic 0xF7 | frame type | flags (0) | payload len | CRC32
    payload = pickled dict, CRC32-checked before unpickling

Pickle is acceptable here for the same reason it is on the worker
pipe: the sidecar is a same-trust-domain process boundary, not an
internet-facing endpoint (README "Network boundary").

Protocol:

  HELLO{tms_id,t}  -> WELCOME{t,t_srv,credits,max_frame}   handshake;
      the client derives RTT and a clock-offset estimate so wire
      deadlines are absolute *server-clock* times.
  SUBMIT{req_id,kind,lane,deadline,payload}  -> RESULT{req_id,...}
      streaming batch submits; rows fan into
      ``VerificationService.submit_*`` and the per-row verdicts are
      demultiplexed back into one RESULT frame.
  SUBMIT_BATCH(columnar payload)  -> RESULT{req_id,...}
      the high-throughput ingest path: one CRC-framed frame carries N
      proofs as contiguous uint32 limb planes + per-row metadata
      columns (serve/columnar.py). The payload is NOT pickled — the
      server decodes it into numpy views over the frame buffer (zero
      per-row Python objects) and admits the whole frame through
      ``VerificationService.submit_batch`` (one admission decision,
      one WAL append, one journal event). Credits are spent in rows,
      same as N legacy SUBMITs. Capability is advertised in WELCOME
      (``v=2, batch=True``); v1 clients never see the type.
  CREDIT{grant}    credit-based flow control: each connection holds a
      row budget; SUBMIT rows consume it, the server replenishes from
      admission headroom (``queue_capacity`` minus the deepest lane),
      so backpressure reaches the client instead of an unbounded
      socket buffer.
  PING{t} -> PONG{t,t_srv}   liveness + RTT/offset refresh.
  GOAWAY{reason}   draining stop: no new submits accepted, in-flight
      frames finish, the server never closes a connection mid-frame
      (asserted by per-connection frame accounting).
  ERROR{...}       protocol-level rejection.

Every read is under an explicit deadline (``asyncio.wait_for``) — a
hung read with no deadline is how rc=124-with-no-diagnosis comes back
(enforced by ``scripts/check_socket_timeouts.py``).
"""

from __future__ import annotations

import asyncio
import pickle
import struct
import time
import zlib
from dataclasses import dataclass

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..obs.journal import JOURNAL
from ..obs.tracing import CONTEXT_WIRE_SIZE, extract_wire_context
from .columnar import ColumnarError, decode_submit_batch, materialize_rows
from .config import LANE_BULK, LANES
from .request import STATUS_OK

MAGIC = 0xF7
_HEADER = struct.Struct(">BBHII")
HEADER_SIZE = _HEADER.size

# Frame types.
HELLO = 1
WELCOME = 2
SUBMIT = 3
RESULT = 4
CREDIT = 5
PING = 6
PONG = 7
GOAWAY = 8
ERROR = 9
SUBMIT_BATCH = 10

FRAME_NAMES = {
    HELLO: "hello", WELCOME: "welcome", SUBMIT: "submit", RESULT: "result",
    CREDIT: "credit", PING: "ping", PONG: "pong", GOAWAY: "goaway",
    ERROR: "error", SUBMIT_BATCH: "submit_batch",
}

#: Frame types whose payload is raw bytes (CRC-checked, never pickled);
#: everything else stays a pickled dict.
RAW_PAYLOAD_TYPES = frozenset({SUBMIT_BATCH})

#: Protocol version advertised in WELCOME: 2 adds SUBMIT_BATCH, 3 adds
#: wire-propagated trace context (SpanContext in SUBMIT/RESULT bodies
#: under key ``"tc"``; a 17-byte prefix on SUBMIT_BATCH payloads when
#: the FLAG_TRACE_CONTEXT header flag is set). v1/v2 peers stay wire
#: compatible: they never set the flag or the key, and a server never
#: requires either — missing context is counted, never an error.
RPC_VERSION = 3

#: Header flag bit: the payload begins with a 17-byte trace context
#: (only meaningful on RAW_PAYLOAD_TYPES frames; pickled bodies carry
#: context in-dict under ``"tc"`` instead).
FLAG_TRACE_CONTEXT = 0x1

DEFAULT_MAX_FRAME = 32 * 1024 * 1024

# RESULT statuses (transport-level; row-level statuses reuse serve's).
RPC_OK = STATUS_OK
RPC_EXPIRED = "expired"            # shed at decode: wire deadline passed
RPC_GOAWAY = "goaway"              # server draining, submit rejected
RPC_ERROR = "error"

_RPC_FAMILIES = {
    "rpc_connections_total":
        "RPC connections accepted by the server, by tenant tms id.",
    "rpc_connections_active":
        "RPC connections currently open on the server.",
    "rpc_frames_total":
        "RPC frames moved, by role (server/client), direction "
        "(sent/recv) and frame type.",
    "rpc_frame_errors_total":
        "RPC frame-level failures by kind: torn (EOF mid-frame), "
        "checksum, oversize, bad_magic, slow_frame (mid-frame stall "
        "past the frame deadline), decode, protocol, credit_violation, "
        "midframe_close, row_count (columnar batch whose byte count "
        "disagrees with its declared shape).",
    "rpc_requests_total":
        "SUBMIT frames accepted into the service, by tenant tms id, "
        "kind and lane.",
    "rpc_credits":
        "Row credits currently granted to a tenant's connection "
        "(server-side view of the client's spendable budget).",
    "rpc_credit_waits_total":
        "Client-side stalls waiting for row credits (backpressure "
        "reached the client).",
    "rpc_redials_total":
        "Client reconnect attempts, by outcome (ok / error).",
    "rpc_goaways_total":
        "GOAWAY frames, by role (server sent / client received).",
    "rpc_deadline_expired_total":
        "SUBMIT frames shed at decode because the wire-propagated "
        "deadline had already passed.",
    "rpc_call_seconds":
        "Client-observed RPC round-trip wall seconds, by kind.",
    "rpc_hedges_total":
        "Hedged duplicate SUBMITs sent for the interactive lane.",
    "rpc_batch_frames_total":
        "Columnar SUBMIT_BATCH frames moved, by role and tenant tms id.",
    "rpc_batch_rows_total":
        "Proof rows carried by columnar SUBMIT_BATCH frames, by role "
        "and tenant tms id.",
    "rpc_batch_bytes_total":
        "Payload bytes carried by columnar SUBMIT_BATCH frames, by "
        "role and tenant tms id.",
    "rpc_decode_seconds":
        "Wall seconds decoding one frame payload, by format (columnar "
        "numpy views vs pickle object graphs).",
    "rpc_tenant_deficit":
        "Deficit-round-robin credit currently held by a tenant's "
        "admission queue (rows it may drain before rotating).",
}


class FrameError(Exception):
    """A frame-level protocol failure; ``kind`` feeds the metric label."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind


def _describe(provider) -> None:
    for fam, help_text in _RPC_FAMILIES.items():
        provider.describe(fam, help_text)


# --------------------------------------------------------------- codec
def encode_raw_frame(ftype: int, payload: bytes,
                     max_frame_bytes: int = DEFAULT_MAX_FRAME,
                     flags: int = 0) -> bytes:
    """Serialize one frame around an already-encoded payload (the
    columnar SUBMIT_BATCH path: bytes in, bytes out, no pickle).
    ``flags`` lands in the header flags field (FLAG_TRACE_CONTEXT when
    a trace-context prefix was prepended to ``payload``)."""
    if len(payload) > max_frame_bytes:
        raise FrameError("oversize",
                         f"{len(payload)}B payload > {max_frame_bytes}B cap")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return _HEADER.pack(MAGIC, ftype, flags, len(payload), crc) + payload


def encode_frame(ftype: int, body: dict,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME) -> bytes:
    """Serialize one frame: 12-byte header + pickled, CRC'd payload."""
    return encode_raw_frame(
        ftype, pickle.dumps(body, protocol=pickle.HIGHEST_PROTOCOL),
        max_frame_bytes)


def decode_header(header: bytes,
                  max_frame_bytes: int = DEFAULT_MAX_FRAME):
    """Validate a 12-byte header -> (ftype, length, crc, flags)."""
    magic, ftype, flags, length, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError("bad_magic", f"0x{magic:02x}")
    if length > max_frame_bytes:
        raise FrameError("oversize",
                         f"{length}B header length > {max_frame_bytes}B cap")
    return ftype, length, crc, flags


def split_trace_prefix(payload: bytes, flags: int, provider=None):
    """Strip the optional trace-context prefix off a raw payload.

    Returns ``(ctx_or_None, remaining_payload)``. Without the flag the
    payload passes through untouched (no drop counted — a v1/v2 peer's
    frame simply has no context slot). With the flag set but fewer
    than 17 bytes available, the bytes are counted as an invalid
    context and the payload passes through untouched — a poisoned
    prefix never fails the frame."""
    if not flags & FLAG_TRACE_CONTEXT:
        return None, payload
    if len(payload) < CONTEXT_WIRE_SIZE:
        ctx = extract_wire_context(bytes(payload), provider)
        return ctx, payload
    ctx = extract_wire_context(bytes(payload[:CONTEXT_WIRE_SIZE]),
                               provider)
    return ctx, payload[CONTEXT_WIRE_SIZE:]


def check_payload_crc(payload: bytes, crc: int) -> bytes:
    """CRC-check a raw payload; returns it untouched on success."""
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise FrameError("checksum",
                         f"crc mismatch over {len(payload)}B payload")
    return payload


def decode_payload(payload: bytes, crc: int):
    """CRC-check then unpickle a frame payload."""
    check_payload_crc(payload, crc)
    t0 = time.perf_counter()
    try:
        body = pickle.loads(payload)
    except Exception as exc:  # corrupt-but-crc-colliding, or bad pickle
        raise FrameError("decode", repr(exc)) from exc
    _METRICS.histogram("rpc_decode_seconds",
                       fmt="pickle").observe(time.perf_counter() - t0)
    return body


def _frame_body(ftype: int, payload: bytes, crc: int):
    """Payload bytes -> frame body: raw (CRC only) for the columnar
    types, unpickled dict for everything else."""
    if ftype in RAW_PAYLOAD_TYPES:
        return check_payload_crc(payload, crc)
    return decode_payload(payload, crc)


async def read_frame(reader: asyncio.StreamReader, *,
                     max_frame_bytes: int = DEFAULT_MAX_FRAME,
                     header_timeout_s: float | None = None,
                     body_timeout_s: float = 30.0):
    """Read one frame -> ``(ftype, body, flags)``; ``None`` on clean
    EOF at a frame boundary.

    ``header_timeout_s`` bounds the idle wait for a new frame
    (``asyncio.TimeoutError`` escapes so the caller can use it as a
    checkpoint); ``body_timeout_s`` bounds the rest of the frame once
    its first byte arrived — a slow-loris peer that trickles a frame
    surfaces as ``FrameError("slow_frame")``, never a hang.
    """
    try:
        header = await asyncio.wait_for(
            reader.readexactly(HEADER_SIZE), header_timeout_s)
    except asyncio.IncompleteReadError as exc:
        if not exc.partial:
            return None  # clean EOF between frames
        raise FrameError("torn",
                         f"EOF after {len(exc.partial)}B of header") from exc
    ftype, length, crc, flags = decode_header(header, max_frame_bytes)
    try:
        payload = await asyncio.wait_for(
            reader.readexactly(length), body_timeout_s)
    except asyncio.IncompleteReadError as exc:
        raise FrameError(
            "torn",
            f"EOF after {len(exc.partial)}/{length}B of payload") from exc
    except asyncio.TimeoutError as exc:
        raise FrameError(
            "slow_frame",
            f"payload stalled past {body_timeout_s}s deadline") from exc
    return ftype, _frame_body(ftype, payload, crc), flags


# ----------------------------------------------------- sync codec (client)
def send_frame_sock(sock, ftype: int, body: dict,
                    max_frame_bytes: int = DEFAULT_MAX_FRAME) -> None:
    """Blocking frame send; the socket's own timeout bounds it."""
    sock.sendall(encode_frame(ftype, body, max_frame_bytes))


def send_raw_frame_sock(sock, ftype: int, payload: bytes,
                        max_frame_bytes: int = DEFAULT_MAX_FRAME,
                        flags: int = 0) -> None:
    """Blocking raw-payload frame send (columnar SUBMIT_BATCH)."""
    sock.sendall(encode_raw_frame(ftype, payload, max_frame_bytes, flags))


def recv_exact_sock(sock, n: int, *, deadline: float | None = None) -> bytes:
    """Blocking exact read; ``deadline`` is an absolute monotonic cap.

    Returns ``b""`` on clean EOF before the first byte. Raises
    ``FrameError("torn")`` on EOF mid-buffer and
    ``FrameError("slow_frame")`` when the deadline passes mid-buffer.
    The socket must carry a finite ``settimeout`` so each recv ticks.
    """
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        if deadline is not None and time.monotonic() >= deadline:
            raise FrameError("slow_frame",
                             f"{got}/{n}B before deadline")
        try:
            # recv_into the preallocated buffer: no per-chunk bytes
            # objects, which matters at columnar batch-frame sizes
            k = sock.recv_into(view[got:])  # io-deadline: settimeout tick
        except TimeoutError:
            if not got and deadline is None:
                raise  # idle tick between frames: caller's checkpoint
            continue
        if not k:
            if not got:
                return b""
            raise FrameError("torn", f"EOF after {got}/{n}B")
        got += k
    return bytes(buf)


def recv_frame_sock(sock, *, max_frame_bytes: int = DEFAULT_MAX_FRAME,
                    body_timeout_s: float = 30.0):
    """Blocking frame read -> ``(ftype, body, flags)``; ``None`` on
    clean EOF at a frame boundary.

    Idle waits between frames raise ``TimeoutError`` (the socket's
    ``settimeout`` tick) so the caller can poll a stop flag; once the
    first byte lands, the whole frame must arrive within
    ``body_timeout_s`` or the read fails as ``slow_frame``.
    """
    first = recv_exact_sock(sock, 1)
    if not first:
        return None
    deadline = time.monotonic() + body_timeout_s
    rest = recv_exact_sock(sock, HEADER_SIZE - 1, deadline=deadline)
    if len(rest) != HEADER_SIZE - 1:
        raise FrameError("torn", "EOF mid-header")
    ftype, length, crc, flags = decode_header(first + rest, max_frame_bytes)
    payload = recv_exact_sock(sock, length, deadline=deadline)
    if len(payload) != length:
        raise FrameError("torn", "EOF mid-payload")
    return ftype, _frame_body(ftype, payload, crc), flags


# -------------------------------------------------------------- server
@dataclass(frozen=True)
class RpcConfig:
    """Network-plane knobs; all waits are finite by construction."""

    host: str = "127.0.0.1"
    port: int = 0                      # 0 = ephemeral (tests)
    max_frame_bytes: int = DEFAULT_MAX_FRAME
    hello_timeout_s: float = 5.0       # handshake must complete in this
    idle_tick_s: float = 0.5           # read-loop checkpoint cadence
    frame_timeout_s: float = 10.0      # slow-loris: whole frame after byte 0
    write_timeout_s: float = 30.0      # drain() cap per frame
    conn_credits: int = 1024           # per-connection row-budget ceiling
    drain_timeout_s: float = 30.0      # stop(): cap on finishing in-flight


class _Conn:
    """Per-connection state: credits, write lock, frame accounting."""

    def __init__(self, server: "RpcServer", reader, writer, cid: int):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.cid = cid
        self.tms_id = "unknown"
        self.credits = 0               # server-side view of client budget
        self.write_lock = asyncio.Lock()
        self.frames_started = 0        # writes begun (header bytes queued)
        self.frames_done = 0           # writes fully drained
        self.inflight: set[asyncio.Task] = set()
        self.goaway_sent = False
        self.closing = False

    async def send(self, ftype: int, body: dict) -> None:
        cfg = self.server.config
        buf = encode_frame(ftype, body, cfg.max_frame_bytes)
        async with self.write_lock:
            if self.closing:
                raise ConnectionResetError("connection closing")
            self.frames_started += 1
            self.writer.write(buf)
            await asyncio.wait_for(self.writer.drain(), cfg.write_timeout_s)
            self.frames_done += 1
        self.server._count_frame("sent", ftype)


class RpcServer:
    """Streaming TCP front door over a running ``VerificationService``.

    Single event loop, shared with the service's dispatch loop. Start
    the service first, then ``await server.start()``; ``stop()`` is a
    draining stop: GOAWAY to every connection, in-flight frames finish,
    no connection is closed mid-frame (``frames_clean`` asserts it).
    """

    def __init__(self, service, config: RpcConfig | None = None, *,
                 provider=None, tracer=None):
        self.service = service
        self.config = config or RpcConfig()
        self.provider = provider or _METRICS
        self.tracer = tracer or _TRACER
        _describe(self.provider)
        self._server: asyncio.base_events.Server | None = None
        self._conns: dict[int, _Conn] = {}
        self._next_cid = 0
        self._draining = False
        self.midframe_closes = 0
        self.address: tuple[str, int] | None = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle, self.config.host, self.config.port,
            reuse_address=True)
        sockname = self._server.sockets[0].getsockname()
        self.address = (sockname[0], sockname[1])
        JOURNAL.record("rpc_listen", addr=f"{sockname[0]}:{sockname[1]}")
        return self.address

    async def stop(self, drain: bool = True) -> None:
        """Draining stop: GOAWAY, finish in-flight, close clean."""
        self._draining = True
        if self._server is not None:
            self._server.close()
        conns = list(self._conns.values())
        for conn in conns:
            if not conn.goaway_sent:
                conn.goaway_sent = True
                try:
                    await conn.send(GOAWAY, {"reason": "draining"})
                    self.provider.counter(
                        "rpc_goaways_total", role="server").add()
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    pass
        if drain:
            pending = [t for c in conns for t in list(c.inflight)]
            if pending:
                await asyncio.wait(
                    pending, timeout=self.config.drain_timeout_s)
        for conn in conns:
            await self._close_conn(conn)
        if self._server is not None:
            await self._server.wait_closed()

    @property
    def frames_clean(self) -> bool:
        """True iff no connection was ever closed mid-write."""
        return self.midframe_closes == 0

    def status(self) -> dict:
        """``/statusz`` payload: connections, credits, accounting."""
        return {
            "address": list(self.address) if self.address else None,
            "draining": self._draining,
            "connections": {
                str(c.cid): {
                    "tms_id": c.tms_id,
                    "credits": c.credits,
                    "inflight": len(c.inflight),
                    "frames_started": c.frames_started,
                    "frames_done": c.frames_done,
                }
                for c in self._conns.values()
            },
            "midframe_closes": self.midframe_closes,
        }

    # ------------------------------------------------------------- metrics
    def _count_frame(self, direction: str, ftype: int) -> None:
        self.provider.counter(
            "rpc_frames_total", role="server", dir=direction,
            type=FRAME_NAMES.get(ftype, str(ftype))).add()

    def _frame_error(self, kind: str) -> None:
        self.provider.counter("rpc_frame_errors_total", kind=kind).add()

    # ------------------------------------------------------------- credits
    def _credit_target(self) -> int:
        """Row budget a connection may hold: admission headroom, capped.

        Headroom follows the deepest lane so credits shrink as queues
        fill — the client stalls on credits instead of stuffing the
        socket buffer with work the admission controller would shed.
        """
        svc = self.service
        deepest = max(
            (svc.scheduler.lane_depth(lane) for lane in LANES), default=0)
        headroom = svc.config.queue_capacity - deepest
        return max(0, min(self.config.conn_credits, headroom))

    async def _replenish(self, conn: _Conn) -> None:
        grant = self._credit_target() - conn.credits
        if grant <= 0 or conn.closing or conn.goaway_sent:
            return
        conn.credits += grant
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        try:
            await conn.send(CREDIT, {"grant": grant})
        except (ConnectionError, OSError, asyncio.TimeoutError):
            conn.credits -= grant

    # ---------------------------------------------------------- connection
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        cfg = self.config
        cid = self._next_cid
        self._next_cid += 1
        conn = _Conn(self, reader, writer, cid)
        try:
            frame = await read_frame(
                reader, max_frame_bytes=cfg.max_frame_bytes,
                header_timeout_s=cfg.hello_timeout_s,
                body_timeout_s=cfg.hello_timeout_s)
        except (FrameError, asyncio.TimeoutError, ConnectionError,
                OSError) as exc:
            kind = exc.kind if isinstance(exc, FrameError) else "torn"
            self._frame_error(kind)
            await self._close_conn(conn)
            return
        if frame is None or frame[0] != HELLO:
            self._frame_error("protocol")
            await self._close_conn(conn)
            return
        hello = frame[1]
        conn.tms_id = str(hello.get("tms_id", "default"))
        conn.credits = self._credit_target()
        self._conns[cid] = conn
        self.provider.counter("rpc_connections_total",
                              tms=conn.tms_id).add()
        self.provider.gauge("rpc_connections_active").set(len(self._conns))
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        self._count_frame("recv", HELLO)
        try:
            await conn.send(WELCOME, {
                "t": hello.get("t", 0.0),
                "t_srv": time.time(),
                "credits": conn.credits,
                "max_frame": cfg.max_frame_bytes,
                # version negotiation: v2 peers may send columnar
                # SUBMIT_BATCH frames, v3 peers may attach trace
                # context; v1/v2 clients ignore the extra keys and keep
                # speaking their protocol unchanged
                "v": RPC_VERSION,
                "batch": True,
                "trace": True,
            })
            if self._draining and not conn.goaway_sent:
                conn.goaway_sent = True
                await conn.send(GOAWAY, {"reason": "draining"})
                self.provider.counter(
                    "rpc_goaways_total", role="server").add()
            await self._read_loop(conn)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
        finally:
            if conn.inflight:
                await asyncio.wait(list(conn.inflight),
                                   timeout=cfg.drain_timeout_s)
            await self._close_conn(conn)
            self._conns.pop(cid, None)
            self.provider.gauge(
                "rpc_connections_active").set(len(self._conns))

    async def _read_loop(self, conn: _Conn) -> None:
        cfg = self.config
        while not conn.closing:
            try:
                frame = await read_frame(
                    conn.reader, max_frame_bytes=cfg.max_frame_bytes,
                    header_timeout_s=cfg.idle_tick_s,
                    body_timeout_s=cfg.frame_timeout_s)
            except asyncio.TimeoutError:
                # idle checkpoint: leave once draining and quiesced
                if self._draining and not conn.inflight:
                    return
                continue
            except FrameError as exc:
                # A poisoned stream: count it, drop THIS connection, and
                # keep the accept loop alive — one bad peer never takes
                # the server down.
                self._frame_error(exc.kind)
                JOURNAL.record("rpc_frame_error", kind=exc.kind,
                               tms_id=conn.tms_id, detail=str(exc))
                return
            if frame is None:
                return  # client closed cleanly
            ftype, body, flags = frame
            self._count_frame("recv", ftype)
            if ftype == PING:
                await conn.send(PONG, {"t": body.get("t", 0.0),
                                       "t_srv": time.time()})
            elif ftype == GOAWAY:
                conn.goaway_sent = True  # client-initiated drain
            elif ftype == SUBMIT:
                self._accept_submit(conn, body)
            elif ftype == SUBMIT_BATCH:
                # trace context rides as a flagged 17-byte prefix on the
                # raw payload (a poisoned prefix is counted + ignored)
                ctx, body = split_trace_prefix(body, flags, self.provider)
                try:
                    batch = self._decode_batch(conn, body)
                except FrameError as exc:
                    # same contract as a poisoned pickled frame: count,
                    # journal, drop THIS connection, server stays up
                    self._frame_error(exc.kind)
                    JOURNAL.record("rpc_frame_error", kind=exc.kind,
                                   tms_id=conn.tms_id, detail=str(exc))
                    return
                self._accept_submit_batch(conn, batch, ctx)
            else:
                self._frame_error("protocol")

    def _decode_batch(self, conn: _Conn, payload: bytes):
        """Raw columnar payload -> numpy-view batch, timed + counted.

        Decode allocates O(1): every column is a view over the frame
        buffer. Malformed payloads surface as ``FrameError`` with the
        codec's kind (``row_count`` / ``decode``)."""
        t0 = time.perf_counter()
        try:
            batch = decode_submit_batch(payload)
        except ColumnarError as exc:
            raise FrameError(exc.kind, str(exc)) from exc
        self.provider.histogram(
            "rpc_decode_seconds",
            fmt="columnar").observe(time.perf_counter() - t0)
        self.provider.counter("rpc_batch_frames_total", role="server",
                              tms=conn.tms_id).add()
        self.provider.counter("rpc_batch_rows_total", role="server",
                              tms=conn.tms_id).add(batch.n_rows)
        self.provider.counter("rpc_batch_bytes_total", role="server",
                              tms=conn.tms_id).add(batch.nbytes)
        return batch

    def _accept_submit_batch(self, conn: _Conn, batch, ctx=None) -> None:
        """Credit accounting in rows — one columnar frame spends exactly
        what its row count would cost as N legacy SUBMITs, so the
        backpressure semantics are unchanged."""
        rows = batch.n_rows
        if rows > conn.credits:
            self._frame_error("credit_violation")
        conn.credits = max(0, conn.credits - rows)
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        task = asyncio.ensure_future(
            self._serve_submit_batch(conn, batch, ctx))
        conn.inflight.add(task)
        task.add_done_callback(conn.inflight.discard)

    async def _serve_submit_batch(self, conn: _Conn, batch,
                                  ctx=None) -> None:
        reply: dict = {"req_id": batch.req_id_base, "status": RPC_OK}
        if ctx is not None:
            reply["tc"] = ctx.to_bytes()  # echo for client correlation
        deadline_s = batch.deadline - time.time()
        if deadline_s <= 0:
            self.provider.counter("rpc_deadline_expired_total").add()
            reply["status"] = RPC_EXPIRED
            reply["error"] = (
                f"deadline passed {-deadline_s * 1000:.1f}ms before decode")
        elif self._draining or conn.goaway_sent:
            reply["status"] = RPC_GOAWAY
            reply["error"] = "server draining"
        if reply["status"] == RPC_OK:
            # ONE rpc_requests_total bump per frame — the whole point
            self.provider.counter("rpc_requests_total", tms=conn.tms_id,
                                  kind="range", lane=batch.lane).add()
            try:
                with self.tracer.span("rpc.serve_batch", rows=batch.n_rows,
                                      fmt=batch.fmt_name, lane=batch.lane,
                                      remote_parent=ctx) as ssp:
                    proofs, coms = materialize_rows(batch)
                    offs = batch.deadline_offsets_s
                    results = await self.service.submit_batch(
                        "range", list(zip(proofs, coms)),
                        deadline_s=deadline_s,
                        deadline_offsets_s=offs if offs.any() else None,
                        lane=batch.lane, tenant=conn.tms_id,
                        trace_ctx=ssp.context() if ctx is not None
                        else None)
                reply["statuses"] = [r.status for r in results]
                reply["verdicts"] = [r.accepted for r in results]
                reply["served_by"] = sorted(
                    {r.served_by for r in results if r.served_by})
            except Exception as exc:  # service-level failure -> typed error
                reply["status"] = RPC_ERROR
                reply["error"] = str(exc)
                reply["error_type"] = type(exc).__name__
        try:
            await conn.send(RESULT, reply)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return  # peer gone; its redial will resubmit
        await self._replenish(conn)

    def _accept_submit(self, conn: _Conn, body: dict) -> None:
        rows = int(body.get("rows", 1))
        if rows > conn.credits:
            self._frame_error("credit_violation")
        conn.credits = max(0, conn.credits - rows)
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        task = asyncio.ensure_future(self._serve_submit(conn, body))
        conn.inflight.add(task)
        task.add_done_callback(conn.inflight.discard)

    async def _serve_submit(self, conn: _Conn, body: dict) -> None:
        req_id = body.get("req_id")
        kind = body.get("kind", "range")
        lane = body.get("lane", LANE_BULK)
        tms_id = str(body.get("tms_id", conn.tms_id))
        # caller's trace context, if any: v1/v2 peers never send "tc"
        # (counted as reason=missing), v3 peers send 17 context bytes;
        # a poisoned value is counted + ignored — never a frame error
        ctx = extract_wire_context(body.get("tc"), self.provider)
        reply: dict = {"req_id": req_id, "status": RPC_OK}
        if ctx is not None:
            reply["tc"] = ctx.to_bytes()  # echo for client correlation
        deadline = body.get("deadline")
        deadline_s = None
        if deadline is not None:
            deadline_s = float(deadline) - time.time()
            if deadline_s <= 0:
                self.provider.counter("rpc_deadline_expired_total").add()
                reply["status"] = RPC_EXPIRED
                reply["error"] = (
                    f"deadline passed {-deadline_s * 1000:.1f}ms before "
                    "decode")
        if reply["status"] == RPC_OK and (self._draining or conn.goaway_sent):
            reply["status"] = RPC_GOAWAY
            reply["error"] = "server draining"
        if reply["status"] == RPC_OK:
            self.provider.counter("rpc_requests_total", tms=tms_id,
                                  kind=kind, lane=lane).add()
            try:
                await self._verify_into(reply, kind, lane, deadline_s, body,
                                        tenant=tms_id, ctx=ctx)
            except Exception as exc:  # service-level failure -> typed error
                reply["status"] = RPC_ERROR
                reply["error"] = str(exc)
                reply["error_type"] = type(exc).__name__
        try:
            await conn.send(RESULT, reply)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return  # peer gone; its redial will resubmit
        await self._replenish(conn)

    async def _verify_into(self, reply: dict, kind: str, lane: str,
                           deadline_s: float | None, body: dict,
                           tenant: str = "default", ctx=None) -> None:
        svc = self.service
        with self.tracer.span("rpc.serve", kind=kind, lane=lane,
                              remote_parent=ctx) as ssp:
            tc = ssp.context() if ctx is not None else None
            if kind == "range":
                proofs, coms = body["payload"]
                results = await asyncio.gather(*[
                    svc.submit_range(p, c, deadline_s=deadline_s, lane=lane,
                                     tenant=tenant, trace_ctx=tc)
                    for p, c in zip(proofs, coms)])
                reply["statuses"] = [r.status for r in results]
                reply["verdicts"] = [r.accepted for r in results]
                reply["served_by"] = sorted(
                    {r.served_by for r in results if r.served_by})
            elif kind == "block":
                transfers, issues = body["payload"]
                t_res, i_res = await asyncio.gather(
                    asyncio.gather(*[
                        svc.submit_transfer(pr, ins, outs,
                                            deadline_s=deadline_s, lane=lane,
                                            tenant=tenant, trace_ctx=tc)
                        for pr, ins, outs in transfers]),
                    asyncio.gather(*[
                        svc.submit_issue(pr, outs, deadline_s=deadline_s,
                                         lane=lane, tenant=tenant,
                                         trace_ctx=tc)
                        for pr, outs in issues]))
                reply["statuses"] = ([r.status for r in t_res],
                                     [r.status for r in i_res])
                reply["verdicts"] = ([r.accepted for r in t_res],
                                     [r.accepted for r in i_res])
                reply["served_by"] = sorted(
                    {r.served_by for r in (*t_res, *i_res) if r.served_by})
            else:
                raise ValueError(f"unknown submit kind {kind!r}")

    async def _close_conn(self, conn: _Conn) -> None:
        if conn.closing:
            return
        conn.closing = True
        if conn.frames_started != conn.frames_done:
            # a write was abandoned between header and drain — the one
            # invariant the draining stop exists to prevent
            self.midframe_closes += 1
            self._frame_error("midframe_close")
        try:
            conn.writer.close()
            await asyncio.wait_for(conn.writer.wait_closed(), 5.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
