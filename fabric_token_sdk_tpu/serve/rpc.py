"""Network front door: asyncio TCP RPC server on the Validator SPI.

The reference SDK's whole point is a pluggable ``driver.Validator``
behind a process boundary (SURVEY §3.2). PR 8's sidecar speaks a
same-host ``multiprocessing`` pipe; this module adds the real network
plane — stdlib-only (asyncio TCP, no grpcio — same policy as the
stdlib-HTTP ``TelemetryServer``) so the failure modes of a network
boundary (half-open connections, torn frames, slow peers, reconnect
storms) are exercised and testable.

Wire format — length-prefixed, CRC-checksummed frames (the WAL's
checksum discipline applied to the socket):

    header  = struct ">BBHII" (12 bytes)
              magic 0xF7 | frame type | flags (0) | payload len | CRC32
    payload = pickled dict, CRC32-checked before unpickling

Pickle is acceptable here for the same reason it is on the worker
pipe: the sidecar is a same-trust-domain process boundary, not an
internet-facing endpoint (README "Network boundary").

Protocol:

  HELLO{tms_id,t}  -> WELCOME{t,t_srv,credits,max_frame}   handshake;
      the client derives RTT and a clock-offset estimate so wire
      deadlines are absolute *server-clock* times.
  SUBMIT{req_id,kind,lane,deadline,payload}  -> RESULT{req_id,...}
      streaming batch submits; rows fan into
      ``VerificationService.submit_*`` and the per-row verdicts are
      demultiplexed back into one RESULT frame.
  SUBMIT_BATCH(columnar payload)  -> RESULT{req_id,...}
      the high-throughput ingest path: one CRC-framed frame carries N
      proofs as contiguous uint32 limb planes + per-row metadata
      columns (serve/columnar.py). The payload is NOT pickled — the
      server decodes it into numpy views over the frame buffer (zero
      per-row Python objects) and admits the whole frame through
      ``VerificationService.submit_batch`` (one admission decision,
      one WAL append, one journal event). Credits are spent in rows,
      same as N legacy SUBMITs. Capability is advertised in WELCOME
      (``v=2, batch=True``); v1 clients never see the type.
  RESULT_BATCH(columnar payload)   the egress mirror (protocol v4):
      verdicts completing on one connection coalesce into one
      CRC-framed columnar frame — req_id/row_idx/status/verdict/
      served_by columns plus an optional per-row trace column — with
      zero per-row pickling and ONE batched drain wakeup per cycle
      instead of a doorbell per result. Sent only to peers whose HELLO
      carried ``v >= 4``; v1–v3 peers keep per-row pickled RESULT
      frames, and non-OK replies (expired/goaway/error) and block
      verdicts stay pickled for every peer (error strings stay
      expressive, fallback stays trivially correct).
  CREDIT{grant}    credit-based flow control: each connection holds a
      row budget; SUBMIT rows consume it, the server replenishes from
      admission headroom (``queue_capacity`` minus the deepest lane),
      so backpressure reaches the client instead of an unbounded
      socket buffer.
  PING{t} -> PONG{t,t_srv}   liveness + RTT/offset refresh.
  GOAWAY{reason}   draining stop: no new submits accepted, in-flight
      frames finish, the server never closes a connection mid-frame
      (asserted by per-connection frame accounting).
  ERROR{...}       protocol-level rejection.

Loop sharding (``RpcConfig.n_loops``): the server runs its own accept
loop(s) over manually-bound listen sockets. ``n_loops=1`` (default)
keeps everything on the service's event loop — today's behavior
exactly. ``n_loops>=2`` starts worker event loops (threads), each
owning its accepted connections end-to-end (read, decode, write):
either every shard holds its own SO_REUSEPORT listen socket (the
kernel load-balances accepts), or — where SO_REUSEPORT is unavailable
— one acceptor hands accepted sockets to shards round-robin. The
shared ``VerificationService`` stays on its own loop; shard loops
reach it through a thread-safe submit handoff
(``run_coroutine_threadsafe`` + ``wrap_future``), one cross-loop
completion per *frame*, and results are written back only by the
connection's owning loop (asserted by an ownership counter). fd
exhaustion in an accept loop backs off with jitter and counts
``rpc_accept_shed_total{reason="emfile"}`` instead of tearing the
acceptor down.

Every read is under an explicit deadline (``asyncio.wait_for``) — a
hung read with no deadline is how rc=124-with-no-diagnosis comes back
(enforced by ``scripts/check_socket_timeouts.py``).
"""

from __future__ import annotations

import asyncio
import errno
import pickle
import random
import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..obs.journal import JOURNAL
from ..obs.tracing import CONTEXT_WIRE_SIZE, extract_wire_context
from .columnar import (ColumnarError, decode_submit_batch,
                       encode_result_batch, materialize_rows)
from .config import LANE_BULK, LANES
from .request import STATUS_OK

MAGIC = 0xF7
_HEADER = struct.Struct(">BBHII")
HEADER_SIZE = _HEADER.size

# Frame types.
HELLO = 1
WELCOME = 2
SUBMIT = 3
RESULT = 4
CREDIT = 5
PING = 6
PONG = 7
GOAWAY = 8
ERROR = 9
SUBMIT_BATCH = 10
RESULT_BATCH = 11

FRAME_NAMES = {
    HELLO: "hello", WELCOME: "welcome", SUBMIT: "submit", RESULT: "result",
    CREDIT: "credit", PING: "ping", PONG: "pong", GOAWAY: "goaway",
    ERROR: "error", SUBMIT_BATCH: "submit_batch",
    RESULT_BATCH: "result_batch",
}

#: Frame types whose payload is raw bytes (CRC-checked, never pickled);
#: everything else stays a pickled dict.
RAW_PAYLOAD_TYPES = frozenset({SUBMIT_BATCH, RESULT_BATCH})

#: Protocol version advertised in WELCOME: 2 adds SUBMIT_BATCH, 3 adds
#: wire-propagated trace context (SpanContext in SUBMIT/RESULT bodies
#: under key ``"tc"``; a 17-byte prefix on SUBMIT_BATCH payloads when
#: the FLAG_TRACE_CONTEXT header flag is set), 4 adds columnar
#: RESULT_BATCH egress — the server coalesces OK range verdicts into
#: columnar frames for peers whose HELLO carried ``v >= 4``. v1–v3
#: peers stay wire compatible: the server answers them with per-row
#: pickled RESULT frames, and a server never requires any of the newer
#: capabilities — missing context/version is counted, never an error.
RPC_VERSION = 4

#: Header flag bit: the payload begins with a 17-byte trace context
#: (only meaningful on RAW_PAYLOAD_TYPES frames; pickled bodies carry
#: context in-dict under ``"tc"`` instead).
FLAG_TRACE_CONTEXT = 0x1

DEFAULT_MAX_FRAME = 32 * 1024 * 1024

# RESULT statuses (transport-level; row-level statuses reuse serve's).
RPC_OK = STATUS_OK
RPC_EXPIRED = "expired"            # shed at decode: wire deadline passed
RPC_GOAWAY = "goaway"              # server draining, submit rejected
RPC_ERROR = "error"

_RPC_FAMILIES = {
    "rpc_connections_total":
        "RPC connections accepted by the server, by tenant tms id.",
    "rpc_connections_active":
        "RPC connections currently open on the server.",
    "rpc_frames_total":
        "RPC frames moved, by role (server/client), direction "
        "(sent/recv) and frame type.",
    "rpc_frame_errors_total":
        "RPC frame-level failures by kind: torn (EOF mid-frame), "
        "checksum, oversize, bad_magic, slow_frame (mid-frame stall "
        "past the frame deadline), decode, protocol, credit_violation, "
        "midframe_close, row_count (columnar batch whose byte count "
        "disagrees with its declared shape).",
    "rpc_requests_total":
        "SUBMIT frames accepted into the service, by tenant tms id, "
        "kind and lane.",
    "rpc_credits":
        "Row credits currently granted to a tenant's connection "
        "(server-side view of the client's spendable budget).",
    "rpc_credit_waits_total":
        "Client-side stalls waiting for row credits (backpressure "
        "reached the client).",
    "rpc_redials_total":
        "Client reconnect attempts, by outcome (ok / error).",
    "rpc_goaways_total":
        "GOAWAY frames, by role (server sent / client received).",
    "rpc_deadline_expired_total":
        "SUBMIT frames shed at decode because the wire-propagated "
        "deadline had already passed.",
    "rpc_call_seconds":
        "Client-observed RPC round-trip wall seconds, by kind.",
    "rpc_hedges_total":
        "Hedged duplicate SUBMITs sent for the interactive lane.",
    "rpc_batch_frames_total":
        "Columnar SUBMIT_BATCH frames moved, by role and tenant tms id.",
    "rpc_batch_rows_total":
        "Proof rows carried by columnar SUBMIT_BATCH frames, by role "
        "and tenant tms id.",
    "rpc_batch_bytes_total":
        "Payload bytes carried by columnar SUBMIT_BATCH frames, by "
        "role and tenant tms id.",
    "rpc_decode_seconds":
        "Wall seconds decoding one frame payload, by format (columnar "
        "numpy views vs pickle object graphs).",
    "rpc_tenant_deficit":
        "Deficit-round-robin credit currently held by a tenant's "
        "admission queue (rows it may drain before rotating).",
    # ---- C10k front door (loop sharding + columnar egress) ----
    "rpc_loops":
        "Serving event loops (accept/IO shards) the RPC server runs.",
    "rpc_conns":
        "RPC connections currently owned by one serving loop, by loop "
        "index.",
    "rpc_wakeups_total":
        "Coalesced egress drain wakeups: one per drain cycle, however "
        "many completed verdicts the cycle flushes (the doorbell-per-"
        "result this replaces would count once per row).",
    "rpc_result_batch_frames_total":
        "Columnar RESULT_BATCH frames moved, by role (server/client).",
    "rpc_result_batch_rows_total":
        "Verdict rows carried by columnar RESULT_BATCH frames, by "
        "role.",
    "rpc_result_batch_bytes_total":
        "Payload bytes carried by columnar RESULT_BATCH frames, by "
        "role.",
    "rpc_accept_shed_total":
        "Accept-loop sheds by reason: emfile (fd exhaustion — the "
        "acceptor backs off with jitter instead of spinning or dying), "
        "error (other transient accept failures).",
}


class FrameError(Exception):
    """A frame-level protocol failure; ``kind`` feeds the metric label."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind


def _describe(provider) -> None:
    for fam, help_text in _RPC_FAMILIES.items():
        provider.describe(fam, help_text)


class ScratchPool:
    """Thread-safe, size-classed pool of mutable scratch bytearrays.

    Steady-state serving reads and encodes thousands of frames per
    second; allocating a fresh bytearray per frame is pure allocator
    churn. ``acquire(n)`` returns a bytearray of at least ``n`` bytes
    (rounded up to a power-of-two size class, floor 4 KiB);
    ``release`` returns it for reuse, keeping at most ``max_per_class``
    buffers per class so a burst of giant frames cannot pin memory
    forever. Buffers are *scratch*: contents are undefined on acquire,
    and callers must copy out (``bytes(view)``) anything that outlives
    the release — frame payloads handed to zero-copy decoders are
    immutable ``bytes`` for exactly this reason.
    """

    _MIN_CLASS = 4096

    def __init__(self, max_per_class: int = 32,
                 max_class_bytes: int = DEFAULT_MAX_FRAME):
        self._lock = threading.Lock()
        self._classes: dict[int, list[bytearray]] = {}
        self._max_per_class = max_per_class
        self._max_class_bytes = max_class_bytes
        self.hits = 0
        self.misses = 0

    def _class_of(self, n: int) -> int:
        size = max(self._MIN_CLASS, 1 << max(0, (n - 1).bit_length()))
        return size

    def acquire(self, n: int) -> bytearray:
        size = self._class_of(n)
        if size > self._max_class_bytes:
            # beyond the pooled range: plain allocation, never cached
            self.misses += 1
            return bytearray(size)
        with self._lock:
            bucket = self._classes.get(size)
            if bucket:
                self.hits += 1
                return bucket.pop()
            self.misses += 1
        return bytearray(size)

    def release(self, buf: bytearray) -> None:
        size = len(buf)
        if size != self._class_of(size) or size > self._max_class_bytes:
            return  # not one of ours (or oversize): let the GC have it
        with self._lock:
            bucket = self._classes.setdefault(size, [])
            if len(bucket) < self._max_per_class:
                bucket.append(buf)


#: Process-wide scratch pool shared by the sync recv path and the
#: server's RESULT_BATCH encode staging.
_SCRATCH = ScratchPool()


# --------------------------------------------------------------- codec
def encode_raw_frame(ftype: int, payload: bytes,
                     max_frame_bytes: int = DEFAULT_MAX_FRAME,
                     flags: int = 0) -> bytes:
    """Serialize one frame around an already-encoded payload (the
    columnar SUBMIT_BATCH path: bytes in, bytes out, no pickle).
    ``flags`` lands in the header flags field (FLAG_TRACE_CONTEXT when
    a trace-context prefix was prepended to ``payload``)."""
    if len(payload) > max_frame_bytes:
        raise FrameError("oversize",
                         f"{len(payload)}B payload > {max_frame_bytes}B cap")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return _HEADER.pack(MAGIC, ftype, flags, len(payload), crc) + payload


def encode_frame(ftype: int, body: dict,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME) -> bytes:
    """Serialize one frame: 12-byte header + pickled, CRC'd payload."""
    return encode_raw_frame(
        ftype, pickle.dumps(body, protocol=pickle.HIGHEST_PROTOCOL),
        max_frame_bytes)


def decode_header(header: bytes,
                  max_frame_bytes: int = DEFAULT_MAX_FRAME):
    """Validate a 12-byte header -> (ftype, length, crc, flags)."""
    magic, ftype, flags, length, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError("bad_magic", f"0x{magic:02x}")
    if length > max_frame_bytes:
        raise FrameError("oversize",
                         f"{length}B header length > {max_frame_bytes}B cap")
    return ftype, length, crc, flags


def split_trace_prefix(payload: bytes, flags: int, provider=None):
    """Strip the optional trace-context prefix off a raw payload.

    Returns ``(ctx_or_None, remaining_payload)``. Without the flag the
    payload passes through untouched (no drop counted — a v1/v2 peer's
    frame simply has no context slot). With the flag set but fewer
    than 17 bytes available, the bytes are counted as an invalid
    context and the payload passes through untouched — a poisoned
    prefix never fails the frame."""
    if not flags & FLAG_TRACE_CONTEXT:
        return None, payload
    if len(payload) < CONTEXT_WIRE_SIZE:
        ctx = extract_wire_context(bytes(payload), provider)
        return ctx, payload
    ctx = extract_wire_context(bytes(payload[:CONTEXT_WIRE_SIZE]),
                               provider)
    return ctx, payload[CONTEXT_WIRE_SIZE:]


def check_payload_crc(payload: bytes, crc: int) -> bytes:
    """CRC-check a raw payload; returns it untouched on success."""
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise FrameError("checksum",
                         f"crc mismatch over {len(payload)}B payload")
    return payload


def decode_payload(payload: bytes, crc: int):
    """CRC-check then unpickle a frame payload."""
    check_payload_crc(payload, crc)
    t0 = time.perf_counter()
    try:
        body = pickle.loads(payload)
    except Exception as exc:  # corrupt-but-crc-colliding, or bad pickle
        raise FrameError("decode", repr(exc)) from exc
    _METRICS.histogram("rpc_decode_seconds",
                       fmt="pickle").observe(time.perf_counter() - t0)
    return body


def _frame_body(ftype: int, payload: bytes, crc: int):
    """Payload bytes -> frame body: raw (CRC only) for the columnar
    types, unpickled dict for everything else."""
    if ftype in RAW_PAYLOAD_TYPES:
        return check_payload_crc(payload, crc)
    return decode_payload(payload, crc)


async def read_frame(reader: asyncio.StreamReader, *,
                     max_frame_bytes: int = DEFAULT_MAX_FRAME,
                     header_timeout_s: float | None = None,
                     body_timeout_s: float = 30.0):
    """Read one frame -> ``(ftype, body, flags)``; ``None`` on clean
    EOF at a frame boundary.

    ``header_timeout_s`` bounds the idle wait for a new frame
    (``asyncio.TimeoutError`` escapes so the caller can use it as a
    checkpoint); ``body_timeout_s`` bounds the rest of the frame once
    its first byte arrived — a slow-loris peer that trickles a frame
    surfaces as ``FrameError("slow_frame")``, never a hang.
    """
    try:
        header = await asyncio.wait_for(
            reader.readexactly(HEADER_SIZE), header_timeout_s)
    except asyncio.IncompleteReadError as exc:
        if not exc.partial:
            return None  # clean EOF between frames
        raise FrameError("torn",
                         f"EOF after {len(exc.partial)}B of header") from exc
    ftype, length, crc, flags = decode_header(header, max_frame_bytes)
    try:
        payload = await asyncio.wait_for(
            reader.readexactly(length), body_timeout_s)
    except asyncio.IncompleteReadError as exc:
        raise FrameError(
            "torn",
            f"EOF after {len(exc.partial)}/{length}B of payload") from exc
    except asyncio.TimeoutError as exc:
        raise FrameError(
            "slow_frame",
            f"payload stalled past {body_timeout_s}s deadline") from exc
    return ftype, _frame_body(ftype, payload, crc), flags


# ----------------------------------------------------- sync codec (client)
def send_frame_sock(sock, ftype: int, body: dict,
                    max_frame_bytes: int = DEFAULT_MAX_FRAME) -> None:
    """Blocking frame send; the socket's own timeout bounds it."""
    sock.sendall(encode_frame(ftype, body, max_frame_bytes))


def send_raw_frame_sock(sock, ftype: int, payload: bytes,
                        max_frame_bytes: int = DEFAULT_MAX_FRAME,
                        flags: int = 0) -> None:
    """Blocking raw-payload frame send (columnar SUBMIT_BATCH)."""
    sock.sendall(encode_raw_frame(ftype, payload, max_frame_bytes, flags))


def recv_exact_sock(sock, n: int, *, deadline: float | None = None) -> bytes:
    """Blocking exact read; ``deadline`` is an absolute monotonic cap.

    Returns ``b""`` on clean EOF before the first byte. Raises
    ``FrameError("torn")`` on EOF mid-buffer and
    ``FrameError("slow_frame")`` when the deadline passes mid-buffer.
    The socket must carry a finite ``settimeout`` so each recv ticks.
    """
    buf = _SCRATCH.acquire(n)
    view = memoryview(buf)
    try:
        got = 0
        while got < n:
            if deadline is not None and time.monotonic() >= deadline:
                raise FrameError("slow_frame",
                                 f"{got}/{n}B before deadline")
            try:
                # recv_into pooled scratch: no per-chunk bytes objects
                # and no per-frame bytearray churn, which matters at
                # columnar batch-frame sizes
                k = sock.recv_into(view[got:n])  # io-deadline: settimeout tick
            except TimeoutError:
                if not got and deadline is None:
                    raise  # idle tick between frames: caller's checkpoint
                continue
            if not k:
                if not got:
                    return b""
                raise FrameError("torn", f"EOF after {got}/{n}B")
            got += k
        return bytes(view[:n])
    finally:
        view.release()
        _SCRATCH.release(buf)


def recv_frame_sock(sock, *, max_frame_bytes: int = DEFAULT_MAX_FRAME,
                    body_timeout_s: float = 30.0):
    """Blocking frame read -> ``(ftype, body, flags)``; ``None`` on
    clean EOF at a frame boundary.

    Idle waits between frames raise ``TimeoutError`` (the socket's
    ``settimeout`` tick) so the caller can poll a stop flag; once the
    first byte lands, the whole frame must arrive within
    ``body_timeout_s`` or the read fails as ``slow_frame``.
    """
    first = recv_exact_sock(sock, 1)
    if not first:
        return None
    deadline = time.monotonic() + body_timeout_s
    rest = recv_exact_sock(sock, HEADER_SIZE - 1, deadline=deadline)
    if len(rest) != HEADER_SIZE - 1:
        raise FrameError("torn", "EOF mid-header")
    ftype, length, crc, flags = decode_header(first + rest, max_frame_bytes)
    payload = recv_exact_sock(sock, length, deadline=deadline)
    if len(payload) != length:
        raise FrameError("torn", "EOF mid-payload")
    return ftype, _frame_body(ftype, payload, crc), flags


# -------------------------------------------------------------- server
@dataclass(frozen=True)
class RpcConfig:
    """Network-plane knobs; all waits are finite by construction."""

    host: str = "127.0.0.1"
    port: int = 0                      # 0 = ephemeral (tests)
    max_frame_bytes: int = DEFAULT_MAX_FRAME
    hello_timeout_s: float = 5.0       # handshake must complete in this
    idle_tick_s: float = 0.5           # read-loop checkpoint cadence
    frame_timeout_s: float = 10.0      # slow-loris: whole frame after byte 0
    write_timeout_s: float = 30.0      # drain() cap per frame
    conn_credits: int = 1024           # per-connection row-budget ceiling
    drain_timeout_s: float = 30.0      # stop(): cap on finishing in-flight
    n_loops: int = 1                   # accept/IO event loops (threads);
    #                                    1 = serve on the service's loop,
    #                                    today's behavior exactly
    accept_backoff_s: float = 0.05     # EMFILE: initial jittered backoff
    accept_backoff_cap_s: float = 1.0  # EMFILE: backoff ceiling


#: Accept-loop errnos that mean fd/buffer exhaustion — shed + back off
#: with jitter; anything else transient counts as reason="error".
_FD_PRESSURE_ERRNOS = frozenset(
    getattr(errno, name) for name in
    ("EMFILE", "ENFILE", "ENOBUFS", "ENOMEM") if hasattr(errno, name))


class _LoopShard:
    """One serving event loop. Shard 0 runs on the loop ``start()`` was
    awaited on (the service's loop — so ``n_loops=1`` reproduces the
    single-loop server exactly); higher shards each run their own loop
    on a daemon thread and own their accepted connections end-to-end
    (read, decode, write)."""

    def __init__(self, index: int, loop, thread=None):
        self.index = index
        self.loop = loop
        self.thread = thread           # None for shard 0
        self.accept_task = None        # Task or concurrent Future
        self.listen_sock = None        # None for handoff-fed shards
        self.n_conns = 0               # guarded by server._conns_lock


class _Conn:
    """Per-connection state: credits, write lock, frame accounting.

    A connection is owned end-to-end by exactly ONE event loop
    (``self.loop``, the shard it was accepted onto); every write must
    run on that loop — ``send``/``send_raw`` assert it by bumping the
    server's ``ownership_violations`` counter on a mismatch.
    ``_egress`` / ``_drain_scheduled`` implement coalesced RESULT_BATCH
    egress and are touched only from the owning loop (no lock needed).
    """

    def __init__(self, server: "RpcServer", reader, writer, cid: int,
                 loop, shard_index: int = 0):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.cid = cid
        self.loop = loop
        self.shard_index = shard_index
        self.tms_id = "unknown"
        self.peer_version = 1          # from HELLO "v"; absent = v1
        self.credits = 0               # server-side view of client budget
        self.write_lock = asyncio.Lock()
        self.frames_started = 0        # writes begun (header bytes queued)
        self.frames_done = 0           # writes fully drained
        self.inflight: set[asyncio.Task] = set()
        self.goaway_sent = False
        self.closing = False
        self._egress: list = []        # queued verdict rows awaiting drain
        self._drain_scheduled = False  # one drain task (= wakeup) at a time

    def _check_owner(self) -> None:
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:           # not on any loop at all
            running = None
        if running is not self.loop:
            self.server.ownership_violations += 1

    async def _send_bytes(self, ftype: int, buf: bytes) -> None:
        cfg = self.server.config
        self._check_owner()
        async with self.write_lock:
            if self.closing:
                raise ConnectionResetError("connection closing")
            self.frames_started += 1
            self.writer.write(buf)
            await asyncio.wait_for(self.writer.drain(), cfg.write_timeout_s)
            self.frames_done += 1
        self.server._count_frame("sent", ftype)

    async def send(self, ftype: int, body: dict) -> None:
        await self._send_bytes(
            ftype, encode_frame(ftype, body, self.server.config.max_frame_bytes))

    async def send_raw(self, ftype: int, payload: bytes,
                       flags: int = 0) -> None:
        await self._send_bytes(
            ftype, encode_raw_frame(ftype, payload,
                                    self.server.config.max_frame_bytes, flags))


class RpcServer:
    """Streaming TCP front door over a running ``VerificationService``.

    Start the service first, then ``await server.start()`` on the
    service's loop. With ``n_loops=1`` everything runs on that loop —
    the single-loop server, unchanged. With ``n_loops>=2`` the server
    starts worker event loops (threads), each owning its accepted
    connections end-to-end; submits reach the shared service through a
    thread-safe handoff (one cross-loop completion per frame).
    ``stop()`` is a draining stop across every shard: GOAWAY to every
    connection on its owning loop, in-flight frames finish, no
    connection is closed mid-frame (``frames_clean`` asserts it).
    """

    def __init__(self, service, config: RpcConfig | None = None, *,
                 provider=None, tracer=None):
        self.service = service
        self.config = config or RpcConfig()
        self.provider = provider or _METRICS
        self.tracer = tracer or _TRACER
        _describe(self.provider)
        self._conns: dict[int, _Conn] = {}
        self._conns_lock = threading.Lock()
        self._next_cid = 0
        self._draining = False
        self._stopped = False
        self.midframe_closes = 0
        self.ownership_violations = 0  # writes attempted off-owner-loop
        self.address: tuple[str, int] | None = None
        self._shards: list[_LoopShard] = []
        self._service_loop = None
        self._handoff = False          # single acceptor feeding all shards
        self._rr = 0                   # handoff round-robin cursor

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> tuple[str, int]:
        cfg = self.config
        self._draining = False
        self._stopped = False
        loop = asyncio.get_running_loop()
        # submits must run where the service's queues/tasks live; the
        # service records its loop at start(), and start() here is
        # documented to run on that same loop (shard 0 reuses it)
        self._service_loop = getattr(self.service, "loop", None) or loop
        n = max(1, int(cfg.n_loops))
        self._shards = [_LoopShard(0, loop)]
        for i in range(1, n):
            shard_loop = asyncio.new_event_loop()
            thread = threading.Thread(
                target=shard_loop.run_forever,
                name=f"rpc-loop-{i}", daemon=True)
            thread.start()
            self._shards.append(_LoopShard(i, shard_loop, thread))
        socks = self._bind_sockets(n)
        if len(socks) == n:
            # one SO_REUSEPORT listen socket per shard: the kernel
            # load-balances accepts, no cross-loop handoff at all
            for shard, lsock in zip(self._shards, socks):
                shard.listen_sock = lsock
                shard.accept_task = self._spawn_accept(shard, lsock)
        else:
            # SO_REUSEPORT unavailable: shard 0 accepts on the single
            # socket and hands sockets to shards round-robin
            self._handoff = n > 1
            self._shards[0].listen_sock = socks[0]
            self._shards[0].accept_task = self._spawn_accept(
                self._shards[0], socks[0])
        sockname = socks[0].getsockname()
        self.address = (sockname[0], sockname[1])
        self._pretouch_metrics()
        JOURNAL.record("rpc_listen", addr=f"{sockname[0]}:{sockname[1]}",
                       loops=n, handoff=self._handoff)
        return self.address

    def _bind_sockets(self, n: int) -> list:
        """Bind the listen socket(s): ``n`` SO_REUSEPORT sockets on one
        port when the platform allows it, else one plain socket (the
        caller falls back to handoff accepts)."""
        cfg = self.config

        def mk(port: int, reuse_port: bool):
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                if reuse_port:
                    sock.setsockopt(
                        socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
                sock.bind((cfg.host, port))
                sock.listen(4096)
                sock.setblocking(False)
            except OSError:
                sock.close()
                raise
            return sock

        if n <= 1 or not hasattr(socket, "SO_REUSEPORT"):
            return [mk(cfg.port, False)]
        try:
            first = mk(cfg.port, True)
        except OSError:
            return [mk(cfg.port, False)]
        port = first.getsockname()[1]
        socks = [first]
        try:
            for _ in range(1, n):
                socks.append(mk(port, True))
        except OSError:
            for sock in socks[1:]:
                sock.close()
            return [first]
        return socks

    def _spawn_accept(self, shard: _LoopShard, lsock):
        """Start the accept loop as a Task on the shard's own loop (so
        stop() can cancel it there and await its unwind)."""
        if shard.loop is asyncio.get_running_loop():
            return asyncio.ensure_future(self._accept_loop(shard, lsock))

        async def _mk():
            return asyncio.ensure_future(self._accept_loop(shard, lsock))

        # brief block: one call_soon round-trip on a just-started loop
        return asyncio.run_coroutine_threadsafe(
            _mk(), shard.loop).result(5.0)

    def _pretouch_metrics(self) -> None:
        """Instantiate the C10k families at zero so ``prometheus_text``
        exports them (with HELP) before the first event."""
        self.provider.gauge("rpc_loops").set(len(self._shards))
        for shard in self._shards:
            self.provider.gauge("rpc_conns", loop=str(shard.index)).set(0)
        self.provider.counter("rpc_wakeups_total").add(0)
        for reason in ("emfile", "error"):
            self.provider.counter(
                "rpc_accept_shed_total", reason=reason).add(0)
        for fam in ("rpc_result_batch_frames_total",
                    "rpc_result_batch_rows_total",
                    "rpc_result_batch_bytes_total"):
            self.provider.counter(fam, role="server").add(0)

    async def stop(self, drain: bool = True) -> None:
        """Draining stop across every loop shard. Idempotent — a second
        stop (e.g. a supervisor racing a test harness teardown) must not
        trip over already-closed shard loops."""
        if self._stopped:
            return
        self._stopped = True
        self._draining = True
        here = asyncio.get_running_loop()

        async def _reap(task):
            try:
                await task
            except asyncio.CancelledError:
                pass

        reaps = []
        for shard in self._shards:
            task, shard.accept_task = shard.accept_task, None
            if task is None:
                continue
            if shard.loop is here:
                task.cancel()
                reaps.append(asyncio.ensure_future(_reap(task)))
            else:
                shard.loop.call_soon_threadsafe(task.cancel)
                reaps.append(asyncio.wrap_future(
                    asyncio.run_coroutine_threadsafe(
                        _reap(task), shard.loop)))
        if reaps:
            await asyncio.wait(reaps, timeout=5.0)
        for shard in self._shards:
            if shard.listen_sock is not None:
                try:
                    shard.listen_sock.close()
                except OSError:
                    pass
                shard.listen_sock = None
        with self._conns_lock:
            conns = list(self._conns.values())
        here = asyncio.get_running_loop()
        waits = []
        for conn in conns:
            if conn.loop is here:
                waits.append(asyncio.ensure_future(
                    self._finish_conn(conn, drain)))
            else:
                waits.append(asyncio.wrap_future(
                    asyncio.run_coroutine_threadsafe(
                        self._finish_conn(conn, drain), conn.loop)))
        if waits:
            await asyncio.wait(
                waits, timeout=self.config.drain_timeout_s + 10.0)
        for shard in self._shards:
            if shard.thread is None:
                continue
            shard.loop.call_soon_threadsafe(shard.loop.stop)
            shard.thread.join(5.0)
            if not shard.thread.is_alive():
                shard.loop.close()

    async def _finish_conn(self, conn: _Conn, drain: bool) -> None:
        """Drain one connection — runs on the connection's owning loop."""
        if not conn.goaway_sent and not conn.closing:
            conn.goaway_sent = True
            try:
                await conn.send(GOAWAY, {"reason": "draining"})
                self.provider.counter(
                    "rpc_goaways_total", role="server").add()
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass
        if drain:
            await self._await_inflight(conn, self.config.drain_timeout_s)
        await self._close_conn(conn)

    @staticmethod
    async def _await_inflight(conn: _Conn, timeout_s: float) -> None:
        """Wait until the connection's inflight set drains, re-snapshotting
        as completing tasks spawn follow-on work — a finishing SUBMIT
        queues egress rows and schedules a coalesced drain task, so a
        one-shot wait on a stale snapshot would close the connection
        while that drain task is mid-write."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while conn.inflight:
            remaining = deadline - loop.time()
            if remaining <= 0:
                return
            await asyncio.wait(list(conn.inflight), timeout=remaining)

    @property
    def frames_clean(self) -> bool:
        """True iff no connection was ever closed mid-write."""
        return self.midframe_closes == 0

    def status(self) -> dict:
        """``/statusz`` payload: connections, credits, accounting."""
        with self._conns_lock:
            conns = list(self._conns.values())
            loops = {
                str(s.index): {
                    "conns": s.n_conns,
                    "alive": (s.thread.is_alive()
                              if s.thread is not None else True),
                    "accepting": s.listen_sock is not None,
                }
                for s in self._shards
            }
        return {
            "address": list(self.address) if self.address else None,
            "draining": self._draining,
            "loops": loops,
            "handoff": self._handoff,
            "ownership_violations": self.ownership_violations,
            "connections": {
                str(c.cid): {
                    "tms_id": c.tms_id,
                    "loop": c.shard_index,
                    "v": c.peer_version,
                    "credits": c.credits,
                    "inflight": len(c.inflight),
                    "frames_started": c.frames_started,
                    "frames_done": c.frames_done,
                }
                for c in conns
            },
            "midframe_closes": self.midframe_closes,
        }

    # ------------------------------------------------------------- accept
    async def _accept(self, loop, lsock):
        """Accept one connection (seam for fd-exhaustion fault tests)."""
        return await loop.sock_accept(lsock)  # io-deadline: cancelled by stop()

    async def _accept_loop(self, shard: _LoopShard, lsock) -> None:
        """Accept until cancelled. fd exhaustion (EMFILE and friends)
        backs off with jitter and counts a shed instead of spinning the
        acceptor hot or tearing it down."""
        cfg = self.config
        backoff = cfg.accept_backoff_s
        while not self._draining:
            try:
                sock, _addr = await self._accept(shard.loop, lsock)
            except asyncio.CancelledError:
                raise
            except OSError as exc:
                if self._draining:
                    return
                reason = ("emfile" if exc.errno in _FD_PRESSURE_ERRNOS
                          else "error")
                self.provider.counter(
                    "rpc_accept_shed_total", reason=reason).add()
                JOURNAL.record("rpc_accept_shed", reason=reason,
                               loop=shard.index, detail=str(exc))
                await asyncio.sleep(
                    random.uniform(backoff / 2, backoff))
                backoff = min(backoff * 2, cfg.accept_backoff_cap_s)
                continue
            backoff = cfg.accept_backoff_s
            target = self._pick_shard(shard)
            if target.loop is shard.loop:
                asyncio.ensure_future(self._adopt(target, sock))
            else:
                asyncio.run_coroutine_threadsafe(
                    self._adopt(target, sock), target.loop)

    def _pick_shard(self, shard: _LoopShard) -> _LoopShard:
        """Owning shard for a just-accepted socket: the accepting shard
        itself (SO_REUSEPORT mode) or round-robin (handoff mode)."""
        if not self._handoff or len(self._shards) == 1:
            return shard
        self._rr += 1
        return self._shards[self._rr % len(self._shards)]

    async def _adopt(self, shard: _LoopShard, sock) -> None:
        """Wrap an accepted socket into streams on the owning shard's
        loop and serve it there end-to-end."""
        try:
            reader, writer = await asyncio.open_connection(sock=sock)
        except OSError:
            try:
                sock.close()
            except OSError:
                pass
            return
        await self._handle(reader, writer, shard)

    # ------------------------------------------------------------- metrics
    def _count_frame(self, direction: str, ftype: int) -> None:
        self.provider.counter(
            "rpc_frames_total", role="server", dir=direction,
            type=FRAME_NAMES.get(ftype, str(ftype))).add()

    def _frame_error(self, kind: str) -> None:
        self.provider.counter("rpc_frame_errors_total", kind=kind).add()

    # ------------------------------------------------------------- credits
    def _credit_target(self) -> int:
        """Row budget a connection may hold: admission headroom, capped.

        Headroom follows the deepest lane so credits shrink as queues
        fill — the client stalls on credits instead of stuffing the
        socket buffer with work the admission controller would shed.
        """
        svc = self.service
        deepest = max(
            (svc.scheduler.lane_depth(lane) for lane in LANES), default=0)
        headroom = svc.config.queue_capacity - deepest
        return max(0, min(self.config.conn_credits, headroom))

    async def _replenish(self, conn: _Conn) -> None:
        grant = self._credit_target() - conn.credits
        if grant <= 0 or conn.closing or conn.goaway_sent:
            return
        conn.credits += grant
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        try:
            await conn.send(CREDIT, {"grant": grant})
        except (ConnectionError, OSError, asyncio.TimeoutError):
            conn.credits -= grant

    # ---------------------------------------------------------- connection
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter,
                      shard: _LoopShard | None = None) -> None:
        cfg = self.config
        if shard is None:
            shard = self._shards[0] if self._shards else _LoopShard(
                0, asyncio.get_running_loop())
        with self._conns_lock:
            cid = self._next_cid
            self._next_cid += 1
        conn = _Conn(self, reader, writer, cid, shard.loop, shard.index)
        try:
            frame = await read_frame(
                reader, max_frame_bytes=cfg.max_frame_bytes,
                header_timeout_s=cfg.hello_timeout_s,
                body_timeout_s=cfg.hello_timeout_s)
        except (FrameError, asyncio.TimeoutError, ConnectionError,
                OSError) as exc:
            kind = exc.kind if isinstance(exc, FrameError) else "torn"
            self._frame_error(kind)
            await self._close_conn(conn)
            return
        if frame is None or frame[0] != HELLO:
            self._frame_error("protocol")
            await self._close_conn(conn)
            return
        hello = frame[1]
        conn.tms_id = str(hello.get("tms_id", "default"))
        try:
            conn.peer_version = int(hello.get("v", 1))
        except (TypeError, ValueError):
            conn.peer_version = 1
        conn.credits = self._credit_target()
        with self._conns_lock:
            self._conns[cid] = conn
            shard.n_conns += 1
            n_active = len(self._conns)
        self.provider.counter("rpc_connections_total",
                              tms=conn.tms_id).add()
        self.provider.gauge("rpc_connections_active").set(n_active)
        self.provider.gauge(
            "rpc_conns", loop=str(shard.index)).set(shard.n_conns)
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        self._count_frame("recv", HELLO)
        try:
            await conn.send(WELCOME, {
                "t": hello.get("t", 0.0),
                "t_srv": time.time(),
                "credits": conn.credits,
                "max_frame": cfg.max_frame_bytes,
                # version negotiation: v2 peers may send columnar
                # SUBMIT_BATCH frames, v3 peers may attach trace
                # context, v4 peers receive columnar RESULT_BATCH
                # egress; older clients ignore the extra keys and keep
                # speaking their protocol unchanged
                "v": RPC_VERSION,
                "batch": True,
                "trace": True,
            })
            if self._draining and not conn.goaway_sent:
                conn.goaway_sent = True
                await conn.send(GOAWAY, {"reason": "draining"})
                self.provider.counter(
                    "rpc_goaways_total", role="server").add()
            await self._read_loop(conn)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
        finally:
            await self._await_inflight(conn, cfg.drain_timeout_s)
            await self._close_conn(conn)
            with self._conns_lock:
                if self._conns.pop(cid, None) is not None:
                    shard.n_conns -= 1
                n_active = len(self._conns)
            self.provider.gauge("rpc_connections_active").set(n_active)
            self.provider.gauge(
                "rpc_conns", loop=str(shard.index)).set(shard.n_conns)

    async def _read_loop(self, conn: _Conn) -> None:
        cfg = self.config
        while not conn.closing:
            try:
                frame = await read_frame(
                    conn.reader, max_frame_bytes=cfg.max_frame_bytes,
                    header_timeout_s=cfg.idle_tick_s,
                    body_timeout_s=cfg.frame_timeout_s)
            except asyncio.TimeoutError:
                # idle checkpoint: leave once draining and quiesced
                if self._draining and not conn.inflight:
                    return
                continue
            except FrameError as exc:
                # A poisoned stream: count it, drop THIS connection, and
                # keep the accept loop alive — one bad peer never takes
                # the server down.
                self._frame_error(exc.kind)
                JOURNAL.record("rpc_frame_error", kind=exc.kind,
                               tms_id=conn.tms_id, detail=str(exc))
                return
            if frame is None:
                return  # client closed cleanly
            ftype, body, flags = frame
            self._count_frame("recv", ftype)
            if ftype == PING:
                await conn.send(PONG, {"t": body.get("t", 0.0),
                                       "t_srv": time.time()})
            elif ftype == GOAWAY:
                conn.goaway_sent = True  # client-initiated drain
            elif ftype == SUBMIT:
                self._accept_submit(conn, body)
            elif ftype == SUBMIT_BATCH:
                # trace context rides as a flagged 17-byte prefix on the
                # raw payload (a poisoned prefix is counted + ignored)
                ctx, body = split_trace_prefix(body, flags, self.provider)
                try:
                    batch = self._decode_batch(conn, body)
                except FrameError as exc:
                    # same contract as a poisoned pickled frame: count,
                    # journal, drop THIS connection, server stays up
                    self._frame_error(exc.kind)
                    JOURNAL.record("rpc_frame_error", kind=exc.kind,
                                   tms_id=conn.tms_id, detail=str(exc))
                    return
                self._accept_submit_batch(conn, batch, ctx)
            else:
                self._frame_error("protocol")

    def _decode_batch(self, conn: _Conn, payload: bytes):
        """Raw columnar payload -> numpy-view batch, timed + counted.

        Decode allocates O(1): every column is a view over the frame
        buffer. Malformed payloads surface as ``FrameError`` with the
        codec's kind (``row_count`` / ``decode``)."""
        t0 = time.perf_counter()
        try:
            batch = decode_submit_batch(payload)
        except ColumnarError as exc:
            raise FrameError(exc.kind, str(exc)) from exc
        self.provider.histogram(
            "rpc_decode_seconds",
            fmt="columnar").observe(time.perf_counter() - t0)
        self.provider.counter("rpc_batch_frames_total", role="server",
                              tms=conn.tms_id).add()
        self.provider.counter("rpc_batch_rows_total", role="server",
                              tms=conn.tms_id).add(batch.n_rows)
        self.provider.counter("rpc_batch_bytes_total", role="server",
                              tms=conn.tms_id).add(batch.nbytes)
        return batch

    def _accept_submit_batch(self, conn: _Conn, batch, ctx=None) -> None:
        """Credit accounting in rows — one columnar frame spends exactly
        what its row count would cost as N legacy SUBMITs, so the
        backpressure semantics are unchanged."""
        rows = batch.n_rows
        if rows > conn.credits:
            self._frame_error("credit_violation")
        conn.credits = max(0, conn.credits - rows)
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        task = asyncio.ensure_future(
            self._serve_submit_batch(conn, batch, ctx))
        conn.inflight.add(task)
        task.add_done_callback(conn.inflight.discard)

    # ------------------------------------------------- service handoff
    async def _service_call(self, coro):
        """Await a service-submit coroutine on the service's loop.

        On the service loop (n_loops=1, or shard 0) this is a plain
        await; from a worker shard it is ONE thread-safe cross-loop
        round trip — the per-row fan-out happens inside the service
        loop, so a whole frame costs one handoff, not one per row.
        """
        try:
            here = asyncio.get_running_loop()
        except RuntimeError:
            here = None
        if self._service_loop is None or here is self._service_loop:
            return await coro
        return await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
            coro, self._service_loop))

    async def _gather(self, coros):
        """Gather service-submit coroutines via one cross-loop handoff."""

        async def _run():
            return await asyncio.gather(*coros)

        return await self._service_call(_run())

    # ---------------------------------------------------- coalesced egress
    def _batch_eligible(self, conn: _Conn, req_id) -> bool:
        """Columnar RESULT_BATCH egress applies to v4+ peers and u64
        req_ids; everything else keeps the legacy pickled RESULT."""
        return (conn.peer_version >= 4 and not conn.closing
                and isinstance(req_id, int) and 0 <= req_id < (1 << 64))

    @staticmethod
    def _result_rows(req_id: int, results, ctx) -> list:
        """Per-row egress tuples from a request's VerifyResults."""
        tc = ctx.to_bytes() if ctx is not None else None
        return [(req_id, i, r.status, r.accepted, r.served_by or "", tc)
                for i, r in enumerate(results)]

    def _queue_result_rows(self, conn: _Conn, rows) -> None:
        """Queue verdict rows for coalesced egress — runs on the
        connection's owning loop. At most ONE drain task (= one wakeup)
        is scheduled per cycle; completions landing while a drain is
        pending ride the same wakeup (``rpc_wakeups_total`` counts
        cycles, where a doorbell-per-result design would count rows).
        """
        conn._egress.extend(rows)
        if conn._drain_scheduled or conn.closing or not conn._egress:
            return
        conn._drain_scheduled = True
        self.provider.counter("rpc_wakeups_total").add()
        task = asyncio.ensure_future(self._drain_egress(conn))
        conn.inflight.add(task)
        task.add_done_callback(conn.inflight.discard)

    async def _drain_egress(self, conn: _Conn) -> None:
        """Flush queued verdict rows as columnar RESULT_BATCH frames:
        one frame + one credit replenish per drain cycle, zero per-row
        pickling, pooled encode scratch."""
        try:
            while conn._egress and not conn.closing:
                rows, conn._egress = conn._egress, []
                try:
                    payload, _traced = encode_result_batch(
                        rows, pool=_SCRATCH)
                except ColumnarError:
                    # pathological string vocabulary (>=256 unique
                    # status/served_by strings in one cycle): fall back
                    # to legacy per-request RESULT frames, stay correct
                    for reply in self._legacy_replies(rows):
                        await conn.send(RESULT, reply)
                else:
                    await conn.send_raw(RESULT_BATCH, payload)
                    self.provider.counter("rpc_result_batch_frames_total",
                                          role="server").add()
                    self.provider.counter("rpc_result_batch_rows_total",
                                          role="server").add(len(rows))
                    self.provider.counter("rpc_result_batch_bytes_total",
                                          role="server").add(len(payload))
                await self._replenish(conn)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            conn._egress.clear()  # peer gone; its redial will resubmit
        finally:
            conn._drain_scheduled = False

    @staticmethod
    def _legacy_replies(rows):
        """Regroup egress tuples into per-request legacy RESULT bodies
        (the encode-fallback path). Rows are queued whole-request, so
        grouping by req_id reconstructs each complete reply."""
        grouped: dict = {}
        for req_id, row_idx, status, verdict, served, tc in rows:
            grouped.setdefault(req_id, []).append(
                (row_idx, status, verdict, served, tc))
        for req_id, rws in grouped.items():
            rws.sort(key=lambda r: r[0])
            reply = {"req_id": req_id, "status": RPC_OK,
                     "statuses": [r[1] for r in rws],
                     "verdicts": [r[2] for r in rws],
                     "served_by": sorted({r[3] for r in rws if r[3]})}
            tc = next((r[4] for r in rws if r[4]), None)
            if tc is not None:
                reply["tc"] = tc
            yield reply

    # ------------------------------------------------------------- serving
    async def _serve_submit_batch(self, conn: _Conn, batch,
                                  ctx=None) -> None:
        reply: dict = {"req_id": batch.req_id_base, "status": RPC_OK}
        if ctx is not None:
            reply["tc"] = ctx.to_bytes()  # echo for client correlation
        deadline_s = batch.deadline - time.time()
        if deadline_s <= 0:
            self.provider.counter("rpc_deadline_expired_total").add()
            reply["status"] = RPC_EXPIRED
            reply["error"] = (
                f"deadline passed {-deadline_s * 1000:.1f}ms before decode")
        elif self._draining or conn.goaway_sent:
            reply["status"] = RPC_GOAWAY
            reply["error"] = "server draining"
        if reply["status"] == RPC_OK:
            # ONE rpc_requests_total bump per frame — the whole point
            self.provider.counter("rpc_requests_total", tms=conn.tms_id,
                                  kind="range", lane=batch.lane).add()
            try:
                with self.tracer.span("rpc.serve_batch", rows=batch.n_rows,
                                      fmt=batch.fmt_name, lane=batch.lane,
                                      remote_parent=ctx) as ssp:
                    proofs, coms = materialize_rows(batch)
                    offs = batch.deadline_offsets_s
                    results = await self._service_call(
                        self.service.submit_batch(
                            "range", list(zip(proofs, coms)),
                            deadline_s=deadline_s,
                            deadline_offsets_s=offs if offs.any() else None,
                            lane=batch.lane, tenant=conn.tms_id,
                            trace_ctx=ssp.context() if ctx is not None
                            else None))
                if self._batch_eligible(conn, batch.req_id_base):
                    # columnar egress: verdict rows coalesce with any
                    # other completions on this connection; the drain
                    # cycle replenishes credits
                    self._queue_result_rows(conn, self._result_rows(
                        batch.req_id_base, results, ctx))
                    return
                reply["statuses"] = [r.status for r in results]
                reply["verdicts"] = [r.accepted for r in results]
                reply["served_by"] = sorted(
                    {r.served_by for r in results if r.served_by})
            except Exception as exc:  # service-level failure -> typed error
                reply["status"] = RPC_ERROR
                reply["error"] = str(exc)
                reply["error_type"] = type(exc).__name__
        try:
            await conn.send(RESULT, reply)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return  # peer gone; its redial will resubmit
        await self._replenish(conn)

    def _accept_submit(self, conn: _Conn, body: dict) -> None:
        rows = int(body.get("rows", 1))
        if rows > conn.credits:
            self._frame_error("credit_violation")
        conn.credits = max(0, conn.credits - rows)
        self.provider.gauge("rpc_credits", tms=conn.tms_id).set(conn.credits)
        task = asyncio.ensure_future(self._serve_submit(conn, body))
        conn.inflight.add(task)
        task.add_done_callback(conn.inflight.discard)

    async def _serve_submit(self, conn: _Conn, body: dict) -> None:
        req_id = body.get("req_id")
        kind = body.get("kind", "range")
        lane = body.get("lane", LANE_BULK)
        tms_id = str(body.get("tms_id", conn.tms_id))
        # caller's trace context, if any: v1/v2 peers never send "tc"
        # (counted as reason=missing), v3 peers send 17 context bytes;
        # a poisoned value is counted + ignored — never a frame error
        ctx = extract_wire_context(body.get("tc"), self.provider)
        reply: dict = {"req_id": req_id, "status": RPC_OK}
        if ctx is not None:
            reply["tc"] = ctx.to_bytes()  # echo for client correlation
        deadline = body.get("deadline")
        deadline_s = None
        if deadline is not None:
            deadline_s = float(deadline) - time.time()
            if deadline_s <= 0:
                self.provider.counter("rpc_deadline_expired_total").add()
                reply["status"] = RPC_EXPIRED
                reply["error"] = (
                    f"deadline passed {-deadline_s * 1000:.1f}ms before "
                    "decode")
        if reply["status"] == RPC_OK and (self._draining or conn.goaway_sent):
            reply["status"] = RPC_GOAWAY
            reply["error"] = "server draining"
        if reply["status"] == RPC_OK:
            self.provider.counter("rpc_requests_total", tms=tms_id,
                                  kind=kind, lane=lane).add()
            try:
                queued = await self._verify_into(
                    reply, kind, lane, deadline_s, body,
                    tenant=tms_id, ctx=ctx, conn=conn)
                if queued:
                    return  # verdicts ride RESULT_BATCH; drain replenishes
            except Exception as exc:  # service-level failure -> typed error
                reply["status"] = RPC_ERROR
                reply["error"] = str(exc)
                reply["error_type"] = type(exc).__name__
        try:
            await conn.send(RESULT, reply)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return  # peer gone; its redial will resubmit
        await self._replenish(conn)

    async def _verify_into(self, reply: dict, kind: str, lane: str,
                           deadline_s: float | None, body: dict,
                           tenant: str = "default", ctx=None,
                           conn: _Conn | None = None) -> bool:
        """Run the verdicts for one SUBMIT into ``reply``; returns True
        when the rows were queued for columnar RESULT_BATCH egress
        instead (flat range verdicts on a v4+ peer — block replies keep
        their nested tuple shape and stay pickled for every peer)."""
        svc = self.service
        with self.tracer.span("rpc.serve", kind=kind, lane=lane,
                              remote_parent=ctx) as ssp:
            tc = ssp.context() if ctx is not None else None
            if kind == "range":
                proofs, coms = body["payload"]
                results = await self._gather([
                    svc.submit_range(p, c, deadline_s=deadline_s, lane=lane,
                                     tenant=tenant, trace_ctx=tc)
                    for p, c in zip(proofs, coms)])
                req_id = reply.get("req_id")
                if conn is not None and self._batch_eligible(conn, req_id):
                    self._queue_result_rows(conn, self._result_rows(
                        req_id, results, ctx))
                    return True
                reply["statuses"] = [r.status for r in results]
                reply["verdicts"] = [r.accepted for r in results]
                reply["served_by"] = sorted(
                    {r.served_by for r in results if r.served_by})
            elif kind == "block":

                async def _run_block(transfers, issues):
                    return await asyncio.gather(
                        asyncio.gather(*[
                            svc.submit_transfer(
                                pr, ins, outs, deadline_s=deadline_s,
                                lane=lane, tenant=tenant, trace_ctx=tc)
                            for pr, ins, outs in transfers]),
                        asyncio.gather(*[
                            svc.submit_issue(
                                pr, outs, deadline_s=deadline_s, lane=lane,
                                tenant=tenant, trace_ctx=tc)
                            for pr, outs in issues]))

                transfers, issues = body["payload"]
                t_res, i_res = await self._service_call(
                    _run_block(transfers, issues))
                reply["statuses"] = ([r.status for r in t_res],
                                     [r.status for r in i_res])
                reply["verdicts"] = ([r.accepted for r in t_res],
                                     [r.accepted for r in i_res])
                reply["served_by"] = sorted(
                    {r.served_by for r in (*t_res, *i_res) if r.served_by})
            else:
                raise ValueError(f"unknown submit kind {kind!r}")
        return False

    async def _close_conn(self, conn: _Conn) -> None:
        if conn.closing:
            return
        conn.closing = True
        # A write may still be suspended between header and drain; give
        # it its own timeout to finish before scoring the accounting.
        # ``closing`` above already fences off new writes.
        try:
            await asyncio.wait_for(conn.write_lock.acquire(),
                                   self.config.write_timeout_s)
            conn.write_lock.release()
        except asyncio.TimeoutError:
            pass
        if conn.frames_started != conn.frames_done:
            # a write was abandoned between header and drain — the one
            # invariant the draining stop exists to prevent
            with self._conns_lock:
                self.midframe_closes += 1
            self._frame_error("midframe_close")
        try:
            conn.writer.close()
            await asyncio.wait_for(conn.writer.wait_closed(), 5.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
