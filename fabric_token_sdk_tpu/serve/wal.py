"""Durable write-ahead log for the verification service.

A SIGKILL'd dispatcher today loses every admitted-but-unanswered
request: the future dies with the process and nobody ever learns a
verdict. This module closes that hole with the classic WAL contract —
*log the intent before acting on it*:

  - ``append_admit`` records every admitted request (kind, lane,
    deadline, full payload) as one flushed JSON line **before** it
    enters the scheduler; ``append_admit_batch`` is the columnar
    front-door counterpart — ONE record covers every row of an
    admitted SUBMIT_BATCH frame, so frame ingest costs one WAL append
    instead of N (the perf contract ``perf_profile.py --mode ingest``
    asserts);
  - ``append_resolve`` records the terminal verdict (status, accepted,
    served_by) when the request completes — exactly once, enforced
    here (a duplicate resolve is counted and dropped, never
    re-written);
  - ``recover()`` scans the segments after a restart and returns the
    admitted-but-unresolved entries so a fresh
    :class:`~fabric_token_sdk_tpu.serve.service.VerificationService`
    can replay them through the normal dispatch path — same batch
    assembly, same device call, bit-identical verdicts.

Durability model: every record is one JSON object with a CRC32 over its
canonical serialization, written with ``write + flush`` (optionally
``fsync``) so a kill loses at most the final, partially-written line.
Recovery tolerates exactly that: a torn tail — or any line whose
checksum disagrees — is skipped and counted (``wal_torn_records_total``)
while every complete prior record is recovered.

Segments rotate at a record/byte budget (``wal-<seq>.jsonl``) so one
long-lived service never grows a single unbounded file, and recovery
*compacts*: the surviving incomplete entries are rewritten into a fresh
segment and the old segments are deleted, so restart cost is
proportional to the live set, not to history.

Stable families: ``wal_appends_total{record}``,
``wal_bytes_written_total``, ``wal_segments_total``,
``wal_torn_records_total``, ``wal_replayed_total``,
``wal_recovery_seconds``, ``wal_compactions_total``,
``wal_open_requests``.
"""

from __future__ import annotations

import base64
import json
import os
import pickle
import time
import zlib
from dataclasses import dataclass

from ..obs import GLOBAL as _METRICS
from ..obs.journal import EVENT_WAL_RECOVERED, JOURNAL

_WAL_FAMILIES = {
    "wal_appends_total":
        "WAL records appended, by record type (admit / admit_batch / "
        "resolve / resolve_duplicate — duplicates are dropped, not "
        "written).",
    "wal_bytes_written_total":
        "Bytes appended to WAL segment files, records plus newlines.",
    "wal_segments_total":
        "WAL segment files created (initial, rotation, compaction).",
    "wal_torn_records_total":
        "Records skipped during recovery: torn tail or checksum mismatch.",
    "wal_replayed_total":
        "Recovered admitted-but-unresolved requests replayed through "
        "dispatch.",
    "wal_recovery_seconds":
        "Wall seconds spent scanning and compacting segments at recovery.",
    "wal_compactions_total":
        "Recovery compactions: incomplete entries rewritten into a fresh "
        "segment, prior segments deleted.",
    "wal_open_requests":
        "Admitted requests with no terminal verdict recorded yet.",
}

#: Record types (the ``t`` field of every JSON line).
RECORD_ADMIT = "admit"
RECORD_RESOLVE = "resolve"
#: One columnar frame admitted as a single record: ``payload`` pickles
#: the TUPLE of row payloads and ``rows`` carries its length, so the
#: durability cost of a 256-row frame is one line, not 256. Resolution
#: is still one RECORD_RESOLVE per batch id (the service counts rows
#: down and resolves once the last row terminates).
RECORD_ADMIT_BATCH = "admit_batch"

_SEGMENT_PREFIX = "wal-"
_SEGMENT_SUFFIX = ".jsonl"


@dataclass(frozen=True)
class WalConfig:
    """Rotation and durability knobs.

    ``fsync=False`` keeps the default at flush-per-record (survives the
    process dying); ``fsync=True`` additionally survives the host dying
    at a per-record syscall cost.
    """

    segment_max_records: int = 4096
    segment_max_bytes: int = 8 << 20
    fsync: bool = False


@dataclass
class WalEntry:
    """One recovered admitted-but-unresolved request (or frame).

    ``record == RECORD_ADMIT_BATCH`` marks a columnar frame: ``payload``
    is the tuple of row payloads and ``rows`` its length; the replayer
    expands it back into per-row requests under the shared wal_id.
    """

    wal_id: int
    kind: str
    lane: str
    deadline_s: float
    payload: tuple
    rows: int = 1
    record: str = RECORD_ADMIT


def _encode_payload(payload) -> str:
    """Payload -> JSON-embeddable string. Pickle round-trips the proof/
    commitment objects byte-exactly, which is what the bit-identical
    replay contract needs; base64 keeps the JSON line printable."""
    return base64.b64encode(
        pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)).decode()


def _decode_payload(data: str) -> tuple:
    return pickle.loads(base64.b64decode(data.encode()))


def _checksum(record: dict) -> int:
    """CRC32 over the canonical serialization minus the ``crc`` field."""
    body = {k: v for k, v in record.items() if k != "crc"}
    return zlib.crc32(
        json.dumps(body, sort_keys=True, separators=(",", ":")).encode())


class WriteAheadLog:
    """Checksummed JSONL segments under one directory.

    Lifecycle: construct over a directory, call :meth:`recover` (the
    service does this in ``start()``), then :meth:`append_admit` /
    :meth:`append_resolve` per request. ``recover`` is idempotent —
    the second call returns ``[]`` — and MUST run before the first
    append so compaction never rewrites a segment the writer already
    appended to; appends enforce this by recovering implicitly (the
    entries stay available on :attr:`recovered_entries`).
    """

    def __init__(self, directory: str | os.PathLike,
                 config: WalConfig | None = None, provider=None):
        self.directory = os.fspath(directory)
        self.config = config or WalConfig()
        self.provider = provider or _METRICS
        os.makedirs(self.directory, exist_ok=True)
        for fam, help_text in _WAL_FAMILIES.items():
            self.provider.describe(fam, help_text)
        self._recovered = False
        self.recovered_entries: list[WalEntry] = []
        self.torn_records = 0
        self._next_id = 1
        self._open_ids: set[int] = set()
        self._file = None
        self._segment_seq = 0
        self._segment_records = 0
        self._segment_bytes = 0

    # ------------------------------------------------------------ segments
    def _segment_paths(self) -> list[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith(_SEGMENT_PREFIX)
                and n.endswith(_SEGMENT_SUFFIX))
        except OSError:
            names = []
        return [os.path.join(self.directory, n) for n in names]

    def _open_segment(self) -> None:
        if self._file is not None:
            self._file.close()
        self._segment_seq += 1
        path = os.path.join(
            self.directory,
            f"{_SEGMENT_PREFIX}{self._segment_seq:06d}{_SEGMENT_SUFFIX}")
        self._file = open(path, "a")
        self._segment_records = 0
        self._segment_bytes = 0
        self.provider.counter("wal_segments_total").add()

    # ------------------------------------------------------------- writing
    def _append(self, record: dict) -> None:
        record["crc"] = _checksum(record)
        line = json.dumps(record, sort_keys=True,
                          separators=(",", ":")) + "\n"
        if self._file is None \
                or self._segment_records >= self.config.segment_max_records \
                or self._segment_bytes >= self.config.segment_max_bytes:
            self._open_segment()
        self._file.write(line)
        self._file.flush()
        if self.config.fsync:
            os.fsync(self._file.fileno())
        self._segment_records += 1
        self._segment_bytes += len(line)
        self.provider.counter("wal_appends_total",
                              record=record["t"]).add()
        self.provider.counter("wal_bytes_written_total").add(len(line))

    def append_admit(self, kind: str, lane: str, deadline_s: float,
                     payload: tuple) -> int:
        """Log one admitted request; returns its WAL id."""
        if not self._recovered:
            self.recover()
        wal_id = self._next_id
        self._next_id += 1
        self._append({"t": RECORD_ADMIT, "id": wal_id, "kind": kind,
                      "lane": lane, "deadline_s": round(deadline_s, 6),
                      "ts": round(time.time(), 6),
                      "payload": _encode_payload(payload)})
        self._open_ids.add(wal_id)
        self._gauge_open()
        return wal_id

    def append_admit_batch(self, kind: str, lane: str, deadline_s: float,
                           payloads: list | tuple) -> int:
        """Log one admitted columnar frame as ONE flushed record.

        ``payloads`` is the frame's row payloads in row order; the whole
        tuple pickles into a single ``payload`` field so a 256-row frame
        costs one append (+ one resolve when the last row terminates)
        instead of 512 records. Returns the shared WAL id.
        """
        if not self._recovered:
            self.recover()
        wal_id = self._next_id
        self._next_id += 1
        self._append({"t": RECORD_ADMIT_BATCH, "id": wal_id, "kind": kind,
                      "lane": lane, "deadline_s": round(deadline_s, 6),
                      "rows": len(payloads), "ts": round(time.time(), 6),
                      "payload": _encode_payload(tuple(payloads))})
        self._open_ids.add(wal_id)
        self._gauge_open()
        return wal_id

    def append_resolve(self, wal_id: int, status: str,
                       accepted: bool | None = None,
                       served_by: str = "") -> bool:
        """Log one terminal verdict; returns False (and writes nothing)
        when ``wal_id`` already has one — the exactly-once guard."""
        if not self._recovered:
            self.recover()
        if wal_id not in self._open_ids:
            self.provider.counter("wal_appends_total",
                                  record="resolve_duplicate").add()
            return False
        self._open_ids.discard(wal_id)
        self._append({"t": RECORD_RESOLVE, "id": wal_id, "status": status,
                      "accepted": accepted, "served_by": served_by,
                      "ts": round(time.time(), 6)})
        self._gauge_open()
        return True

    def _gauge_open(self) -> None:
        self.provider.gauge("wal_open_requests").set(len(self._open_ids))

    @property
    def open_count(self) -> int:
        return len(self._open_ids)

    def summary(self) -> dict:
        """``/statusz`` payload: segment + open-request accounting."""
        segments = self._segment_paths()
        return {
            "directory": self.directory,
            "segments": len(segments),
            "segment_seq": self._segment_seq,
            "segment_records": self._segment_records,
            "segment_bytes": self._segment_bytes,
            "open_requests": len(self._open_ids),
            "next_id": self._next_id,
            "recovered": self._recovered,
            "recovered_entries": len(self.recovered_entries),
            "torn_records": self.torn_records,
            "fsync": self.config.fsync,
        }

    # ------------------------------------------------------------ recovery
    def _scan(self, paths: list[str]):
        """(ordered admit records, resolved ids, torn count, max id)."""
        admits: dict[int, dict] = {}
        resolved: set[int] = set()
        torn = 0
        max_id = 0
        for path in paths:
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            for raw in data.split(b"\n"):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    record = json.loads(raw.decode(errors="replace"))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    torn += 1
                    continue
                if not isinstance(record, dict) \
                        or record.get("crc") != _checksum(record):
                    torn += 1
                    continue
                rid = int(record.get("id", 0))
                max_id = max(max_id, rid)
                if record.get("t") in (RECORD_ADMIT, RECORD_ADMIT_BATCH):
                    admits[rid] = record
                elif record.get("t") == RECORD_RESOLVE:
                    resolved.add(rid)
                else:
                    torn += 1
        return admits, resolved, torn, max_id

    def recover(self) -> list[WalEntry]:
        """Scan + compact; returns the incomplete entries, admit order.

        Idempotent: only the first call scans (and compacts); later
        calls return ``[]``. The first call's result stays readable on
        :attr:`recovered_entries`.
        """
        if self._recovered:
            return []
        self._recovered = True
        t0 = time.perf_counter()
        paths = self._segment_paths()
        admits, resolved, torn, max_id = self._scan(paths)
        self.torn_records = torn
        if torn:
            self.provider.counter("wal_torn_records_total").add(torn)
        self._next_id = max_id + 1
        incomplete = [rec for rid, rec in admits.items()
                      if rid not in resolved]
        entries = []
        for rec in incomplete:
            try:
                payload = _decode_payload(rec["payload"])
            except Exception:  # noqa: BLE001 — a CRC-valid but
                # undecodable payload is as lost as a torn line
                self.torn_records += 1
                self.provider.counter("wal_torn_records_total").add()
                continue
            entries.append(WalEntry(
                wal_id=int(rec["id"]), kind=rec["kind"], lane=rec["lane"],
                deadline_s=float(rec["deadline_s"]), payload=payload,
                rows=int(rec.get("rows", 1)),
                record=rec.get("t", RECORD_ADMIT)))
        if paths:
            # compaction: the incomplete set is the only state worth
            # keeping — rewrite it into a fresh segment, drop history
            try:
                tail = os.path.basename(paths[-1])
                self._segment_seq = int(
                    tail[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)])
            except ValueError:
                self._segment_seq = len(paths)
            self._open_segment()
            for entry in entries:
                rec = {"t": entry.record, "id": entry.wal_id,
                       "kind": entry.kind, "lane": entry.lane,
                       "deadline_s": round(entry.deadline_s, 6),
                       "ts": round(time.time(), 6),
                       "payload": _encode_payload(entry.payload)}
                if entry.record == RECORD_ADMIT_BATCH:
                    rec["rows"] = entry.rows
                self._append(rec)
            for path in paths:
                try:
                    os.remove(path)
                except OSError:
                    pass
            self.provider.counter("wal_compactions_total").add()
        self._open_ids = {e.wal_id for e in entries}
        self._gauge_open()
        self.recovered_entries = entries
        self.provider.histogram("wal_recovery_seconds").observe(
            time.perf_counter() - t0)
        JOURNAL.record(EVENT_WAL_RECOVERED, segments=len(paths),
                       incomplete=len(entries), torn=self.torn_records)
        return entries

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
