"""X.509-style signing identities (ECDSA P-256 + SHA-256).

Mirrors the role of reference token/services/identity/x509 (MSP identities):
an identity is the DER SubjectPublicKeyInfo of an ECDSA P-256 key; signatures
are DER-encoded ECDSA over SHA-256 — the same primitive Fabric MSP uses.
Certificate-chain/MSP validation is intentionally out of scope for the
in-process trust model (identities are registered, not CA-issued).
"""

from __future__ import annotations

from dataclasses import dataclass

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec

from ...driver.identity import Identity


class SignatureError(Exception):
    pass


@dataclass
class X509Verifier:
    """driver.Verifier for an ECDSA P-256 public identity."""

    public_key: ec.EllipticCurvePublicKey

    @classmethod
    def from_identity(cls, identity: bytes) -> "X509Verifier":
        try:
            key = serialization.load_der_public_key(bytes(identity))
        except Exception as e:
            raise SignatureError(f"failed to deserialize identity: {e}") from e
        if not isinstance(key, ec.EllipticCurvePublicKey):
            raise SignatureError("identity is not an EC public key")
        return cls(key)

    def verify(self, message: bytes, signature: bytes) -> None:
        try:
            self.public_key.verify(signature, message,
                                   ec.ECDSA(hashes.SHA256()))
        except InvalidSignature as e:
            raise SignatureError("invalid signature") from e


@dataclass
class X509KeyPair:
    """Signing identity: private key + serialized public identity."""

    private_key: ec.EllipticCurvePrivateKey
    identity: Identity

    def sign(self, message: bytes) -> bytes:
        return self.private_key.sign(message, ec.ECDSA(hashes.SHA256()))

    def verifier(self) -> X509Verifier:
        return X509Verifier(self.private_key.public_key())


def new_signing_identity() -> X509KeyPair:
    key = ec.generate_private_key(ec.SECP256R1())
    pub = key.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    return X509KeyPair(key, Identity(pub))


def keypair_to_pem(kp: X509KeyPair) -> tuple[bytes, bytes]:
    """(private PEM, public PEM) for on-disk artifacts (tokengen)."""
    priv = kp.private_key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    pub = kp.private_key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    return priv, pub


def keypair_from_pem(private_pem: bytes) -> X509KeyPair:
    key = serialization.load_pem_private_key(private_pem, password=None)
    if not isinstance(key, ec.EllipticCurvePrivateKey):
        raise SignatureError("PEM is not an EC private key")
    pub = key.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    return X509KeyPair(key, Identity(pub))
