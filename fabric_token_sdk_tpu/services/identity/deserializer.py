"""Deserializer mux: identity bytes -> verifier, dispatched on identity type.

Mirrors reference token/services/identity/deserializer.go (mux of typed
verifier deserializers) plus the driver-side wrapping in
token/core/zkatdlog/nogh/v1/driver/driver.go:69-169 (authorization mux of
TMS + HTLC script + multisig escrow).

Raw (untyped) identities resolve as X.509 public keys; typed identities
dispatch on their type tag. HTLC script identities resolve recursively to
the participant that must sign (sender before deadline has passed is
handled by the htlc validator; here the script accepts either party's key
at signature level — the validator enforces which one).
"""

from __future__ import annotations

from ...driver.identity import Identity
from . import typed as typed_mod
from .x509 import X509Verifier

X509_TYPE = "x509"


class DeserializerError(Exception):
    pass


class Deserializer:
    """driver.Deserializer: owner/issuer/auditor verifier resolution."""

    def __init__(self, extra_owner_resolvers: list | None = None):
        # resolvers: callables (typed_identity) -> Verifier | None
        self.extra_owner_resolvers = list(extra_owner_resolvers or [])

    # -- plain key identities -------------------------------------------------
    def _raw_verifier(self, identity: Identity) -> X509Verifier:
        return X509Verifier.from_identity(identity)

    def get_issuer_verifier(self, identity: Identity):
        return self._resolve(identity)

    def get_auditor_verifier(self, identity: Identity):
        return self._resolve(identity)

    def get_owner_verifier(self, identity: Identity):
        return self._resolve(identity)

    def _resolve(self, identity: Identity):
        try:
            ti = typed_mod.unmarshal_typed_identity(bytes(identity))
        except Exception:
            return self._raw_verifier(identity)
        if ti.type == X509_TYPE:
            return self._raw_verifier(Identity(ti.identity))
        from .multisig import MULTISIG_TYPE, multisig_owner_resolver

        if ti.type == MULTISIG_TYPE:
            # multisig escrow: recursive resolution of every co-owner
            # (identity/multisig/deserializer.go:95-110) via the shared hook
            return multisig_owner_resolver(self._resolve)(ti)
        for resolver in self.extra_owner_resolvers:
            v = resolver(ti)
            if v is not None:
                return v
        raise DeserializerError(
            f"no verifier deserializer for identity type [{ti.type}]")
