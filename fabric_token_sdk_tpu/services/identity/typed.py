"""Typed identities: (type, identity) pairs in Go-asn1-compatible DER.

Behavioral mirror of reference token/services/identity/typed.go:22-49:
TypedIdentity is ASN.1 SEQUENCE { PrintableString type, OCTET STRING
identity }. Ownership scripts (HTLC, multisig) and role identities (x509,
idemix) are dispatched on the type string.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...crypto import serialization as ser
from ...driver.identity import Identity


def _der_printable_string(s: str) -> bytes:
    body = s.encode("ascii")
    return b"\x13" + ser._der_len(len(body)) + body


@dataclass
class TypedIdentity:
    type: str
    identity: bytes

    def to_bytes(self) -> bytes:
        return ser.der_sequence(
            _der_printable_string(self.type),
            ser.der_octet_string(self.identity),
        )


def wrap_with_type(id_type: str, identity: bytes) -> Identity:
    """identity.WrapWithType (typed.go:42-49)."""
    return Identity(TypedIdentity(id_type, identity).to_bytes())


def unmarshal_typed_identity(raw: bytes) -> TypedIdentity:
    """identity.UnmarshalTypedIdentity (typed.go:33-40)."""
    seq = ser.DerReader(raw).read_sequence()
    tag = seq.raw[seq.pos] if seq.pos < len(seq.raw) else None
    if tag not in (0x13, 0x0C):  # PrintableString | UTF8String
        raise ValueError("failed to unmarshal to TypedIdentity")
    n = seq._read_header(tag)
    body = seq.raw[seq.pos:seq.pos + n]
    if len(body) != n:
        raise ValueError("failed to unmarshal to TypedIdentity: truncated")
    seq.pos += n
    identity = seq.read_octet_string()
    return TypedIdentity(body.decode("utf-8"), identity)
