"""Role-based wallet registry + local membership.

Behavioral mirror of reference token/services/identity/{role,wallet}
(role/role.go MapToIdentity resolution order, wallet/service.go role
registries, wallet/wallets.go concrete wallets) and the membership layer
(identity/membership): a node holds one registry per role
(Owner/Issuer/Auditor/Certifier), each backed by a local membership of
long-term identities, persisted through IdentityDB so wallets and
identity->enrollment bindings survive restart.

Flattened from the reference's dig-DI shape: registries are plain objects;
the cache layer (wallet/cache.go pre-derived pseudonyms) collapses into
the Idemix key manager, which derives pseudonyms on demand.
"""

from __future__ import annotations

from dataclasses import dataclass

from .wallet import IdemixOwnerWallet, X509OwnerWallet


class RoleType:
    """identity.RoleType constants (identity/role/role.go)."""

    OWNER = "owner"
    ISSUER = "issuer"
    AUDITOR = "auditor"
    CERTIFIER = "certifier"

    ALL = (OWNER, ISSUER, AUDITOR, CERTIFIER)


class RegistryError(Exception):
    pass


@dataclass
class IdentityInfo:
    """idriver.IdentityInfo: a resolvable wallet entry."""

    id: str
    enrollment_id: str
    remote: bool  # True for third-party recipient identities (no signer)


class LocalMembership:
    """identity/membership localMembership: the long-term identities this
    node can sign with, for ONE role, keyed by label."""

    def __init__(self, default_label: str | None = None):
        self._by_label: dict[str, object] = {}   # label -> wallet object
        self._eids: dict[str, str] = {}          # label -> enrollment id
        self.default_label = default_label

    def register(self, label: str, wallet, enrollment_id: str = "",
                 default: bool = False) -> None:
        self._by_label[label] = wallet
        self._eids[label] = enrollment_id or label
        if default or self.default_label is None:
            self.default_label = label

    def ids(self) -> list[str]:
        return sorted(self._by_label)

    def is_me(self, identity: bytes) -> bool:
        return any(w.owns(identity) for w in self._by_label.values())

    def get_identifier(self, identity: bytes) -> str | None:
        for label, w in self._by_label.items():
            if w.owns(identity):
                return label
        return None

    def wallet(self, label: str):
        return self._by_label.get(label)

    def enrollment_id(self, label: str) -> str:
        return self._eids.get(label, label)


class Role:
    """role/role.go: maps a WalletLookupID (label string, identity bytes,
    or None) to a wallet identifier within one role's membership."""

    def __init__(self, role_id: str, membership: LocalMembership):
        self.role_id = role_id
        self.membership = membership

    def map_to_identifier(self, lookup) -> str | None:
        """Resolution order of role.go mapStringToID/mapIdentityToID:
        empty -> default; known label -> that label; owned identity ->
        its label; unknown -> None (reference returns the raw label and
        fails later at wallet construction; failing here is the same
        observable outcome with a clearer error site)."""
        m = self.membership
        if lookup is None or lookup == "" or lookup == b"":
            return m.default_label
        if isinstance(lookup, str):
            if lookup in m.ids():
                return lookup
            ident = lookup.encode()
            return m.get_identifier(ident)
        ident = bytes(lookup)
        label = m.get_identifier(ident)
        if label is not None:
            return label
        return None


class WalletRegistry:
    """wallet/wallets.go registry for one role: wallet lookup + identity
    bindings, persisted via IdentityDB."""

    def __init__(self, role: Role, identity_db):
        self.role = role
        self.db = identity_db
        # identity bytes -> (enrollment id, wallet id); the ledger-visible
        # pseudonyms bound to each wallet (BindIdentity)
        self._bindings: dict[bytes, tuple[str, str]] = {}

    def wallet_ids(self) -> list[str]:
        return self.role.membership.ids()

    def lookup(self, lookup=None):
        """Returns (wallet, wallet_id). Raises RegistryError when the
        lookup resolves to nothing."""
        wid = self.role.map_to_identifier(lookup)
        if wid is None:
            raise RegistryError(
                f"no {self.role.role_id} wallet for lookup [{lookup!r}]")
        w = self.role.membership.wallet(wid)
        if w is None:
            raise RegistryError(
                f"{self.role.role_id} wallet [{wid}] not registered")
        return w, wid

    def register_wallet(self, wallet_id: str, wallet,
                        enrollment_id: str = "") -> None:
        self.role.membership.register(wallet_id, wallet, enrollment_id)
        ident = getattr(wallet, "long_term_identity", None)
        if ident is not None:
            self.db.register_wallet(wallet_id, self.role.role_id,
                                    bytes(ident), enrollment_id)

    def bind_identity(self, identity: bytes, enrollment_id: str,
                      wallet_id: str, audit_info: bytes = b"") -> None:
        """BindIdentity: associate a ledger identity (e.g. a fresh Idemix
        pseudonym) with the wallet that controls it."""
        self._bindings[bytes(identity)] = (enrollment_id, wallet_id)
        if audit_info:
            self.db.store_audit_info(bytes(identity), audit_info)

    def contains_identity(self, identity: bytes,
                          wallet_id: str | None = None) -> bool:
        entry = self._bindings.get(bytes(identity))
        if entry is not None:
            return wallet_id is None or entry[1] == wallet_id
        label = self.role.membership.get_identifier(bytes(identity))
        if label is None:
            return False
        return wallet_id is None or label == wallet_id

    def owning_wallet(self, identity: bytes):
        """The registered wallet owning `identity` (long-term identity or
        bound pseudonym), else None — one scan, no private access for
        callers."""
        ident = bytes(identity)
        m = self.role.membership
        label = m.get_identifier(ident)
        if label is None:
            entry = self._bindings.get(ident)
            label = entry[1] if entry is not None else None
        return m.wallet(label) if label is not None else None


class WalletService:
    """wallet/service.go: the per-TMS wallet manager — one registry per
    role, plus third-party recipient registration."""

    def __init__(self, identity_db, info_matcher=None):
        self.db = identity_db
        self.info_matcher = info_matcher
        self.registries = {
            r: WalletRegistry(Role(r, LocalMembership()), identity_db)
            for r in RoleType.ALL
        }
        # third-party recipients: identity -> audit info
        self._recipients: dict[bytes, bytes] = {}

    # -------------------------------------------------------------- lookups
    def owner_wallet(self, lookup=None):
        return self.registries[RoleType.OWNER].lookup(lookup)[0]

    def issuer_wallet(self, lookup=None):
        return self.registries[RoleType.ISSUER].lookup(lookup)[0]

    def auditor_wallet(self, lookup=None):
        return self.registries[RoleType.AUDITOR].lookup(lookup)[0]

    def certifier_wallet(self, lookup=None):
        return self.registries[RoleType.CERTIFIER].lookup(lookup)[0]

    def wallet_ids(self, role: str) -> list[str]:
        return self.registries[role].wallet_ids()

    def wallet(self, identity: bytes):
        """wallet/service.go Wallet(identity): the wallet owning
        `identity` across every role (long-term identities and bound
        pseudonyms alike), else None. request.go:1069 BindTo uses this
        to recognize — and skip — locally-owned identities."""
        for r in RoleType.ALL:
            w = self.registries[r].owning_wallet(identity)
            if w is not None:
                return w
        return None

    # -------------------------------------------------------- registration
    def register_owner_wallet(self, wallet_id: str, wallet,
                              enrollment_id: str = "") -> None:
        self.registries[RoleType.OWNER].register_wallet(
            wallet_id, wallet, enrollment_id)

    def register_issuer_wallet(self, wallet_id: str, wallet,
                               enrollment_id: str = "") -> None:
        self.registries[RoleType.ISSUER].register_wallet(
            wallet_id, wallet, enrollment_id)

    def register_recipient_identity(self, identity: bytes,
                                    audit_info: bytes) -> None:
        """service.go RegisterRecipientIdentity: a THIRD PARTY's recipient
        data — verify the audit info matches the identity (Deserializer.
        MatchIdentity) before trusting it for future outputs."""
        if identity is None:
            raise RegistryError("nil recipient data")
        if self.info_matcher is not None:
            self.info_matcher.match_identity(bytes(identity), audit_info)
        self._recipients[bytes(identity)] = audit_info
        self.db.store_audit_info(bytes(identity), audit_info)

    def get_audit_info(self, identity: bytes) -> bytes | None:
        info = self._recipients.get(bytes(identity))
        if info is not None:
            return info
        return self.db.get_audit_info(bytes(identity))

    # ------------------------------------------------------------- helpers
    @classmethod
    def for_node(cls, name: str, keys, identity_db, owner_wallet=None,
                 idemix_km=None, info_matcher=None) -> "WalletService":
        """Assemble the default registries of a TokenNode: the node's
        ACTIVE owner wallet under the node's name (x509 from `keys` when
        none is supplied; pseudonymous wallets persist no single long-term
        identity), and the node key as issuer/auditor/certifier wallet —
        the same defaulting the reference driver factory performs from
        config (zkatdlog v1/driver/driver.go wallet service assembly)."""
        ws = cls(identity_db, info_matcher=info_matcher)
        if owner_wallet is None:
            owner_wallet = X509OwnerWallet(keys)
        ws.register_owner_wallet(name, owner_wallet, enrollment_id=name)
        if idemix_km is not None:
            ws.register_owner_wallet(f"{name}.idemix",
                                     IdemixOwnerWallet(idemix_km),
                                     enrollment_id=name)
        for role in (RoleType.ISSUER, RoleType.AUDITOR, RoleType.CERTIFIER):
            ws.registries[role].register_wallet(name, X509OwnerWallet(keys),
                                                enrollment_id=name)
        return ws
