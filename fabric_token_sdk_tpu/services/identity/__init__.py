"""Identity service: typed identities, role-based wallets, signers/verifiers.

Mirrors reference token/services/identity (SURVEY.md §2.4): X.509-style
signing identities (ECDSA P-256), typed-identity wrapping used by ownership
scripts (HTLC, multisig), and the deserializer mux that routes identity bytes
to the right verifier.
"""

from .typed import TypedIdentity, wrap_with_type, unmarshal_typed_identity  # noqa: F401
from .x509 import X509KeyPair, X509Verifier, new_signing_identity  # noqa: F401
