"""Multisig (escrow) identities: co-owned tokens requiring all signatures.

Behavioral mirror of reference token/services/identity/multisig:
  - ``MultiIdentity`` (identity.go:23-38): Go asn1.Marshal of
    {Identities [][]byte} — SEQUENCE { SEQUENCE OF OCTET STRING };
  - ``WrapIdentities`` (identity.go:41-56): typed identity with type "ms";
  - ``MultiSignature`` + ``JoinSignatures`` (sig.go): one signature blob
    carrying every co-owner's signature in identity order;
  - ``Verifier`` (sig.go:52+): all co-signatures must verify;
  - audit-info matcher (deserializer.go:25-122): per-co-owner audit infos
    matched recursively.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ...crypto import serialization as ser
from ...driver.identity import Identity
from . import typed as typed_mod

MULTISIG_TYPE = "ms"  # identity.go:21


class MultisigError(Exception):
    pass


@dataclass
class MultiIdentity:
    """identity.go:23-38."""

    identities: list[bytes] = field(default_factory=list)

    def serialize(self) -> bytes:
        return ser.der_sequence(
            ser.der_sequence(*[ser.der_octet_string(bytes(i))
                               for i in self.identities]))

    @classmethod
    def deserialize(cls, raw: bytes) -> "MultiIdentity":
        outer = ser.DerReader(raw).read_sequence()
        inner = outer.read_sequence()
        ids = []
        while not inner.eof():
            ids.append(inner.read_octet_string())
        return cls(identities=ids)


def wrap_identities(*identities: bytes) -> Identity:
    """identity.go:41-56 WrapIdentities."""
    if not identities:
        raise MultisigError("no identities provided")
    mi = MultiIdentity(identities=[bytes(i) for i in identities])
    return typed_mod.wrap_with_type(MULTISIG_TYPE, mi.serialize())


def unwrap(raw: bytes) -> tuple[bool, list[bytes]]:
    """identity.go:59-74 Unwrap: (is_multisig, co-owner identities)."""
    try:
        ti = typed_mod.unmarshal_typed_identity(bytes(raw))
    except Exception:
        return False, []
    if ti.type != MULTISIG_TYPE:
        return False, []
    return True, MultiIdentity.deserialize(ti.identity).identities


@dataclass
class MultiSignature:
    """sig.go MultiSignature: {Signatures [][]byte} (Go asn1)."""

    signatures: list[bytes] = field(default_factory=list)

    def serialize(self) -> bytes:
        return ser.der_sequence(
            ser.der_sequence(*[ser.der_octet_string(s)
                               for s in self.signatures]))

    @classmethod
    def deserialize(cls, raw: bytes) -> "MultiSignature":
        outer = ser.DerReader(raw).read_sequence()
        inner = outer.read_sequence()
        sigs = []
        while not inner.eof():
            sigs.append(inner.read_octet_string())
        return cls(signatures=sigs)


def join_signatures(identities: list[bytes],
                    sigmas: dict[bytes, bytes]) -> bytes:
    """sig.go JoinSignatures: signatures in identity order."""
    sigs = []
    for ident in identities:
        sigma = sigmas.get(bytes(ident))
        if sigma is None:
            raise MultisigError(
                "signature for a co-owner identity is missing")
        sigs.append(sigma)
    return MultiSignature(signatures=sigs).serialize()


class MultisigVerifier:
    """sig.go Verifier: every co-signature must verify, in order."""

    def __init__(self, verifiers: list):
        self.verifiers = verifiers

    def verify(self, message: bytes, signature: bytes) -> None:
        try:
            sig = MultiSignature.deserialize(signature)
        except Exception as e:
            raise MultisigError(
                f"failed to unmarshal multisig: {e}") from e
        if len(self.verifiers) != len(sig.signatures):
            raise MultisigError(
                f"invalid multisig: expect [{len(self.verifiers)}] "
                f"signatures, but received [{len(sig.signatures)}]")
        for k, verifier in enumerate(self.verifiers):
            try:
                verifier.verify(message, sig.signatures[k])
            except Exception as e:
                raise MultisigError(
                    f"invalid multisig: signature at index [{k}] does not "
                    f"verify") from e


def multisig_owner_resolver(resolve_verifier):
    """Deserializer hook: TypedIdentity('ms', ...) -> MultisigVerifier with
    recursively-resolved co-owner verifiers (deserializer.go:95-110)."""

    def resolver(ti: typed_mod.TypedIdentity):
        if ti.type != MULTISIG_TYPE:
            return None
        mi = MultiIdentity.deserialize(ti.identity)
        return MultisigVerifier(
            [resolve_verifier(Identity(i)) for i in mi.identities])

    return resolver


class MultisigInfoMatcher:
    """deserializer.go:64-92: audit info is a JSON list of per-co-owner
    audit infos; each must match its identity via the inner matcher."""

    def __init__(self, inner_matcher):
        self.inner = inner_matcher

    def audit_info(self, owner_raw: bytes,
                   info_for: "callable") -> bytes:
        is_ms, ids = unwrap(owner_raw)
        if not is_ms:
            raise MultisigError("not a multisig identity")
        infos = [info_for(i).hex() for i in ids]
        return json.dumps({"identity_audit_infos": infos}).encode()

    def match_identity(self, identity: bytes, audit_info: bytes) -> None:
        is_ms, ids = unwrap(identity)
        if not is_ms:
            raise MultisigError("not a multisig identity")
        try:
            infos = [bytes.fromhex(h) for h in
                     json.loads(audit_info)["identity_audit_infos"]]
        except Exception as e:
            raise MultisigError(
                f"malformed multisig audit info: {e}") from e
        if len(ids) != len(infos):
            raise MultisigError(
                f"expected {len(ids)} audit info but received {len(infos)}")
        for ident, info in zip(ids, infos):
            self.inner.match_identity(ident, info)
