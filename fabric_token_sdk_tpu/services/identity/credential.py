"""Pairing-based anonymous credentials (Idemix-style BBS+ over BN254).

Restores the reference capability the round-2 dlog-pseudonym layer lacked:
an ISSUER certifies a user's attributes once, and every pseudonymous
identity carries an unlinkable zero-knowledge proof of possessing that
credential — so only enrolled users can mint pseudonyms (reference
token/services/identity/idemix/km.go:46-365, which proves possession of an
IBM/idemix CL credential; the scheme here is the BBS+ form of the same
construction over the same curve family).

Scheme (all group work host-side BN254, crypto/pairing.py):

  Issuer key:  x <- Zr,  w = g2^x; generators HSk, HRand, HAttr_i
               (nothing-up-my-sleeve hash-to-curve).
  Credential on (sk, attrs):  e, s <- Zr,
               B = g1 * HSk^sk * HRand^s * prod_i HAttr_i^{m_i}
               A = B^{1/(e+x)}            — classic BBS+ signature (A, e, s).
  Presentation bound to a pseudonym Nym = HSk^sk * HRand^{rNym} and a
  message: randomize A' = A^{r1}, Abar = B^{r1} * A'^{-e},
  d = B^{r1} * HRand^{-r2}, s' = s - r2*r3 (r3 = 1/r1), then prove in ZK
      (i)   Abar / d         = A'^{-e} * HRand^{r2}
      (ii)  g1 * prod_D HAttr_i^{m_i}
                             = d^{r3} * HRand^{-s'} * HSk^{-sk}
                               * prod_hidden HAttr_i^{-m_i}
      (iii) Nym              = HSk^sk * HRand^{rNym}
  with one shared Fiat-Shamir challenge (sk is bound across (ii) and
  (iii)). The verifier additionally checks the pairing equation
      e(A', w) == e(Abar, g2)   and   A' != identity.
  Two transactions by the same holder are unlinkable: every element the
  verifier sees is uniformly re-randomized per presentation.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass, field

from ...crypto import bn254, pairing as pr
from ...crypto import serialization as ser
from ...crypto.bn254 import (G1, fr_add, fr_inv, fr_mul, fr_neg, fr_rand,
                             fr_sub, g1_add, g1_mul, g1_neg, hash_to_g1,
                             hash_to_zr)

R = bn254.R


class CredentialError(Exception):
    pass


#: The sk generator, shared with the idemix pseudonym layer (idemix.HSK_GEN
#: is this same point): credential-mode masters are HSK^sk and the Nym
#: equation in presentations must use the identical generator.
H_SK = hash_to_g1(b"fabric_token_sdk_tpu.idemix.cred.hsk")


def _g2_to_bytes(q) -> bytes:
    """Twist point -> 128-byte encoding (x0||x1||y0||y1, 32-byte BE each);
    identity encodes as all-zero (mirrors the G1 convention)."""
    if q is None:
        return bytes(128)
    (x0, x1), (y0, y1) = q
    return b"".join(v.to_bytes(32, "big") for v in (x0, x1, y0, y1))


def _g2_from_bytes(raw: bytes):
    if len(raw) != 128:
        raise CredentialError("bad G2 encoding length")
    if raw == bytes(128):
        return None
    v = [int.from_bytes(raw[i * 32:(i + 1) * 32], "big") for i in range(4)]
    q = ((v[0], v[1]), (v[2], v[3]))
    if not pr.g2_in_subgroup(q):
        raise CredentialError("G2 point not in the r-torsion subgroup")
    return q


def attr_to_zr(value: bytes | str) -> int:
    """Attribute encoding: hash into the scalar field."""
    if isinstance(value, str):
        value = value.encode()
    return hash_to_zr(b"idemix.cred.attr" + value)


# ---------------------------------------------------------------------------
# issuer key
# ---------------------------------------------------------------------------

@dataclass
class IssuerPublicKey:
    w: object                    # g2^x
    h_sk: G1
    h_rand: G1
    h_attrs: tuple               # one G1 generator per attribute slot

    def digest_bytes(self) -> bytes:
        return (b"idemix.cred.ipk" + _g2_to_bytes(self.w)
                + ser.g1_to_bytes(self.h_sk) + ser.g1_to_bytes(self.h_rand)
                + b"".join(ser.g1_to_bytes(h) for h in self.h_attrs))


@dataclass
class IssuerKey:
    x: int
    public: IssuerPublicKey

    @classmethod
    def generate(cls, n_attrs: int, h_rand: G1 | None = None) -> "IssuerKey":
        """Fresh issuer key. `h_rand` may be pinned to the pseudonym layer's
        second generator so Nym audit info stays scheme-agnostic."""
        x = fr_rand()
        return cls(
            x=x,
            public=IssuerPublicKey(
                w=pr.g2_mul(pr.G2_GENERATOR, x),
                h_sk=H_SK,
                h_rand=h_rand if h_rand is not None
                else hash_to_g1(b"fabric_token_sdk_tpu.idemix.cred.hrand"),
                h_attrs=tuple(
                    hash_to_g1(b"fabric_token_sdk_tpu.idemix.cred.hattr"
                               + i.to_bytes(4, "big"))
                    for i in range(n_attrs)),
            ))


# ---------------------------------------------------------------------------
# issuance (blind in sk: the issuer never learns the user secret key)
# ---------------------------------------------------------------------------

@dataclass
class CredentialRequest:
    """User -> issuer: Nu = HSk^sk plus a Schnorr PoK of sk."""

    nu: G1
    t: G1
    z: int

    @classmethod
    def create(cls, ipk: IssuerPublicKey, sk: int,
               nonce: bytes) -> "CredentialRequest":
        nu = g1_mul(ipk.h_sk, sk)
        rho = fr_rand()
        t = g1_mul(ipk.h_sk, rho)
        c = hash_to_zr(b"idemix.cred.req" + ipk.digest_bytes()
                       + ser.g1_to_bytes(nu) + ser.g1_to_bytes(t) + nonce)
        return cls(nu=nu, t=t, z=fr_add(rho, fr_mul(c, sk)))

    def verify(self, ipk: IssuerPublicKey, nonce: bytes) -> None:
        c = hash_to_zr(b"idemix.cred.req" + ipk.digest_bytes()
                       + ser.g1_to_bytes(self.nu) + ser.g1_to_bytes(self.t)
                       + nonce)
        if g1_mul(ipk.h_sk, self.z) != g1_add(self.t, g1_mul(self.nu, c)):
            raise CredentialError("credential request PoK invalid")


@dataclass
class Credential:
    """BBS+ signature (A, e, s) over (sk, attrs); attrs stored alongside
    in the clear like the reference credential blob (km.go attributes)."""

    a: G1
    e: int
    s: int
    attrs: tuple                 # Zr-encoded attribute values

    def verify(self, ipk: IssuerPublicKey, sk: int) -> None:
        """Holder-side validity check: e(A, w * g2^e) == e(B, g2)."""
        b = _compute_b(ipk, sk, self.s, self.attrs)
        lhs_q = pr.g2_add(ipk.w, pr.g2_mul(pr.G2_GENERATOR, self.e))
        if not pr.gt_eq(self.a, lhs_q, b, pr.G2_GENERATOR):
            raise CredentialError("credential signature invalid")


def _compute_b(ipk: IssuerPublicKey, sk: int, s: int, attrs) -> G1:
    b = g1_add(bn254.G1_GENERATOR, g1_mul(ipk.h_sk, sk))
    b = g1_add(b, g1_mul(ipk.h_rand, s))
    for h, m in zip(ipk.h_attrs, attrs):
        b = g1_add(b, g1_mul(h, m))
    return b


def issue_credential(isk: IssuerKey, req: CredentialRequest, nonce: bytes,
                     attrs) -> Credential:
    """Issuer side: verify the request PoK, sign (Nu, attrs)."""
    ipk = isk.public
    if len(attrs) != len(ipk.h_attrs):
        raise CredentialError("attribute count mismatch")
    req.verify(ipk, nonce)
    e, s = fr_rand(), fr_rand()
    b = g1_add(bn254.G1_GENERATOR, req.nu)
    b = g1_add(b, g1_mul(ipk.h_rand, s))
    for h, m in zip(ipk.h_attrs, attrs):
        b = g1_add(b, g1_mul(h, m))
    a = g1_mul(b, fr_inv(fr_add(e, isk.x)))
    return Credential(a=a, e=e, s=s, attrs=tuple(attrs))


# ---------------------------------------------------------------------------
# presentation
# ---------------------------------------------------------------------------

@dataclass
class Presentation:
    """Unlinkable proof of credential possession bound to (Nym, message).

    disclosed: {index: attr_value} revealed to the verifier; all other
    attribute slots stay hidden inside the proof.
    """

    a_prime: G1
    a_bar: G1
    d: G1
    disclosed: dict = field(default_factory=dict)
    # Schnorr proof: challenge + responses
    c: int = 0
    s_e: int = 0
    s_r2: int = 0
    s_r3: int = 0
    s_sprime: int = 0
    s_sk: int = 0
    s_rnym: int = 0
    s_hidden: dict = field(default_factory=dict)   # index -> response

    def serialize(self) -> bytes:
        disc = ser.der_sequence(*[
            ser.der_sequence(ser.der_octet_string(i.to_bytes(4, "big")),
                             ser.der_octet_string(ser.zr_to_bytes(m)))
            for i, m in sorted(self.disclosed.items())])
        hid = ser.der_sequence(*[
            ser.der_sequence(ser.der_octet_string(i.to_bytes(4, "big")),
                             ser.der_octet_string(ser.zr_to_bytes(z)))
            for i, z in sorted(self.s_hidden.items())])
        return ser.der_sequence(
            ser.der_octet_string(ser.g1_to_bytes(self.a_prime)),
            ser.der_octet_string(ser.g1_to_bytes(self.a_bar)),
            ser.der_octet_string(ser.g1_to_bytes(self.d)),
            disc, hid,
            *[ser.der_octet_string(ser.zr_to_bytes(v))
              for v in (self.c, self.s_e, self.s_r2, self.s_r3,
                        self.s_sprime, self.s_sk, self.s_rnym)])

    @classmethod
    def deserialize(cls, raw: bytes) -> "Presentation":
        try:
            seq = ser.DerReader(raw).read_sequence()
            a_prime = ser.g1_from_bytes(seq.read_octet_string())
            a_bar = ser.g1_from_bytes(seq.read_octet_string())
            d = ser.g1_from_bytes(seq.read_octet_string())
            disclosed, hidden = {}, {}
            disc = seq.read_sequence()
            while not disc.eof():
                item = disc.read_sequence()
                idx = int.from_bytes(item.read_octet_string(), "big")
                disclosed[idx] = ser.zr_from_bytes(item.read_octet_string())
            hid = seq.read_sequence()
            while not hid.eof():
                item = hid.read_sequence()
                idx = int.from_bytes(item.read_octet_string(), "big")
                hidden[idx] = ser.zr_from_bytes(item.read_octet_string())
            vals = [ser.zr_from_bytes(seq.read_octet_string())
                    for _ in range(7)]
        except CredentialError:
            raise
        except Exception as exc:
            raise CredentialError(f"malformed presentation: {exc}") from exc
        return cls(a_prime=a_prime, a_bar=a_bar, d=d, disclosed=disclosed,
                   c=vals[0], s_e=vals[1], s_r2=vals[2], s_r3=vals[3],
                   s_sprime=vals[4], s_sk=vals[5], s_rnym=vals[6],
                   s_hidden=hidden)


def _presentation_challenge(ipk: IssuerPublicKey, a_prime, a_bar, d, nym,
                            t1, t2, t3, disclosed: dict,
                            message: bytes) -> int:
    buf = [b"idemix.cred.present", ipk.digest_bytes()]
    for p in (a_prime, a_bar, d, nym, t1, t2, t3):
        buf.append(ser.g1_to_bytes(p))
    for i, m in sorted(disclosed.items()):
        buf.append(i.to_bytes(4, "big") + ser.zr_to_bytes(m))
    buf.append(message)
    return hash_to_zr(b"".join(buf))


def present(ipk: IssuerPublicKey, cred: Credential, sk: int, nym: G1,
            r_nym: int, disclose: set, message: bytes) -> Presentation:
    """Build an unlinkable possession proof revealing `disclose` slots."""
    attrs = cred.attrs
    hidden_idx = [i for i in range(len(attrs)) if i not in disclose]
    b = _compute_b(ipk, sk, cred.s, attrs)

    r1 = 1 + secrets.randbelow(R - 1)
    r2 = fr_rand()
    r3 = fr_inv(r1)
    a_prime = g1_mul(cred.a, r1)
    a_bar = g1_add(g1_mul(b, r1), g1_neg(g1_mul(a_prime, cred.e)))
    d = g1_add(g1_mul(b, r1), g1_neg(g1_mul(ipk.h_rand, r2)))
    s_prime = fr_sub(cred.s, fr_mul(r2, r3))

    # Schnorr commitments
    rho_e, rho_r2, rho_r3 = fr_rand(), fr_rand(), fr_rand()
    rho_sp, rho_sk, rho_rn = fr_rand(), fr_rand(), fr_rand()
    rho_hidden = {i: fr_rand() for i in hidden_idx}
    t1 = g1_add(g1_mul(a_prime, fr_neg(rho_e)), g1_mul(ipk.h_rand, rho_r2))
    t2 = g1_add(g1_mul(d, rho_r3), g1_neg(g1_mul(ipk.h_rand, rho_sp)))
    t2 = g1_add(t2, g1_neg(g1_mul(ipk.h_sk, rho_sk)))
    for i in hidden_idx:
        t2 = g1_add(t2, g1_neg(g1_mul(ipk.h_attrs[i], rho_hidden[i])))
    t3 = g1_add(g1_mul(ipk.h_sk, rho_sk), g1_mul(ipk.h_rand, rho_rn))

    disclosed = {i: attrs[i] for i in disclose}
    c = _presentation_challenge(ipk, a_prime, a_bar, d, nym, t1, t2, t3,
                                disclosed, message)
    return Presentation(
        a_prime=a_prime, a_bar=a_bar, d=d, disclosed=disclosed, c=c,
        s_e=fr_add(rho_e, fr_mul(c, cred.e)),
        s_r2=fr_add(rho_r2, fr_mul(c, r2)),
        s_r3=fr_add(rho_r3, fr_mul(c, r3)),
        s_sprime=fr_add(rho_sp, fr_mul(c, s_prime)),
        s_sk=fr_add(rho_sk, fr_mul(c, sk)),
        s_rnym=fr_add(rho_rn, fr_mul(c, r_nym)),
        s_hidden={i: fr_add(rho_hidden[i], fr_mul(c, attrs[i]))
                  for i in hidden_idx},
    )


def verify_presentation(ipk: IssuerPublicKey, pres: Presentation, nym: G1,
                        message: bytes) -> None:
    """Verifier side: pairing check + the three Schnorr equations."""
    if pres.a_prime is None or pres.a_prime.is_identity():
        raise CredentialError("A' is the identity")
    n_attrs = len(ipk.h_attrs)
    idx_seen = set(pres.disclosed) | set(pres.s_hidden)
    if (len(pres.disclosed) + len(pres.s_hidden) != n_attrs
            or idx_seen != set(range(n_attrs))):
        raise CredentialError("attribute slots mismatch")

    # pairing: e(A', w) == e(Abar, g2)
    if not pr.gt_eq(pres.a_prime, ipk.w, pres.a_bar, pr.G2_GENERATOR):
        raise CredentialError("credential pairing check failed")

    c = pres.c
    # (i)  A'^{-s_e} HRand^{s_r2} == t1 * (Abar/d)^c
    lhs = g1_add(g1_mul(pres.a_prime, fr_neg(pres.s_e)),
                 g1_mul(ipk.h_rand, pres.s_r2))
    t1 = g1_add(lhs, g1_neg(
        g1_mul(g1_add(pres.a_bar, g1_neg(pres.d)), c)))
    # (ii) d^{s_r3} HRand^{-s_s'} HSk^{-s_sk} prod HAttr^{-s_mi}
    #      == t2 * (g1 * prod_D HAttr^{m_i})^c
    lhs = g1_add(g1_mul(pres.d, pres.s_r3),
                 g1_neg(g1_mul(ipk.h_rand, pres.s_sprime)))
    lhs = g1_add(lhs, g1_neg(g1_mul(ipk.h_sk, pres.s_sk)))
    for i, z in pres.s_hidden.items():
        lhs = g1_add(lhs, g1_neg(g1_mul(ipk.h_attrs[i], z)))
    pub = bn254.G1_GENERATOR
    for i, m in pres.disclosed.items():
        pub = g1_add(pub, g1_mul(ipk.h_attrs[i], m))
    t2 = g1_add(lhs, g1_neg(g1_mul(pub, c)))
    # (iii) HSk^{s_sk} HRand^{s_rnym} == t3 * Nym^c
    lhs = g1_add(g1_mul(ipk.h_sk, pres.s_sk),
                 g1_mul(ipk.h_rand, pres.s_rnym))
    t3 = g1_add(lhs, g1_neg(g1_mul(nym, c)))

    expect = _presentation_challenge(ipk, pres.a_prime, pres.a_bar, pres.d,
                                     nym, t1, t2, t3, pres.disclosed,
                                     message)
    if expect != c:
        raise CredentialError("presentation proof invalid")
