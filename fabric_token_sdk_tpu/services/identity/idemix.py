"""Idemix-style anonymous owner identities: per-tx pseudonyms + EID audit.

Capability mirror of reference token/services/identity/idemix/km.go:46-365
(KeyManager: fresh pseudonym per transaction, NymEID audit info, signature
verification against the pseudonym) and the auditor's identity inspection
(crypto/audit/auditor.go:265-282 InspectIdentity).

Scheme (documented divergence from IBM/idemix): the reference proves
possession of a pairing-based CL/BBS+ credential chain; this framework
implements the dlog pseudonym layer that gives the zkatdlog driver its
privacy capabilities —
  - OWNER PSEUDONYMS: Nym = g^sk * h^r with fresh r per transaction; two
    transfers by the same owner are unlinkable under DDH.
  - SIGNATURES: two-generator Schnorr proof of knowledge of (sk, r) for
    Nym, bound to the message — validators verify against the pseudonym
    alone and learn nothing about the long-term key.
  - REGISTRATION: an enrollment authority binds eid -> master key U = g^sk
    with an ECDSA enrollment certificate (the role the idemix issuer's
    credential plays in the reference).
  - AUDIT (NymEID matching): the audit info carries (eid, r); the auditor
    recomputes Nym == U_eid * h^r against its registration directory and
    verifies the enrollment certificate, recovering WHO transacted without
    the validators ever learning it.
The pairing-based credential chain is the one reference capability
intentionally replaced (SURVEY.md §7 hard-part 4 keeps pairings off the
hot path); everything downstream — pseudonymous owners, unlinkability,
auditor-only deanonymization — is preserved and tested.

All group work is host-side BN254 (per-tx, not per-proof — it never touches
the TPU batch path).
"""

from __future__ import annotations

from dataclasses import dataclass

from ...crypto import bn254
from ...crypto import serialization as ser
from ...crypto.bn254 import (G1, fr_add, fr_mul, fr_rand, g1_add, g1_mul,
                             g1_neg, hash_to_g1, hash_to_zr)
from ...driver.identity import Identity
from . import typed as typed_mod
from .x509 import X509KeyPair, X509Verifier, new_signing_identity

IDEMIX_TYPE = "idemix"

#: Second pseudonym generator, nothing-up-my-sleeve (hash-to-curve).
H_GEN = hash_to_g1(b"fabric_token_sdk_tpu.idemix.nym.h")
G_GEN = bn254.G1_GENERATOR


class IdemixError(Exception):
    pass


def _schnorr_challenge(nym: G1, t: G1, message: bytes) -> int:
    return hash_to_zr(b"idemix.nym.sig"
                      + ser.g1_to_bytes(G_GEN) + ser.g1_to_bytes(H_GEN)
                      + ser.g1_to_bytes(nym) + ser.g1_to_bytes(t)
                      + message)


@dataclass
class Pseudonym:
    """One per-transaction identity: Nym = g^sk * h^r."""

    nym: G1
    r: int

    def identity(self) -> Identity:
        return typed_mod.wrap_with_type(IDEMIX_TYPE, ser.g1_to_bytes(self.nym))


class NymVerifier:
    """driver.Verifier for a pseudonym: checks the two-generator Schnorr
    PoK (km.go signature verification against the Nym)."""

    def __init__(self, nym: G1):
        self.nym = nym

    @classmethod
    def from_typed(cls, identity_bytes: bytes) -> "NymVerifier":
        try:
            return cls(ser.g1_from_bytes(identity_bytes))
        except Exception as e:
            raise IdemixError(f"invalid idemix pseudonym: {e}") from e

    def verify(self, message: bytes, signature: bytes) -> None:
        try:
            seq = ser.DerReader(signature).read_sequence()
            t = ser.g1_from_bytes(seq.read_octet_string())
            z1 = ser.zr_from_bytes(seq.read_octet_string())
            z2 = ser.zr_from_bytes(seq.read_octet_string())
        except Exception as e:
            raise IdemixError(f"malformed idemix signature: {e}") from e
        c = _schnorr_challenge(self.nym, t, message)
        # g^z1 h^z2 == t * Nym^c
        lhs = g1_add(g1_mul(G_GEN, z1), g1_mul(H_GEN, z2))
        rhs = g1_add(t, g1_mul(self.nym, c))
        if lhs != rhs:
            raise IdemixError("invalid idemix signature")


class EnrollmentAuthority:
    """Registration CA: binds enrollment IDs to master keys (the role of
    the idemix issuer key in km.go; ECDSA instead of a CL credential)."""

    def __init__(self):
        self.keys: X509KeyPair = new_signing_identity()

    def enroll(self, eid: str, master: G1) -> bytes:
        """Enrollment certificate over (eid, U)."""
        return self.keys.sign(b"idemix.enroll" + eid.encode()
                              + ser.g1_to_bytes(master))

    def ca_identity(self) -> Identity:
        return self.keys.identity


class IdemixKeyManager:
    """User-side key manager (km.go:46-365): long-term sk, fresh pseudonyms,
    per-pseudonym signing, audit info emission."""

    def __init__(self, eid: str, authority: EnrollmentAuthority):
        self.eid = eid
        self.sk = fr_rand()
        self.master = g1_mul(G_GEN, self.sk)     # U = g^sk
        self.cert = authority.enroll(eid, self.master)
        #: nym bytes -> Pseudonym (the wallet registry of own pseudonyms)
        self._mine: dict[bytes, Pseudonym] = {}

    # ------------------------------------------------------------ identity
    def fresh_pseudonym(self) -> Pseudonym:
        """New unlinkable identity for one transaction (km.go pseudonym
        generation)."""
        r = fr_rand()
        nym = g1_add(self.master, g1_mul(H_GEN, r))
        p = Pseudonym(nym=nym, r=r)
        self._mine[bytes(p.identity())] = p
        return p

    def owns(self, owner_raw: bytes) -> bool:
        return bytes(owner_raw) in self._mine

    # ------------------------------------------------------------- signing
    def sign(self, owner_raw: bytes, message: bytes) -> bytes:
        """Schnorr PoK of (sk, r) for the pseudonym `owner_raw`."""
        p = self._mine.get(bytes(owner_raw))
        if p is None:
            raise IdemixError("unknown pseudonym: cannot sign")
        a, b = fr_rand(), fr_rand()
        t = g1_add(g1_mul(G_GEN, a), g1_mul(H_GEN, b))
        c = _schnorr_challenge(p.nym, t, message)
        z1 = fr_add(a, fr_mul(c, self.sk))
        z2 = fr_add(b, fr_mul(c, p.r))
        return ser.der_sequence(
            ser.der_octet_string(ser.g1_to_bytes(t)),
            ser.der_octet_string(ser.zr_to_bytes(z1)),
            ser.der_octet_string(ser.zr_to_bytes(z2)),
        )

    # ------------------------------------------------------------ auditing
    def audit_info(self, owner_raw: bytes) -> bytes:
        """NymEID-style audit info: (eid, U, r, enrollment cert) — lets the
        auditor (and only the auditor) recompute and match the pseudonym
        (km.go NymEID audit info; auditor.go:265-282)."""
        p = self._mine.get(bytes(owner_raw))
        if p is None:
            raise IdemixError("unknown pseudonym: no audit info")
        return ser.der_sequence(
            ser.der_octet_string(self.eid.encode()),
            ser.der_octet_string(ser.g1_to_bytes(self.master)),
            ser.der_octet_string(ser.zr_to_bytes(p.r)),
            ser.der_octet_string(self.cert),
        )


class IdemixInfoMatcher:
    """Auditor-side matcher (auditor.go:265-282 InspectIdentity for idemix
    identities): verify the enrollment certificate, recompute the pseudonym
    from (U, r), and require equality with the on-ledger identity."""

    def __init__(self, ca_identity: Identity):
        self.ca = X509Verifier.from_identity(ca_identity)

    def match_identity(self, identity: bytes, audit_info: bytes) -> None:
        try:
            ti = typed_mod.unmarshal_typed_identity(bytes(identity))
        except Exception as e:
            raise IdemixError(f"not a typed identity: {e}") from e
        if ti.type != IDEMIX_TYPE:
            raise IdemixError(f"not an idemix identity [{ti.type}]")
        nym = ser.g1_from_bytes(ti.identity)
        try:
            seq = ser.DerReader(audit_info).read_sequence()
            eid = seq.read_octet_string().decode()
            master = ser.g1_from_bytes(seq.read_octet_string())
            r = ser.zr_from_bytes(seq.read_octet_string())
            cert = seq.read_octet_string()
        except Exception as e:
            raise IdemixError(f"malformed idemix audit info: {e}") from e
        self.ca.verify(b"idemix.enroll" + eid.encode()
                       + ser.g1_to_bytes(master), cert)
        if g1_add(master, g1_mul(H_GEN, r)) != nym:
            raise IdemixError(
                f"pseudonym does not open to enrollment id [{eid}]")

    def enrollment_id(self, audit_info: bytes) -> str:
        """Recover WHO transacted (auditdb EID locks use this)."""
        seq = ser.DerReader(audit_info).read_sequence()
        return seq.read_octet_string().decode()


class MuxInfoMatcher:
    """Dispatch matcher: idemix identities -> IdemixInfoMatcher; everything
    else -> plain equality (x509 convention in this framework)."""

    def __init__(self, ca_identity: Identity | None = None):
        self.idemix = IdemixInfoMatcher(ca_identity) if ca_identity else None

    def match_identity(self, identity: bytes, audit_info: bytes) -> None:
        try:
            ti = typed_mod.unmarshal_typed_identity(bytes(identity))
            is_idemix = ti.type == IDEMIX_TYPE
        except Exception:
            is_idemix = False
        if is_idemix:
            if self.idemix is None:
                raise IdemixError("no enrollment authority configured")
            self.idemix.match_identity(identity, audit_info)
            return
        if bytes(identity) != bytes(audit_info):
            raise IdemixError("identity does not match audit info")


def idemix_owner_resolver(ti: typed_mod.TypedIdentity):
    """Deserializer hook: TypedIdentity('idemix', nym) -> NymVerifier."""
    if ti.type != IDEMIX_TYPE:
        return None
    return NymVerifier.from_typed(ti.identity)
