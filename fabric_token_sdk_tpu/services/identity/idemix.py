"""Idemix-style anonymous owner identities: per-tx pseudonyms + EID audit.

Capability mirror of reference token/services/identity/idemix/km.go:46-365
(KeyManager: fresh pseudonym per transaction, NymEID audit info, signature
verification against the pseudonym) and the auditor's identity inspection
(crypto/audit/auditor.go:265-282 InspectIdentity).

Two modes share this surface:

  - DLOG MODE (round-2 scheme, kept for cheap enrollment):
    Nym = g^sk * h^r per transaction (unlinkable under DDH), two-generator
    Schnorr signatures against the Nym, ECDSA enrollment certificate
    binding eid -> U = g^sk, and NymEID audit info (eid, U, r) letting the
    auditor — and only the auditor — recompute Nym == U * h^r.

  - CREDENTIAL MODE (reference-parity, km.go's actual capability): the
    enrollment authority is ALSO a pairing-based credential issuer
    (services/identity/credential.py, BBS+ over BN254). Enrollment issues
    a credential over the attribute slots (OU, Role, EnrollmentID,
    RevocationHandle); every pseudonym identity then CARRIES an unlinkable
    zero-knowledge proof of credential possession bound to the Nym —
    validators verify "this pseudonym belongs to an enrolled member of
    OU/Role" without learning who, exactly as the reference's idemix MSP
    identity validation does. Per-transaction signatures stay the cheap
    Nym-Schnorr (km.go signs with the nym key too; the credential proof
    lives in the identity, not in every signature).

Audit (both modes): audit info carries (eid, master, r, enrollment cert);
the auditor recomputes Nym == master * h^r and verifies the certificate —
master is U = g^sk in dlog mode and HSk^sk in credential mode; the matcher
is generator-agnostic.

All group work is host-side BN254 (per-tx, not per-proof — it never touches
the TPU batch path); pairings only at enrollment / identity validation.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...crypto import bn254
from ...crypto import serialization as ser
from ...crypto.bn254 import (G1, fr_add, fr_mul, fr_rand, g1_add, g1_mul,
                             g1_neg, hash_to_g1, hash_to_zr)
from ...driver.identity import Identity
from . import credential as cred_mod
from . import typed as typed_mod
from .x509 import X509KeyPair, X509Verifier, new_signing_identity

IDEMIX_TYPE = "idemix"

#: Second pseudonym generator, nothing-up-my-sleeve (hash-to-curve).
H_GEN = hash_to_g1(b"fabric_token_sdk_tpu.idemix.nym.h")
G_GEN = bn254.G1_GENERATOR
#: Credential-mode sk generator — the same point credential.IssuerKey
#: bakes into every issuer public key (single shared constant).
HSK_GEN = cred_mod.H_SK

#: Attribute slot layout, mirroring the reference idemix credential
#: (OU, Role, EnrollmentID, RevocationHandle — km.go attribute order).
ATTR_OU, ATTR_ROLE, ATTR_EID, ATTR_RH = range(4)
N_ATTRS = 4
#: Identity-validation discloses OU + Role, hides EID + RH (the reference's
#: default disclosure mask for transaction identities).
DEFAULT_DISCLOSE = {ATTR_OU, ATTR_ROLE}


class IdemixError(Exception):
    pass


def _schnorr_challenge(nym: G1, t: G1, message: bytes,
                       gen_sk: G1 = None) -> int:
    """Fiat-Shamir challenge binding the ACTUAL generator pair in use —
    dlog-mode (G_GEN) and credential-mode (HSK_GEN) transcripts are
    domain-separated."""
    gen_sk = G_GEN if gen_sk is None else gen_sk
    return hash_to_zr(b"idemix.nym.sig"
                      + ser.g1_to_bytes(gen_sk) + ser.g1_to_bytes(H_GEN)
                      + ser.g1_to_bytes(nym) + ser.g1_to_bytes(t)
                      + message)


@dataclass
class Pseudonym:
    """One per-transaction identity: Nym = gen_sk^sk * h^r.

    In credential mode the identity bytes additionally carry the
    possession proof (presentation) bound to this Nym."""

    nym: G1
    r: int
    presentation: bytes | None = None   # credential-mode possession proof

    def identity(self) -> Identity:
        if self.presentation is None:
            return typed_mod.wrap_with_type(IDEMIX_TYPE,
                                            ser.g1_to_bytes(self.nym))
        payload = ser.der_sequence(
            ser.der_octet_string(ser.g1_to_bytes(self.nym)),
            ser.der_octet_string(self.presentation))
        return typed_mod.wrap_with_type(IDEMIX_TYPE, payload)


def parse_identity(identity_bytes: bytes) -> tuple[G1, bytes | None]:
    """Idemix identity payload -> (nym, presentation | None).

    Legacy dlog identities are exactly the 64-byte G1 encoding; credential
    identities are DER [nym, presentation]."""
    identity_bytes = bytes(identity_bytes)
    if len(identity_bytes) == 64:
        return ser.g1_from_bytes(identity_bytes), None
    seq = ser.DerReader(identity_bytes).read_sequence()
    nym = ser.g1_from_bytes(seq.read_octet_string())
    return nym, seq.read_octet_string()


class NymVerifier:
    """driver.Verifier for a pseudonym: checks the two-generator Schnorr
    PoK (km.go signature verification against the Nym). Credential-mode
    pseudonyms use HSk as the first generator; the PoK transcript pins
    which pair was used."""

    def __init__(self, nym: G1, gen_sk: G1 = G_GEN):
        self.nym = nym
        self.gen_sk = gen_sk

    @classmethod
    def from_typed(cls, identity_bytes: bytes) -> "NymVerifier":
        try:
            nym, presentation = parse_identity(identity_bytes)
        except Exception as e:
            raise IdemixError(f"invalid idemix pseudonym: {e}") from e
        return cls(nym, HSK_GEN if presentation is not None else G_GEN)

    def verify(self, message: bytes, signature: bytes) -> None:
        try:
            seq = ser.DerReader(signature).read_sequence()
            t = ser.g1_from_bytes(seq.read_octet_string())
            z1 = ser.zr_from_bytes(seq.read_octet_string())
            z2 = ser.zr_from_bytes(seq.read_octet_string())
        except Exception as e:
            raise IdemixError(f"malformed idemix signature: {e}") from e
        c = _schnorr_challenge(self.nym, t, message, self.gen_sk)
        # gen_sk^z1 h^z2 == t * Nym^c
        lhs = g1_add(g1_mul(self.gen_sk, z1), g1_mul(H_GEN, z2))
        rhs = g1_add(t, g1_mul(self.nym, c))
        if lhs != rhs:
            raise IdemixError("invalid idemix signature")


class CredentialIdentityVerifier:
    """Identity-level validation for credential-mode pseudonyms: the
    possession proof must verify against the issuer public key and bind
    the Nym (reference idemix MSP identity validation in km.go /
    msp/idemix Validate)."""

    def __init__(self, ipk: cred_mod.IssuerPublicKey):
        self.ipk = ipk

    def validate(self, identity_bytes: bytes) -> dict:
        """Returns the disclosed attribute slots on success."""
        try:
            nym, presentation = parse_identity(identity_bytes)
        except Exception as e:
            raise IdemixError(f"invalid idemix identity: {e}") from e
        if presentation is None:
            raise IdemixError("identity carries no credential proof")
        try:
            pres = cred_mod.Presentation.deserialize(presentation)
            cred_mod.verify_presentation(self.ipk, pres, nym,
                                         b"idemix.identity")
        except cred_mod.CredentialError as e:
            raise IdemixError(f"credential possession proof: {e}") from e
        return dict(pres.disclosed)


class EnrollmentAuthority:
    """Registration CA + (optionally) pairing-based credential issuer.

    Always binds eid -> master key with an ECDSA enrollment certificate
    (the NymEID audit anchor). With `with_credentials=True` it also holds
    a BBS+ issuer key and signs attribute credentials at enrollment — the
    role the idemix issuer plays in the reference (km.go:46-365)."""

    def __init__(self, with_credentials: bool = False):
        self.keys: X509KeyPair = new_signing_identity()
        self.issuer_key: cred_mod.IssuerKey | None = (
            cred_mod.IssuerKey.generate(N_ATTRS, h_rand=H_GEN)
            if with_credentials else None)

    def enroll(self, eid: str, master: G1) -> bytes:
        """Enrollment certificate over (eid, U)."""
        return self.keys.sign(b"idemix.enroll" + eid.encode()
                              + ser.g1_to_bytes(master))

    def issue_credential(self, req: cred_mod.CredentialRequest,
                         nonce: bytes, ou: str, role: str, eid: str,
                         rh: str) -> cred_mod.Credential:
        """Credential over the (OU, Role, EID, RH) attribute slots."""
        if self.issuer_key is None:
            raise IdemixError("authority has no credential issuer key")
        attrs = [cred_mod.attr_to_zr(v) for v in (ou, role, eid, rh)]
        return cred_mod.issue_credential(self.issuer_key, req, nonce, attrs)

    @property
    def issuer_public_key(self) -> cred_mod.IssuerPublicKey | None:
        return self.issuer_key.public if self.issuer_key else None

    def ca_identity(self) -> Identity:
        return self.keys.identity


class IdemixKeyManager:
    """User-side key manager (km.go:46-365): long-term sk, fresh pseudonyms,
    per-pseudonym signing, audit info emission."""

    def __init__(self, eid: str, authority: EnrollmentAuthority,
                 ou: str = "org", role: str = "member"):
        self.eid = eid
        self.sk = fr_rand()
        #: credential mode iff the authority holds an issuer key
        self.ipk = authority.issuer_public_key
        self._gen_sk = HSK_GEN if self.ipk is not None else G_GEN
        self.master = g1_mul(self._gen_sk, self.sk)
        self.cert = authority.enroll(eid, self.master)
        self.credential: cred_mod.Credential | None = None
        if self.ipk is not None:
            nonce = fr_rand().to_bytes(32, "big")
            req = cred_mod.CredentialRequest.create(self.ipk, self.sk, nonce)
            self.credential = authority.issue_credential(
                req, nonce, ou, role, eid, rh=f"rh-{eid}")
            self.credential.verify(self.ipk, self.sk)
        #: nym bytes -> Pseudonym (the wallet registry of own pseudonyms)
        self._mine: dict[bytes, Pseudonym] = {}

    # ------------------------------------------------------------ identity
    def fresh_pseudonym(self) -> Pseudonym:
        """New unlinkable identity for one transaction (km.go pseudonym
        generation); in credential mode the identity carries a fresh
        possession proof bound to the new Nym."""
        r = fr_rand()
        nym = g1_add(self.master, g1_mul(H_GEN, r))
        presentation = None
        if self.credential is not None:
            pres = cred_mod.present(self.ipk, self.credential, self.sk,
                                    nym, r, DEFAULT_DISCLOSE,
                                    b"idemix.identity")
            presentation = pres.serialize()
        p = Pseudonym(nym=nym, r=r, presentation=presentation)
        self._mine[bytes(p.identity())] = p
        return p

    def owns(self, owner_raw: bytes) -> bool:
        return bytes(owner_raw) in self._mine

    # ------------------------------------------------------------- signing
    def sign(self, owner_raw: bytes, message: bytes) -> bytes:
        """Schnorr PoK of (sk, r) for the pseudonym `owner_raw`."""
        p = self._mine.get(bytes(owner_raw))
        if p is None:
            raise IdemixError("unknown pseudonym: cannot sign")
        a, b = fr_rand(), fr_rand()
        t = g1_add(g1_mul(self._gen_sk, a), g1_mul(H_GEN, b))
        c = _schnorr_challenge(p.nym, t, message, self._gen_sk)
        z1 = fr_add(a, fr_mul(c, self.sk))
        z2 = fr_add(b, fr_mul(c, p.r))
        return ser.der_sequence(
            ser.der_octet_string(ser.g1_to_bytes(t)),
            ser.der_octet_string(ser.zr_to_bytes(z1)),
            ser.der_octet_string(ser.zr_to_bytes(z2)),
        )

    # ------------------------------------------------------------ auditing
    def audit_info(self, owner_raw: bytes) -> bytes:
        """NymEID-style audit info: (eid, U, r, enrollment cert) — lets the
        auditor (and only the auditor) recompute and match the pseudonym
        (km.go NymEID audit info; auditor.go:265-282)."""
        p = self._mine.get(bytes(owner_raw))
        if p is None:
            raise IdemixError("unknown pseudonym: no audit info")
        return ser.der_sequence(
            ser.der_octet_string(self.eid.encode()),
            ser.der_octet_string(ser.g1_to_bytes(self.master)),
            ser.der_octet_string(ser.zr_to_bytes(p.r)),
            ser.der_octet_string(self.cert),
        )


class IdemixInfoMatcher:
    """Auditor-side matcher (auditor.go:265-282 InspectIdentity for idemix
    identities): verify the enrollment certificate, recompute the pseudonym
    from (U, r), and require equality with the on-ledger identity."""

    def __init__(self, ca_identity: Identity):
        self.ca = X509Verifier.from_identity(ca_identity)

    def match_identity(self, identity: bytes, audit_info: bytes) -> None:
        try:
            ti = typed_mod.unmarshal_typed_identity(bytes(identity))
        except Exception as e:
            raise IdemixError(f"not a typed identity: {e}") from e
        if ti.type != IDEMIX_TYPE:
            raise IdemixError(f"not an idemix identity [{ti.type}]")
        try:
            nym, _ = parse_identity(ti.identity)
        except Exception as e:
            raise IdemixError(f"invalid idemix identity: {e}") from e
        try:
            seq = ser.DerReader(audit_info).read_sequence()
            eid = seq.read_octet_string().decode()
            master = ser.g1_from_bytes(seq.read_octet_string())
            r = ser.zr_from_bytes(seq.read_octet_string())
            cert = seq.read_octet_string()
        except Exception as e:
            raise IdemixError(f"malformed idemix audit info: {e}") from e
        self.ca.verify(b"idemix.enroll" + eid.encode()
                       + ser.g1_to_bytes(master), cert)
        if g1_add(master, g1_mul(H_GEN, r)) != nym:
            raise IdemixError(
                f"pseudonym does not open to enrollment id [{eid}]")

    def enrollment_id(self, audit_info: bytes) -> str:
        """Recover WHO transacted (auditdb EID locks use this)."""
        seq = ser.DerReader(audit_info).read_sequence()
        return seq.read_octet_string().decode()


class MuxInfoMatcher:
    """Dispatch matcher: idemix identities -> IdemixInfoMatcher; everything
    else -> plain equality (x509 convention in this framework)."""

    def __init__(self, ca_identity: Identity | None = None):
        self.idemix = IdemixInfoMatcher(ca_identity) if ca_identity else None

    def match_identity(self, identity: bytes, audit_info: bytes) -> None:
        try:
            ti = typed_mod.unmarshal_typed_identity(bytes(identity))
            is_idemix = ti.type == IDEMIX_TYPE
        except Exception:
            is_idemix = False
        if is_idemix:
            if self.idemix is None:
                raise IdemixError("no enrollment authority configured")
            self.idemix.match_identity(identity, audit_info)
            return
        if bytes(identity) != bytes(audit_info):
            raise IdemixError("identity does not match audit info")


def idemix_owner_resolver(ti: typed_mod.TypedIdentity):
    """Deserializer hook: TypedIdentity('idemix', nym) -> NymVerifier."""
    if ti.type != IDEMIX_TYPE:
        return None
    return NymVerifier.from_typed(ti.identity)
