"""Owner wallets: who a node is, per token, when receiving and spending.

The node-side slice of the reference identity/wallet registry
(token/services/identity/wallet, role.Owner): a wallet answers
  - recipient_identity(): the identity to put on an output destined to me
    (+ its audit info) — for x509 a stable public key, for Idemix a FRESH
    pseudonym per call (recipients.go exchange semantics);
  - owns(owner_raw): is this on-ledger identity mine (ownership resolution
    at ingestion, tokens.go:64-129);
  - sign(owner_raw, message): endorse a spend of the token owned by
    owner_raw (ttx/endorse.go:719 signing view);
  - audit_info_for(owner_raw): sender-side audit info for the request
    metadata (km.go NymEID audit info / x509 equality convention).
"""

from __future__ import annotations

from .idemix import IdemixKeyManager
from .x509 import X509KeyPair


class X509OwnerWallet:
    """Long-term-key wallet: one stable, linkable owner identity."""

    def __init__(self, keys: X509KeyPair):
        self.keys = keys
        # the registry persists long-term wallets to IdentityDB by this
        # attribute; pseudonymous wallets have none
        self.long_term_identity = bytes(keys.identity)

    def recipient_identity(self) -> tuple[bytes, bytes]:
        ident = bytes(self.keys.identity)
        return ident, ident

    def owns(self, owner_raw: bytes) -> bool:
        return bytes(owner_raw) == bytes(self.keys.identity)

    def sign(self, owner_raw: bytes, message: bytes) -> bytes:
        return self.keys.sign(message)

    def audit_info_for(self, owner_raw: bytes) -> bytes:
        return bytes(owner_raw)


class IdemixOwnerWallet:
    """Pseudonymous wallet: unlinkable fresh identity per receipt."""

    def __init__(self, km: IdemixKeyManager):
        self.km = km

    def recipient_identity(self) -> tuple[bytes, bytes]:
        p = self.km.fresh_pseudonym()
        raw = bytes(p.identity())
        return raw, self.km.audit_info(raw)

    def owns(self, owner_raw: bytes) -> bool:
        return self.km.owns(owner_raw)

    def sign(self, owner_raw: bytes, message: bytes) -> bytes:
        return self.km.sign(owner_raw, message)

    def audit_info_for(self, owner_raw: bytes) -> bytes:
        return self.km.audit_info(owner_raw)
