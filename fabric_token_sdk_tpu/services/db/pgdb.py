"""PostgreSQL backend: the sqlite query layer over a DB-API driver.

Mirrors the reference's multi-driver SQL design (token/services/db/sql:
ONE schema + query layer in db/sql/common, thin per-driver dialects in
db/sql/{sqlite,postgres}): every store subclasses its sqldb counterpart
and only the dialect changes. Translation happens at the connection
boundary, so the store logic stays written once:

  - `?` placeholders              -> `%s`
  - `INSERT OR REPLACE INTO t`    -> `INSERT ... ON CONFLICT (pk) DO UPDATE`
    (primary keys harvested from the shared SCHEMA declarations)
  - `BLOB` / `x''`                -> `BYTEA` / `''::bytea`
  - `INTEGER PRIMARY KEY AUTOINCREMENT` -> `BIGSERIAL PRIMARY KEY`
  - sqlite3.IntegrityError        -> driver IntegrityError (re-raised as
    the shared type so store-level except clauses fire identically)

The driver module (psycopg2 or any DB-API 2 module with pyformat/format
paramstyle) is injected, keeping this importable — and the translation
logic testable with a fake connection — on hosts without postgres
(reference runs its postgres contract tests only under testcontainers;
tests/test_db_contract.py skips the postgres backend the same way).
"""

from __future__ import annotations

import re
import sqlite3
import threading

from . import sqldb


def _pk_columns(schema: str) -> dict[str, str]:
    """Harvest table -> 'col, col' primary-key map from a CREATE script."""
    out: dict[str, str] = {}
    for table_sql in schema.split(";"):
        m = re.search(r"CREATE TABLE IF NOT EXISTS (\w+)", table_sql)
        if not m:
            continue
        table = m.group(1)
        pk = re.search(r"PRIMARY KEY \(([^)]*)\)", table_sql)
        if pk:
            out[table] = pk.group(1).strip()
            continue
        # inline form: "<col> <TYPE> ... PRIMARY KEY" on one column line
        for line in table_sql.splitlines():
            inline = re.match(r"\s*(\w+)\s+\w+.*PRIMARY KEY", line)
            if inline and "CREATE TABLE" not in line:
                out[table] = inline.group(1)
                break
    return out


def translate_schema(schema: str) -> str:
    """sqlite DDL -> postgres DDL for the shared store schemas."""
    s = schema
    s = s.replace("INTEGER PRIMARY KEY AUTOINCREMENT",
                  "BIGSERIAL PRIMARY KEY")
    s = s.replace("BLOB", "BYTEA")
    s = s.replace("x''", "''::bytea")
    s = re.sub(r"\bREAL\b", "DOUBLE PRECISION", s)
    s = re.sub(r"\bINTEGER\b", "BIGINT", s)
    return s


def translate_query(sql: str, pks: dict[str, str]) -> str:
    """One sqlite query -> postgres. Placeholders and upserts only — the
    stores use no other sqlite-isms in DML."""
    sql = sql.replace("?", "%s")
    m = re.match(r"\s*INSERT OR REPLACE INTO (\w+)\s*(\(([^)]*)\))?\s*"
                 r"VALUES\s*(\(.*\))", sql, re.S)
    if m:
        table, _, cols, values = m.groups()
        pk = pks.get(table)
        if pk is None:
            raise ValueError(f"no primary key known for table [{table}]")
        if cols is None:
            raise ValueError(
                f"INSERT OR REPLACE into [{table}] must list columns for "
                "the postgres dialect")
        col_list = [c.strip() for c in cols.split(",")]
        pk_cols = {c.strip() for c in pk.split(",")}
        updates = [f"{c} = EXCLUDED.{c}" for c in col_list
                   if c not in pk_cols]
        action = (f"DO UPDATE SET {', '.join(updates)}" if updates
                  else "DO NOTHING")
        return (f"INSERT INTO {table} ({', '.join(col_list)}) "
                f"VALUES {values} ON CONFLICT ({pk}) {action}")
    return sql


class _Cursorish:
    """The slice of sqlite3's connection-level execute API the stores use,
    emulated over a DB-API cursor."""

    def __init__(self, cursor):
        self._cursor = cursor
        self.rowcount = cursor.rowcount

    def fetchone(self):
        return self._cursor.fetchone()

    def fetchall(self):
        return self._cursor.fetchall()


class _Prefetched:
    """Result of a SELECT whose transaction was already closed."""

    def __init__(self, rows):
        self._rows = list(rows)
        self.rowcount = len(self._rows)

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self):
        return self._rows


class PGConnection:
    """Adapter giving a DB-API postgres connection the sqlite3 connection
    surface the shared stores rely on (execute/executemany/executescript/
    commit/close), translating each statement on the way through."""

    def __init__(self, dbapi_conn, driver_module, pks: dict[str, str]):
        self._conn = dbapi_conn
        self._driver = driver_module
        self._pks = pks
        # Uncommitted-DML flag: a SELECT normally ends its implicit read
        # transaction with a rollback (no idle-in-transaction), but doing
        # that after uncommitted writes would silently discard them — a
        # read-modify-write store would pass on sqlite and lose data here.
        self._dirty = False

    def execute(self, sql: str, params=()):
        translated = translate_query(sql, self._pks)
        cur = self._conn.cursor()
        try:
            cur.execute(translated, tuple(params))
        except self._driver.IntegrityError as e:
            self._conn.rollback()
            self._dirty = False
            raise sqlite3.IntegrityError(str(e)) from e
        except Exception:
            # any other failure would leave a real postgres connection in
            # an aborted transaction, wedging every later statement
            self._conn.rollback()
            self._dirty = False
            raise
        if translated.lstrip().upper().startswith("SELECT"):
            # end the implicit read transaction (no idle-in-transaction);
            # rows are prefetched so the caller's fetch still works —
            # UNLESS uncommitted DML is pending on this connection, in
            # which case the transaction must stay open until commit()
            rows = cur.fetchall()
            if not self._dirty:
                self._conn.rollback()
            return _Prefetched(rows)
        self._dirty = True
        return _Cursorish(cur)

    def executemany(self, sql: str, seq_of_params):
        cur = self._conn.cursor()
        try:
            cur.executemany(translate_query(sql, self._pks),
                            [tuple(p) for p in seq_of_params])
        except self._driver.IntegrityError as e:
            self._conn.rollback()
            self._dirty = False
            raise sqlite3.IntegrityError(str(e)) from e
        except Exception:
            self._conn.rollback()
            self._dirty = False
            raise
        self._dirty = True
        return _Cursorish(cur)

    def executescript(self, script: str):
        cur = self._conn.cursor()
        try:
            for stmt in translate_schema(script).split(";"):
                if stmt.strip():
                    cur.execute(stmt)
        except Exception:
            # same aborted-transaction hygiene as execute()
            self._conn.rollback()
            self._dirty = False
            raise
        self._dirty = True

    def commit(self):
        self._conn.commit()
        self._dirty = False

    def close(self):
        self._conn.close()


def _pg_base(store_cls):
    """Build the postgres variant of one sqldb store class."""

    class _PGStore(store_cls):
        def __init__(self, dsn: str, driver_module=None):
            if driver_module is None:
                import psycopg2 as driver_module  # noqa: PLC0415
            # bypass sqldb._Base.__init__ (sqlite connect); same schema
            self.conn = PGConnection(driver_module.connect(dsn),
                                     driver_module,
                                     _pk_columns(self.SCHEMA))
            self._mu = threading.RLock()
            with self._mu:
                self.conn.executescript(self.SCHEMA)
                self.conn.commit()

    _PGStore.__name__ = store_cls.__name__
    _PGStore.__qualname__ = f"pg.{store_cls.__name__}"
    return _PGStore


TokenDB = _pg_base(sqldb.TokenDB)
TransactionDB = _pg_base(sqldb.TransactionDB)
AuditDB = _pg_base(sqldb.AuditDB)
TokenLockDB = _pg_base(sqldb.TokenLockDB)
IdentityDB = _pg_base(sqldb.IdentityDB)
CertificationDB = _pg_base(sqldb.CertificationDB)

# re-exported shared contract types
DBError = sqldb.DBError
TxRecord = sqldb.TxRecord
TxStatus = sqldb.TxStatus


def available() -> bool:
    """True when a postgres driver module is importable (server liveness is
    the contract tests' concern, mirroring dbtest + testcontainers)."""
    try:
        import psycopg2  # noqa: F401, PLC0415
    except ImportError:
        return False
    return True
