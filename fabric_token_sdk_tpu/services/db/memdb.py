"""Pure in-memory DB backend: same contract as the sqlite driver.

The reference ships one SQL schema with multiple drivers (sqlite, postgres,
memory — token/services/db/sql/*, db/dbtest) and runs ONE shared test
suite against all of them. This is the memory driver: plain dicts behind
the exact TokenDB/TransactionDB/AuditDB/TokenLockDB/IdentityDB API, for
tests and ephemeral nodes where durability is not wanted.

tests/test_db_contract.py runs the shared contract suite against both this
module and sqldb.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace

from ...token.model import ID, UnspentToken
from .sqldb import DBError, TxRecord, TxStatus  # shared contract types


class _Base:
    def __init__(self, path: str = ":memory:"):
        # path accepted for driver-interface parity; always ephemeral
        self._mu = threading.RLock()

    def close(self) -> None:
        pass


@dataclass
class _TokenRow:
    owner_raw: bytes
    token_type: str
    quantity: str
    amount: int
    ledger_format: str = ""
    ledger_token: bytes = b""
    ledger_metadata: bytes = b""
    is_deleted: bool = False
    spent_by: str = ""
    spendable: bool = True
    owners: list[str] = field(default_factory=list)


class TokenDB(_Base):
    def __init__(self, path: str = ":memory:"):
        super().__init__(path)
        self._rows: dict[tuple[str, int], _TokenRow] = {}

    def store_token(self, token_id: ID, owner_raw: bytes, token_type: str,
                    quantity_hex: str, owners: list[str],
                    ledger_format: str = "", ledger_token: bytes = b"",
                    ledger_metadata: bytes = b"",
                    spendable: bool = True) -> None:
        with self._mu:
            self._rows[(token_id.tx_id, token_id.index)] = _TokenRow(
                owner_raw=bytes(owner_raw), token_type=token_type,
                quantity=quantity_hex, amount=int(quantity_hex, 16),
                ledger_format=ledger_format, ledger_token=ledger_token,
                ledger_metadata=ledger_metadata, spendable=spendable,
                owners=list(owners))

    def delete_token(self, token_id: ID, spent_by: str) -> None:
        with self._mu:
            row = self._rows.get((token_id.tx_id, token_id.index))
            if row is not None:
                row.is_deleted = True
                row.spent_by = spent_by

    def is_mine(self, token_id: ID, wallet_id: str) -> bool:
        with self._mu:
            row = self._rows.get((token_id.tx_id, token_id.index))
            return row is not None and wallet_id in row.owners

    def unspent_tokens(self, wallet_id: str | None = None,
                       token_type: str | None = None) -> list[UnspentToken]:
        with self._mu:
            out = []
            for (tx_id, idx), row in sorted(self._rows.items()):
                if row.is_deleted:
                    continue
                if wallet_id is not None and wallet_id not in row.owners:
                    continue
                if token_type is not None and row.token_type != token_type:
                    continue
                out.append(UnspentToken(id=ID(tx_id, idx),
                                        owner=row.owner_raw,
                                        type=row.token_type,
                                        quantity=row.quantity))
            return out

    def balance(self, wallet_id: str | None, token_type: str) -> int:
        with self._mu:
            total = 0
            for row in self._rows.values():
                if row.is_deleted or row.token_type != token_type:
                    continue
                if wallet_id is not None and wallet_id not in row.owners:
                    continue
                total += row.amount
            return total

    def get_token(self, token_id: ID, include_deleted: bool = False):
        with self._mu:
            row = self._rows.get((token_id.tx_id, token_id.index))
            if row is None or (row.is_deleted and not include_deleted):
                return None
            return UnspentToken(id=token_id, owner=row.owner_raw,
                                type=row.token_type, quantity=row.quantity)

    def get_ledger_token(self, token_id: ID) -> tuple[bytes, bytes] | None:
        with self._mu:
            row = self._rows.get((token_id.tx_id, token_id.index))
            if row is None or row.is_deleted:
                return None
            return (row.ledger_token, row.ledger_metadata)

    def whose(self, token_id: ID) -> list[str]:
        with self._mu:
            row = self._rows.get((token_id.tx_id, token_id.index))
            return list(row.owners) if row else []


class TransactionDB(_Base):
    def __init__(self, path: str = ":memory:"):
        super().__init__(path)
        self._transactions: list[TxRecord] = []
        self._requests: dict[str, bytes] = {}
        self._status: dict[str, tuple[str, str]] = {}
        self._acks: dict[str, dict[bytes, bytes]] = {}
        self._validations: dict[str, tuple[bytes, dict]] = {}

    def add_transaction(self, rec: TxRecord) -> None:
        with self._mu:
            # copy on write: sqldb materializes rows, so live references
            # must not alias the store across the shared contract
            self._transactions.append(replace(rec))
            self._status.setdefault(rec.tx_id, (rec.status, ""))

    def add_token_request(self, tx_id: str, request: bytes,
                          status: str = TxStatus.PENDING) -> None:
        with self._mu:
            self._requests[tx_id] = request
            self._status.setdefault(tx_id, (status, ""))

    def get_token_request(self, tx_id: str) -> bytes | None:
        with self._mu:
            return self._requests.get(tx_id)

    def set_status(self, tx_id: str, status: str, message: str = "") -> None:
        with self._mu:
            self._status[tx_id] = (status, message)
            for rec in self._transactions:
                if rec.tx_id == tx_id:
                    rec.status = status

    def get_status(self, tx_id: str) -> str:
        with self._mu:
            return self._status.get(tx_id, (TxStatus.UNKNOWN, ""))[0]

    def query_transactions(self, tx_id: str | None = None,
                           statuses: list[str] | None = None,
                           action_type: str | None = None) -> list[TxRecord]:
        with self._mu:
            out = []
            for rec in self._transactions:
                if tx_id is not None and rec.tx_id != tx_id:
                    continue
                if statuses and rec.status not in statuses:
                    continue
                if action_type is not None and rec.action_type != action_type:
                    continue
                out.append(replace(rec))
            return out

    def add_endorsement_ack(self, tx_id: str, endorser: bytes,
                            sigma: bytes) -> None:
        with self._mu:
            self._acks.setdefault(tx_id, {})[bytes(endorser)] = sigma

    def get_endorsement_acks(self, tx_id: str) -> dict[bytes, bytes]:
        with self._mu:
            return dict(self._acks.get(tx_id, {}))

    def add_validation_record(self, tx_id: str, token_request: bytes,
                              metadata: bytes = b"") -> None:
        with self._mu:
            self._validations[tx_id] = (token_request, metadata)


class AuditDB(TransactionDB):
    def __init__(self, path: str = ":memory:"):
        super().__init__(path)
        self._locks: dict[str, str] = {}  # eid -> tx_id

    def acquire_locks(self, tx_id: str, eids: list[str]) -> None:
        with self._mu:
            for eid in eids:
                holder = self._locks.get(eid)
                if holder is not None and holder != tx_id:
                    raise DBError(
                        f"eid [{eid}] already locked by [{holder}]")
            for eid in eids:
                self._locks[eid] = tx_id

    def release_locks(self, tx_id: str) -> None:
        with self._mu:
            for eid in [e for e, t in self._locks.items() if t == tx_id]:
                del self._locks[eid]

    def locked_eids(self) -> list[str]:
        with self._mu:
            return sorted(self._locks)

    def payments(self, eid_field: str, token_type: str | None = None
                 ) -> list[TxRecord]:
        with self._mu:
            out = []
            for rec in self._transactions:
                if eid_field not in (rec.sender, rec.recipient):
                    continue
                if token_type is not None and rec.token_type != token_type:
                    continue
                out.append(replace(rec))
            return out


class TokenLockDB(_Base):
    def __init__(self, path: str = ":memory:"):
        super().__init__(path)
        self._locks: dict[tuple[str, int], tuple[str, float]] = {}

    def lock(self, token_id: ID, consumer_tx_id: str) -> bool:
        with self._mu:
            key = (token_id.tx_id, token_id.index)
            holder = self._locks.get(key)
            if holder is not None:
                # re-entrant for the same consumer; the lease timestamp is
                # NOT refreshed (matches the sqlite driver, where the
                # original INSERT's created_at stands)
                return holder[0] == consumer_tx_id
            self._locks[key] = (consumer_tx_id, time.time())
            return True

    def unlock_by_consumer(self, consumer_tx_id: str) -> None:
        with self._mu:
            for key in [k for k, (c, _) in self._locks.items()
                        if c == consumer_tx_id]:
                del self._locks[key]

    def holder(self, token_id: ID) -> str | None:
        with self._mu:
            entry = self._locks.get((token_id.tx_id, token_id.index))
            return entry[0] if entry else None

    def evict_expired(self, lease_seconds: float) -> int:
        with self._mu:
            now = time.time()
            expired = [k for k, (_, t) in self._locks.items()
                       if now - t > lease_seconds]
            for k in expired:
                del self._locks[k]
            return len(expired)


class CertificationDB(_Base):
    def __init__(self, path: str = ":memory:"):
        super().__init__(path)
        self._certs: dict[tuple[str, int], bytes] = {}

    def exists(self, token_id: ID) -> bool:
        with self._mu:
            return (token_id.tx_id, token_id.index) in self._certs

    def store(self, certifications: dict[ID, bytes]) -> None:
        with self._mu:
            for i, c in certifications.items():
                self._certs[(i.tx_id, i.index)] = bytes(c)

    def get(self, token_id: ID) -> bytes | None:
        with self._mu:
            return self._certs.get((token_id.tx_id, token_id.index))


class IdentityDB(_Base):
    def __init__(self, path: str = ":memory:"):
        super().__init__(path)
        self._wallets: dict[tuple[str, str], tuple[bytes, bytes]] = {}
        self._audit_info: dict[bytes, bytes] = {}

    def register_wallet(self, wallet_id: str, role: str, identity: bytes,
                        config: bytes = b"") -> None:
        with self._mu:
            self._wallets[(wallet_id, role)] = (bytes(identity), config)

    def wallet_identity(self, wallet_id: str, role: str) -> bytes | None:
        with self._mu:
            entry = self._wallets.get((wallet_id, role))
            return entry[0] if entry else None

    def wallets(self, role: str | None = None) -> list[tuple[str, str, bytes]]:
        with self._mu:
            out = []
            for (wid, r), (ident, _) in sorted(self._wallets.items()):
                if role is not None and r != role:
                    continue
                out.append((wid, r, ident))
            return out

    def store_audit_info(self, identity: bytes, audit_info: bytes) -> None:
        with self._mu:
            self._audit_info[bytes(identity)] = audit_info

    def get_audit_info(self, identity: bytes) -> bytes | None:
        with self._mu:
            return self._audit_info.get(bytes(identity))
