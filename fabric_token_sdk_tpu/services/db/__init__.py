"""Database facades: token, transaction, audit, identity, token-lock stores.

Mirrors reference token/services/db + db/sql (SURVEY.md §2.4 "db/sql"): one
schema + query layer serving all five DBs, with sqlite (file or :memory:)
as the default backend — the reference's sqlite/postgres/unity/memory driver
matrix collapses to sqlite-file and sqlite-memory here, behind the same
facade API so a postgres driver can slot in later.
"""

from .sqldb import (  # noqa: F401
    TokenDB,
    TransactionDB,
    AuditDB,
    TokenLockDB,
    IdentityDB,
    CertificationDB,
    TxStatus,
)
