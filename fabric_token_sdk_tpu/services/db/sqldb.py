"""SQLite-backed stores for tokens, transactions, audit records, locks.

Behavioral mirror of the reference SQL layer (token/services/db/sql/common:
tokens.go:38-560, transactions, auditdb, tokenlockdb) over Python sqlite3.
All stores accept a path or ":memory:"; connections are serialized behind a
lock (sqlite3 default isolation), which stands in for the reference's
per-driver connection pools.

Quantities are stored as the canonical "0x" hex string plus a numeric
column for range/balance queries (precision <= 64 fits SQLite INTEGER).
"""

from __future__ import annotations

import functools
import sqlite3
import threading
import time
from dataclasses import dataclass

from ...obs import GLOBAL as _METRICS
from ...token.model import ID, UnspentToken


class DBError(Exception):
    pass


def _timed(fn):
    """Per-method latency histogram ``db_<method>_seconds{db=<Class>}``
    on the store methods the ttx hot path hits (token ingest, selection
    scans, status flips, lock takes)."""
    name = f"db_{fn.__name__}_seconds"

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        t0 = time.perf_counter()
        try:
            return fn(self, *args, **kwargs)
        finally:
            _METRICS.histogram(name, db=type(self).__name__).observe(
                time.perf_counter() - t0)

    return wrapper


class TxStatus:
    """ttxdb status machine (reference ttxdb/db.go:60-100)."""

    UNKNOWN = "Unknown"
    PENDING = "Pending"
    CONFIRMED = "Confirmed"
    DELETED = "Deleted"


class _Base:
    def __init__(self, path: str = ":memory:"):
        self.conn = sqlite3.connect(path, check_same_thread=False)
        self._mu = threading.RLock()
        with self._mu:
            self.conn.executescript(self.SCHEMA)
            self.conn.commit()

    def close(self) -> None:
        self.conn.close()


class TokenDB(_Base):
    """Unspent-token store + ownership index (db/sql/common/tokens.go)."""

    SCHEMA = """
    CREATE TABLE IF NOT EXISTS tokens (
        tx_id TEXT NOT NULL,
        idx INTEGER NOT NULL,
        owner_raw BLOB NOT NULL,
        token_type TEXT NOT NULL,
        quantity TEXT NOT NULL,
        amount INTEGER NOT NULL,
        ledger_format TEXT NOT NULL DEFAULT '',
        ledger_token BLOB NOT NULL DEFAULT x'',
        ledger_metadata BLOB NOT NULL DEFAULT x'',
        is_deleted INTEGER NOT NULL DEFAULT 0,
        spent_by TEXT NOT NULL DEFAULT '',
        spendable INTEGER NOT NULL DEFAULT 1,
        PRIMARY KEY (tx_id, idx)
    );
    CREATE TABLE IF NOT EXISTS ownership (
        tx_id TEXT NOT NULL,
        idx INTEGER NOT NULL,
        wallet_id TEXT NOT NULL,
        PRIMARY KEY (tx_id, idx, wallet_id)
    );
    CREATE INDEX IF NOT EXISTS idx_tokens_live
        ON tokens (is_deleted, token_type);
    """

    @_timed
    def store_token(self, token_id: ID, owner_raw: bytes, token_type: str,
                    quantity_hex: str, owners: list[str],
                    ledger_format: str = "", ledger_token: bytes = b"",
                    ledger_metadata: bytes = b"",
                    spendable: bool = True) -> None:
        amount = int(quantity_hex, 16)
        with self._mu:
            self.conn.execute(
                "INSERT OR REPLACE INTO tokens (tx_id, idx, owner_raw, "
                "token_type, quantity, amount, ledger_format, ledger_token, "
                "ledger_metadata, spendable) VALUES (?,?,?,?,?,?,?,?,?,?)",
                (token_id.tx_id, token_id.index, owner_raw, token_type,
                 quantity_hex, amount, ledger_format, ledger_token,
                 ledger_metadata, int(spendable)))
            for wid in owners:
                self.conn.execute(
                    "INSERT OR REPLACE INTO ownership (tx_id, idx, wallet_id)"
                    " VALUES (?,?,?)", (token_id.tx_id, token_id.index, wid))
            self.conn.commit()

    @_timed
    def delete_token(self, token_id: ID, spent_by: str) -> None:
        with self._mu:
            self.conn.execute(
                "UPDATE tokens SET is_deleted = 1, spent_by = ? "
                "WHERE tx_id = ? AND idx = ?",
                (spent_by, token_id.tx_id, token_id.index))
            self.conn.commit()

    def is_mine(self, token_id: ID, wallet_id: str) -> bool:
        with self._mu:
            row = self.conn.execute(
                "SELECT 1 FROM ownership WHERE tx_id=? AND idx=? AND "
                "wallet_id=?",
                (token_id.tx_id, token_id.index, wallet_id)).fetchone()
        return row is not None

    @_timed
    def unspent_tokens(self, wallet_id: str | None = None,
                       token_type: str | None = None) -> list[UnspentToken]:
        q = ("SELECT t.tx_id, t.idx, t.owner_raw, t.token_type, t.quantity "
             "FROM tokens t")
        params: list = []
        clauses = ["t.is_deleted = 0"]
        if wallet_id is not None:
            q += " JOIN ownership o ON t.tx_id=o.tx_id AND t.idx=o.idx"
            clauses.append("o.wallet_id = ?")
            params.append(wallet_id)
        if token_type is not None:
            clauses.append("t.token_type = ?")
            params.append(token_type)
        q += " WHERE " + " AND ".join(clauses) + " ORDER BY t.tx_id, t.idx"
        with self._mu:
            rows = self.conn.execute(q, params).fetchall()
        return [UnspentToken(id=ID(r[0], r[1]), owner=r[2], type=r[3],
                             quantity=r[4]) for r in rows]

    def balance(self, wallet_id: str | None, token_type: str) -> int:
        q = "SELECT COALESCE(SUM(t.amount), 0) FROM tokens t"
        params: list = []
        clauses = ["t.is_deleted = 0", "t.token_type = ?"]
        params2 = [token_type]
        if wallet_id is not None:
            q += " JOIN ownership o ON t.tx_id=o.tx_id AND t.idx=o.idx"
            clauses.append("o.wallet_id = ?")
            params2.append(wallet_id)
        q += " WHERE " + " AND ".join(clauses)
        with self._mu:
            row = self.conn.execute(q, params + params2).fetchone()
        return int(row[0])

    def get_token(self, token_id: ID, include_deleted: bool = False):
        q = ("SELECT tx_id, idx, owner_raw, token_type, quantity, is_deleted "
             "FROM tokens WHERE tx_id=? AND idx=?")
        with self._mu:
            row = self.conn.execute(
                q, (token_id.tx_id, token_id.index)).fetchone()
        if row is None or (row[5] and not include_deleted):
            return None
        return UnspentToken(id=ID(row[0], row[1]), owner=row[2], type=row[3],
                            quantity=row[4])

    def get_ledger_token(self, token_id: ID) -> tuple[bytes, bytes] | None:
        with self._mu:
            row = self.conn.execute(
                "SELECT ledger_token, ledger_metadata FROM tokens WHERE "
                "tx_id=? AND idx=? AND is_deleted=0",
                (token_id.tx_id, token_id.index)).fetchone()
        return (row[0], row[1]) if row else None

    def whose(self, token_id: ID) -> list[str]:
        with self._mu:
            rows = self.conn.execute(
                "SELECT wallet_id FROM ownership WHERE tx_id=? AND idx=?",
                (token_id.tx_id, token_id.index)).fetchall()
        return [r[0] for r in rows]


@dataclass
class TxRecord:
    tx_id: str
    action_type: str  # "issue" | "transfer" | "redeem"
    sender: str
    recipient: str
    token_type: str
    amount: int
    status: str
    timestamp: float
    application_metadata: bytes = b""


class TransactionDB(_Base):
    """ttxdb: transaction records + endorsement acks (ttxdb/db.go:159-327)."""

    SCHEMA = """
    CREATE TABLE IF NOT EXISTS transactions (
        tx_id TEXT NOT NULL,
        action_type TEXT NOT NULL,
        sender TEXT NOT NULL DEFAULT '',
        recipient TEXT NOT NULL DEFAULT '',
        token_type TEXT NOT NULL DEFAULT '',
        amount INTEGER NOT NULL DEFAULT 0,
        status TEXT NOT NULL,
        status_message TEXT NOT NULL DEFAULT '',
        timestamp REAL NOT NULL,
        application_metadata BLOB NOT NULL DEFAULT x'',
        seq INTEGER PRIMARY KEY AUTOINCREMENT
    );
    CREATE INDEX IF NOT EXISTS idx_tx_id ON transactions (tx_id);
    CREATE TABLE IF NOT EXISTS token_requests (
        tx_id TEXT PRIMARY KEY,
        request BLOB NOT NULL,
        status TEXT NOT NULL
    );
    CREATE TABLE IF NOT EXISTS endorsement_acks (
        tx_id TEXT NOT NULL,
        endorser BLOB NOT NULL,
        sigma BLOB NOT NULL,
        PRIMARY KEY (tx_id, endorser)
    );
    CREATE TABLE IF NOT EXISTS validation_records (
        tx_id TEXT PRIMARY KEY,
        token_request BLOB NOT NULL,
        metadata BLOB NOT NULL DEFAULT x'',
        timestamp REAL NOT NULL
    );
    """

    @_timed
    def add_transaction(self, rec: TxRecord) -> None:
        with self._mu:
            self.conn.execute(
                "INSERT INTO transactions (tx_id, action_type, sender, "
                "recipient, token_type, amount, status, timestamp, "
                "application_metadata) VALUES (?,?,?,?,?,?,?,?,?)",
                (rec.tx_id, rec.action_type, rec.sender, rec.recipient,
                 rec.token_type, rec.amount, rec.status, rec.timestamp,
                 rec.application_metadata))
            self.conn.commit()

    def add_token_request(self, tx_id: str, request: bytes,
                          status: str = TxStatus.PENDING) -> None:
        with self._mu:
            self.conn.execute(
                "INSERT OR REPLACE INTO token_requests (tx_id, request, "
                "status) VALUES (?,?,?)", (tx_id, request, status))
            self.conn.commit()

    def get_token_request(self, tx_id: str) -> bytes | None:
        with self._mu:
            row = self.conn.execute(
                "SELECT request FROM token_requests WHERE tx_id=?",
                (tx_id,)).fetchone()
        return row[0] if row else None

    @_timed
    def set_status(self, tx_id: str, status: str, message: str = "") -> None:
        with self._mu:
            self.conn.execute(
                "UPDATE transactions SET status=?, status_message=? "
                "WHERE tx_id=?", (status, message, tx_id))
            self.conn.execute(
                "UPDATE token_requests SET status=? WHERE tx_id=?",
                (status, tx_id))
            self.conn.commit()

    def get_status(self, tx_id: str) -> str:
        with self._mu:
            row = self.conn.execute(
                "SELECT status FROM transactions WHERE tx_id=? "
                "ORDER BY seq DESC LIMIT 1", (tx_id,)).fetchone()
            if row is None:
                row = self.conn.execute(
                    "SELECT status FROM token_requests WHERE tx_id=?",
                    (tx_id,)).fetchone()
        return row[0] if row else TxStatus.UNKNOWN

    def query_transactions(self, tx_id: str | None = None,
                           statuses: list[str] | None = None,
                           action_type: str | None = None) -> list[TxRecord]:
        q = ("SELECT tx_id, action_type, sender, recipient, token_type, "
             "amount, status, timestamp, application_metadata "
             "FROM transactions")
        clauses, params = [], []
        if tx_id is not None:
            clauses.append("tx_id = ?")
            params.append(tx_id)
        if statuses:
            clauses.append(
                "status IN (" + ",".join("?" * len(statuses)) + ")")
            params.extend(statuses)
        if action_type is not None:
            clauses.append("action_type = ?")
            params.append(action_type)
        if clauses:
            q += " WHERE " + " AND ".join(clauses)
        q += " ORDER BY seq"
        with self._mu:
            rows = self.conn.execute(q, params).fetchall()
        return [TxRecord(*r) for r in rows]

    def add_endorsement_ack(self, tx_id: str, endorser: bytes,
                            sigma: bytes) -> None:
        with self._mu:
            self.conn.execute(
                "INSERT OR REPLACE INTO endorsement_acks (tx_id, endorser, sigma) "
                "VALUES (?,?,?)",
                (tx_id, endorser, sigma))
            self.conn.commit()

    def get_endorsement_acks(self, tx_id: str) -> dict[bytes, bytes]:
        with self._mu:
            rows = self.conn.execute(
                "SELECT endorser, sigma FROM endorsement_acks WHERE tx_id=?",
                (tx_id,)).fetchall()
        return {r[0]: r[1] for r in rows}

    def add_validation_record(self, tx_id: str, token_request: bytes,
                              metadata: bytes = b"") -> None:
        with self._mu:
            self.conn.execute(
                "INSERT OR REPLACE INTO validation_records (tx_id, token_request, "
                "metadata, timestamp) VALUES (?,?,?,?)",
                (tx_id, token_request, metadata, time.time()))
            self.conn.commit()


class AuditDB(TransactionDB):
    """auditdb: audit records + enrollment-ID locks (auditdb/db.go)."""

    SCHEMA = TransactionDB.SCHEMA + """
    CREATE TABLE IF NOT EXISTS eid_locks (
        eid TEXT NOT NULL,
        tx_id TEXT NOT NULL,
        created_at REAL NOT NULL,
        PRIMARY KEY (eid, tx_id)
    );
    """

    def acquire_locks(self, tx_id: str, eids: list[str]) -> None:
        """All-or-nothing EID locking (auditor/auditor.go:80-100): an eid
        held by ANOTHER transaction conflicts; re-acquiring under the same
        transaction is idempotent."""
        with self._mu:
            for eid in eids:
                row = self.conn.execute(
                    "SELECT tx_id FROM eid_locks WHERE eid=?",
                    (eid,)).fetchone()
                if row is not None and row[0] != tx_id:
                    raise DBError(
                        f"eid [{eid}] already locked by [{row[0]}]")
            for eid in eids:
                self.conn.execute(
                    "INSERT OR REPLACE INTO eid_locks (eid, tx_id, created_at) "
                    "VALUES (?,?,?)",
                    (eid, tx_id, time.time()))
            self.conn.commit()

    def release_locks(self, tx_id: str) -> None:
        with self._mu:
            self.conn.execute("DELETE FROM eid_locks WHERE tx_id=?", (tx_id,))
            self.conn.commit()

    def locked_eids(self) -> list[str]:
        with self._mu:
            rows = self.conn.execute(
                "SELECT DISTINCT eid FROM eid_locks").fetchall()
        return [r[0] for r in rows]

    # payments/holdings filters (auditdb/db.go payments/holdings)
    def payments(self, eid_field: str, token_type: str | None = None
                 ) -> list[TxRecord]:
        recs = self.query_transactions()
        out = [r for r in recs
               if (r.sender == eid_field or r.recipient == eid_field)
               and (token_type is None or r.token_type == token_type)]
        return out


class TokenLockDB(_Base):
    """tokenlockdb: selector lease store (db/sql/common tokenlockdb)."""

    SCHEMA = """
    CREATE TABLE IF NOT EXISTS token_locks (
        tx_id TEXT NOT NULL,
        idx INTEGER NOT NULL,
        consumer_tx_id TEXT NOT NULL,
        created_at REAL NOT NULL,
        PRIMARY KEY (tx_id, idx)
    );
    """

    @_timed
    def lock(self, token_id: ID, consumer_tx_id: str) -> bool:
        """Returns True if the lock was acquired. Re-entrant for the SAME
        consumer (sherdlock lease semantics)."""
        with self._mu:
            try:
                self.conn.execute(
                    "INSERT INTO token_locks VALUES (?,?,?,?)",
                    (token_id.tx_id, token_id.index, consumer_tx_id,
                     time.time()))
                self.conn.commit()
                return True
            except sqlite3.IntegrityError:
                row = self.conn.execute(
                    "SELECT consumer_tx_id FROM token_locks WHERE tx_id=? "
                    "AND idx=?",
                    (token_id.tx_id, token_id.index)).fetchone()
                return row is not None and row[0] == consumer_tx_id

    def unlock_by_consumer(self, consumer_tx_id: str) -> None:
        with self._mu:
            self.conn.execute(
                "DELETE FROM token_locks WHERE consumer_tx_id=?",
                (consumer_tx_id,))
            self.conn.commit()

    def holder(self, token_id: ID) -> str | None:
        with self._mu:
            row = self.conn.execute(
                "SELECT consumer_tx_id FROM token_locks WHERE tx_id=? AND "
                "idx=?", (token_id.tx_id, token_id.index)).fetchone()
        return row[0] if row else None

    def evict_expired(self, lease_seconds: float) -> int:
        cutoff = time.time() - lease_seconds
        with self._mu:
            cur = self.conn.execute(
                "DELETE FROM token_locks WHERE created_at < ?", (cutoff,))
            self.conn.commit()
            return cur.rowcount


class CertificationDB(_Base):
    """Token-certification store (reference sdk/vault CertificationStorage:
    Exists/Store over the vault's certification section)."""

    SCHEMA = """
    CREATE TABLE IF NOT EXISTS certifications (
        tx_id TEXT NOT NULL,
        idx INTEGER NOT NULL,
        certification BLOB NOT NULL,
        PRIMARY KEY (tx_id, idx)
    );
    """

    def exists(self, token_id: ID) -> bool:
        with self._mu:
            row = self.conn.execute(
                "SELECT 1 FROM certifications WHERE tx_id=? AND idx=?",
                (token_id.tx_id, token_id.index)).fetchone()
        return row is not None

    def store(self, certifications: dict[ID, bytes]) -> None:
        with self._mu:
            self.conn.executemany(
                "INSERT OR REPLACE INTO certifications (tx_id, idx, certification) "
                "VALUES (?,?,?)",
                [(i.tx_id, i.index, c) for i, c in certifications.items()])
            self.conn.commit()

    def get(self, token_id: ID) -> bytes | None:
        with self._mu:
            row = self.conn.execute(
                "SELECT certification FROM certifications WHERE tx_id=? AND "
                "idx=?", (token_id.tx_id, token_id.index)).fetchone()
        return row[0] if row else None


class IdentityDB(_Base):
    """identitydb: wallet/identity persistence (identitydb, SURVEY §2.4)."""

    SCHEMA = """
    CREATE TABLE IF NOT EXISTS wallets (
        wallet_id TEXT NOT NULL,
        role TEXT NOT NULL,
        identity BLOB NOT NULL,
        enrollment_id TEXT NOT NULL DEFAULT '',
        created_at REAL NOT NULL,
        PRIMARY KEY (wallet_id, role)
    );
    CREATE TABLE IF NOT EXISTS audit_infos (
        identity BLOB PRIMARY KEY,
        audit_info BLOB NOT NULL
    );
    CREATE TABLE IF NOT EXISTS signer_infos (
        identity BLOB PRIMARY KEY,
        signer_info BLOB NOT NULL
    );
    """

    def register_wallet(self, wallet_id: str, role: str, identity: bytes,
                        enrollment_id: str = "") -> None:
        with self._mu:
            self.conn.execute(
                "INSERT OR REPLACE INTO wallets (wallet_id, role, identity, "
                "enrollment_id, created_at) VALUES (?,?,?,?,?)",
                (wallet_id, role, identity, enrollment_id, time.time()))
            self.conn.commit()

    def wallet_identity(self, wallet_id: str, role: str) -> bytes | None:
        with self._mu:
            row = self.conn.execute(
                "SELECT identity FROM wallets WHERE wallet_id=? AND role=?",
                (wallet_id, role)).fetchone()
        return row[0] if row else None

    def wallets(self, role: str | None = None) -> list[tuple[str, str, bytes]]:
        q = "SELECT wallet_id, role, identity FROM wallets"
        params = []
        if role is not None:
            q += " WHERE role=?"
            params.append(role)
        with self._mu:
            return self.conn.execute(q, params).fetchall()

    def store_audit_info(self, identity: bytes, audit_info: bytes) -> None:
        with self._mu:
            self.conn.execute(
                "INSERT OR REPLACE INTO audit_infos (identity, audit_info) "
                "VALUES (?,?)",
                (identity, audit_info))
            self.conn.commit()

    def get_audit_info(self, identity: bytes) -> bytes | None:
        with self._mu:
            row = self.conn.execute(
                "SELECT audit_info FROM audit_infos WHERE identity=?",
                (identity,)).fetchone()
        return row[0] if row else None
