"""Auditor service: application-level audit of token requests.

Behavioral mirror of reference token/services/auditor/auditor.go:73-151 and
ttx/auditor.go:128-254: on an audit request the auditor Validates the
request (driver AuditorCheck), locks the involved enrollment IDs, appends
the records to its auditdb, endorses (signs) the request, and releases the
locks when finality arrives.
"""

from __future__ import annotations

import time

from .db.sqldb import AuditDB, TxStatus, TxRecord
from .node import TokenNode
from .ttx import Transaction, TtxError


class AuditError(Exception):
    pass


class AuditorNode(TokenNode):
    """A TokenNode playing the auditor role."""

    def __init__(self, *args, audit_check=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.auditdb = AuditDB(":memory:")
        # audit_check(tx) -> None: optional extra inspection hook; the
        # driver-specific check (zkatdlog commitment-reopen batch,
        # crypto/audit/auditor.go:135) runs via self.driver.audit_check.
        self.audit_check = audit_check

    # responder view (ttx/auditor.go:265-282 AuditApproveView)
    def audit(self, tx: Transaction) -> bytes:
        # 1. validate (auditor/auditor.go:73: Validate -> Request.AuditCheck
        #    -> driver AuditorService.AuditorCheck)
        try:
            self.driver.audit_check(tx.request, tx.metadata, None, tx.tx_id)
        except Exception as e:
            raise AuditError(f"audit check failed: {e}") from e
        if self.audit_check is not None:
            try:
                self.audit_check(tx)
            except Exception as e:
                raise AuditError(f"audit check failed: {e}") from e
        # 2. lock enrollment IDs (auditor/auditor.go:80-100); a multisig
        # input lists every co-owner — each one's EID is locked
        eids = set()
        for owner in tx.input_owners:
            if isinstance(owner, (list, tuple)):
                eids.update(owner)
            else:
                eids.add(owner)
        self.auditdb.acquire_locks(tx.tx_id, sorted(eids))
        # 3. append records + subscribe finality (auditor/auditor.go:102)
        for rec in tx.records:
            self.auditdb.add_transaction(rec)
        self.auditdb.add_token_request(tx.tx_id, tx.request.to_bytes())
        self._watched[tx.tx_id] = tx.request
        # 4. endorse: sign the request (crypto/audit/auditor.go:117-132)
        return self.keys.sign(tx.message_to_sign())

    def _on_commit(self, ev) -> None:
        super()._on_commit(ev)
        # release EID locks at finality (auditor/auditor.go:117-151)
        self.auditdb.release_locks(ev.tx_id)
        status = (TxStatus.CONFIRMED if ev.status == "VALID"
                  else TxStatus.DELETED)
        self.auditdb.set_status(ev.tx_id, status, ev.message)

    # reporting API (auditdb payments/holdings filters)
    def audited_payments(self, party: str) -> list[TxRecord]:
        return self.auditdb.payments(party)
