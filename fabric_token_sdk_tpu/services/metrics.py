"""Compatibility shim: the metrics/tracing stack lives in
``fabric_token_sdk_tpu.obs`` now.

Every name exported here aliases the obs implementation — including the
process-global ``GLOBAL`` provider and ``TRACER``, which are the SAME
objects as ``obs.GLOBAL`` / ``obs.TRACER``, so old importers and the new
pipeline instrumentation share one registry and one span tree.
"""

from __future__ import annotations

from ..obs.metrics import (  # noqa: F401
    GLOBAL,
    Counter,
    Histogram,
    MetricsProvider,
    escape_label_value,
    sanitize_label_name,
    sanitize_metric_name,
)
from ..obs.tracing import TRACER, Span, Tracer  # noqa: F401

__all__ = [
    "Counter",
    "Histogram",
    "MetricsProvider",
    "GLOBAL",
    "Span",
    "Tracer",
    "TRACER",
    "sanitize_metric_name",
    "sanitize_label_name",
    "escape_label_value",
]
