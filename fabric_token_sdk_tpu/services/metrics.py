"""Metrics + tracing: observability for the verification hot path.

Behavioral mirror of the reference's observability stack:
  - token/core/common/metrics/provider.go:26-75 — a metrics provider that
    namespaces every instrument with TMS labels;
  - token/core/zkatdlog/nogh/v1/metrics.go:14-40 — per-driver duration
    histograms around zk issue/transfer;
  - token/core/common/tracing/tracing.go:18-26 — spans threaded through
    validator/auditor calls (OpenTelemetry in the reference).

TPU-native equivalent: in-process counters/histograms (scrapeable as a
dict, printable as Prometheus text format) plus a span tracer that can
optionally drive the JAX profiler for device-level traces
(jax.profiler.start_trace / TraceAnnotation) — SURVEY.md §5 "JAX profiler +
xprof traces per batch, span per validator call".
"""

from __future__ import annotations

import bisect
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Counter:
    value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta


#: Histogram bucket boundaries (seconds) tuned for proof verification:
#: sub-ms host ops up to multi-second cold batches.
_DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                    30.0)


@dataclass
class Histogram:
    buckets: tuple = _DEFAULT_BUCKETS
    counts: list = None
    total: float = 0.0
    n: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def __post_init__(self):
        if self.counts is None:
            self.counts = [0] * (len(self.buckets) + 1)

    def observe(self, value: float) -> None:
        with self._lock:
            self.counts[bisect.bisect_left(self.buckets, value)] += 1
            self.total += value
            self.n += 1

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


def _key(name: str, labels: dict | None) -> tuple:
    return (name, tuple(sorted((labels or {}).items())))


class MetricsProvider:
    """Label-namespaced metrics registry (metrics/provider.go:26-75)."""

    def __init__(self, namespace_labels: dict | None = None):
        self.namespace_labels = dict(namespace_labels or {})
        self._counters: dict[tuple, Counter] = {}
        self._histograms: dict[tuple, Histogram] = {}
        self._lock = threading.Lock()

    def with_labels(self, **labels) -> "MetricsProvider":
        """Derived provider with extra namespace labels (TMS-id labelling
        in the reference). Shares the registry AND its lock — parent and
        children registering the same instrument concurrently must
        serialize on one lock or increments race away."""
        child = MetricsProvider({**self.namespace_labels, **labels})
        child._counters = self._counters
        child._histograms = self._histograms
        child._lock = self._lock
        return child

    def counter(self, name: str, **labels) -> Counter:
        key = _key(name, {**self.namespace_labels, **labels})
        with self._lock:
            if key not in self._counters:
                self._counters[key] = Counter()
            return self._counters[key]

    def histogram(self, name: str, **labels) -> Histogram:
        key = _key(name, {**self.namespace_labels, **labels})
        with self._lock:
            if key not in self._histograms:
                self._histograms[key] = Histogram()
            return self._histograms[key]

    # ------------------------------------------------------------- scraping
    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            for (name, labels), c in self._counters.items():
                out[(name, labels)] = c.value
            for (name, labels), h in self._histograms.items():
                out[(name, labels)] = {"count": h.n, "sum": h.total,
                                       "mean": h.mean}
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition format (what the reference's provider
        ultimately serves)."""
        lines = []

        def fmt_labels(labels):
            if not labels:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in labels)
            return "{" + inner + "}"

        with self._lock:
            for (name, labels), c in sorted(self._counters.items()):
                lines.append(f"{name}{fmt_labels(labels)} {c.value}")
            for (name, labels), h in sorted(self._histograms.items()):
                cum = 0
                for bound, cnt in zip(h.buckets, h.counts):
                    cum += cnt
                    lbl = fmt_labels(labels + (("le", bound),))
                    lines.append(f"{name}_bucket{lbl} {cum}")
                lines.append(
                    f'{name}_bucket{fmt_labels(labels + (("le", "+Inf"),))} '
                    f"{h.n}")
                lines.append(f"{name}_sum{fmt_labels(labels)} {h.total}")
                lines.append(f"{name}_count{fmt_labels(labels)} {h.n}")
        return "\n".join(lines) + "\n"


#: Process-global default provider (sdk/dig singleton equivalent).
GLOBAL = MetricsProvider()


@dataclass
class Span:
    name: str
    start: float
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)
    duration: float | None = None

    def add_event(self, name: str) -> None:
        """tracing span AddEvent (audit/auditor.go:143-171 pattern)."""
        self.events.append((name, time.perf_counter() - self.start))

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value


class Tracer:
    """Span tracer: durations into a histogram, optional JAX device trace.

    With profile_dir set, each top-level span wraps the work in
    jax.profiler.start_trace/stop_trace so xprof captures the device
    timeline for that span (SURVEY.md §5).
    """

    def __init__(self, provider: MetricsProvider | None = None,
                 profile_dir: str | None = None, keep_spans: int = 256):
        self.provider = provider or GLOBAL
        self.profile_dir = profile_dir
        self.finished: list[Span] = []
        self._keep = keep_spans
        self._lock = threading.Lock()

    @contextmanager
    def span(self, name: str, **attributes):
        sp = Span(name=name, start=time.perf_counter(),
                  attributes=dict(attributes))
        profiling = False
        if self.profile_dir is not None:
            import jax

            try:
                jax.profiler.start_trace(self.profile_dir)
                profiling = True
            except RuntimeError:
                pass  # a trace is already running (nested span)
        try:
            yield sp
        finally:
            if profiling:
                import jax

                jax.profiler.stop_trace()
            sp.duration = time.perf_counter() - sp.start
            self.provider.histogram(f"span_{name}_seconds").observe(
                sp.duration)
            with self._lock:
                self.finished.append(sp)
                if len(self.finished) > self._keep:
                    self.finished.pop(0)
