"""External (remote) wallet signing for the ttx flow.

Behavioral mirror of reference token/services/ttx/external.go:19-210: a
node that keeps its keys in an external wallet does not sign locally —
the ttx endorsement step runs a SIGNER SERVER that streams sign requests
to the remote wallet process, which answers with signatures until the
server sends Done.

Wire protocol (matching the reference message set):
    SigRequest    {party, message}       server -> client
    SignResponse  {sigma}                client -> server
    Done          {}                     server -> client
Messages are JSON objects {"type": int, "raw": {...}} with bytes fields
hex-encoded; any duplex byte/obj stream works — the harness' IPC pipes
(harness/nwo.py) or the in-process QueuePairStream below.
"""

from __future__ import annotations

import json
import queue


class ExternalWalletError(Exception):
    pass


# message types (external.go:21-27)
SIG_REQUEST = 1
SIGN_RESPONSE = 2
DONE = 3


def _encode(type_: int, raw: dict | None) -> str:
    return json.dumps({"type": type_, "raw": raw or {}})


def _decode(data: str) -> tuple[int, dict]:
    obj = json.loads(data)
    return int(obj["type"]), obj.get("raw") or {}


class QueuePairStream:
    """In-process duplex stream: a pair of queues. `pair()` returns the
    two connected endpoints (server side, client side)."""

    def __init__(self, inbox: queue.Queue, outbox: queue.Queue):
        self._in = inbox
        self._out = outbox

    @classmethod
    def pair(cls) -> tuple["QueuePairStream", "QueuePairStream"]:
        a, b = queue.Queue(), queue.Queue()
        return cls(a, b), cls(b, a)

    def send(self, data: str) -> None:
        self._out.put(data)

    def recv(self, timeout: float = 30.0) -> str:
        try:
            return self._in.get(timeout=timeout)
        except queue.Empty as exc:
            raise ExternalWalletError("stream receive timed out") from exc


class StreamExternalWalletSignerServer:
    """ttx-side endpoint: forwards sign requests to the remote wallet
    (external.go:61-107). Drop-in for a local signer in the endorsement
    step: `sign(party, message) -> sigma`."""

    def __init__(self, stream):
        self.stream = stream

    def sign(self, party: bytes, message: bytes) -> bytes:
        self.stream.send(_encode(SIG_REQUEST, {
            "party": bytes(party).hex(), "message": bytes(message).hex()}))
        type_, raw = _decode(self.stream.recv())
        if type_ != SIGN_RESPONSE:
            raise ExternalWalletError(
                f"expected sign response msg, got [{type_}]")
        return bytes.fromhex(raw["sigma"])

    def done(self) -> None:
        self.stream.send(_encode(DONE, None))


class StreamExternalWalletSignerClient:
    """Remote-wallet-side endpoint (external.go:114-210): answers sign
    requests with the wallet's own signers until Done arrives.

    signer_provider(party: bytes) -> signer with .sign(message) -> bytes
    """

    def __init__(self, signer_provider, stream):
        self.signer_provider = signer_provider
        self.stream = stream

    def respond(self) -> int:
        """Serve sign requests until Done; returns how many were signed."""
        served = 0
        while True:
            type_, raw = _decode(self.stream.recv())
            if type_ == DONE:
                return served
            if type_ != SIG_REQUEST:
                raise ExternalWalletError(
                    f"msg type [{type_}] not recognized")
            party = bytes.fromhex(raw["party"])
            message = bytes.fromhex(raw["message"])
            signer = self.signer_provider(party)
            if signer is None:
                raise ExternalWalletError(
                    f"no signer for party [{party.hex()[:16]}]")
            sigma = signer.sign(message)
            self.stream.send(_encode(SIGN_RESPONSE,
                                     {"sigma": bytes(sigma).hex()}))
            served += 1
