"""Custodian-mediated ledger backend (the Orion-style network family).

Behavioral mirror of reference token/services/network/orion: clients never
talk to the ledger database directly — a CUSTODIAN node fronts it. The
client asks the custodian for approval (the custodian runs the driver
Validator over current state and signs off — orion/approval.go:74-109,
140-272) and then asks it to broadcast (submit + commit with bounded
retries — orion/broadcast.go:52,128-137). Finality events flow back to
client subscribers through the custodian's event fan-out.

`CustodianChaincodeFacade` exposes the same surface as TokenChaincode
(process_request / query_* / .ledger reads / finality listeners), so a
TokenNode runs on this backend unchanged — the backend swap the reference
achieves behind driver.Network (network/driver/network.go:38).
"""

from __future__ import annotations

from ...resilience import RetryExhausted, RetryPolicy
from .rws import KeyTranslator
from .tcc import CommitEvent


class CustodianError(Exception):
    pass


def _approval_digest(tx_id: str, request_raw: bytes) -> bytes:
    """Domain-separated bytes the custodian signs for an approval; shared
    by signer and verifier so the framing cannot drift apart."""
    import hashlib

    return hashlib.sha256(
        b"custodian-approval\x00" + tx_id.encode() + b"\x00"
        + request_raw).digest()


class CustodianNode:
    """The custodian: sole owner of the ledger + chaincode; serves
    approval/broadcast/query views over the session plane."""

    def __init__(self, name: str, keys, chaincode, bus,
                 max_broadcast_attempts: int = 3, retry_wait: float = 0.01):
        self.name = name
        self.keys = keys
        self.cc = chaincode
        self.max_broadcast_attempts = max_broadcast_attempts
        self.retry_wait = retry_wait
        self._broadcast_retry = RetryPolicy(
            max_attempts=max_broadcast_attempts, base_s=retry_wait,
            cap_s=retry_wait * 8, op="custodian_broadcast")
        self._subscribers: list = []
        # test/fault hook: raised-once transient failures (broadcast.go
        # retry path); a callable returning True means "fail this attempt"
        self.fault_hook = None
        bus.register(name, self)
        chaincode.ledger.add_finality_listener(self._forward_event)

    # ------------------------------------------------------------ views
    def request_approval(self, tx_id: str, request_raw: bytes) -> bytes:
        """orion/approval.go: the custodian validates the request against
        CURRENT ledger state and signs its approval. No state change."""
        rws = self.cc.ledger.new_rwset()

        def get_state(token_id):
            return rws.get_state(self.cc.keys.output_key(
                token_id.tx_id, token_id.index))

        try:
            self.cc.validator.verify_token_request_from_raw(
                get_state, tx_id, request_raw)
        except Exception as e:
            raise CustodianError(
                f"custodian rejects tx [{tx_id}]: {e}") from e
        return self.keys.sign(_approval_digest(tx_id, request_raw))

    def broadcast(self, tx_id: str, request_raw: bytes) -> CommitEvent:
        """orion/broadcast.go:52: submit for ordering + commit, retrying
        transient submission failures (:128-137) under the shared
        :class:`RetryPolicy` (ConnectionError and friends are transient;
        validation failures propagate unchanged)."""
        attempt = 0

        def submit():
            nonlocal attempt
            this_attempt, attempt = attempt, attempt + 1
            if self.fault_hook is not None and self.fault_hook(this_attempt):
                raise ConnectionError("transient submission failure")
            return self.cc.process_request(tx_id, request_raw)

        try:
            return self._broadcast_retry.call(submit)
        except RetryExhausted as e:
            raise CustodianError(
                f"broadcast of [{tx_id}] failed after "
                f"{e.attempts} attempts: {e.last_error}") from e.last_error

    def query_state(self, key: str) -> bytes | None:
        return self.cc.ledger.get_state(key)

    def query_public_params(self) -> bytes | None:
        return self.cc.query_public_params()

    def emit_invalid(self, tx_id: str, message: str) -> CommitEvent:
        """Fan an INVALID event out to every subscriber — the custodian
        equivalent of TokenChaincode emitting validation failures
        ledger-wide (tcc.py _process_request), so distributed openings and
        pending ttxdb records get cleaned up on every node."""
        ev = CommitEvent(tx_id, "INVALID", message)
        self._forward_event(ev)
        return ev

    def subscribe(self, callback) -> None:
        self._subscribers.append(callback)

    def unsubscribe(self, callback) -> None:
        if callback in self._subscribers:
            self._subscribers.remove(callback)

    def _forward_event(self, ev: CommitEvent) -> None:
        for cb in list(self._subscribers):
            try:
                cb(ev)
            except Exception:  # subscriber isolation, like MemoryLedger
                import logging

                logging.getLogger(
                    "fabric_token_sdk_tpu.custodian").exception(
                    "custodian subscriber failed for tx [%s]", ev.tx_id)


class _CustodianLedgerView:
    """Read-only ledger facade: every access is a custodian query."""

    def __init__(self, custodian: CustodianNode):
        self._custodian = custodian

    def get_state(self, key: str) -> bytes | None:
        return self._custodian.query_state(key)

    def add_finality_listener(self, listener) -> None:
        self._custodian.subscribe(listener)

    def remove_finality_listener(self, listener) -> None:
        self._custodian.unsubscribe(listener)


class CustodianChaincodeFacade:
    """Client-side stand-in for TokenChaincode over the custodian.

    process_request == approval + broadcast through the custodian
    (the orion transaction path); reads and finality ride the custodian's
    query/event views. The local validator handles unmarshalling only
    (nodes hold the pp; the custodian owns validation-for-commit).
    """

    def __init__(self, custodian: CustodianNode, validator,
                 approval_required: bool = True):
        from ..identity.x509 import X509Verifier

        self.keys = KeyTranslator()
        self.validator = validator
        self.ledger = _CustodianLedgerView(custodian)
        self._custodian = custodian
        self.approval_required = approval_required
        # one DER parse for the custodian's static identity, not one per tx
        self._custodian_verifier = X509Verifier.from_identity(
            bytes(custodian.keys.identity))

    def process_request(self, tx_id: str, request_raw: bytes) -> CommitEvent:
        if self.approval_required:
            try:
                approval = self._custodian.request_approval(tx_id,
                                                            request_raw)
            except CustodianError as e:
                # fan the rejection out like the chaincode path does, so
                # every node's finality listener cleans up pending state
                return self._custodian.emit_invalid(tx_id, str(e))
            # the approval is the custodian's signature; verify before
            # submitting (client-side sanity, approval.go response check)
            self._custodian_verifier.verify(
                _approval_digest(tx_id, request_raw), approval)
        try:
            return self._custodian.broadcast(tx_id, request_raw)
        except CustodianError as e:
            # broadcast exhaustion must surface as an INVALID event, never
            # an exception: node.execute only releases the selector locks
            # on a returned non-VALID event
            return self._custodian.emit_invalid(tx_id, str(e))

    def query_public_params(self) -> bytes | None:
        return self._custodian.query_public_params()
