"""Token chaincode: the on-ledger validation + commit entry point.

Behavioral mirror of reference token/services/network/fabric/tcc/tcc.go:
ProcessRequest reads the token request, runs the driver Validator, feeds the
verified actions through the Translator into the RW set, and stores the
request hash. Queries: public params, tokens, spent-status
(tcc.go:90-255,126-143).

`MemoryLedger` is the standalone backend (the "fake-ledger multi-process
harness on one TPU host" of SURVEY.md §4 last row); commit applies the
RW set atomically with MVCC conflict detection against the read set.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from ...obs import GLOBAL as _METRICS
from ...obs import TRACER as _TRACER
from ...token.model import ID
from .rws import KeyTranslator, MemoryRWSet, Translator, TranslatorError

#: Chaincode family metadata (HELP independent of call-site order).
_TCC_FAMILIES = {
    "tcc_requests_total": "Token requests processed by the chaincode",
    "tcc_request_status_total":
        "Token-request outcomes, by commit status",
    "tcc_process_request_seconds":
        "Full process-request wall: validate + translate + commit",
    "tcc_validate_seconds": "Token-request validation wall",
    "tcc_translate_seconds": "Action -> RWSet translation wall",
    "tcc_commit_seconds": "Ledger commit wall per token request",
}
for _fam, _help in _TCC_FAMILIES.items():
    _METRICS.describe(_fam, _help)


class LedgerError(Exception):
    pass


class MVCCConflict(LedgerError):
    pass


@dataclass
class CommitEvent:
    tx_id: str
    status: str  # "VALID" | "INVALID"
    message: str = ""
    # total output slots of the committed request, INCLUDING redeem outputs
    # (which occupy an index but leave no ledger key). Lets ledger-scan
    # ingestion walk every slot instead of stopping at the first gap — the
    # RW-set processor equivalent of knowing the full write set.
    n_outputs: int = 0


class MemoryLedger:
    """Single-host ordered ledger with MVCC commit and finality events."""

    def __init__(self):
        self.state: dict[str, bytes] = {}
        self.blocks: list[CommitEvent] = []
        self.listeners: list = []
        self.lock = threading.RLock()
        self.keys = KeyTranslator()

    def new_rwset(self) -> MemoryRWSet:
        return MemoryRWSet(self.state)

    def commit(self, tx_id: str, rws: MemoryRWSet,
               n_outputs: int = 0) -> CommitEvent:
        """Atomically validate the read set and apply writes (total order)."""
        with self.lock:
            for key, seen in rws.reads.items():
                if self.state.get(key) != seen:
                    ev = CommitEvent(tx_id, "INVALID",
                                     f"MVCC conflict on [{key!r}]")
                    self._emit(ev)
                    return ev
            rws.apply()
            ev = CommitEvent(tx_id, "VALID", n_outputs=n_outputs)
            self._emit(ev)
            return ev

    def _emit(self, ev: CommitEvent) -> None:
        self.blocks.append(ev)
        for listener in list(self.listeners):
            # Listener isolation (network/common/finality.go listener
            # manager semantics): one node failing to ingest a commit —
            # e.g. fed a malformed opening by a misbehaving peer — must
            # not starve the other nodes of the finality event, and must
            # never unwind the already-committed ledger state.
            try:
                listener(ev)
            except Exception:
                logging.getLogger("fabric_token_sdk_tpu.ledger").exception(
                    "finality listener failed for tx [%s]", ev.tx_id)

    def add_finality_listener(self, listener) -> None:
        self.listeners.append(listener)

    def remove_finality_listener(self, listener) -> None:
        if listener in self.listeners:
            self.listeners.remove(listener)

    # -- convenience direct reads (committed state)
    def get_state(self, key: str) -> bytes | None:
        with self.lock:
            return self.state.get(key)


class TokenChaincode:
    """tcc.go:59-255 equivalent bound to one validator + ledger."""

    def __init__(self, validator, ledger: MemoryLedger, pp_raw: bytes):
        self.validator = validator
        self.ledger = ledger
        self.keys = KeyTranslator()
        # init: store public parameters (tcc.go Init path)
        rws = ledger.new_rwset()
        tr = Translator(tx_id="genesis", rws=rws)
        tr.commit_setup(pp_raw)
        ledger.commit("genesis", rws)
        # pp-install prewarm (tcc.go:90 availability): compile the device
        # verification kernels NOW so the first invoke answers at
        # steady-state latency. Opt-in (FTS_PREWARM=1, or comma-separated
        # batch buckets e.g. FTS_PREWARM=1,256): test topologies build
        # many chaincodes and must not pay a compile per node.
        import os

        spec = os.environ.get("FTS_PREWARM")
        zk = getattr(validator, "zk_verifier", None) or getattr(
            getattr(validator, "pp", None), "zk_verifier", None)
        disabled = (spec or "").strip().lower() in ("", "0", "false", "no",
                                                    "off")
        if not disabled and zk is not None and hasattr(zk, "prewarm"):
            # positive numeric tokens select buckets; any other truthy
            # value (FTS_PREWARM=1 / true / yes) means the default bucket
            sizes = tuple(v for v in (int(s) for s in spec.split(",")
                                      if s.strip().isdigit()) if v > 0)
            elapsed = zk.prewarm(batch_sizes=sizes or (1,))
            logging.getLogger("fabric_token_sdk_tpu.tcc").info(
                "pp-install prewarm: %.1fs (buckets %s)", elapsed,
                sizes or (1,))

    # ---- invoke("invoke") -------------------------------------------------
    def process_request(self, tx_id: str, request_raw: bytes) -> CommitEvent:
        """Validate + translate + commit one token request (tcc.go:220-255).

        Instrumented with the span/histogram pair the reference threads
        through its validator service (tracing.go:18-26, v1/metrics.go):
        one "tcc.process_request" span with validate/translate/commit
        children, phase histograms per stage, and outcome counters.
        ``tcc_requests_total`` stays a single unlabelled family (the
        steady scrape-delta interface); statuses land in the separate
        ``tcc_request_status_total{status}`` family."""
        t0 = time.perf_counter()
        ev = None
        try:
            with _TRACER.span("tcc.process_request", tx_id=tx_id) as sp:
                ev = self._process_request(tx_id, request_raw)
                sp.set_attribute("status", ev.status)
            return ev
        finally:
            _METRICS.histogram("tcc_process_request_seconds").observe(
                time.perf_counter() - t0)
            _METRICS.counter("tcc_requests_total").add()
            _METRICS.counter(
                "tcc_request_status_total",
                status=(ev.status if ev is not None else "ERROR")).add()

    def _process_request(self, tx_id: str,
                         request_raw: bytes) -> CommitEvent:
        rws = self.ledger.new_rwset()
        translator = Translator(tx_id=tx_id, rws=rws)

        def get_state(token_id: ID) -> bytes | None:
            return rws.get_state(self.keys.output_key(token_id.tx_id,
                                                      token_id.index))

        t0 = time.perf_counter()
        try:
            with _TRACER.span("tcc.validate"):
                actions, _attrs = \
                    self.validator.verify_token_request_from_raw(
                        get_state, tx_id, request_raw)
        except Exception as e:
            ev = CommitEvent(tx_id, "INVALID", f"validation failed: {e}")
            self.ledger._emit(ev)
            return ev
        finally:
            _METRICS.histogram("tcc_validate_seconds").observe(
                time.perf_counter() - t0)
        t1 = time.perf_counter()
        try:
            with _TRACER.span("tcc.translate"):
                translator.add_public_params_dependency()
                for action in actions:
                    translator.write(action)
                translator.commit_token_request(request_raw)
        except TranslatorError as e:
            ev = CommitEvent(tx_id, "INVALID", f"translation failed: {e}")
            self.ledger._emit(ev)
            return ev
        finally:
            _METRICS.histogram("tcc_translate_seconds").observe(
                time.perf_counter() - t1)
        n_outputs = sum(len(a.get_outputs()) for a in actions)
        t2 = time.perf_counter()
        try:
            with _TRACER.span("tcc.commit"):
                return self.ledger.commit(tx_id, rws, n_outputs=n_outputs)
        finally:
            _METRICS.histogram("tcc_commit_seconds").observe(
                time.perf_counter() - t2)

    # ---- queries (tcc.go:126-143) ----------------------------------------
    def query_public_params(self) -> bytes | None:
        return self.ledger.get_state(self.keys.setup_key())

    def query_tokens(self, ids: list[ID]) -> list[bytes]:
        out = []
        missing = []
        for tid in ids:
            raw = self.ledger.get_state(self.keys.output_key(tid.tx_id,
                                                             tid.index))
            if raw is None:
                missing.append(str(tid))
            else:
                out.append(raw)
        if missing:
            raise LedgerError(f"tokens not found: {missing}")
        return out

    def are_tokens_spent(self, ids: list[ID]) -> list[bool]:
        out = []
        for tid in ids:
            raw = self.ledger.get_state(self.keys.output_key(tid.tx_id,
                                                             tid.index))
            out.append(raw is None)
        return out
