"""FSC-style endorsement: re-validate, sign the RW set, check a policy.

Behavioral mirror of reference token/services/network/fabric/endorsement
(approval.go:40-259) and the fsc_endorsement config (docs/core-token.md
policy `1outn` | `all`): instead of Fabric peers running the token
chaincode, designated endorser nodes each re-run the driver Validator
locally over the current ledger state, translate the verified actions into
an RW set, and sign a digest of it. The client collects signatures under
the configured policy into an envelope that CARRIES the endorsed RW set
(Fabric tx.Envelope()); the ordering backend verifies the policy and the
digest, then commits the RW set under MVCC — it does not re-execute.
Deterministic re-execution across endorsers is enforced at collection
time: a second endorser deriving a different RW set voids the envelope.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ...token.model import ID
from .rws import MemoryRWSet, Translator
from .tcc import CommitEvent, LedgerError


class EndorsementError(Exception):
    pass


class Policy:
    ALL = "all"          # every listed endorser must sign
    ONE_OUT_N = "1outn"  # one valid endorsement suffices


def rwset_digest(tx_id: str, reads: dict[str, bytes | None],
                 writes: dict[str, bytes | None]) -> bytes:
    """Canonical digest of an RW set: reads (with observed values) and
    writes/deletes in key order — the byte string every endorser signs.
    Deterministic re-execution makes this digest identical across honest
    endorsers."""
    h = hashlib.sha256()
    h.update(b"token-rwset/v1\x00")
    h.update(tx_id.encode() + b"\x00")
    for tag, entries in ((b"R", reads), (b"W", writes)):
        for key in sorted(entries):
            val = entries[key]
            h.update(tag + key.encode() + b"\x00")
            h.update(b"\x00" if val is None else b"\x01" + val)
    return h.digest()


@dataclass
class Envelope:
    """The endorsed transaction the client broadcasts (tx.Envelope()):
    the RW set derived by the endorsers plus their signatures."""

    tx_id: str
    request_raw: bytes
    reads: dict[str, bytes | None]
    writes: dict[str, bytes | None]
    digest: bytes
    # endorser identity -> signature over the digest
    signatures: dict[bytes, bytes] = field(default_factory=dict)
    n_outputs: int = 0


class EndorserNode:
    """RequestApprovalResponderView: one FSC endorser — re-validates the
    token request against its ledger view and signs the RW-set digest."""

    def __init__(self, name: str, keys, validator, ledger, bus=None):
        self.name = name
        self.keys = keys
        self.validator = validator
        self.ledger = ledger
        if bus is not None:
            bus.register(name, self)

    def identity(self) -> bytes:
        return bytes(self.keys.identity)

    def endorse(self, tx_id: str, request_raw: bytes) -> Envelope:
        """Returns a single-signature envelope; raises on invalid requests
        — an endorser never signs a request it cannot validate."""
        rws = self.ledger.new_rwset()
        translator = Translator(tx_id=tx_id, rws=rws)

        def get_state(token_id: ID) -> bytes | None:
            return rws.get_state(self.ledger.keys.output_key(
                token_id.tx_id, token_id.index))

        try:
            actions, _attrs = self.validator.verify_token_request_from_raw(
                get_state, tx_id, request_raw)
            translator.add_public_params_dependency()
            for action in actions:
                translator.write(action)
            translator.commit_token_request(request_raw)
        except Exception as e:
            raise EndorsementError(
                f"endorser [{self.name}] rejects tx [{tx_id}]: {e}") from e
        digest = rwset_digest(tx_id, rws.reads, rws.writes)
        return Envelope(
            tx_id=tx_id, request_raw=request_raw, reads=dict(rws.reads),
            writes=dict(rws.writes), digest=digest,
            signatures={self.identity(): self.keys.sign(digest)},
            n_outputs=sum(len(a.get_outputs()) for a in actions))


class EndorsementService:
    """Client side (RequestApprovalView + policy selection) and ordering
    side (policy verification at commit) of FSC endorsement."""

    def __init__(self, ledger, endorser_names: list[str], bus,
                 endorser_identities: dict[str, bytes],
                 policy: str = Policy.ALL):
        if policy not in (Policy.ALL, Policy.ONE_OUT_N):
            raise EndorsementError(f"unknown policy [{policy}]")
        self.ledger = ledger
        self.endorser_names = list(endorser_names)
        self.bus = bus
        self.identities = dict(endorser_identities)
        self.policy = policy

    # ------------------------------------------------------------- client
    def request_approval(self, tx_id: str, request_raw: bytes) -> Envelope:
        """Collect endorsements under the policy. ALL contacts every
        endorser (parallel-collect in the reference); 1outn walks the list
        until one endorsement succeeds."""
        envelope: Envelope | None = None
        errors: list[str] = []
        for name in self.endorser_names:
            try:
                env = self.bus.node(name).endorse(tx_id, request_raw)
            except Exception as e:  # endorser refused or unreachable
                if self.policy == Policy.ALL:
                    raise EndorsementError(
                        f"policy [all]: endorser [{name}] failed: {e}") from e
                errors.append(f"[{name}]: {e}")
                continue
            if envelope is None:
                envelope = env
            elif env.digest != envelope.digest:
                # non-deterministic re-execution: never broadcastable
                raise EndorsementError(
                    f"endorser [{name}] derived a different RW set for "
                    f"tx [{tx_id}]")
            else:
                envelope.signatures.update(env.signatures)
            if self.policy == Policy.ONE_OUT_N:
                return envelope
        if envelope is None:
            raise EndorsementError(
                f"policy [{self.policy}]: no endorser approved tx "
                f"[{tx_id}]: " + "; ".join(errors))
        return envelope

    # ----------------------------------------------------------- ordering
    def verify_policy(self, envelope: Envelope) -> None:
        """Ordering/commit-side check: the digest matches the carried RW
        set, signatures verify over it, and the count satisfies the
        policy threshold."""
        from ..identity.x509 import X509Verifier

        if rwset_digest(envelope.tx_id, envelope.reads,
                        envelope.writes) != envelope.digest:
            raise EndorsementError("envelope digest does not match RW set")
        valid = 0
        for ident, sig in envelope.signatures.items():
            if ident not in self.identities.values():
                raise EndorsementError("signature from unknown endorser")
            X509Verifier.from_identity(ident).verify(envelope.digest, sig)
            valid += 1
        needed = len(self.endorser_names) if self.policy == Policy.ALL else 1
        if valid < needed:
            raise EndorsementError(
                f"policy [{self.policy}] needs {needed} endorsements, "
                f"got {valid}")

    def broadcast(self, envelope: Envelope) -> CommitEvent:
        """Ordering + commit of an endorsed envelope: verify the policy,
        then apply the CARRIED RW set under MVCC (ledger.commit checks the
        endorsement-time reads against current state, so a conflicting
        commit in between invalidates this envelope) — the Fabric
        committer path, no re-execution."""
        try:
            self.verify_policy(envelope)
        except EndorsementError as e:
            ev = CommitEvent(envelope.tx_id, "INVALID",
                             f"endorsement policy: {e}")
            self.ledger._emit(ev)
            return ev
        rws = MemoryRWSet(self.ledger.state)
        rws.reads = dict(envelope.reads)
        rws.writes = dict(envelope.writes)
        return self.ledger.commit(envelope.tx_id, rws,
                                  n_outputs=envelope.n_outputs)


class LedgerQueryService:
    """Network.QueryTokens / AreTokensSpent over the endorsement plane
    (network/driver/network.go:38-90) for nodes that are not endorsers."""

    def __init__(self, ledger):
        self.ledger = ledger

    def query_tokens(self, ids: list[ID]) -> list[bytes]:
        out, missing = [], []
        for tid in ids:
            raw = self.ledger.get_state(
                self.ledger.keys.output_key(tid.tx_id, tid.index))
            if raw is None:
                missing.append(str(tid))
            else:
                out.append(raw)
        if missing:
            raise LedgerError(f"tokens not found: {missing}")
        return out

    def are_tokens_spent(self, ids: list[ID]) -> list[bool]:
        return [self.ledger.get_state(
                    self.ledger.keys.output_key(t.tx_id, t.index)) is None
                for t in ids]
