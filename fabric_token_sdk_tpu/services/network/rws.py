"""RW-set translation: verified actions -> ledger key/value writes.

Behavioral mirror of reference token/services/network/common/rws/
{translator,keys} (SURVEY.md §2.4 "rws/translator"): composite keys in the
Fabric chaincode namespace style, output keys (txID, index), output serial
numbers hashing the serialized token (existence check at spend time),
token-request hash storage, setup-key dependency, and metadata keys.
Double-spend protection is MVCC: spends read-then-delete the SN key, so two
transactions spending the same token conflict.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field

from ...token.model import ID

# keys.go constants
TOKEN_KEY_PREFIX = "ztoken"
TOKEN_REQUEST_KEY_PREFIX = "token_request"
TOKEN_SETUP_KEY_PREFIX = "setup"
TOKEN_SETUP_HASH_KEY_PREFIX = "setup.hash"
OUTPUT_SN_KEY_PREFIX = "osn"
INPUT_SN_PREFIX = "sn"
ISSUE_METADATA_PREFIX = "iam"
TRANSFER_METADATA_PREFIX = "tam"

_MIN_UNICODE = "\x00"
_COMPOSITE_NS = "\x00"

NOT_EMPTY = b"\x01"


class TranslatorError(Exception):
    pass


def composite_key(object_type: str, attributes: list[str]) -> str:
    """Fabric shim createCompositeKey (keys.go:96-120)."""
    ck = _COMPOSITE_NS + object_type + _MIN_UNICODE
    for attr in attributes:
        ck += attr + _MIN_UNICODE
    return ck


class KeyTranslator:
    """keys.go:38-95."""

    def token_request_key(self, tx_id: str) -> str:
        return composite_key(TOKEN_REQUEST_KEY_PREFIX, [tx_id])

    def setup_key(self) -> str:
        return composite_key(TOKEN_SETUP_KEY_PREFIX, [])

    def setup_hash_key(self) -> str:
        return composite_key(TOKEN_SETUP_HASH_KEY_PREFIX, [])

    def output_sn_key(self, tx_id: str, index: int, output: bytes) -> str:
        h = hashlib.sha256()
        h.update(OUTPUT_SN_KEY_PREFIX.encode())
        h.update(tx_id.encode())
        h.update(struct.pack("<Q", index))
        h.update(output)
        return composite_key(OUTPUT_SN_KEY_PREFIX, [h.hexdigest()])

    def output_key(self, tx_id: str, index: int) -> str:
        return composite_key(tx_id, [str(index)])

    def input_sn_key(self, sn: str) -> str:
        return composite_key(INPUT_SN_PREFIX, [sn])

    def issue_metadata_key(self, key: str) -> str:
        return composite_key(ISSUE_METADATA_PREFIX, [key])

    def transfer_metadata_key(self, key: str) -> str:
        return composite_key(TRANSFER_METADATA_PREFIX, [key])


class MemoryRWSet:
    """In-process read-write set over a backing store dict.

    Mirrors the semantics the translator needs from Fabric's RWSet:
    GetState / SetState / DeleteState / StateMustExist / StateMustNotExist,
    with reads recorded against the backing snapshot (MVCC read set) and
    writes staged until apply().
    """

    def __init__(self, backing: dict[str, bytes]):
        self.backing = backing
        self.writes: dict[str, bytes | None] = {}
        self.reads: dict[str, bytes | None] = {}

    def get_state(self, key: str) -> bytes | None:
        if key in self.writes:
            return self.writes[key]
        val = self.backing.get(key)
        self.reads[key] = val
        return val

    def set_state(self, key: str, value: bytes) -> None:
        self.writes[key] = value

    def delete_state(self, key: str) -> None:
        self.writes[key] = None

    def state_must_exist(self, key: str) -> None:
        if not self.get_state(key):
            raise TranslatorError(f"state [{key!r}] does not exist")

    def state_must_not_exist(self, key: str) -> None:
        if self.get_state(key):
            raise TranslatorError(f"state [{key!r}] already exists")

    def apply(self) -> None:
        for k, v in self.writes.items():
            if v is None:
                self.backing.pop(k, None)
            else:
                self.backing[k] = v


@dataclass
class Translator:
    """translator.go:44-489."""

    tx_id: str
    rws: MemoryRWSet
    keys: KeyTranslator = field(default_factory=KeyTranslator)
    counter: int = 0
    spent_ids: list[str] = field(default_factory=list)

    # ---- validation-side checks (translator.go:388-437)
    def write(self, action) -> None:
        self._check_action(action)
        self._commit_action(action)

    def _check_action(self, action) -> None:
        serial_numbers = getattr(action, "get_serial_numbers", lambda: [])()
        for sn in serial_numbers:
            try:
                self.rws.state_must_not_exist(self.keys.input_sn_key(sn))
            except TranslatorError as e:
                raise TranslatorError(
                    f"invalid transfer: serial number must not exist: {e}"
                ) from e
        inputs = action.get_inputs()
        serialized = (action.get_serialized_inputs()
                      if hasattr(action, "get_serialized_inputs") else [])
        if inputs:
            if len(serialized) != len(inputs):
                raise TranslatorError(
                    "inputs and serialized inputs length mismatch")
            for tid, raw in zip(inputs, serialized):
                key = self.keys.output_sn_key(tid.tx_id, tid.index, raw)
                try:
                    self.rws.state_must_exist(key)
                except TranslatorError as e:
                    raise TranslatorError(
                        f"invalid transfer: input must exist: {e}") from e

    # ---- commit (translator.go:242-385)
    def _commit_action(self, action) -> None:
        base = self.counter
        graph_hiding = getattr(action, "is_graph_hiding", lambda: False)()
        outputs = action.get_serialized_outputs()
        is_redeem_at = getattr(action, "is_redeem_at", lambda i: False)
        for i, output in enumerate(outputs):
            if is_redeem_at(i):
                continue
            self.rws.set_state(self.keys.output_key(self.tx_id, base + i),
                               output)
            if not graph_hiding:
                sn = self.keys.output_sn_key(self.tx_id, base + i, output)
                self.rws.set_state(sn, NOT_EMPTY)
        self._spend_inputs(action)
        metadata = action.get_metadata() or {}
        for key, value in metadata.items():
            k = (self.keys.transfer_metadata_key(key)
                 if hasattr(action, "is_redeem_at")
                 else self.keys.issue_metadata_key(key))
            try:
                self.rws.state_must_not_exist(k)
            except TranslatorError:
                raise TranslatorError(
                    f"entry with metadata key [{key}] is already occupied")
            self.rws.set_state(k, value)
        self.counter += len(outputs)

    def _spend_inputs(self, action) -> None:
        inputs = action.get_inputs()
        if inputs:
            serialized = action.get_serialized_inputs()
            for tid, raw in zip(inputs, serialized):
                sn_key = self.keys.output_sn_key(tid.tx_id, tid.index, raw)
                self.rws.delete_state(sn_key)
                out_key = self.keys.output_key(tid.tx_id, tid.index)
                self.rws.delete_state(out_key)
                self.spent_ids.append(out_key)
        for sn in getattr(action, "get_serial_numbers", lambda: [])():
            self.rws.set_state(self.keys.input_sn_key(sn), NOT_EMPTY)
            self.spent_ids.append(sn)

    # ---- request bookkeeping (translator.go:62-102)
    def commit_token_request(self, raw: bytes, store_hash: bool = True) -> bytes:
        key = self.keys.token_request_key(self.tx_id)
        self.rws.state_must_not_exist(key)
        stored = hashlib.sha256(raw).digest() if store_hash else raw
        self.rws.set_state(key, stored)
        return stored if store_hash else b""

    def read_token_request(self) -> bytes | None:
        return self.rws.get_state(self.keys.token_request_key(self.tx_id))

    # ---- setup (translator.go:254-289)
    def commit_setup(self, pp_raw: bytes) -> None:
        self.rws.set_state(self.keys.setup_key(), pp_raw)
        self.rws.set_state(self.keys.setup_hash_key(),
                           hashlib.sha256(pp_raw).digest())

    def read_setup_parameters(self) -> bytes | None:
        return self.rws.get_state(self.keys.setup_key())

    def add_public_params_dependency(self) -> None:
        self.rws.state_must_exist(self.keys.setup_hash_key())

    # ---- queries (translator.go:126-186)
    def query_tokens(self, ids: list[ID]) -> list[bytes]:
        res = []
        errs = []
        for tid in ids:
            raw = self.rws.get_state(self.keys.output_key(tid.tx_id, tid.index))
            if not raw:
                errs.append(f"output for key [{tid}] does not exist")
                continue
            res.append(raw)
        if errs:
            raise TranslatorError(
                f"failed querying tokens with errs [{len(errs)}][{errs}]")
        return res

    def are_tokens_spent(self, ids: list[str], graph_hiding: bool) -> list[bool]:
        out = []
        for key in ids:
            if graph_hiding:
                v = self.rws.get_state(self.keys.input_sn_key(key))
                out.append(bool(v))
            else:
                v = self.rws.get_state(key)
                out.append(not v)
        return out
