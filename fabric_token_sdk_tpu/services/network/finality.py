"""Finality listener manager: the delivery-type state machine.

Behavioral mirror of the reference finality manager documented at
docs/core-token.md:33-77 ("type: delivery") and implemented under
fabric-smart-client's delivery listener manager: an LRU cache of recently
finalized transactions plus a list of listeners waiting for future ones.
A finality query escalates through four steps of decreasing probability:

  a) recently final        -> LRU cache lookup
  b) final shortly         -> wait on a registered listener with a timeout
  c) final long ago        -> query the ledger for the transaction
  d) beyond timeout/never  -> return UNKNOWN (caller may retry or give up)

Eviction: the cache holds lruSize entries once it grows past
lruSize + lruBuffer (docs/core-token.md lruSize/lruBuffer semantics).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass

from .tcc import CommitEvent


class FinalityStatus:
    VALID = "VALID"
    INVALID = "INVALID"
    UNKNOWN = "UNKNOWN"


@dataclass
class _Waiter:
    event: threading.Event
    result: CommitEvent | None = None


class FinalityManager:
    """Delivery-plane finality manager bound to one ledger."""

    def __init__(self, ledger, lru_size: int = 30, lru_buffer: int = 15,
                 listener_timeout: float = 10.0):
        self.ledger = ledger
        self.lru_size = lru_size
        self.lru_buffer = lru_buffer
        self.listener_timeout = listener_timeout
        self._mu = threading.Lock()
        self._cache: "OrderedDict[str, CommitEvent]" = OrderedDict()
        self._waiters: dict[str, list[_Waiter]] = {}
        self._listeners: dict[str, list] = {}
        ledger.add_finality_listener(self._on_event)

    # ------------------------------------------------------------- delivery
    def _on_event(self, ev: CommitEvent) -> None:
        """One transaction from the delivery stream: cache it, wake waiters,
        fire one-shot listeners."""
        with self._mu:
            self._cache[ev.tx_id] = ev
            self._cache.move_to_end(ev.tx_id)
            if len(self._cache) > self.lru_size + self.lru_buffer:
                while len(self._cache) > self.lru_size:
                    self._cache.popitem(last=False)
            waiters = self._waiters.pop(ev.tx_id, [])
            listeners = self._listeners.pop(ev.tx_id, [])
        for w in waiters:
            w.result = ev
            w.event.set()
        for cb in listeners:
            cb(ev)

    # -------------------------------------------------------------- queries
    def add_finality_listener(self, tx_id: str, callback) -> None:
        """Invoke callback(ev) when tx_id reaches finality. If it already
        did (cache or ledger), the callback fires immediately — the
        committer-type polling guarantee collapsed to a lookup."""
        with self._mu:
            ev = self._cache.get(tx_id)
            if ev is None:
                # register BEFORE the (slow) ledger query, under the same
                # lock the delivery path takes: a commit landing after the
                # cache miss will find and fire this callback
                self._listeners.setdefault(tx_id, []).append(callback)
        if ev is not None:
            callback(ev)
            return
        ev = self._ledger_query(tx_id)
        if ev is not None:
            with self._mu:
                cbs = self._listeners.get(tx_id, [])
                if callback in cbs:
                    cbs.remove(callback)
                else:
                    return  # delivery already fired it
            callback(ev)

    def remove_finality_listener(self, tx_id: str, callback) -> None:
        with self._mu:
            cbs = self._listeners.get(tx_id, [])
            if callback in cbs:
                cbs.remove(callback)

    def is_final(self, tx_id: str, timeout: float | None = None) -> str:
        """The a->b->c->d escalation. Returns a FinalityStatus constant."""
        # a) recently final: cache
        with self._mu:
            ev = self._cache.get(tx_id)
            if ev is not None:
                return ev.status
            # b) register a waiter under the lock so the delivery path
            # cannot slip the event between lookup and registration
            waiter = _Waiter(threading.Event())
            self._waiters.setdefault(tx_id, []).append(waiter)
        if waiter.event.wait(self.listener_timeout if timeout is None
                             else timeout):
            return waiter.result.status
        with self._mu:
            ws = self._waiters.get(tx_id, [])
            if waiter in ws:
                ws.remove(waiter)
        # c) final long ago: query the ledger
        ev = self._ledger_query(tx_id)
        if ev is not None:
            return ev.status
        # d) unknown: beyond the timeout or never
        return FinalityStatus.UNKNOWN

    def _lookup(self, tx_id: str) -> CommitEvent | None:
        with self._mu:
            ev = self._cache.get(tx_id)
        if ev is not None:
            return ev
        return self._ledger_query(tx_id)

    def _ledger_query(self, tx_id: str) -> CommitEvent | None:
        """Step c: a committed token transaction leaves its request hash at
        the token-request key; presence on the ledger IS validity (invalid
        transactions write nothing)."""
        raw = self.ledger.get_state(self.ledger.keys.token_request_key(tx_id))
        if raw is not None:
            return CommitEvent(tx_id, FinalityStatus.VALID)
        return None
