"""Network services: ledger abstraction, RW-set translation, validation entry.

Mirrors reference token/services/network (SURVEY.md §2.4): the driver.Network
surface, the rws/translator that converts verified actions into ledger
key/value writes with MVCC double-spend semantics, and the token-chaincode
(tcc) processing entry point.
"""
