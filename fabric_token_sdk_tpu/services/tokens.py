"""Tokens service: ingest committed requests into the token store.

Behavioral mirror of reference token/services/tokens/tokens.go:64-239: on
finality, extract the outputs of each action (driver Deobfuscate for
commitment drivers; plaintext parse for fabtoken), compute ownership wallet
IDs, store unspent tokens, and delete spent inputs. Idempotent append keyed
by (tx_id, index) so ledger replay reconstructs the store (SURVEY.md §5
"Tokens can be re-derived from the ledger").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from ..token.model import ID
from .db.sqldb import TokenDB


@dataclass
class ExtractedOutput:
    index: int
    owner_raw: bytes
    token_type: str
    quantity_hex: str
    ledger_format: str = ""
    ledger_token: bytes = b""
    ledger_metadata: bytes = b""


class Tokens:
    """tokens.go Tokens service bound to one TMS's tokendb."""

    def __init__(self, tokendb: TokenDB,
                 ownership: Callable[[bytes], list[str]],
                 extractor: Callable | None = None):
        """ownership maps an owner identity to wallet IDs (tokens.go:64-129
        ownership resolution via authorization mux); extractor is the
        driver's Deobfuscate — ``extractor(action, openings) ->
        list[ExtractedOutput]`` with per-action local opening indexes
        (zkatdlog v1/tokens.go:111; plaintext default below)."""
        self.db = tokendb
        self.ownership = ownership
        self.extractor = extractor or self._extract_plaintext

    def append_transaction(self, tx_id: str, actions: list,
                           openings: dict[int, bytes] | None = None) -> None:
        """Ingest the verified actions of a committed transaction
        (tokens.go:171-238). ``openings`` maps GLOBAL output index (across
        all actions, in order) to the serialized opening this node received
        at distribution time."""
        openings = openings or {}
        base = 0
        for action in actions:
            n_out = len(action.get_outputs())
            local = {i: openings[base + i] for i in range(n_out)
                     if base + i in openings}
            outputs = self.extractor(action, local)
            for out in outputs:
                owners = self.ownership(out.owner_raw)
                if not out.owner_raw:
                    continue  # redeem/opaque output: not stored
                self.db.store_token(
                    ID(tx_id, base + out.index), out.owner_raw,
                    out.token_type, out.quantity_hex, owners,
                    ledger_format=out.ledger_format,
                    ledger_token=out.ledger_token,
                    ledger_metadata=out.ledger_metadata)
            for input_id in action.get_inputs():
                self.db.delete_token(input_id, spent_by=tx_id)
            base += n_out

    @staticmethod
    def _extract_plaintext(action, openings=None) -> list[ExtractedOutput]:
        """Plaintext actions expose typed outputs directly."""
        outs = []
        for i, out in enumerate(action.get_outputs()):
            outs.append(ExtractedOutput(
                index=i,
                owner_raw=bytes(out.owner),
                token_type=out.type,
                quantity_hex=out.quantity,
            ))
        return outs

    # tokens.go:239: PruneInvalidUnspentTokens — revalidate against ledger
    def prune_invalid_unspent_tokens(self, exists: Callable[[ID], bool]) -> list[ID]:
        pruned = []
        for tok in self.db.unspent_tokens():
            if not exists(tok.id):
                self.db.delete_token(tok.id, spent_by="<pruned>")
                pruned.append(tok.id)
        return pruned
