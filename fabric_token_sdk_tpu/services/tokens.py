"""Tokens service: ingest committed requests into the token store.

Behavioral mirror of reference token/services/tokens/tokens.go:64-239: on
finality, extract the outputs of each action (driver Deobfuscate for
commitment drivers; plaintext parse for fabtoken), compute ownership wallet
IDs, store unspent tokens, and delete spent inputs. Idempotent append keyed
by (tx_id, index) so ledger replay reconstructs the store (SURVEY.md §5
"Tokens can be re-derived from the ledger").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from ..token.model import ID
from .db.sqldb import TokenDB


@dataclass
class ExtractedOutput:
    index: int
    owner_raw: bytes
    token_type: str
    quantity_hex: str
    ledger_format: str = ""
    ledger_token: bytes = b""
    ledger_metadata: bytes = b""


class Tokens:
    """tokens.go Tokens service bound to one TMS's tokendb."""

    def __init__(self, tokendb: TokenDB,
                 ownership: Callable[[bytes], list[str]]):
        """ownership maps an owner identity to wallet IDs (tokens.go:64-129
        ownership resolution via authorization mux)."""
        self.db = tokendb
        self.ownership = ownership

    def append_transaction(self, tx_id: str, actions: list) -> None:
        """Ingest the verified actions of a committed transaction
        (tokens.go:171-238)."""
        base = 0
        for action in actions:
            outputs = self._extract_outputs(action)
            for out in outputs:
                owners = self.ownership(out.owner_raw)
                if not out.owner_raw:
                    base += 1
                    continue  # redeem output: not stored
                self.db.store_token(
                    ID(tx_id, base + out.index), out.owner_raw,
                    out.token_type, out.quantity_hex, owners,
                    ledger_format=out.ledger_format,
                    ledger_token=out.ledger_token,
                    ledger_metadata=out.ledger_metadata)
            for input_id in action.get_inputs():
                self.db.delete_token(input_id, spent_by=tx_id)
            base += len(outputs)

    @staticmethod
    def _extract_outputs(action) -> list[ExtractedOutput]:
        """Deobfuscate equivalent: plaintext actions expose typed outputs
        directly; commitment actions carry clear values in metadata and are
        deobfuscated by the zkatdlog TokensService wrapper before reaching
        here (zkatdlog v1/tokens.go:111)."""
        outs = []
        for i, out in enumerate(action.get_outputs()):
            outs.append(ExtractedOutput(
                index=i,
                owner_raw=bytes(out.owner),
                token_type=out.type,
                quantity_hex=out.quantity,
            ))
        return outs

    # tokens.go:239: PruneInvalidUnspentTokens — revalidate against ledger
    def prune_invalid_unspent_tokens(self, exists: Callable[[ID], bool]) -> list[ID]:
        pruned = []
        for tok in self.db.unspent_tokens():
            if not exists(tok.id):
                self.db.delete_token(tok.id, spent_by="<pruned>")
                pruned.append(tok.id)
        return pruned
