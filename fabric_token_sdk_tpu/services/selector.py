"""Token selectors: the double-spend guard at tx-assembly time.

Behavioral mirror of reference token/services/selector (SURVEY.md §2.4):

- SimpleSelector ~ selector/simple: in-process mutex + lock table.
- SherdLockSelector ~ selector/sherdlock: DB-lease-based distributed lock
  that is safe across replicas sharing one lock DB; leases expire so stuck
  locks recover (docs/core-token.md:25-31). Eager fetcher with retry/backoff
  (sherdlock/selector.go:92-157) — backoff schedule comes from the shared
  :class:`~..resilience.RetryPolicy` (seeded decorrelated jitter), so the
  waits are observable under ``resil_retries_total{op="selector_select"}``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..obs import GLOBAL as _METRICS
from ..resilience import RetryPolicy
from ..token import quantity as q
from ..token.model import ID, UnspentToken

#: Selector family metadata (HELP independent of call-site order).
_SELECTOR_FAMILIES = {
    "selector_select_seconds": "token selection + locking latency",
    "selector_retries_total":
        "Selection retries after an insufficient unlocked balance",
    "selector_tokens_locked_total": "Tokens locked by successful selections",
    "selector_insufficient_funds_total":
        "Selections that exhausted retries without covering the amount",
}
for _fam, _help in _SELECTOR_FAMILIES.items():
    _METRICS.describe(_fam, _help)
from .db.sqldb import TokenDB, TokenLockDB


class SelectorError(Exception):
    pass


class InsufficientFunds(SelectorError):
    pass


@dataclass
class Selection:
    tokens: list[UnspentToken]
    sum: int


class SherdLockSelector:
    """Lease-based selector over (tokendb, tokenlockdb)."""

    def __init__(self, tokendb: TokenDB, lockdb: TokenLockDB,
                 precision: int = 64, lease_seconds: float = 180.0,
                 retries: int = 3, backoff: float = 0.05, seed: int = 0):
        self.tokendb = tokendb
        self.lockdb = lockdb
        self.precision = precision
        self.lease_seconds = lease_seconds
        self.retries = retries
        self.backoff = backoff
        self.retry = RetryPolicy(max_attempts=retries, base_s=backoff,
                                 cap_s=backoff * 8, seed=seed,
                                 op="selector_select")

    def select(self, wallet_id: str, token_type: str, amount_hex: str,
               consumer_tx_id: str) -> Selection:
        """Lock enough tokens to cover `amount`; all-or-nothing."""
        t0 = time.perf_counter()
        target = q.to_quantity(amount_hex, self.precision).value
        delays = self.retry.delays()
        for attempt in range(self.retries):
            if attempt:
                _METRICS.counter("selector_retries_total").add()
            picked: list[UnspentToken] = []
            total = 0
            for tok in self.tokendb.unspent_tokens(wallet_id, token_type):
                if total >= target:
                    break
                if self.lockdb.lock(tok.id, consumer_tx_id):
                    picked.append(tok)
                    total += int(tok.quantity, 16)
            if total >= target:
                _METRICS.histogram(
                    "selector_select_seconds",
                    help="token selection + locking latency").observe(
                    time.perf_counter() - t0)
                _METRICS.counter("selector_tokens_locked_total").add(
                    len(picked))
                return Selection(tokens=picked, sum=total)
            # not enough: release and retry after lease eviction/backoff
            self.lockdb.unlock_by_consumer(consumer_tx_id)
            self.lockdb.evict_expired(self.lease_seconds)
            if attempt < self.retries - 1:
                self.retry.pause(next(delays))
        _METRICS.counter("selector_insufficient_funds_total").add()
        _METRICS.histogram(
            "selector_select_seconds",
            help="token selection + locking latency").observe(
            time.perf_counter() - t0)
        raise InsufficientFunds(
            f"insufficient funds, only [{total}] tokens of type [{token_type}] "
            f"are available, but [{target}] were requested and "
            f"[{len(picked)}] were locked")

    def unselect(self, consumer_tx_id: str) -> None:
        self.lockdb.unlock_by_consumer(consumer_tx_id)


class SimpleSelector(SherdLockSelector):
    """selector/simple equivalent: same behavior over an in-memory lock DB."""

    def __init__(self, tokendb: TokenDB, precision: int = 64):
        super().__init__(tokendb, TokenLockDB(":memory:"),
                         precision=precision)
