"""Token certification service (graph-hiding driver support).

Behavioral mirror of reference token/services/certifier: a client scans the
vault for uncertified unspent tokens and asks a certifier node to certify
them over the session plane; the certifier loads the token outputs from the
ledger, signs them with its certifier identity, and the client verifies and
stores the certifications (interactive/client.go:98-210, service.go:63-120).
A dummy driver (dummy/driver.go) treats every token as certified — the
reference ships no driver with GraphHiding enabled, so dummy is the default
there too (crypto/setup.go:243-245 GraphHiding=false).

TPU note: certification of commitment tokens is signing, not proving — it
stays on the host. The batchable part (re-verifying the commitments being
certified) rides the same device MSM used by the auditor re-open
(models/audit.py) when a driver with graph hiding lands.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..resilience import RetryExhausted, RetryPolicy
from ..token.model import ID
from .db.sqldb import CertificationDB


class CertificationError(Exception):
    pass


def _certification_payload(namespace: str, token_id: ID,
                           ledger_token: bytes) -> bytes:
    """Domain-separated bytes the certifier signs for one token output."""
    h = hashlib.sha256()
    h.update(b"token-certification/v1\x00")
    h.update(namespace.encode() + b"\x00")
    h.update(token_id.tx_id.encode() + b"\x00")
    h.update(token_id.index.to_bytes(8, "big"))
    h.update(ledger_token)
    return h.digest()


class CertifierService:
    """Certifier-node responder (interactive/service.go Call): load the
    requested token outputs from the ledger and sign each one.

    Registered on the session bus under its node name; the client reaches it
    with bus.node(name).certify_tokens(...).
    """

    def __init__(self, name: str, keys, chaincode, bus,
                 namespace: str = "token"):
        self.name = name
        self.keys = keys
        self.cc = chaincode
        self.namespace = namespace
        bus.register(name, self)

    def identity(self) -> bytes:
        return bytes(self.keys.identity)

    def certify_tokens(self, ids: list[ID]) -> list[bytes]:
        """Responder view: one certification (signature) per requested id.

        Unknown ids are an error — certifying a token that is not on the
        ledger would certify a spend of nothing (service.go step 3 fails
        when Backend.Load cannot resolve the outputs).
        """
        out = []
        for token_id in ids:
            raw = self.cc.ledger.get_state(
                self.cc.keys.output_key(token_id.tx_id, token_id.index))
            if raw is None:
                raise CertificationError(
                    f"cannot certify [{token_id.tx_id}:{token_id.index}]: "
                    "no such token on the ledger")
            out.append(self.keys.sign(
                _certification_payload(self.namespace, token_id, raw)))
        return out


@dataclass
class CertificationClient:
    """Vault-side client (interactive/client.go): batch uncertified tokens,
    request certification, verify + store the responses."""

    node: object                 # TokenNode whose vault is being certified
    certifier_name: str
    certifier_identity: bytes
    db: object = field(default_factory=lambda: CertificationDB(":memory:"))
    namespace: str = "token"
    max_attempts: int = 3
    wait_time: float = 0.05

    def is_certified(self, token_id: ID) -> bool:
        return self.db.exists(token_id)

    def request_certification(self, ids: list[ID]) -> None:
        """interactive/client.go:104-137: skip already-certified ids, ask
        the certifier (with bounded retry), verify every signature against
        the certifier identity and this node's own view of the ledger, then
        store."""
        to_certify = [i for i in ids if not self.is_certified(i)]
        if not to_certify:
            return
        policy = RetryPolicy(max_attempts=self.max_attempts,
                             base_s=self.wait_time,
                             cap_s=self.wait_time * 8,
                             op="certify_request")
        try:
            # CertificationError is a deterministic refusal (e.g. unknown
            # token): permanent, surfaces unchanged. Anything else is a
            # session-plane hiccup worth the bounded retry.
            sigs = policy.call(
                lambda: self.node.bus.node(
                    self.certifier_name).certify_tokens(to_certify),
                classify=lambda e: not isinstance(e, CertificationError))
        except RetryExhausted as e:
            raise CertificationError(
                f"certification request failed after {e.attempts} "
                f"attempts: {e.last_error}") from e.last_error
        if len(sigs) != len(to_certify):
            raise CertificationError(
                f"certifier returned {len(sigs)} certifications for "
                f"{len(to_certify)} tokens")
        self.db.store(dict(zip(to_certify, self._verify(to_certify, sigs))))

    def _verify(self, ids: list[ID], sigs: list[bytes]) -> list[bytes]:
        """VerifyCertifications (client.go step 4): recompute each payload
        from this node's ledger view — a certifier cannot attest to bytes
        the client does not itself see."""
        from .identity.x509 import X509Verifier

        verifier = X509Verifier.from_identity(self.certifier_identity)
        cc = self.node.cc
        for token_id, sig in zip(ids, sigs):
            raw = cc.ledger.get_state(
                cc.keys.output_key(token_id.tx_id, token_id.index))
            if raw is None:
                raise CertificationError(
                    f"certified token [{token_id.tx_id}:{token_id.index}] "
                    "is not on this node's ledger")
            verifier.verify(
                _certification_payload(self.namespace, token_id, raw), sig)
        return sigs

    def scan(self) -> int:
        """interactive/client.go:141-177: walk unspent tokens, certify the
        uncertified ones. Covers the node's whole vault — personal tokens
        AND co-owned escrow tokens (filed under '<name>.ms' by
        node._ownership; the reference iterates every vault token).
        Returns how many were newly certified."""
        pending = [
            t.id
            for wallet in (self.node.name, f"{self.node.name}.ms")
            for t in self.node.tokendb.unspent_tokens(wallet)
            if not self.is_certified(t.id)
        ]
        if pending:
            self.request_certification(pending)
        return len(pending)


class DummyCertificationClient:
    """dummy/driver.go: every token is born certified."""

    def is_certified(self, token_id: ID) -> bool:
        return True

    def request_certification(self, ids: list[ID]) -> None:
        return None

    def scan(self) -> int:
        return 0


CERTIFICATION_DRIVERS = {
    "interactive": CertificationClient,
    "dummy": DummyCertificationClient,
}
