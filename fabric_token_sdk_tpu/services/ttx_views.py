"""Per-step ttx view choreography over real message sessions.

Behavioral mirror of the reference's view surface that services/ttx.py's
direct dispatch collapses (VERDICT r3 missing #3):

  - recipient exchange     reference token/services/ttx/recipients.go:82-180
  - withdrawal             reference token/services/ttx/withdrawal.go:50-192
  - accept                 reference token/services/ttx/accept.go:39-120
  - status                 reference token/services/ttx/status.go + ttxdb

Each step is a paired initiator/responder view exchanging typed JSON
messages over a duplex stream (the same QueuePairStream transport the
external-wallet protocol uses, ttx_external.py): the responder runs in its
own thread on the responder node, exactly like FSC spawns a responder view
per incoming session. Apps see the reference's protocol surface — request
message, response message, ack signature — not a Python method call.
"""

from __future__ import annotations

import json
import threading
import time

from .db.sqldb import TxRecord, TxStatus
from .ttx import SessionBus, Transaction, TtxError, collect_endorsements, \
    ordering_and_finality
from .ttx_external import QueuePairStream


class Session:
    """One side of a paired view session: typed JSON messages over a
    duplex stream (FSC session.Send/Receive with timeouts,
    ttx/endorse.go:190-296)."""

    def __init__(self, stream: QueuePairStream, timeout: float = 30.0):
        self._stream = stream
        self.timeout = timeout

    def send(self, msg: dict) -> None:
        self._stream.send(json.dumps(msg))

    def recv(self) -> dict:
        from .ttx_external import ExternalWalletError

        try:
            return json.loads(self._stream.recv(timeout=self.timeout))
        except ExternalWalletError as e:
            raise TtxError(f"view session receive failed: {e}") from e


class ViewBus:
    """Session-spawning wrapper over the SessionBus: `open_session`
    starts the named responder view on the target node in a thread and
    hands the initiator its session endpoint (FSC's InitiateView +
    responder registration)."""

    #: responder view registry: view name -> handler(node, session, bus)
    RESPONDERS: dict = {}

    def __init__(self, bus: SessionBus):
        self.bus = bus
        self._threads: list[threading.Thread] = []

    @classmethod
    def responder(cls, name: str):
        def deco(fn):
            cls.RESPONDERS[name] = fn
            return fn
        return deco

    def open_session(self, responder_node: str, view_name: str) -> Session:
        handler = self.RESPONDERS.get(view_name)
        if handler is None:
            raise TtxError(f"no responder registered for [{view_name}]")
        node = self.bus.node(responder_node)
        initiator_end, responder_end = QueuePairStream.pair()
        t = threading.Thread(
            target=handler, args=(node, Session(responder_end), self),
            name=f"view-{view_name}@{responder_node}", daemon=True)
        t.start()
        # reap finished responders so a long-lived bus doesn't accumulate
        # dead Thread objects
        self._threads = [x for x in self._threads if x.is_alive()]
        self._threads.append(t)
        return Session(initiator_end)

    def join(self, timeout: float = 30.0) -> None:
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads.clear()


# --------------------------------------------------------------------------
# recipient exchange (recipients.go:82-180)
# --------------------------------------------------------------------------

def request_recipient_identity(vbus: ViewBus, recipient_node: str,
                               wallet_id: str = "") -> tuple[bytes, bytes]:
    """RequestRecipientIdentityView: ask the recipient's node for the
    identity it wants tokens assigned to (+ audit info). Returns
    (identity, audit_info) — fresh per call for pseudonymous wallets."""
    session = vbus.open_session(recipient_node, "recipient")
    session.send({"wallet_id": wallet_id})
    resp = session.recv()
    if "error" in resp:
        raise TtxError(f"recipient exchange failed: {resp['error']}")
    return bytes.fromhex(resp["identity"]), bytes.fromhex(resp["audit_info"])


@ViewBus.responder("recipient")
def _respond_recipient(node, session: Session, vbus: ViewBus) -> None:
    """RespondRequestRecipientIdentityView (recipients.go:140-180)."""
    try:
        msg = session.recv()  # RecipientRequest{wallet_id}
        ident, audit_info = node.recipient_identity(msg.get("wallet_id", ""))
        session.send({"identity": ident.hex(),
                      "audit_info": bytes(audit_info).hex()})
    except Exception as e:  # responder views report, never crash the node
        session.send({"error": str(e)})


# --------------------------------------------------------------------------
# accept (accept.go:39-120)
# --------------------------------------------------------------------------

def _accept_tx(node, msg: dict) -> bytes:
    """The responder half of acceptance: store the tx records + openings,
    sign the request bytes as ack (accept.go:54-75)."""
    tx_id = msg["tx_id"]
    request_raw = bytes.fromhex(msg["request_raw"])
    for idx_s, opening_hex in msg.get("openings", {}).items():
        node.receive_opening(tx_id, int(idx_s), bytes.fromhex(opening_hex))
    rec = msg.get("record")
    if rec:
        node.ttxdb.add_transaction(TxRecord(
            tx_id=tx_id, action_type=rec["action_type"],
            sender=rec.get("sender", ""), recipient=rec.get("recipient", ""),
            token_type=rec.get("token_type", ""),
            amount=int(rec.get("amount", 0)), status=TxStatus.PENDING,
            timestamp=time.time()))
    node.ttxdb.add_token_request(tx_id, request_raw)
    sigma = node.keys.sign(request_raw)
    node.ttxdb.add_endorsement_ack(tx_id, node.identity(), sigma)
    return sigma


def _verify_ack(resp: dict, expected_identity: bytes, request_raw: bytes,
                deserializer) -> bytes:
    """Shared ack check: bind the responder's claimed identity to the node
    the session was opened to, then verify the signature under it. A reply
    claiming some other (fresh) identity proves nothing even if its
    signature verifies. Returns the verified sigma."""
    sigma = bytes.fromhex(resp["ack"])
    identity = bytes.fromhex(resp["identity"])
    if identity != bytes(expected_identity):
        raise TtxError("ack identity mismatch: responder answered with a "
                       "different identity")
    deserializer.get_owner_verifier(identity).verify(request_raw, sigma)
    return sigma


@ViewBus.responder("accept")
def _respond_accept(node, session: Session, vbus: ViewBus) -> None:
    try:
        msg = session.recv()
        sigma = _accept_tx(node, msg)
        session.send({"ack": sigma.hex(),
                      "identity": node.identity().hex()})
    except Exception as e:
        session.send({"error": str(e)})


def distribute_for_acceptance(vbus: ViewBus, tx: Transaction,
                              deserializer=None,
                              parties: list[str] | None = None
                              ) -> dict[str, bytes]:
    """Send each party the envelope (+ its outputs' openings, if the
    driver produces any) over a session and collect verified ack
    signatures (endorse.go:444 distributeEnvToParties + accept.go ack
    round-trip). Returns node -> ack signature.

    `parties` adds envelope-only recipients — plaintext drivers have no
    openings to distribute but their parties still accept and ack."""
    per_node: dict[str, dict[int, bytes]] = {}
    for node_name, index, opening_raw in tx.distribution:
        per_node.setdefault(node_name, {})[index] = opening_raw
    for name in parties or []:
        per_node.setdefault(name, {})
    request_raw = tx.request.to_bytes()
    acks: dict[str, bytes] = {}
    for node_name, openings in per_node.items():
        session = vbus.open_session(node_name, "accept")
        session.send({
            "tx_id": tx.tx_id,
            "request_raw": request_raw.hex(),
            "openings": {str(i): o.hex() for i, o in openings.items()},
            "record": _record_for(tx, node_name),
        })
        resp = session.recv()
        if "error" in resp:
            raise TtxError(f"acceptance by [{node_name}] failed: "
                           f"{resp['error']}")
        acks[node_name] = _verify_ack(
            resp, vbus.bus.node(node_name).identity(), request_raw,
            deserializer or _default_deserializer())
    return acks


def _default_deserializer():
    """x509 fallback so an ack is never accepted unverified (node
    identities are x509; drivers with richer owners pass their own)."""
    from .identity.deserializer import Deserializer

    return Deserializer()


def _record_for(tx: Transaction, node_name: str) -> dict | None:
    for rec in tx.records:
        if rec.recipient == node_name or rec.sender == node_name:
            return {"action_type": rec.action_type, "sender": rec.sender,
                    "recipient": rec.recipient,
                    "token_type": rec.token_type, "amount": rec.amount}
    return None


# --------------------------------------------------------------------------
# withdrawal (withdrawal.go:50-192)
# --------------------------------------------------------------------------

def request_withdrawal(vbus: ViewBus, requester_node: str, issuer_node: str,
                       token_type: str, amount: int) -> str:
    """RequestWithdrawalView: generate a recipient identity locally, send
    the WithdrawalRequest to the issuer, then respond to the acceptance
    leg the issuer drives back. Returns the committed tx id."""
    requester = vbus.bus.node(requester_node)
    ident, audit_info = requester.recipient_identity()
    session = vbus.open_session(issuer_node, "withdrawal")
    session.send({
        "requester": requester_node,
        "token_type": token_type,
        "amount": amount,
        "recipient": {"identity": ident.hex(),
                      "audit_info": bytes(audit_info).hex()},
    })
    # acceptance leg: the issuer sends the assembled tx for this node to
    # accept (openings + records + ack) over the SAME session
    msg = session.recv()
    if "error" in msg:
        raise TtxError(f"withdrawal failed: {msg['error']}")
    sigma = _accept_tx(requester, msg)
    session.send({"ack": sigma.hex(), "identity": requester.identity().hex()})
    final = session.recv()
    if "error" in final:
        # the issuer died AFTER this node accepted (stored a PENDING
        # record) but BEFORE ordering: no commit event will ever fire, so
        # close out the local record here — otherwise status stays
        # Pending forever for a tx that will never exist
        requester.ttxdb.set_status(msg["tx_id"], TxStatus.DELETED,
                                   str(final["error"]))
        raise TtxError(f"withdrawal failed: {final['error']}")
    if final["status"] != "VALID":
        raise TtxError(f"withdrawal tx invalid: {final.get('message', '')}")
    return final["tx_id"]


@ViewBus.responder("withdrawal")
def _respond_withdrawal(node, session: Session, vbus: ViewBus) -> None:
    """Issuer-side responder (withdrawal.go:131-192 + IssueCash view
    shape): assemble the issue, endorse + audit, drive the requester's
    acceptance over the session, then order and report finality."""
    from ..core.fabtoken.driver import OutputSpec
    from ..token.request_builder import Request

    stored_tx: str | None = None
    try:
        msg = session.recv()
        ident = bytes.fromhex(msg["recipient"]["identity"])
        audit_info = bytes.fromhex(msg["recipient"]["audit_info"])
        token_type, value = msg["token_type"], int(msg["amount"])
        requester = msg["requester"]

        tx_id = Transaction.new_anchor()
        req = Request(tx_id, node.driver)
        req.issue(node.issuer_public_identity(),
                  [OutputSpec(owner=ident, token_type=token_type,
                              value=value, audit_info=audit_info)],
                  receivers=[requester])
        tx = Transaction(tx_id=tx_id, request=req.token_request(),
                         issuer_node=node.name,
                         metadata=req.request_metadata(),
                         distribution=req.distribution())
        tx.records.append(TxRecord(
            tx_id=tx_id, action_type="issue", sender="",
            recipient=requester, token_type=token_type, amount=value,
            status=TxStatus.PENDING, timestamp=time.time()))

        # endorsement: issuer signature + audit ride the bus as before;
        # distribution rides THIS session (acceptance leg)
        saved_distribution, tx.distribution = tx.distribution, []
        collect_endorsements(tx, node.bus, node.auditor_name)
        tx.distribution = saved_distribution

        request_raw = tx.request.to_bytes()
        per_requester = {i: o for (n, i, o) in tx.distribution
                         if n == requester}
        session.send({
            "tx_id": tx_id,
            "request_raw": request_raw.hex(),
            "openings": {str(i): o.hex()
                         for i, o in per_requester.items()},
            "record": _record_for(tx, requester),
        })
        resp = session.recv()
        if "error" in resp:
            raise TtxError(f"acceptance failed: {resp['error']}")
        sigma = _verify_ack(
            resp, node.bus.node(requester).identity(), request_raw,
            getattr(node.cc.validator, "deserializer", None)
            or _default_deserializer())
        node.ttxdb.add_endorsement_ack(
            tx_id, bytes.fromhex(resp["identity"]), sigma)

        node._watched[tx_id] = tx.request
        node.ttxdb.add_token_request(tx_id, request_raw)
        for rec in tx.records:
            node.ttxdb.add_transaction(rec)
        stored_tx = tx_id
        ev = ordering_and_finality(tx, node.cc)
        # Ordered: the ledger outcome is now authoritative, so the failure
        # close-out below must NOT mark the record DELETED if the final
        # status send to a disconnected requester raises (ADVICE r4).
        stored_tx = None
        session.send({"tx_id": tx_id, "status": ev.status,
                      "message": ev.message})
    except Exception as e:
        if stored_tx is not None:
            # failed AFTER storing the issuer's PENDING record but before
            # (or during) ordering: no commit event will ever fire, so
            # close out the issuer's own record and stop watching —
            # mirroring the requester-side close-out in request_withdrawal
            node._watched.pop(stored_tx, None)
            node.ttxdb.set_status(stored_tx, TxStatus.DELETED, str(e))
        session.send({"error": str(e)})


# --------------------------------------------------------------------------
# status (status.go + ttxdb.GetStatus)
# --------------------------------------------------------------------------

def request_status(vbus: ViewBus, node_name: str, tx_id: str) -> str:
    """StatusView: ask a node for its recorded status of tx_id
    (Unknown/Pending/Confirmed/Deleted vocabulary, status.go:14-23)."""
    session = vbus.open_session(node_name, "status")
    session.send({"tx_id": tx_id})
    resp = session.recv()
    if "error" in resp:
        raise TtxError(f"status query failed: {resp['error']}")
    return resp["status"]


@ViewBus.responder("status")
def _respond_status(node, session: Session, vbus: ViewBus) -> None:
    try:
        msg = session.recv()
        session.send({"status": node.ttxdb.get_status(msg["tx_id"])})
    except Exception as e:
        session.send({"error": str(e)})
