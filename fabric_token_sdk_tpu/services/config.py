"""Config service: typed access to the `token:` configuration tree.

Behavioral mirror of reference token/services/config/config.go:80-147 over
the YAML schema documented at reference docs/core-token.md:1-200: TMS
enumeration keyed by (network, channel, namespace), selector and finality
tuning, db driver choice, wallet trees. YAML parsing uses a small built-in
subset loader when PyYAML is unavailable (zero new dependencies).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any


class ConfigError(Exception):
    pass


@dataclass(frozen=True)
class TMSID:
    """(network, channel, namespace) triple identifying one TMS."""

    network: str
    channel: str = ""
    namespace: str = ""

    def __str__(self) -> str:
        return f"{self.network},{self.channel},{self.namespace}"


@dataclass
class TMSConfig:
    tms_id: TMSID
    driver: str = "fabtoken"
    public_params_path: str = ""
    db_driver: str = "sqlite"
    db_path: str = ":memory:"
    selector: dict = field(default_factory=lambda: {
        # docs/core-token.md:13-31 selector tree
        "driver": "sherdlock",
        "retryInterval": "5s",
        "numRetries": 3,
        "leaseExpiry": "180s",
        "leaseCleanupTickPeriod": "60s",
    })
    finality: dict = field(default_factory=lambda: {
        # docs/core-token.md:33-77 finality/delivery tuning
        "committerParallelism": 8,
        "mapperParallelism": 8,
        "blockProcessParallelism": 1,
    })
    wallets: dict = field(default_factory=dict)
    raw: dict = field(default_factory=dict)


class Config:
    """config.go:80-147: the `token:` section of the node config."""

    def __init__(self, tree: dict | None = None):
        self.tree = tree or {}

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path) as f:
            text = f.read()
        try:
            import yaml  # type: ignore

            return cls(yaml.safe_load(text) or {})
        except ImportError:
            try:
                return cls(json.loads(text))
            except json.JSONDecodeError as e:
                raise ConfigError(
                    "config must be JSON when PyYAML is unavailable") from e

    def token_enabled(self) -> bool:
        return bool(self.tree.get("token", {}).get("enabled", True))

    def version(self) -> str:
        return str(self.tree.get("token", {}).get("version", "v1"))

    def tms_configs(self) -> list[TMSConfig]:
        """Enumerate configured TMSs (config.go:96-147)."""
        out = []
        tms_tree = self.tree.get("token", {}).get("tms", {})
        for key, entry in tms_tree.items():
            entry = entry or {}
            tms_id = TMSID(
                network=entry.get("network", key),
                channel=entry.get("channel", ""),
                namespace=entry.get("namespace", ""),
            )
            cfg = TMSConfig(tms_id=tms_id, raw=entry)
            if "driver" in entry:
                cfg.driver = entry["driver"]
            if "publicParameters" in entry:
                cfg.public_params_path = (
                    entry["publicParameters"].get("path", ""))
            db = entry.get("db", {}).get("persistence", {})
            if db:
                cfg.db_driver = db.get("type", cfg.db_driver)
                opts = db.get("opts", {})
                cfg.db_path = opts.get("dataSource", cfg.db_path)
            if "selector" in entry:
                cfg.selector.update(entry["selector"])
            if "finality" in entry:
                cfg.finality.update(entry["finality"])
            if "wallets" in entry:
                cfg.wallets = entry["wallets"]
            out.append(cfg)
        return out

    def tms(self, tms_id: TMSID) -> TMSConfig:
        for cfg in self.tms_configs():
            if cfg.tms_id == tms_id:
                return cfg
        raise ConfigError(f"no TMS configured for [{tms_id}]")


def parse_duration(s: str | float | int) -> float:
    """Go-style duration strings ("5s", "1m30s", "500ms") -> seconds."""
    if isinstance(s, (int, float)):
        return float(s)
    total = 0.0
    num = ""
    i = 0
    units = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6,
             "ns": 1e-9}
    while i < len(s):
        c = s[i]
        if c.isdigit() or c == ".":
            num += c
            i += 1
            continue
        unit = c
        if s[i : i + 2] in ("ms", "us", "ns"):
            unit = s[i : i + 2]
            i += 2
        else:
            i += 1
        if not num or unit not in units:
            raise ConfigError(f"invalid duration [{s}]")
        total += float(num) * units[unit]
        num = ""
    if num:
        raise ConfigError(f"invalid duration [{s}]")
    return total
