"""ttx: token transaction lifecycle choreography.

Behavioral mirror of reference token/services/ttx (SURVEY.md §2.4, §3.1):
Transaction{tx_id, anchor, TokenRequest}; collect-endorsements (owner
signatures -> auditor audit+endorse -> approval -> distribution); ordering
broadcast; finality wait. The FSC view/session plane collapses to an
in-process SessionBus between named nodes — the same paired
initiator/responder steps, minus the websocket transport (SURVEY.md §2.5:
the session plane is control-plane and stays on CPU).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field

from ..driver import TokenRequest
from ..token.model import ID
from .db.sqldb import TxRecord, TxStatus
from .network.tcc import CommitEvent


class TtxError(Exception):
    pass


@dataclass
class Transaction:
    """ttx/transaction.go:24-46: payload of one token transaction."""

    tx_id: str
    request: TokenRequest
    # client-side bookkeeping: which signer nodes own each transfer input,
    # populated at assembly time (mirror of TokenRequest metadata)
    input_owners: list[str] = field(default_factory=list)
    # raw on-ledger owner identity per transfer input (a pseudonym for
    # Idemix wallets) — tells the signing node WHICH identity must endorse
    input_owner_ids: list[bytes] = field(default_factory=list)
    issuer_node: str | None = None
    # record stream for ttxdb
    records: list[TxRecord] = field(default_factory=list)
    # request metadata (commitment openings + audit info for commitment
    # drivers; None for plaintext drivers). Never reaches the ledger: it
    # flows over sessions to the auditor and — per-output — to receivers.
    metadata: object | None = None
    # opening distribution plan: (recipient node, global output index,
    # serialized opening), computed at assembly time
    # (ttx/endorse.go:444 distributeEnvToParties).
    distribution: list[tuple[str, int, bytes]] = field(default_factory=list)

    @staticmethod
    def new_anchor() -> str:
        return uuid.uuid4().hex

    def message_to_sign(self) -> bytes:
        return self.request.message_to_sign(self.tx_id.encode())


class SessionBus:
    """In-process replacement for FSC sessions: named nodes, direct calls.

    Every multi-party step in the reference runs as paired views over
    sessions (ttx/endorse.go:190-296); here a session is a method dispatch
    to the responder node object, preserving the request/response shape.
    """

    def __init__(self):
        self.nodes: dict[str, object] = {}
        self.lock = threading.RLock()

    def register(self, name: str, node) -> None:
        with self.lock:
            self.nodes[name] = node

    def node(self, name: str):
        with self.lock:
            if name not in self.nodes:
                raise TtxError(f"unknown node [{name}]")
            return self.nodes[name]


def collect_endorsements(tx: Transaction, bus: SessionBus,
                         auditor_node: str | None) -> None:
    """ttx/endorse.go:86-163: sign -> audit -> (approval happens at
    ordering in the standalone backend) -> distribute.

    Mutates tx.request with collected signatures.
    """
    msg = tx.message_to_sign()

    # 1. collect action signatures. The validator consumes the signature
    # list with one cursor in validation order — issues first, then
    # transfers (common/validator.go verifies issues before transfers;
    # reference ttx/endorse.go:93-99 likewise collects issue signatures
    # first) — so the issuer signature must precede the owner signatures.
    if tx.issuer_node is not None:
        responder = bus.node(tx.issuer_node)
        sigma = responder.sign_issue(tx.tx_id, msg)
        tx.request.signatures.append(sigma)
    for i, owner_name in enumerate(tx.input_owners):
        owner_raw = tx.input_owner_ids[i] if tx.input_owner_ids else None
        if isinstance(owner_name, (list, tuple)):
            # multisig escrow input: every co-owner signs; signatures are
            # joined in the multisig identity's own order
            # (identity/multisig/sig.go JoinSignatures).
            from .identity.multisig import join_signatures, unwrap

            _, ids = unwrap(owner_raw)
            sigmas: dict[bytes, bytes] = {}
            for co_name in owner_name:
                ident, sigma = bus.node(co_name).sign_as_co_owner(
                    tx.tx_id, msg, owner_raw)
                sigmas[ident] = sigma
            tx.request.signatures.append(join_signatures(ids, sigmas))
            continue
        responder = bus.node(owner_name)
        sigma = responder.sign_transfer(tx.tx_id, msg, owner_raw)
        tx.request.signatures.append(sigma)

    # 2. request audit (endorse.go:409; ttx/auditor.go:128-254)
    if auditor_node is not None:
        auditor = bus.node(auditor_node)
        sigma = auditor.audit(tx)
        tx.request.auditor_signatures.append(sigma)

    # 3. distribute openings to output receivers (endorse.go:444
    # distributeEnvToParties): each receiver learns the openings of the
    # outputs destined to it so it can ingest them at finality.
    for node_name, index, opening_raw in tx.distribution:
        bus.node(node_name).receive_opening(tx.tx_id, index, opening_raw)


def ordering_and_finality(tx: Transaction, chaincode,
                          timeout: float = 10.0) -> CommitEvent:
    """ttx/ordering.go:36-66 + ttx/finality.go:50-140 against the
    standalone ordered ledger: broadcast == process + commit; the commit
    event is the finality signal (listeners fire synchronously)."""
    return chaincode.process_request(tx.tx_id, tx.request.to_bytes())


class FinalityListener:
    """network/common/finality.go:57-121: re-extract tokens on commit."""

    def __init__(self, node):
        self.node = node

    def __call__(self, ev: CommitEvent) -> None:
        self.node.on_finality(ev)
