"""HTLC (hash time-locked contract) scripts-as-owner.

Behavioral mirror of reference token/services/interop/htlc (script.go,
keys.go, signer.go) + token/services/identity/interop/htlc/validator.go:
a token owned by an HTLC script can be claimed by the recipient before the
deadline by revealing the hash pre-image (recorded in the action metadata
under ClaimKey), or reclaimed by the sender after the deadline; lock actions
must record LockKey. Driver validators call transfer_htlc_validate from
their transfer chains (fabtoken validator_transfer.go:96-170, zkatdlog
validator_transfer.go:112-175).
"""

from __future__ import annotations

import base64
import hashlib
import json
import time as time_mod
from dataclasses import dataclass, field

from ...driver.identity import Identity
from ..identity import typed as typed_mod

SCRIPT_TYPE = "htlc"  # reference htlc/transaction.go:27

CLAIM_PREIMAGE = "htlc.cpi"  # reference htlc/keys.go:14
LOCK_HASH = "htlc.lh"        # reference htlc/keys.go:15

# OperationType (identity/interop/htlc/validator.go:19-25)
OP_NONE, OP_CLAIM, OP_RECLAIM = 0, 1, 2

# Supported hash functions (reference uses Go crypto.Hash; SHA-256 is the
# default used by the interop suites).
_HASH_FUNCS = {"SHA256": hashlib.sha256, "SHA512": hashlib.sha512}


class HTLCError(Exception):
    pass


def claim_key(image: bytes) -> str:
    return CLAIM_PREIMAGE + image.hex()


def lock_key(hash_value: bytes) -> str:
    return LOCK_HASH + hash_value.hex()


def lock_value(hash_value: bytes) -> bytes:
    return hash_value.hex().encode()


@dataclass
class HashInfo:
    """reference script.go:24-62 (hex-encoded image by default)."""

    hash: bytes
    hash_func: str = "SHA256"
    hash_encoding: str = "hex"

    def validate(self) -> None:
        if self.hash_func not in _HASH_FUNCS:
            raise HTLCError("hash function not available")
        if self.hash_encoding not in ("hex", "none"):
            raise HTLCError("encoding function not available")

    def image(self, preimage: bytes) -> bytes:
        self.validate()
        digest = _HASH_FUNCS[self.hash_func](preimage).digest()
        if self.hash_encoding == "hex":
            return digest.hex().encode()
        return digest

    def compare(self, image: bytes) -> None:
        if image != self.hash:
            raise HTLCError(
                f"passed image does not match the hash")


@dataclass
class Script:
    """reference script.go:64-95."""

    sender: bytes
    recipient: bytes
    deadline: float  # unix seconds
    hash_info: HashInfo

    def validate(self, time_reference: float) -> None:
        if len(self.sender) == 0:
            raise HTLCError("sender not set")
        if len(self.recipient) == 0:
            raise HTLCError("recipient not set")
        if self.deadline < time_reference:
            raise HTLCError("expiration date has already passed")
        self.hash_info.validate()

    def to_json(self) -> bytes:
        return json.dumps({
            "sender": base64.b64encode(self.sender).decode(),
            "recipient": base64.b64encode(self.recipient).decode(),
            "deadline": self.deadline,
            "hash_info": {
                "hash": base64.b64encode(self.hash_info.hash).decode(),
                "hash_func": self.hash_info.hash_func,
                "hash_encoding": self.hash_info.hash_encoding,
            },
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Script":
        d = json.loads(raw)
        hi = d.get("hash_info") or {}
        return cls(
            sender=base64.b64decode(d.get("sender", "")),
            recipient=base64.b64decode(d.get("recipient", "")),
            deadline=d.get("deadline", 0),
            hash_info=HashInfo(
                hash=base64.b64decode(hi.get("hash", "")),
                hash_func=hi.get("hash_func", "SHA256"),
                hash_encoding=hi.get("hash_encoding", "hex"),
            ),
        )

    def to_owner(self) -> Identity:
        """Wrap as a typed identity usable as a token owner."""
        return typed_mod.wrap_with_type(SCRIPT_TYPE, self.to_json())


@dataclass
class ClaimSignature:
    """reference signer.go:19-22."""

    recipient_signature: bytes
    preimage: bytes

    def to_json(self) -> bytes:
        return json.dumps({
            "recipient_signature": base64.b64encode(
                self.recipient_signature).decode(),
            "preimage": base64.b64encode(self.preimage).decode(),
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ClaimSignature":
        d = json.loads(raw)
        return cls(
            recipient_signature=base64.b64decode(
                d.get("recipient_signature", "")),
            preimage=base64.b64decode(d.get("preimage", "")),
        )


class ScriptVerifier:
    """driver.Verifier for script-owned tokens: dispatches to sender or
    recipient key based on claim-signature framing (htlc/signer.go
    ClaimVerifier semantics)."""

    def __init__(self, script: Script, resolve_verifier):
        self.script = script
        self.resolve = resolve_verifier

    def verify(self, message: bytes, signature: bytes) -> None:
        try:
            claim = ClaimSignature.from_json(signature)
            if claim.preimage and claim.recipient_signature:
                # claim path: recipient signs; image must match the lock
                self.script.hash_info.compare(
                    self.script.hash_info.image(claim.preimage))
                verifier = self.resolve(Identity(self.script.recipient))
                verifier.verify(message, claim.recipient_signature)
                return
        except (ValueError, KeyError):
            pass
        # reclaim path: sender signs plainly
        verifier = self.resolve(Identity(self.script.sender))
        verifier.verify(message, signature)


def script_verifier_resolver(resolve_verifier):
    """Extra-owner resolver pluggable into identity.Deserializer."""
    def resolver(ti: typed_mod.TypedIdentity):
        if ti.type != SCRIPT_TYPE:
            return None
        return ScriptVerifier(Script.from_json(ti.identity), resolve_verifier)
    return resolver


def verify_owner(sender_raw_owner: bytes, out_raw_owner: bytes,
                 now: float) -> tuple[Script, int]:
    """identity/interop/htlc/validator.go:31-59."""
    sender = typed_mod.unmarshal_typed_identity(sender_raw_owner)
    if sender.type != SCRIPT_TYPE:
        raise HTLCError(
            f"invalid identity type, expected [{SCRIPT_TYPE}], got "
            f"[{sender.type}]")
    script = Script.from_json(sender.identity)
    if now < script.deadline:
        if bytes(script.recipient) != bytes(out_raw_owner):
            raise HTLCError("owner of output token does not correspond to "
                            "recipient in htlc request")
        return script, OP_CLAIM
    if bytes(script.sender) != bytes(out_raw_owner):
        raise HTLCError("owner of output token does not correspond to "
                        "sender in htlc request")
    return script, OP_RECLAIM


def metadata_claim_key_check(action, script: Script, op: int,
                             sig: bytes) -> str:
    """identity/interop/htlc/validator.go:62-97."""
    if op == OP_RECLAIM:
        return ""
    try:
        claim = ClaimSignature.from_json(sig)
    except Exception as e:
        raise HTLCError(
            f"failed unmarshalling claim signature: {e}") from e
    if not claim.preimage or not claim.recipient_signature:
        raise HTLCError(
            "expected a valid claim preImage and recipient signature")
    metadata = action.get_metadata() or {}
    if not metadata:
        raise HTLCError("cannot find htlc pre-image, no metadata")
    image = script.hash_info.image(claim.preimage)
    key = claim_key(image)
    if key not in metadata:
        raise HTLCError("cannot find htlc pre-image, missing metadata entry")
    if metadata[key] != claim.preimage:
        raise HTLCError(
            "invalid action, cannot match htlc pre-image with metadata")
    return key


def metadata_lock_key_check(action, script: Script) -> str:
    """identity/interop/htlc/validator.go:100-115."""
    metadata = action.get_metadata() or {}
    if not metadata:
        raise HTLCError("cannot find htlc lock, no metadata")
    key = lock_key(script.hash_info.hash)
    if key not in metadata:
        raise HTLCError("cannot find htlc lock, missing metadata entry")
    if metadata[key] != lock_value(script.hash_info.hash):
        raise HTLCError("invalid action, cannot match htlc lock with metadata")
    return key


def _unmarshal_owner_or_plain(raw: bytes, what: str) -> typed_mod.TypedIdentity | None:
    """Owner bytes -> TypedIdentity, None for plain keys, error otherwise.

    The reference validators fail hard when an owner does not parse as a
    TypedIdentity ("failed to unmarshal owner of input token",
    fabtoken/zkatdlog validator_transfer.go). Deliberate divergence: this
    framework also admits raw (untyped) EC public keys as owners
    (identity/deserializer.py falls back to X509Verifier); those are
    demonstrably plain — they parse as a public key — carry no script, and
    are skipped. Malformed bytes that are neither remain an error, matching
    the reference.
    """
    try:
        return typed_mod.unmarshal_typed_identity(raw)
    except Exception:
        pass
    from ..identity.x509 import X509Verifier

    try:
        X509Verifier.from_identity(Identity(raw))
        return None
    except Exception:
        raise HTLCError(f"failed to unmarshal owner of {what} token")


def _validate_output_scripts(ctx, action, now: float) -> None:
    """Shared output-side loop (both reference validators are identical
    here): every non-redeem output owned by a live script must carry the
    matching LockKey metadata entry."""
    for output in action.get_outputs():
        if output.is_redeem():
            continue
        owner = _unmarshal_owner_or_plain(output.owner, "output")
        if owner is None or owner.type != SCRIPT_TYPE:
            continue
        script = Script.from_json(owner.identity)
        try:
            script.validate(now)
        except HTLCError as e:
            raise HTLCError(f"htlc script invalid: {e}") from e
        key = metadata_lock_key_check(action, script)
        ctx.count_metadata_key(key)


def transfer_htlc_validate_fabtoken(ctx, now: float | None = None) -> None:
    """fabtoken driver-chain step (fabtoken validator_transfer.go:96-170):
    a script spend must be the action's only output with identical plaintext
    type and quantity, and must not redeem."""
    if now is None:
        now = time_mod.time()
    action = ctx.transfer_action

    for i, tok in enumerate(ctx.input_tokens):
        owner = _unmarshal_owner_or_plain(tok.get_owner(), "input")
        if owner is None or owner.type != SCRIPT_TYPE:
            continue
        outputs = action.get_outputs()
        if len(outputs) != 1:
            raise HTLCError("invalid transfer action: an htlc script only "
                            "transfers the ownership of a token")
        output = outputs[0]
        first = ctx.input_tokens[0]
        if first.type != output.type:
            raise HTLCError("invalid transfer action: type of input does "
                            "not match type of output")
        if first.quantity != output.quantity:
            raise HTLCError("invalid transfer action: quantity of input "
                            "does not match quantity of output")
        if output.is_redeem():
            raise HTLCError("invalid transfer action: the output "
                            "corresponding to an htlc spending should not "
                            "be a redeem")
        script, op = verify_owner(first.get_owner(), output.owner, now)
        sigma = ctx.signatures[i]
        key = metadata_claim_key_check(action, script, op, sigma)
        if op != OP_RECLAIM:
            ctx.count_metadata_key(key)

    _validate_output_scripts(ctx, action, now)


def transfer_htlc_validate_zkatdlog(ctx, now: float | None = None) -> None:
    """zkatdlog driver-chain step (zkatdlog validator_transfer.go:112-175):
    a script spend must be exactly 1-in/1-out; commitment tokens hide type
    and quantity, so no plaintext equality checks exist (value conservation
    is enforced by the ZK proof)."""
    if now is None:
        now = time_mod.time()
    action = ctx.transfer_action

    for i, tok in enumerate(ctx.input_tokens):
        owner = _unmarshal_owner_or_plain(tok.get_owner(), "input")
        if owner is None or owner.type != SCRIPT_TYPE:
            continue
        if len(ctx.input_tokens) != 1 or len(action.get_outputs()) != 1:
            raise HTLCError("invalid transfer action: an htlc script only "
                            "transfers the ownership of a token")
        output = action.get_outputs()[0]
        script, op = verify_owner(ctx.input_tokens[0].get_owner(),
                                  output.owner, now)
        sigma = ctx.signatures[i]
        key = metadata_claim_key_check(action, script, op, sigma)
        if op != OP_RECLAIM:
            ctx.count_metadata_key(key)

    _validate_output_scripts(ctx, action, now)
