"""Interoperability services (HTLC atomic swaps)."""
