"""Logging service: named loggers per driver/TMS.

Behavioral mirror of reference token/services/logging/logger.go:19-39 (zap
named loggers under the "token-sdk" root) over the stdlib logging module.
"""

from __future__ import annotations

import logging as _logging

ROOT = "token-sdk"

_configured = False


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    root = _logging.getLogger(ROOT)
    if not root.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(_logging.Formatter(
            "%(asctime)s %(levelname).4s %(name)s: %(message)s"))
        root.addHandler(handler)
        root.setLevel(_logging.INFO)
        root.propagate = False
    _configured = True


def get_logger(name: str = "") -> _logging.Logger:
    """logging.MustGetLogger equivalent: namespaced under token-sdk."""
    _ensure_configured()
    full = f"{ROOT}.{name}" if name else ROOT
    return _logging.getLogger(full)


def driver_logger(driver: str, tms_id: str) -> _logging.Logger:
    """Named logger per (driver, TMS) (logger.go:27-39)."""
    return get_logger(f"{driver}.{tms_id}")
