"""Services tier: transaction lifecycle, auditing, storage, identity, network.

Mirrors the capability surface of reference token/services (SURVEY.md §2.4).
"""
