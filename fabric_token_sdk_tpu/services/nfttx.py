"""nfttx: non-fungible tokens over the fungible API.

Behavioral mirror of reference token/services/nfttx (SURVEY.md §2.4): an NFT
is a quantity-1 token whose Type carries the marshalled JSON state with a
unique ID; queries filter unspent tokens by JSON key/value (qe.go:52), and
transfers move the whole state to a new owner.
"""

from __future__ import annotations

import base64
import json
import uuid

from ..token.model import UnspentToken


class NFTError(Exception):
    pass


class NoResults(NFTError):
    """qe.go:20 ErrNoResults."""


UNIQUE_ID_KEY = "_ID"


def marshal_state(state: dict) -> str:
    """nfttx/marshaller: stamp a unique ID and encode state as the token
    type (base64 keeps the Type a clean string)."""
    if UNIQUE_ID_KEY not in state or not state[UNIQUE_ID_KEY]:
        state = dict(state)
        state[UNIQUE_ID_KEY] = uuid.uuid4().hex
    raw = json.dumps(state, sort_keys=True)
    return base64.urlsafe_b64encode(raw.encode()).decode("ascii")


def unmarshal_state(token_type: str) -> dict:
    try:
        return json.loads(base64.urlsafe_b64decode(token_type.encode()))
    except Exception as e:
        raise NFTError(f"failed unmarshalling NFT state: {e}") from e


def state_id(state: dict) -> str:
    sid = state.get(UNIQUE_ID_KEY)
    if not sid:
        raise NFTError("state has no unique ID")
    return sid


class NFTService:
    """NFT views over a TokenNode (nfttx/transaction.go:80-116)."""

    def __init__(self, node):
        self.node = node

    def issue(self, issuer_node: str, to_node: str, state: dict):
        """Issue a fresh NFT carrying `state` to `to_node`."""
        token_type = marshal_state(state)
        tx = self.node.issue(issuer_node, to_node, token_type, hex(1))
        ev = self.node.execute(tx)
        if ev.status != "VALID":
            raise NFTError(f"NFT issue failed: {ev.message}")
        return unmarshal_state(token_type)

    def transfer(self, state_or_id, to_node: str):
        """Transfer the NFT matching the state/id to a new owner."""
        sid = (state_or_id if isinstance(state_or_id, str)
               else state_id(state_or_id))
        tok = self._find(sid)
        tx = self.node.transfer(tok.type, hex(1), to_node)
        ev = self.node.execute(tx)
        if ev.status != "VALID":
            raise NFTError(f"NFT transfer failed: {ev.message}")

    def query_by_key(self, key: str, value) -> dict:
        """qe.go:52-78: first unspent NFT whose state[key] == value."""
        for tok in self.node.tokendb.unspent_tokens(self.node.name):
            try:
                state = unmarshal_state(tok.type)
            except NFTError:
                continue
            if state.get(key) == value:
                return state
        raise NoResults("no results found")

    def _find(self, sid: str) -> UnspentToken:
        for tok in self.node.tokendb.unspent_tokens(self.node.name):
            try:
                state = unmarshal_state(tok.type)
            except NFTError:
                continue
            if state.get(UNIQUE_ID_KEY) == sid:
                return tok
        raise NoResults("no results found")
