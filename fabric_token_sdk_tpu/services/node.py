"""TokenNode: one participant's full runtime.

The standalone equivalent of an FSC node with the token SDK installed
(reference token/sdk/dig/sdk.go:84 wires the same pieces): signing identity,
wallets, token store, transaction store, selector, tokens-ingestion service,
driver services (fabtoken plaintext or zkatdlog ZK), and views for the ttx
choreography (sign/audit/issue/transfer/redeem). Nodes share a MemoryLedger
+ TokenChaincode (the ledger consensus plane) and a SessionBus (the
view/session plane).
"""

from __future__ import annotations

import time
from collections import OrderedDict

from ..core.fabtoken.driver import FabTokenDriverService, OutputSpec
from ..driver import TokenRequest
from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER
from ..token import quantity as q
from ..token.model import ID
from .db.sqldb import IdentityDB, TokenDB, TokenLockDB, TransactionDB, \
    TxRecord, TxStatus
from .selector import SherdLockSelector
from .tokens import Tokens
from .ttx import SessionBus, Transaction, TtxError, collect_endorsements, \
    ordering_and_finality

#: Family metadata for the ttx_* lifecycle instruments, hoisted so every
#: family carries a HELP line regardless of which call site registers it
#: first (scripts/check_metric_help.py enforces this for stable families).
_TTX_FAMILIES = {
    "ttx_executions_total": "ttx lifecycle outcomes per node",
    "ttx_execute_seconds":
        "end-to-end ttx latency: endorse -> order -> finality",
    "ttx_collect_endorsements_seconds":
        "endorsement collection wall per ttx",
    "ttx_ordering_finality_seconds":
        "ordering submission -> finality event wall per ttx",
    "ttx_commits_total": "finality events observed, by commit status",
    "ttx_commit_ingest_seconds":
        "finality listener: vault sync per observed commit",
}


class TokenNode:
    """One party: wallet + stores + ttx views over the shared backends."""

    def __init__(self, name: str, keys, bus: SessionBus, chaincode,
                 precision: int = 64, auditor_name: str | None = None,
                 driver=None, db_path_prefix: str | None = None,
                 owner_wallet=None):
        from .identity.wallet import X509OwnerWallet

        self.name = name
        self.keys = keys
        self.bus = bus
        self.cc = chaincode
        self.precision = precision
        self.auditor_name = auditor_name
        self.driver = driver or FabTokenDriverService(precision)
        # how this node RECEIVES and SPENDS tokens: stable x509 key by
        # default, per-tx Idemix pseudonyms when configured
        self.owner_wallet = owner_wallet or X509OwnerWallet(keys)

        def _db(which: str) -> str:
            if db_path_prefix is None:
                return ":memory:"
            return f"{db_path_prefix}.{which}.sqlite"

        self.tokendb = TokenDB(_db("tokens"))
        self.ttxdb = TransactionDB(_db("ttx"))
        self.lockdb = TokenLockDB(_db("locks"))
        self.identitydb = IdentityDB(_db("identity"))
        # role-based wallet manager (identity/wallet registry); the node's
        # active owner wallet is registered under the node name
        from .identity.registry import WalletService

        self.wallets = WalletService.for_node(
            name, keys, self.identitydb, owner_wallet=self.owner_wallet)
        # driver-composable ownership chain + vault token loader
        # (core/common/plumbing.py, reference authrorization.go:123,
        # loaders.go:209-231): identity helpers are injected so the core
        # layer never imports the services tier
        from ..core.common.plumbing import (AuthorizationMultiplexer,
                                            EscrowOwnership,
                                            VaultTokenLoader,
                                            WalletOwnership)
        from .identity.multisig import unwrap
        from .identity.typed import unmarshal_typed_identity

        self.auth = AuthorizationMultiplexer(
            WalletOwnership(name, self.owner_wallet,
                            auditor=(auditor_name == name)),
            EscrowOwnership(name, self.owner_wallet, unwrap),
            unmarshal_typed=unmarshal_typed_identity)
        self.token_loader = VaultTokenLoader(self.tokendb)
        self.selector = SherdLockSelector(self.tokendb, self.lockdb,
                                          precision=precision)
        self.tokens = Tokens(self.tokendb, self._ownership,
                             extractor=self.driver.extract_outputs)
        # node-labelled view of the process-global registry: every family
        # this node touches carries a node="<name>" label, and
        # prometheus_text() serves the shared registry per node
        self.metrics = _METRICS.with_labels(node=name)
        for fam, help_text in _TTX_FAMILIES.items():
            self.metrics.describe(fam, help_text)
        bus.register(name, self)
        chaincode.ledger.add_finality_listener(self._on_commit)
        # txs this node assembled or endorsed: refresh ttxdb on finality
        self._watched: dict[str, TokenRequest] = {}
        # openings received at distribution time, keyed by tx then global
        # output index (ttx/endorse.go:444; consumed at finality). Bounded:
        # txs that are distributed but never reach finality would otherwise
        # accumulate forever, so the oldest entries are evicted past a cap.
        self._pending_openings: "OrderedDict[str, dict[int, bytes]]" = \
            OrderedDict()
        self._pending_openings_cap = 10_000
        # ManagementService facades, one per TMSID (management_service)
        self._tms: dict = {}

    def management_service(self, tmsid=None):
        """The token.ManagementService view of this node (tms.go:32):
        the TMS facade over this node's driver, with the node-scoped
        vault/wallets/selector/signing bound (sdk/dig wiring). One cached
        instance per TMSID, like TMSProvider (core/tms.go:63), so bind()
        customisations persist across calls."""
        from ..core.registry import TMSID, DriverBundle, RegistryError
        from ..token.tms import TokenManagementService, Vault

        tmsid = tmsid or TMSID("default")
        cached = self._tms.get(tmsid)
        if cached is not None:
            return cached
        pp = getattr(self.driver, "pp", None)
        if pp is None:
            # plaintext driver holds no pp object: rebuild from the ledger's
            # setup key (the fetcher leg of pp resolution, tms.go:207-274)
            from ..core.fabtoken.setup import PublicParams

            pp_raw = self.cc.query_public_params()
            if pp_raw is None:
                raise RegistryError(
                    f"cannot resolve public parameters for TMS [{tmsid}]: "
                    "no setup state on the ledger")
            pp = PublicParams.deserialize(pp_raw)
        bundle = DriverBundle(
            label=getattr(self.driver, "label", "fabtoken"),
            public_params=pp,
            services=self.driver,
            validator=self.cc.validator,
            deserializer=getattr(self.cc.validator, "deserializer", None))
        tms = TokenManagementService(tmsid, bundle).bind(
            vault=Vault(self.tokendb, self.ttxdb),
            wallet_manager=self.wallets,
            selector_manager=self.selector,
            sig_service=self.keys)
        self._tms[tmsid] = tms
        return tms

    def verification_frontend(self, config=None, resilience=None,
                              telemetry=None, slo=None):
        """The continuous-batching verification service (serve/) over this
        node's validator ZK backend. One cached instance per node — the
        service owns the device dispatch queue, so every caller must share
        it. Raises for drivers without a device ZK backend (fabtoken).
        The caller starts/stops it (``await svc.start()``).

        A node frontend always runs resilient: retries with seeded
        jitter, circuit breaker, watchdog, and host fallback under the
        default :class:`ResilienceConfig` unless the caller passes their
        own (see resilience/).

        An :class:`SloMonitor` (``slo`` overrides the default policy)
        tracks rolling availability/p99 over every result, with fast-burn
        wired to the breaker's kill switch so sustained overload degrades
        to host fallback. Passing ``telemetry`` (a ``TelemetryConfig``)
        additionally starts the live HTTP plane — /metrics, /healthz,
        /readyz, /statusz, /tracez — on a daemon thread; the server
        handle is ``svc.telemetry`` (``.stop()`` to shut it down)."""
        if getattr(self, "_serve", None) is not None:
            return self._serve
        zk = getattr(getattr(self.cc.validator, "pp", None),
                     "zk_verifier", None)
        if zk is None or zk._range is None:
            raise RuntimeError(
                f"node [{self.name}]: validator has no device ZK backend "
                "to serve")
        from ..obs.slo import SloMonitor
        from ..resilience import ResilienceConfig
        from ..serve import VerificationService

        if resilience is None:
            resilience = ResilienceConfig()
        if slo is None:
            slo = SloMonitor()
        svc = VerificationService(zk, config=config,
                                  resilience=resilience, slo=slo)
        if svc.breaker is not None:
            slo.bind_breaker(svc.breaker)
        svc.telemetry = None
        if telemetry is not None:
            from ..obs.telemetry import serve_telemetry
            svc.telemetry = serve_telemetry(svc, telemetry)
        self._serve = svc
        return self._serve

    def prometheus_text(self) -> str:
        """This node's scrape endpoint body (what an FSC node's operations
        port would serve). The registry is process-global; per-node series
        are distinguished by the node="<name>" label this node's
        instruments carry."""
        return self.metrics.prometheus_text()

    # ------------------------------------------------------------------ util
    def _ownership(self, owner_raw: bytes) -> list[str]:
        """tokens.go:64-129 ownership resolution via the composable
        authorization chain (core/common/plumbing.py): personal tokens
        under the node name; multisig co-owned (escrow) tokens under a
        separate '<name>.ms' wallet so the ordinary selector never spends
        them (ttx/multisig/wallet.go separation)."""
        ids, _ = self.auth.is_mine(owner_raw)
        return ids

    def identity(self) -> bytes:
        return bytes(self.keys.identity)

    def recipient_identity(self, wallet_id: str = "") -> tuple[bytes, bytes]:
        """Recipient-exchange responder view (ttx/recipients.go): the
        identity to make an output to + its audit info. Fresh per call for
        pseudonymous wallets. A non-empty wallet_id resolves through the
        role registry (recipients.go honors the request's wallet id) and
        raises for unknown wallets rather than silently substituting the
        default."""
        if not wallet_id:
            return self.owner_wallet.recipient_identity()
        return self.wallets.owner_wallet(wallet_id).recipient_identity()

    def issuer_public_identity(self) -> bytes:
        """Issuer-identity responder view (withdrawal flow's first leg):
        method, not attribute reach-through, so it works over any session
        transport (in-process or RPC)."""
        return bytes(self.keys.identity)

    def owns_identity(self, owner_raw: bytes) -> bool:
        """Responder view: does this node's wallet own the identity?"""
        return self.owner_wallet.owns(owner_raw)

    def sign_as_co_owner(self, tx_id: str, message: bytes,
                         escrow_owner_raw: bytes) -> tuple[bytes, bytes]:
        """Escrow co-signing responder view (ttx/multisig/spend.go): find
        which component of the multisig identity this wallet owns, sign as
        it, and return (component identity, signature) so the initiator can
        join signatures in identity order."""
        from .identity.multisig import MultisigError, unwrap

        is_ms, ids = unwrap(escrow_owner_raw)
        if not is_ms:
            raise MultisigError("not a multisig owner")
        for ident in ids:
            if self.owner_wallet.owns(ident):
                sigma = self.owner_wallet.sign(ident, message)
                self.ttxdb.add_endorsement_ack(tx_id, self.identity(), sigma)
                return bytes(ident), sigma
        raise MultisigError(
            f"node [{self.name}] owns no component of the escrow identity")

    def balance(self, token_type: str) -> int:
        return self.tokendb.balance(self.name, token_type)

    # ------------------------------------------------- responder views (ttx)
    def sign_transfer(self, tx_id: str, message: bytes,
                      owner_raw: bytes | None = None) -> bytes:
        """Owner-side endorsement view (ttx/endorse.go:719-726): sign as
        the identity that owns the spent input (a pseudonym for Idemix
        wallets)."""
        if owner_raw is None:
            owner_raw = self.identity()
        sigma = self.owner_wallet.sign(owner_raw, message)
        self.ttxdb.add_endorsement_ack(tx_id, self.identity(), sigma)
        return sigma

    def sign_issue(self, tx_id: str, message: bytes) -> bytes:
        return self.keys.sign(message)

    def receive_opening(self, tx_id: str, index: int, opening: bytes) -> None:
        """Distribution responder: remember the opening of output `index`
        until finality ingestion (recipients.go semantics)."""
        self._pending_openings.setdefault(tx_id, {})[index] = opening
        while len(self._pending_openings) > self._pending_openings_cap:
            self._pending_openings.popitem(last=False)

    def audit(self, tx: Transaction) -> bytes:
        """Auditor-side view (ttx/auditor.go:265; auditor service semantics
        live in services/auditor.py — plain signing here for non-auditor
        nodes is an error)."""
        raise TtxError(f"node [{self.name}] is not an auditor")

    # ------------------------------------------------- initiator views (ttx)
    def issue(self, issuer_node: str, to_node: str, token_type: str,
              amount_hex: str) -> Transaction:
        """Withdrawal flow: ask the issuer node to issue to `to_node`
        (token/request.go:225 via the Request builder)."""
        from ..token.request_builder import Request

        issuer_identity = self.bus.node(issuer_node).issuer_public_identity()
        recipient_owner, recipient_ai = \
            self.bus.node(to_node).recipient_identity()
        value = int(amount_hex, 16)
        tx_id = Transaction.new_anchor()
        req = Request(tx_id, self.driver)
        req.issue(issuer_identity,
                  [OutputSpec(owner=recipient_owner, token_type=token_type,
                              value=value, audit_info=recipient_ai)],
                  receivers=[to_node])
        tx = Transaction(tx_id=tx_id, request=req.token_request(),
                         issuer_node=issuer_node,
                         metadata=req.request_metadata(),
                         distribution=req.distribution())
        tx.records.append(TxRecord(
            tx_id=tx.tx_id, action_type="issue", sender="",
            recipient=to_node, token_type=token_type,
            amount=value, status=TxStatus.PENDING,
            timestamp=time.time()))
        return tx

    def transfer(self, token_type: str, amount_hex: str, to_node: str,
                 redeem: bool = False,
                 recipient: tuple[bytes, bytes] | None = None) -> Transaction:
        """Assemble a transfer spending this node's tokens
        (token/request.go:287 prepareTransfer + driver Transfer).

        `recipient` carries (identity, audit_info) already exchanged via
        the recipient-exchange view (ttx_views.request_recipient_identity,
        recipients.go:82-180); without it the exchange collapses to a
        direct responder call."""
        from ..token.request_builder import Request

        tx_id = Transaction.new_anchor()
        selection = self.selector.select(self.name, token_type, amount_hex,
                                         tx_id)
        target = q.to_quantity(amount_hex, self.precision).value
        change = selection.sum - target
        recipient_owner, recipient_ai = (b"", b"") if redeem else \
            (recipient or self.bus.node(to_node).recipient_identity())
        specs = [OutputSpec(owner=recipient_owner, token_type=token_type,
                            value=target, audit_info=recipient_ai)]
        receivers = [None if redeem else to_node]
        if change > 0:
            change_owner, change_ai = self.owner_wallet.recipient_identity()
            specs.append(OutputSpec(owner=change_owner,
                                    token_type=token_type, value=change,
                                    audit_info=change_ai))
            receivers.append(self.name)
        req = Request(tx_id, self.driver)
        try:
            req.transfer(selection.tokens, specs,
                         wallet=self.token_loader,
                         sender_audit_info=self.owner_wallet.audit_info_for,
                         receivers=receivers)
        except Exception:
            self.selector.unselect(tx_id)
            raise
        tx = Transaction(
            tx_id=tx_id,
            request=req.token_request(),
            input_owners=[self.name] * len(selection.tokens),
            input_owner_ids=req.input_owner_ids(),
            metadata=req.request_metadata(),
            distribution=req.distribution(),
        )
        tx.records.append(TxRecord(
            tx_id=tx_id, action_type="redeem" if redeem else "transfer",
            sender=self.name, recipient="" if redeem else to_node,
            token_type=token_type, amount=target, status=TxStatus.PENDING,
            timestamp=time.time()))
        return tx

    # --------------------------------------------------- escrow (multisig)
    def lock_in_escrow(self, token_type: str, amount_hex: str,
                       co_owner_nodes: list[str]) -> Transaction:
        """ttx/multisig lock: transfer funds to a co-owned multisig
        identity; every co-owner receives the opening."""
        from ..token.request_builder import Request
        from .identity.multisig import wrap_identities

        tx_id = Transaction.new_anchor()
        selection = self.selector.select(self.name, token_type, amount_hex,
                                         tx_id)
        target = q.to_quantity(amount_hex, self.precision).value
        change = selection.sum - target
        recips = [self.bus.node(n).recipient_identity()
                  for n in co_owner_nodes]
        escrow_owner = bytes(wrap_identities(*[r[0] for r in recips]))
        specs = [OutputSpec(owner=escrow_owner, token_type=token_type,
                            value=target, audit_info=escrow_owner)]
        receivers = [None]  # distribution handled manually for co-owners
        if change > 0:
            change_owner, change_ai = self.owner_wallet.recipient_identity()
            specs.append(OutputSpec(owner=change_owner,
                                    token_type=token_type, value=change,
                                    audit_info=change_ai))
            receivers.append(self.name)
        req = Request(tx_id, self.driver)
        try:
            req.transfer(selection.tokens, specs,
                         wallet=self.token_loader,
                         sender_audit_info=self.owner_wallet.audit_info_for,
                         receivers=receivers)
        except Exception:
            self.selector.unselect(tx_id)
            raise
        tx = Transaction(
            tx_id=tx_id, request=req.token_request(),
            input_owners=[self.name] * len(selection.tokens),
            input_owner_ids=req.input_owner_ids(),
            metadata=req.request_metadata(),
            distribution=req.distribution(),
        )
        if tx.metadata is not None:
            # the escrow output's opening goes to EVERY co-owner
            opening = tx.metadata.transfers[0].outputs[0].output_metadata
            for n in co_owner_nodes:
                tx.distribution.append((n, 0, opening))
        tx.records.append(TxRecord(
            tx_id=tx_id, action_type="transfer", sender=self.name,
            recipient="escrow:" + ",".join(co_owner_nodes),
            token_type=token_type, amount=target, status=TxStatus.PENDING,
            timestamp=time.time()))
        return tx

    def spend_escrow(self, token_type: str, to_node: str,
                     co_owner_nodes: list[str]) -> Transaction:
        """ttx/multisig spend: move the escrow funds of `token_type`
        co-owned with EXACTLY `co_owner_nodes` to `to_node`; requires every
        co-owner's signature (collected by collect_endorsements).

        Only tokens whose multisig identity the listed co-owners can fully
        sign are selected (a node may hold escrows with different partner
        sets); selection takes token locks like every other spend so
        concurrent escrow spends fail fast instead of at ordering.
        """
        from ..token.request_builder import Request
        from .identity.multisig import unwrap

        tx_id = Transaction.new_anchor()
        candidates = self.tokendb.unspent_tokens(f"{self.name}.ms",
                                                 token_type)
        rows = []
        for r in candidates:
            is_ms, ids = unwrap(bytes(r.owner))
            if not is_ms:
                continue
            # exact partner-set match: every component signable by a listed
            # node AND every listed node owns a component (a superset list
            # would later fail co-signing and leak the token locks)
            owns = {nm: [self.bus.node(nm).owns_identity(i) for i in ids]
                    for nm in co_owner_nodes}
            covered = all(any(owns[nm][j] for nm in co_owner_nodes)
                          for j in range(len(ids)))
            all_participate = all(any(flags) for flags in owns.values())
            if covered and all_participate and self.lockdb.lock(r.id, tx_id):
                rows.append(r)
        if not rows:
            raise TtxError("no escrow tokens to spend")
        total = sum(int(r.quantity, 16) for r in rows)
        try:
            recipient_owner, recipient_ai = \
                self.bus.node(to_node).recipient_identity()
            req = Request(tx_id, self.driver)
            req.transfer(rows,
                         [OutputSpec(owner=recipient_owner,
                                     token_type=token_type, value=total,
                                     audit_info=recipient_ai)],
                         wallet=self.token_loader,
                         sender_audit_info=lambda raw: bytes(raw),
                         receivers=[to_node])
        except Exception:
            self.lockdb.unlock_by_consumer(tx_id)
            raise
        tx = Transaction(
            tx_id=tx_id, request=req.token_request(),
            # a LIST of names marks a multisig input: every listed node
            # must co-sign (collect_endorsements joins the signatures)
            input_owners=[list(co_owner_nodes) for _ in rows],
            input_owner_ids=req.input_owner_ids(),
            metadata=req.request_metadata(),
            distribution=req.distribution(),
        )
        tx.records.append(TxRecord(
            tx_id=tx_id, action_type="transfer", sender=self.name,
            recipient=to_node, token_type=token_type, amount=total,
            status=TxStatus.PENDING, timestamp=time.time()))
        return tx

    def execute(self, tx: Transaction):
        """collect endorsements -> order -> wait finality (SURVEY §3.1)."""
        t0 = time.perf_counter()
        with _TRACER.span("ttx.execute", node=self.name,
                          tx_id=tx.tx_id) as sp:
            with _TRACER.span("ttx.collect_endorsements"):
                collect_endorsements(tx, self.bus, self.auditor_name)
            self.metrics.histogram(
                "ttx_collect_endorsements_seconds").observe(
                time.perf_counter() - t0)
            self._watched[tx.tx_id] = tx.request
            self.ttxdb.add_token_request(tx.tx_id, tx.request.to_bytes())
            for rec in tx.records:
                self.ttxdb.add_transaction(rec)
            t1 = time.perf_counter()
            with _TRACER.span("ttx.ordering_and_finality"):
                ev = ordering_and_finality(tx, self.cc)
            self.metrics.histogram(
                "ttx_ordering_finality_seconds").observe(
                time.perf_counter() - t1)
            if ev.status != "VALID":
                self.selector.unselect(tx.tx_id)
            sp.set_attribute("status", ev.status)
        self.metrics.counter(
            "ttx_executions_total",
            help="ttx lifecycle outcomes per node",
            status=ev.status).add()
        self.metrics.histogram(
            "ttx_execute_seconds",
            help="end-to-end ttx latency: endorse -> order -> finality"
        ).observe(time.perf_counter() - t0)
        return ev

    # ------------------------------------------------- finality (vault sync)
    def _on_commit(self, ev) -> None:
        """network/common/finality.go:57-121 + tokens.Append (SURVEY §3.5).

        Every node observes every commit; it ingests outputs owned by it
        (for commitment drivers: outputs it holds an opening for).
        """
        t0 = time.perf_counter()
        try:
            self._on_commit_inner(ev)
        finally:
            self.metrics.counter("ttx_commits_total",
                                 status=ev.status).add()
            self.metrics.histogram(
                "ttx_commit_ingest_seconds",
                help="finality listener: vault sync per observed commit"
            ).observe(time.perf_counter() - t0)

    def _on_commit_inner(self, ev) -> None:
        if ev.status != "VALID":
            self.ttxdb.set_status(ev.tx_id, TxStatus.DELETED, ev.message)
            self._pending_openings.pop(ev.tx_id, None)
            return
        raw = self.cc.ledger.get_state(
            self.cc.keys.token_request_key(ev.tx_id))
        if raw is None:
            return  # genesis/setup
        openings = self._pending_openings.pop(ev.tx_id, {})
        request_raw = self._watched.get(ev.tx_id)
        if request_raw is None:
            # fetch from a peer that assembled it (finality.go:65-121 fetch
            # escalation); standalone: read tokens directly from the ledger
            self._ingest_from_ledger(ev.tx_id, openings, ev.n_outputs)
        else:
            actions = self.cc.validator.unmarshal_actions(
                request_raw.to_bytes())
            self.tokens.append_transaction(ev.tx_id, actions, openings)
        self.ttxdb.set_status(ev.tx_id, TxStatus.CONFIRMED)

    def _ingest_from_ledger(self, tx_id: str, openings: dict[int, bytes],
                            n_outputs: int) -> None:
        """Scan ledger outputs of tx_id (processor.go:40 RW-set indexing).

        Walks every output SLOT of the transaction — redeem outputs occupy
        an index but leave no ledger key, so gaps must not end the scan.
        """
        for idx in range(n_outputs):
            raw = self.cc.ledger.get_state(self.cc.keys.output_key(tx_id, idx))
            if raw is None:
                continue  # redeem output: indexed but never written
            out = self.driver.parse_ledger_output(raw, openings.get(idx))
            if out is not None and out.owner_raw:
                owners = self._ownership(out.owner_raw)
                self.tokendb.store_token(
                    ID(tx_id, idx), out.owner_raw, out.token_type,
                    out.quantity_hex, owners,
                    ledger_format=out.ledger_format,
                    ledger_token=out.ledger_token,
                    ledger_metadata=out.ledger_metadata)
        # mark spent inputs: any of my unspent tokens no longer on ledger
        for tok in self.tokendb.unspent_tokens(self.name):
            key = self.cc.keys.output_key(tok.id.tx_id, tok.id.index)
            if self.cc.ledger.get_state(key) is None:
                self.tokendb.delete_token(tok.id, spent_by=tx_id)
