"""TokenNode: one participant's full runtime.

The standalone equivalent of an FSC node with the token SDK installed
(reference token/sdk/dig/sdk.go:84 wires the same pieces): signing identity,
wallets, token store, transaction store, selector, tokens-ingestion service,
and views for the ttx choreography (sign/audit/issue/transfer/redeem).
Nodes share a MemoryLedger + TokenChaincode (the ledger consensus plane) and
a SessionBus (the view/session plane).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..driver import TokenRequest
from ..token import quantity as q
from ..token.model import ID
from .db.sqldb import (AuditDB, TokenDB, TokenLockDB, TransactionDB,
                       TxRecord, TxStatus)
from .selector import SherdLockSelector
from .tokens import Tokens
from .ttx import SessionBus, Transaction, TtxError, collect_endorsements, \
    ordering_and_finality


class TokenNode:
    """One party: wallet + stores + ttx views over the shared backends."""

    def __init__(self, name: str, keys, bus: SessionBus, chaincode,
                 precision: int = 64, auditor_name: str | None = None,
                 action_module=None):
        from ..core.fabtoken import actions as fabtoken_actions

        self.name = name
        self.keys = keys
        self.bus = bus
        self.cc = chaincode
        self.precision = precision
        self.auditor_name = auditor_name
        self.actions = action_module or fabtoken_actions

        self.tokendb = TokenDB(":memory:")
        self.ttxdb = TransactionDB(":memory:")
        self.lockdb = TokenLockDB(":memory:")
        self.selector = SherdLockSelector(self.tokendb, self.lockdb,
                                          precision=precision)
        self.tokens = Tokens(self.tokendb, self._ownership)
        bus.register(name, self)
        chaincode.ledger.add_finality_listener(self._on_commit)
        # txs this node assembled or endorsed: refresh ttxdb on finality
        self._watched: dict[str, TokenRequest] = {}

    # ------------------------------------------------------------------ util
    def _ownership(self, owner_raw: bytes) -> list[str]:
        return [self.name] if owner_raw == bytes(self.keys.identity) else []

    def identity(self) -> bytes:
        return bytes(self.keys.identity)

    def balance(self, token_type: str) -> int:
        return self.tokendb.balance(self.name, token_type)

    # ------------------------------------------------- responder views (ttx)
    def sign_transfer(self, tx_id: str, message: bytes) -> bytes:
        """Owner-side endorsement view (ttx/endorse.go:719-726)."""
        sigma = self.keys.sign(message)
        self.ttxdb.add_endorsement_ack(tx_id, self.identity(), sigma)
        return sigma

    def sign_issue(self, tx_id: str, message: bytes) -> bytes:
        return self.keys.sign(message)

    def audit(self, tx: Transaction) -> bytes:
        """Auditor-side view (ttx/auditor.go:265; auditor service semantics
        live in services/auditor.py — plain signing here for non-auditor
        nodes is an error)."""
        raise TtxError(f"node [{self.name}] is not an auditor")

    # ------------------------------------------------- initiator views (ttx)
    def issue(self, issuer_node: str, to_node: str, token_type: str,
              amount_hex: str) -> Transaction:
        """Withdrawal flow: ask the issuer node to issue to `to_node`."""
        issuer = self.bus.node(issuer_node)
        recipient = self.bus.node(to_node)
        action = self.actions.IssueAction(
            issuer=issuer.keys.identity,
            outputs=[self.actions.Output(
                owner=recipient.identity(), type=token_type,
                quantity=amount_hex)],
        )
        tx = Transaction(tx_id=Transaction.new_anchor(),
                         request=TokenRequest(issues=[action.serialize()]),
                         issuer_node=issuer_node)
        tx.records.append(TxRecord(
            tx_id=tx.tx_id, action_type="issue", sender="",
            recipient=to_node, token_type=token_type,
            amount=int(amount_hex, 16), status=TxStatus.PENDING,
            timestamp=time.time()))
        return tx

    def transfer(self, token_type: str, amount_hex: str, to_node: str,
                 redeem: bool = False) -> Transaction:
        """Assemble a transfer spending this node's tokens
        (token/request.go:287 prepareTransfer + driver Transfer)."""
        tx_id = Transaction.new_anchor()
        selection = self.selector.select(self.name, token_type, amount_hex,
                                         tx_id)
        target = q.to_quantity(amount_hex, self.precision).value
        change = selection.sum - target
        recipient_owner = b"" if redeem else \
            self.bus.node(to_node).identity()
        outputs = [self.actions.Output(owner=recipient_owner,
                                       type=token_type,
                                       quantity=hex(target))]
        if change > 0:
            outputs.append(self.actions.Output(
                owner=self.identity(), type=token_type,
                quantity=hex(change)))
        input_tokens = []
        for tok in selection.tokens:
            input_tokens.append(self.actions.Output(
                owner=bytes(tok.owner), type=tok.type,
                quantity=tok.quantity))
        action = self.actions.TransferAction(
            inputs=[t.id for t in selection.tokens],
            input_tokens=input_tokens,
            outputs=outputs,
        )
        tx = Transaction(
            tx_id=tx_id,
            request=TokenRequest(transfers=[action.serialize()]),
            input_owners=[self.name] * len(selection.tokens),
        )
        tx.records.append(TxRecord(
            tx_id=tx_id, action_type="redeem" if redeem else "transfer",
            sender=self.name, recipient="" if redeem else to_node,
            token_type=token_type, amount=target, status=TxStatus.PENDING,
            timestamp=time.time()))
        return tx

    def execute(self, tx: Transaction):
        """collect endorsements -> order -> wait finality (SURVEY §3.1)."""
        collect_endorsements(tx, self.bus, self.auditor_name)
        self._watched[tx.tx_id] = tx.request
        self.ttxdb.add_token_request(tx.tx_id, tx.request.to_bytes())
        for rec in tx.records:
            self.ttxdb.add_transaction(rec)
        ev = ordering_and_finality(tx, self.cc)
        if ev.status != "VALID":
            self.selector.unselect(tx.tx_id)
        return ev

    # ------------------------------------------------- finality (vault sync)
    def _on_commit(self, ev) -> None:
        """network/common/finality.go:57-121 + tokens.Append (SURVEY §3.5).

        Every node observes every commit; it ingests outputs owned by it.
        """
        if ev.status != "VALID":
            self.ttxdb.set_status(ev.tx_id, TxStatus.DELETED, ev.message)
            return
        raw = self.cc.ledger.get_state(
            self.cc.keys.token_request_key(ev.tx_id))
        if raw is None:
            return  # genesis/setup
        request_raw = self._watched.get(ev.tx_id)
        if request_raw is None:
            # fetch from a peer that assembled it (finality.go:65-121 fetch
            # escalation); standalone: read tokens directly from the ledger
            self._ingest_from_ledger(ev.tx_id)
        else:
            actions = self.cc.validator.unmarshal_actions(
                request_raw.to_bytes())
            self.tokens.append_transaction(ev.tx_id, actions)
        self.ttxdb.set_status(ev.tx_id, TxStatus.CONFIRMED)

    def _ingest_from_ledger(self, tx_id: str) -> None:
        """Scan ledger outputs of tx_id (processor.go:40 RW-set indexing)."""
        idx = 0
        while True:
            raw = self.cc.ledger.get_state(self.cc.keys.output_key(tx_id, idx))
            if raw is None:
                break
            out = self.actions.Output.deserialize(raw)
            owners = self._ownership(out.owner)
            self.tokendb.store_token(ID(tx_id, idx), out.owner, out.type,
                                     out.quantity, owners)
            idx += 1
        # mark spent inputs: any of my unspent tokens no longer on ledger
        for tok in self.tokendb.unspent_tokens(self.name):
            key = self.cc.keys.output_key(tok.id.tx_id, tok.id.index)
            if self.cc.ledger.get_state(key) is None:
                self.tokendb.delete_token(tok.id, spent_by=tx_id)
