"""Device range-proof synthesis: the whole prover as ONE fused program.

A chunk of B witnesses (value, blinding factor, pinned blinding draws)
becomes one packed (B, W) u32 upload and one fused dispatch that runs
the complete ``crypto.rp.range_prove`` computation on device:

  stage A   C = <bits, G> + <bits-1, H> + rho*P,
            D = <random_left, G> + <random_right, H> + eta*P,
            com = value*cg0 + bf*cg1             (one 3B-stacked MSM)
  y, z      SHA-256 transcripts of the stage-A bytes (y takes the FULL
            canonical reduction — its 32 big-endian bytes are re-hashed
            for z; everything else rides the verifier's one-cond-sub
            rule, ops/prove.py)
  stage B   T1 = t1*cg0 + tau1*cg1, T2 = t2*cg0 + tau2*cg1
            (one 2B-stacked MSM), then x, the final folded vectors,
            tau, delta and ip = <left, right>
  IPA       rgp = y^-i * H_i (fixed-base gather), com_ipa (one MSM),
            x_ipa via the verifier's own transcript template
            (_xipa_device_fn), then `rounds` folding rounds as ONE
            lax.scan whose body is shape-uniform in the ORIGINAL index
            space — so the whole IPA compiles a single 2B-stacked MSM
            instead of one kernel per round.

Scan-uniform round state: for every original index i we track the
generator fold coefficients c_i (of G_i in the folded left generator)
and d_i (of H'_i in the folded right generator) plus lval_i/rval_i, the
CURRENT vector entries at position e_i = i mod n_r (n_r = n/2^r — a
static per-round constant, so the partner gathers i +- h and the
low/high masks are baked numpy tables). Every round then reads

  L = sum_{e_i >= h} c_i lval_{i-h} G_i + sum_{e_i < h} d_i rval_{i+h} H_i
      + (x_ipa * <l[:h], r[h:]>) Q
  R = sum_{e_i < h} c_i lval_{i+h} G_i + sum_{e_i >= h} d_i rval_{i-h} H_i
      + (x_ipa * <l[h:], r[:h]>) Q

off one full-width fixed-base MSM (zero scalars are exact no-ops), and
folds lval/rval/c/d with the round challenge. After the last round
lval_0/rval_0 are ipa.left/ipa.right.

Everything serialized (tau, delta, ip, ipa.left/right, all point bytes)
leaves the device canonical, so ``models.witness_pack.unpack`` rebuilds
``rp.RangeProof`` objects byte-identical to the host prover under the
same ``RangeProverDraws`` — the parity bar tests/test_prover_parity.py
pins against BOTH verifier paths.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254
from ..crypto import rp
from ..crypto import serialization as ser
from ..models import range_verifier as rv
from ..models import witness_pack
from ..models.batching import next_pow2 as _next_pow2
from ..obs import GLOBAL as _METRICS
from ..obs import PROFILER
from ..obs import TRACER as _TRACER
from ..ops import ec, field, limbs
from ..ops import prove as dprove
from ..ops import sha256 as dsha

R = bn254.R
FR = field.FR
_NL = limbs.NLIMBS

#: rows per fused prove chunk (shared compiled shape across calls).
_CHUNK_ROWS = max(1, int(os.environ.get("FTS_PROVE_CHUNK", "64")))

#: Prover metric family metadata (HELP independent of call-site order;
#: tests/test_metric_family_guard.py pins the names, check_metric_help
#: lints the HELP text).
_PROVER_FAMILIES = {
    "prover_proofs_total":
        "Range proofs synthesized by the device prover",
    "prover_rows_total":
        "Witness rows packed into prover chunk uploads (incl. padding)",
    "prover_pad_rows_total":
        "All-zero witness rows padded in for chunk shape reuse",
    "prover_chunks_total":
        "Fused prover chunk programs dispatched",
    "prover_synthesize_seconds":
        "Wall seconds per fused prover chunk (upload->unpack)",
}
for _fam, _help in _PROVER_FAMILIES.items():
    _METRICS.describe(_fam, _help)


def _observe_chunk(bits_lbl: str, rows: int, live_rows: int,
                   seconds: float) -> None:
    """Per-chunk instrument writes (one fused upload->unpack cycle).

    Module-level so the exposition smoke test can light the chunk
    families through the production write path without paying a device
    compile (tests/test_obs_smoke.py)."""
    _METRICS.histogram("prover_synthesize_seconds",
                       bits=bits_lbl).observe(seconds)
    _METRICS.counter("prover_chunks_total", bits=bits_lbl).add()
    _METRICS.counter("prover_rows_total", bits=bits_lbl).add(rows)
    _METRICS.counter("prover_pad_rows_total",
                     bits=bits_lbl).add(rows - live_rows)


def _observe_proofs(bits_lbl: str, count: int, forged: bool) -> None:
    _METRICS.counter("prover_proofs_total", bits=bits_lbl,
                     forged=str(bool(forged)).lower()).add(count)


def _round_consts(n: int):
    """Static per-round index tables for the scan-uniform IPA.

    Returns (rounds, mask_lo, a_idx, b_idx, ip_mask), each table shaped
    (rounds, n): mask_lo[r, i] = (i mod n_r) < h; a/b are the fold
    partner gathers (lval' = x*lval[a] + x^-1*lval[b]); ip_mask marks
    the representative indices i < h whose lval/rval pairs form the
    round inner products."""
    rounds = n.bit_length() - 1
    idx = np.arange(n)
    mask_lo = np.zeros((rounds, n), dtype=bool)
    a_idx = np.zeros((rounds, n), dtype=np.int32)
    b_idx = np.zeros((rounds, n), dtype=np.int32)
    ip_mask = np.zeros((rounds, n), dtype=bool)
    for r in range(rounds):
        n_r = n >> r
        h = n_r >> 1
        e = idx % n_r
        lo = e < h
        mask_lo[r] = lo
        a_idx[r] = np.where(lo, idx, idx - h)
        b_idx[r] = np.where(lo, idx + h, idx)
        ip_mask[r] = idx < h
    return rounds, mask_lo, a_idx, b_idx, ip_mask


_PROVE_FNS: dict = {}


def _prove_fn(params, B: int):
    """Jitted fused prove program for (params, B): (tables, packed) ->
    ((B, 5 + 2*rounds, 64) u8 point bytes in the order
    [C, D, com, T1, T2, L.., R..], (B, 5, 16) u32 canonical scalars
    [tau, delta, ip, ipa.left, ipa.right])."""
    key = (params.bit_length, params.cache_digest, params.q_bytes,
           params.left_gen_bytes, B)
    if key in _PROVE_FNS:
        return _PROVE_FNS[key]

    n = params.bit_length
    rounds = params.rounds
    T = 2 * n + 5
    nw = 6 + 2 * n
    c_rounds, mask_lo, a_idx, b_idx, ip_mask = _round_consts(n)
    assert c_rounds == rounds
    xipa_fn = rv._xipa_device_fn(params)
    two_i = jnp.asarray(rv._pow2_mont_limbs(n))          # 2^i mont
    rgp_idx = params.rgp_idx
    sep = np.frombuffer(ser.SEPARATOR, dtype=np.uint8)
    mont_neg1 = jnp.asarray(
        limbs.int_to_limbs((R - 1) * limbs.MONT_R % R))  # mont(-1)
    r_minus_1 = jnp.asarray(limbs.int_to_limbs(R - 1))   # plain -1
    bit_limb = np.arange(n) // 16
    bit_shift = jnp.asarray(np.arange(n) % 16, dtype=np.uint32)
    consts = (jnp.asarray(mask_lo), jnp.asarray(a_idx),
              jnp.asarray(b_idx), jnp.asarray(ip_mask))

    def seg(arr, L):
        return jnp.broadcast_to(jnp.asarray(arr), (B, L))

    def pow_chain(shifter_m, count):
        """[1, s, s^2, ..., s^(count-1)] in mont form by log-doubling."""
        pows = jnp.broadcast_to(FR.r1_arr, (B, 1, _NL))
        sh = shifter_m
        while pows.shape[1] < count:
            nxt = field.mont_mul(pows, sh[:, None], FR)
            pows = jnp.concatenate([pows, nxt], axis=1)
            if pows.shape[1] < count:
                sh = field.mont_mul(sh, sh, FR)
        return pows[:, :count]

    def pts_bytes_flat(pts):
        """(B, K, 3, 16) -> (B, K, 64) with ONE Fermat for the chunk."""
        K = pts.shape[1]
        flat = pts.reshape(1, B * K, 3, _NL)
        return dprove.points_to_bytes(flat).reshape(B, K, 64)

    def fn(tables, packed):
        w = packed.reshape(B, nw, _NL)
        value, bf = w[:, 0], w[:, 1]
        rho, eta, tau1, tau2 = w[:, 2], w[:, 3], w[:, 4], w[:, 5]
        rl, rr = w[:, 6:6 + n], w[:, 6 + n:6 + 2 * n]

        bits = (value[:, bit_limb] >> bit_shift) & 1       # (B, n)
        bit_on = bits[..., None] != 0

        # ---- stage A: {C, D, com} off one 3B-stacked fixed-base MSM.
        # C's G/H scalars need no mont trip: left_i IS the bit, right_i
        # is bit-1 = 0 or R-1 (plain residues).
        left_plain = jnp.zeros((B, n, _NL), jnp.uint32
                               ).at[..., 0].set(bits)
        right_plain = jnp.where(bit_on, jnp.zeros((B, n, _NL), jnp.uint32),
                                jnp.broadcast_to(r_minus_1, (B, n, _NL)))
        scA = jnp.zeros((B, 3, T, _NL), jnp.uint32)
        scA = scA.at[:, 0, 0:n].set(left_plain)
        scA = scA.at[:, 0, n:2 * n].set(right_plain)
        scA = scA.at[:, 0, 2 * n].set(rho)
        scA = scA.at[:, 1, 0:n].set(rl)
        scA = scA.at[:, 1, n:2 * n].set(rr)
        scA = scA.at[:, 1, 2 * n].set(eta)
        scA = scA.at[:, 2, 2 * n + 2].set(value)
        scA = scA.at[:, 2, 2 * n + 3].set(bf)
        bytesA = pts_bytes_flat(ec.fixed_base_msm(tables, scA))

        # ---- y, z (bulletproof.go:276-282 layout, 388-byte message)
        hexA = rv._hex_ascii_dev(bytesA)                   # (B, 3, 128)
        msgy = jnp.concatenate(
            [hexA[:, 0], seg(sep, 2), hexA[:, 1], seg(sep, 2),
             hexA[:, 2], seg(dsha.pad_tail(388), 60)], axis=1)
        y = dprove.digest_to_fr(dsha.digest_padded(msgy), full=True)
        msgz = jnp.concatenate(
            [dprove.fr_limbs_to_bytes(y), seg(dsha.pad_tail(32), 32)],
            axis=1)
        z = dprove.digest_to_fr(dsha.digest_padded(msgz))
        y_m, z_m = field.to_mont(y, FR), field.to_mont(z, FR)

        # ---- polynomial commitment inputs (bulletproof.go:336-466)
        y_pows = pow_chain(y_m, n)                         # y^i
        yinv_m = field.inv(y_m, FR)
        yinv_pows = pow_chain(yinv_m, n)                   # y^-i
        z_b = jnp.broadcast_to(z_m[:, None], (B, n, _NL))
        z_sq = field.mont_sqr(z_m, FR)
        left_m = jnp.where(bit_on,
                           jnp.broadcast_to(FR.r1_arr, (B, n, _NL)),
                           jnp.zeros((B, n, _NL), jnp.uint32))
        right_m = jnp.where(bit_on, jnp.zeros((B, n, _NL), jnp.uint32),
                            jnp.broadcast_to(mont_neg1, (B, n, _NL)))
        rl_m, rr_m = field.to_mont(rl, FR), field.to_mont(rr, FR)
        lp_m = field.sub(left_m, z_b, FR)
        rp_m = field.mont_mul(field.add(right_m, z_b, FR), y_pows, FR)
        rrp_m = field.mont_mul(rr_m, y_pows, FR)
        zp_m = field.mont_mul(
            jnp.broadcast_to(z_sq[:, None], (B, n, _NL)),
            jnp.broadcast_to(two_i[None], (B, n, _NL)), FR)
        t1_m = field.add(
            field.add(dprove.fr_dot(lp_m, rrp_m),
                      dprove.fr_dot(rp_m, rl_m), FR),
            dprove.fr_dot(zp_m, rl_m), FR)
        t2_m = dprove.fr_dot(rl_m, rrp_m)

        # ---- stage B: {T1, T2} off one 2B-stacked MSM, then x.
        scB = jnp.zeros((B, 2, T, _NL), jnp.uint32)
        scB = scB.at[:, 0, 2 * n + 2].set(field.from_mont(t1_m, FR))
        scB = scB.at[:, 0, 2 * n + 3].set(tau1)
        scB = scB.at[:, 1, 2 * n + 2].set(field.from_mont(t2_m, FR))
        scB = scB.at[:, 1, 2 * n + 3].set(tau2)
        bytesB = pts_bytes_flat(ec.fixed_base_msm(tables, scB))
        hexB = rv._hex_ascii_dev(bytesB)
        msgx = jnp.concatenate(
            [hexB[:, 0], seg(sep, 2), hexB[:, 1],
             seg(dsha.pad_tail(258), 62)], axis=1)
        x = dprove.digest_to_fr(dsha.digest_padded(msgx))
        x_m = field.to_mont(x, FR)
        x_b = jnp.broadcast_to(x_m[:, None], (B, n, _NL))

        # ---- final folded vectors + serialized scalars
        lfin = field.add(lp_m, field.mont_mul(x_b, rl_m, FR), FR)
        rfin = field.add(
            field.add(rp_m, field.mont_mul(x_b, rrp_m, FR), FR),
            zp_m, FR)
        tau_m = field.add(
            field.add(field.mont_mul(x_m, field.to_mont(tau1, FR), FR),
                      field.mont_mul(field.to_mont(tau2, FR),
                                     field.mont_sqr(x_m, FR), FR), FR),
            field.mont_mul(z_sq, field.to_mont(bf, FR), FR), FR)
        delta_m = field.add(field.to_mont(rho, FR),
                            field.mont_mul(field.to_mont(eta, FR), x_m,
                                           FR), FR)
        ip_m = dprove.fr_dot(lfin, rfin)
        ip_plain = field.from_mont(ip_m, FR)

        # ---- IPA setup: rgp points, com_ipa, x_ipa
        yinv_plain = field.from_mont(yinv_pows, FR)
        rgp_pts = ec.fixed_base_gather(
            jnp.take(tables, rgp_idx, axis=0), yinv_plain)
        rgp_bytes = dprove.points_to_bytes(rgp_pts)        # (B, n, 64)
        scI = jnp.zeros((B, T, _NL), jnp.uint32)
        scI = scI.at[:, 0:n].set(field.from_mont(lfin, FR))
        scI = scI.at[:, n:2 * n].set(
            field.from_mont(field.mont_mul(yinv_pows, rfin, FR), FR))
        com_ipa_pt = ec.fixed_base_msm(tables, scI)        # (B, 3, 16)
        com_ipa_bytes = dprove.points_to_bytes(
            com_ipa_pt.reshape(1, B, 3, _NL)).reshape(B, 64)
        ip_bytes = dprove.fr_limbs_to_bytes(ip_plain)
        x_ipa = dprove.digest_to_fr(
            xipa_fn(rgp_bytes, com_ipa_bytes, ip_bytes))
        x_ipa_m = field.to_mont(x_ipa, FR)

        # ---- IPA rounds: one scan, one 2B-stacked MSM per round body
        zero_v = jnp.zeros((B, n, _NL), jnp.uint32)
        tail258 = dsha.pad_tail(258)

        def body(carry, xs):
            lval, rval, c, d = carry
            lo, a, b, ipm = xs
            lo_b = jnp.broadcast_to(lo[None, :], (B, n))
            hi_b = jnp.logical_not(lo_b)
            ipm_b = jnp.broadcast_to(ipm[None, :], (B, n))
            lval_a = jnp.take(lval, a, axis=1)
            lval_b = jnp.take(lval, b, axis=1)
            rval_a = jnp.take(rval, a, axis=1)
            rval_b = jnp.take(rval, b, axis=1)
            lip = dprove.fr_sum(field.select(
                ipm_b, field.mont_mul(lval, rval_b, FR), zero_v))
            rip = dprove.fr_sum(field.select(
                ipm_b, field.mont_mul(lval_b, rval, FR), zero_v))
            sc2 = jnp.zeros((B, 2, T, _NL), jnp.uint32)
            sc2 = sc2.at[:, 0, 0:n].set(field.from_mont(field.select(
                hi_b, field.mont_mul(c, lval_a, FR), zero_v), FR))
            sc2 = sc2.at[:, 0, n:2 * n].set(field.from_mont(field.select(
                lo_b, field.mont_mul(d, rval_b, FR), zero_v), FR))
            sc2 = sc2.at[:, 0, 2 * n + 1].set(field.from_mont(
                field.mont_mul(x_ipa_m, lip, FR), FR))
            sc2 = sc2.at[:, 1, 0:n].set(field.from_mont(field.select(
                lo_b, field.mont_mul(c, lval_b, FR), zero_v), FR))
            sc2 = sc2.at[:, 1, n:2 * n].set(field.from_mont(field.select(
                hi_b, field.mont_mul(d, rval_a, FR), zero_v), FR))
            sc2 = sc2.at[:, 1, 2 * n + 1].set(field.from_mont(
                field.mont_mul(x_ipa_m, rip, FR), FR))
            pb = pts_bytes_flat(ec.fixed_base_msm(tables, sc2))
            hexLR = rv._hex_ascii_dev(pb)
            msg = jnp.concatenate(
                [hexLR[:, 0], seg(sep, 2), hexLR[:, 1],
                 seg(tail258, 62)], axis=1)
            xr = dprove.digest_to_fr(dsha.digest_padded(msg))
            xr_m = field.to_mont(xr, FR)
            xrinv_m = field.inv(xr_m, FR)
            xr_b = jnp.broadcast_to(xr_m[:, None], (B, n, _NL))
            xrinv_b = jnp.broadcast_to(xrinv_m[:, None], (B, n, _NL))
            c = field.mont_mul(c, field.select(lo_b, xrinv_b, xr_b), FR)
            d = field.mont_mul(d, field.select(lo_b, xr_b, xrinv_b), FR)
            lval = field.add(field.mont_mul(xr_b, lval_a, FR),
                             field.mont_mul(xrinv_b, lval_b, FR), FR)
            rval = field.add(field.mont_mul(xrinv_b, rval_a, FR),
                             field.mont_mul(xr_b, rval_b, FR), FR)
            return (lval, rval, c, d), pb

        c0 = jnp.broadcast_to(FR.r1_arr, (B, n, _NL))
        (lval, rval, _, _), pbs = jax.lax.scan(
            body, (lfin, rfin, c0, yinv_pows), consts)
        lr = jnp.transpose(pbs, (1, 2, 0, 3))              # (B, 2, r, 64)

        pts_out = jnp.concatenate(
            [bytesA, bytesB, lr[:, 0], lr[:, 1]], axis=1)
        scalars_out = jnp.stack(
            [field.from_mont(tau_m, FR), field.from_mont(delta_m, FR),
             ip_plain, field.from_mont(lval[:, 0], FR),
             field.from_mont(rval[:, 0], FR)], axis=1)
        return pts_out, scalars_out

    _PROVE_FNS[key] = jax.jit(fn)
    return _PROVE_FNS[key]


class DeviceRangeProver:
    """Batched on-device range prover for one PublicParams set.

    Reuses the verifier's fixed-base tables (`rv._params_for`) — the
    prover adds no table memory of its own. ``prove`` rejects
    out-of-range witnesses up front (the host ``range_prove`` silently
    truncates; the prove-time contract lives here) unless ``forge=True``
    seeds deliberately invalid rows for adversarial corpora — those
    produce proofs byte-identical to the host prover's on the same
    draws, and both verifiers reject them.
    """

    def __init__(self, pp, chunk_rows: int | None = None):
        self.pp = pp
        self.bit_length = pp.range_proof_params.bit_length
        self.rounds = pp.range_proof_params.number_of_rounds
        self.chunk_rows = chunk_rows
        self._params = None

    @property
    def params(self):
        """Verifier-shared device params; built lazily so witness
        validation (and its tests) never pays the table build."""
        if self._params is None:
            self._params = rv._params_for(self.pp)
        return self._params

    def _chunk_rows_for(self, total: int) -> int:
        if self.chunk_rows is not None:
            return self.chunk_rows
        return min(_CHUNK_ROWS, _next_pow2(total))

    def prove(self, values, blinding_factors, draws=None,
              forge: bool = False):
        """Synthesize proofs for every (value, bf) witness row.

        Returns (proofs, commitments): ``rp.RangeProof`` objects plus
        the device-computed Pedersen commitments value*cg0 + bf*cg1.
        Raises ValueError at prove time for out-of-range values unless
        ``forge=True``.
        """
        n = self.bit_length
        values = list(values)
        bfs = list(blinding_factors)
        if len(values) != len(bfs):
            raise ValueError(
                f"{len(values)} values vs {len(bfs)} blinding factors")
        if not forge:
            for i, v in enumerate(values):
                if not 0 <= v < (1 << n):
                    raise ValueError(
                        f"witness {i} out of range for {n}-bit proof: "
                        f"{v} (pass forge=True to seed invalid rows)")
        if draws is None:
            draws = [rp.RangeProverDraws.random(n) for _ in values]
        if len(draws) != len(values):
            raise ValueError(
                f"{len(draws)} draws rows vs {len(values)} values")

        rows = self._chunk_rows_for(len(values))
        fn = _prove_fn(self.params, rows)
        bits_lbl = str(n)
        proofs: list[rp.RangeProof] = []
        commitments: list[bn254.G1] = []
        for lo in range(0, len(values), rows):
            hi = min(lo + rows, len(values))
            packed = witness_pack.pack_range_witnesses(
                values[lo:hi], bfs[lo:hi], draws[lo:hi], n)
            padded = witness_pack.pad_witness_rows(packed, rows)
            t0 = time.perf_counter()
            with _TRACER.span("prover.synthesize", rows=hi - lo,
                              chunk=rows, bits=n):
                dev = jnp.asarray(padded)
                rv._count("prove_chunk_upload")
                pts, sc = fn(self.params.tables, dev)
                rv._count("prove_chunk_dispatch")
                pts_np = np.asarray(jax.device_get(pts))
                sc_np = np.asarray(jax.device_get(sc))
            _observe_chunk(bits_lbl, rows, hi - lo,
                           time.perf_counter() - t0)
            ch_proofs, ch_coms = witness_pack.unpack_range_outputs(
                pts_np[:hi - lo], sc_np[:hi - lo], self.rounds)
            proofs.extend(ch_proofs)
            commitments.extend(ch_coms)
        _observe_proofs(bits_lbl, len(proofs), forge)
        return proofs, commitments

    def kernel_cost(self, rows: int | None = None) -> dict | None:
        """XLA cost analysis of the fused prove chunk program, published
        under the `profile_bucket_*` gauges as kind "prove_chunk"."""
        rows = rows or self._chunk_rows_for(_CHUNK_ROWS)
        fn = _prove_fn(self.params, rows)
        packed_sd = jax.ShapeDtypeStruct(
            (rows, witness_pack.witness_width(self.bit_length)),
            jnp.uint32)
        return PROFILER.capture_kernel_cost(
            "prove_chunk", rows, fn, self.params.tables, packed_sd)
