"""TPU-side proof synthesis (ROADMAP open item 2).

The prover subsystem mirrors the verifier's architecture one layer up:
``prover/range.py`` synthesizes Bulletproofs-style range proofs (and
their IPA) in one fused device program per witness chunk, and
``prover/transfer.py`` adds the sigma-protocol type-and-sum proof plus
the full transfer composition. Both are pinned byte-for-byte to the
host provers in ``crypto/rp.py`` / ``crypto/transfer_proof.py`` through
the ``RangeProverDraws`` / ``TransferDraws`` seams.
"""

from .range import DeviceRangeProver
from .transfer import DeviceTransferProver

__all__ = ["DeviceRangeProver", "DeviceTransferProver"]
