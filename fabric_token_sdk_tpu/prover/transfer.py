"""Device transfer proving: type-and-sum sigma protocol + composition.

The type-and-sum proof (crypto/transfer_proof.py, reference
typeandsum.go) is one fused device program per (n_inputs, n_outputs, B)
shape: a single packed u32 upload carries the witness scalars AND the
statement points (inputs, outputs, commitment_to_type as projective
limbs), one dispatch computes

  - the sigma commitments com_type / com_inputs / com_sum off one
    fixed-base MSM over a 3-generator [ped0, ped1, ped2] plane table,
  - the adjusted points adj = pt - com_type (complete projective adds)
    and their signed sum via an add_zlazy chain (Z-carry resolution
    deferred to one normalize_point — the same lazy discipline
    `scripts/check_lazy_bounds.py` enforces on the verifier kernels),
  - the Fiat-Shamir challenge over the canonical point bytes
    (typeandsum.go:214,267 ordering; FULL digest reduction — the
    challenge is serialized into the proof),
  - and the sigma responses, all leaving the device canonical.

Parity bar: the same ``TypeAndSumDraws`` fed to the host
``type_and_sum_prove`` must yield a byte-identical ``serialize()``.
``DeviceTransferProver.transfer_prove`` composes this with
``DeviceRangeProver`` for the output range proofs — the adjusted output
commitment outputs_i - com_type equals cg0^value * cg1^(bf - type_bf),
exactly the commitment the range chunk program computes on device.
"""

from __future__ import annotations

import hashlib
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254
from ..crypto import transfer_proof as tp
from ..crypto import serialization as ser
from ..crypto.bn254 import fr_sub, g1_add, g1_mul, hash_to_zr
from ..models import range_verifier as rv
from ..obs import TRACER as _TRACER
from ..ops import ec, field, limbs
from ..ops import prove as dprove
from ..ops import sha256 as dsha
from .range import _observe_chunk, _observe_proofs

R = bn254.R
FR = field.FR
_NL = limbs.NLIMBS

#: pedersen-generator plane tables for the sigma commitments, keyed by
#: the generator digest (same never-share-across-pp rule as the
#: verifier's _PARAMS_CACHE).
_PED_TABLES: dict = {}


def _ped_tables(pp):
    h = hashlib.sha256()
    for p in pp.pedersen_generators[:3]:
        h.update(ser.g1_to_bytes(p))
    key = h.digest()
    if key not in _PED_TABLES:
        pts = jnp.asarray(limbs.points_to_projective_limbs(
            list(pp.pedersen_generators[:3])))
        _PED_TABLES[key] = (key.hex()[:16], rv._tables_kernel(pts))
    return _PED_TABLES[key]


def _adjusted_sum(adj_in, adj_out_neg):
    """sum(adj_in) - sum(adj_out) as one lazy-Z fold: (B, k, 3, 16)
    Montgomery projective operands (already-negated outputs), carries
    resolved once at the chain end."""
    B = adj_in.shape[0]
    acc = jnp.broadcast_to(
        jnp.asarray(limbs.point_to_projective_limbs(bn254.G1_IDENTITY)),
        (B, 3, _NL))
    for i in range(adj_in.shape[1]):
        acc = ec.add_zlazy(acc, adj_in[:, i])
    for j in range(adj_out_neg.shape[1]):
        acc = ec.add_zlazy(acc, adj_out_neg[:, j])
    return ec.normalize_point(acc)


_TS_FNS: dict = {}


def _ts_fn(digest: str, n_in: int, n_out: int, B: int):
    """Jitted fused type-and-sum program: (tables3, packed) ->
    (B, 4 + 2*n_in, 16) canonical plain scalars in the order
    [challenge, type_, type_blinding_factor, equality_of_sum,
    input_values.., input_blinding_factors..]."""
    key = (digest, n_in, n_out, B)
    if key in _TS_FNS:
        return _TS_FNS[key]

    ns = 5 + 4 * n_in + n_out               # packed scalar count
    npts = n_in + n_out + 1                 # inputs ++ outputs ++ ct
    M = 2 * n_in + n_out + 4                # transcript point count
    msg_len = 130 * M - 2
    sep = np.frombuffer(ser.SEPARATOR, dtype=np.uint8)
    tail = dsha.pad_tail(msg_len)

    def fn(tables3, packed):
        sc = packed[:, :ns * _NL].reshape(B, ns, _NL)
        pts = packed[:, ns * _NL:].reshape(B, npts, 3, _NL)
        type_zr, type_bf = sc[:, 0], sc[:, 1]
        r_type, r_type_bf, r_sum_bf = sc[:, 2], sc[:, 3], sc[:, 4]
        in_values = sc[:, 5:5 + n_in]
        in_bfs = sc[:, 5 + n_in:5 + 2 * n_in]
        r_in_values = sc[:, 5 + 2 * n_in:5 + 3 * n_in]
        r_in_bfs = sc[:, 5 + 3 * n_in:5 + 4 * n_in]
        out_bfs = sc[:, 5 + 4 * n_in:]
        inputs = pts[:, :n_in]
        outputs = pts[:, n_in:n_in + n_out]
        ct = pts[:, n_in + n_out]

        # sigma commitments: one (n_in + 2)-row fixed-base MSM over
        # [ped0, ped1, ped2]; row order [com_inputs.., com_type, com_sum]
        scm = jnp.zeros((B, n_in + 2, 3, _NL), jnp.uint32)
        scm = scm.at[:, :n_in, 1].set(r_in_values)
        scm = scm.at[:, :n_in, 2].set(r_in_bfs)
        scm = scm.at[:, n_in, 0].set(r_type)
        scm = scm.at[:, n_in, 2].set(r_type_bf)
        scm = scm.at[:, n_in + 1, 2].set(r_sum_bf)
        coms = ec.fixed_base_msm(tables3, scm)   # (B, n_in + 2, 3, 16)

        # adjusted statement: adj = pt - com_type, signed lazy-Z sum
        neg_ct = ec.neg(ct)
        adj_in = ec.add(
            inputs, jnp.broadcast_to(neg_ct[:, None], inputs.shape))
        adj_out = ec.add(
            outputs, jnp.broadcast_to(neg_ct[:, None], outputs.shape))
        sum_ = _adjusted_sum(adj_in, ec.neg(adj_out))

        # transcript: [com_inputs.., com_type, com_sum, adj_in..,
        # adj_out.., ct, sum_] -> hex-"||" join -> SHA-256 -> chal
        allpts = jnp.concatenate(
            [coms, adj_in, adj_out, ct[:, None], sum_[:, None]], axis=1)
        hexes = rv._hex_ascii_dev(dprove.points_to_bytes(allpts))
        sep_b = jnp.broadcast_to(jnp.asarray(sep), (B, M, 2))
        joined = jnp.concatenate([hexes, sep_b], axis=2).reshape(
            B, 130 * M)[:, :msg_len]
        msg = jnp.concatenate(
            [joined, jnp.broadcast_to(jnp.asarray(tail),
                                      (B, len(tail)))], axis=1)
        chal = dprove.digest_to_fr(dsha.digest_padded(msg), full=True)

        # sigma responses (typeandsum.go:280-316)
        chal_m = field.to_mont(chal, FR)
        tm = lambda a: field.to_mont(a, FR)
        resp = lambda w, r: field.from_mont(
            field.add(field.mont_mul(
                jnp.broadcast_to(chal_m[..., None, :]
                                 if w.ndim == 3 else chal_m, w.shape),
                w, FR), r, FR), FR)
        type_resp = resp(tm(type_zr), tm(r_type))
        tbf_resp = resp(tm(type_bf), tm(r_type_bf))
        t = field.sub(tm(in_bfs),
                      jnp.broadcast_to(tm(type_bf)[:, None],
                                       (B, n_in, _NL)), FR)
        iv_resp = resp(tm(in_values), tm(r_in_values))
        ibf_resp = resp(t, tm(r_in_bfs))
        t_out = field.sub(tm(out_bfs),
                          jnp.broadcast_to(tm(type_bf)[:, None],
                                           (B, n_out, _NL)), FR)
        sum_bf = field.sub(dprove.fr_sum(t), dprove.fr_sum(t_out), FR)
        eq_resp = resp(sum_bf, tm(r_sum_bf))

        return jnp.concatenate(
            [jnp.stack([chal, type_resp, tbf_resp, eq_resp], axis=1),
             iv_resp, ibf_resp], axis=1)

    _TS_FNS[key] = jax.jit(fn)
    return _TS_FNS[key]


class DeviceTransferProver:
    """Device type-and-sum + transfer composition for one PublicParams.

    ``prove_type_and_sum`` batches same-shape sigma proofs;
    ``transfer_prove`` is the device twin of
    ``crypto.transfer_proof.transfer_prove`` (same TransferDraws seam,
    byte-identical serialized proof)."""

    def __init__(self, pp, range_chunk_rows: int | None = None):
        self.pp = pp
        self._digest, self._tables3 = _ped_tables(pp)
        self._range = None
        self._range_chunk_rows = range_chunk_rows

    def _range_prover(self):
        if self._range is None:
            from .range import DeviceRangeProver

            self._range = DeviceRangeProver(
                self.pp, chunk_rows=self._range_chunk_rows)
        return self._range

    def prove_type_and_sum(self, statements, draws=None):
        """statements: list of dicts with keys inputs, outputs (G1
        lists, same shape across the batch), commitment_to_type (G1),
        in_values, in_bfs, out_bfs, type_zr, type_bf. Returns one
        ``TypeAndSumProof`` per statement."""
        B = len(statements)
        n_in = len(statements[0]["inputs"])
        n_out = len(statements[0]["outputs"])
        if draws is None:
            draws = [tp.TypeAndSumDraws.random(n_in) for _ in statements]
        ns = 5 + 4 * n_in + n_out
        packed = np.zeros((B, (ns + (n_in + n_out + 1) * 3) * _NL),
                          dtype=np.uint32)
        for r, st in enumerate(statements):
            if (len(st["inputs"]) != n_in
                    or len(st["outputs"]) != n_out):
                raise ValueError("mixed statement shapes in one batch")
            d = draws[r]
            row = ([st["type_zr"] % R, st["type_bf"] % R, d.r_type % R,
                    d.r_type_bf % R, d.r_sum_bf % R]
                   + [v % R for v in st["in_values"]]
                   + [v % R for v in st["in_bfs"]]
                   + [v % R for v in d.r_in_values]
                   + [v % R for v in d.r_in_bfs]
                   + [v % R for v in st["out_bfs"]])
            packed[r, :ns * _NL] = limbs.ints_to_limbs(row).reshape(-1)
            pts = limbs.points_to_projective_limbs(
                list(st["inputs"]) + list(st["outputs"])
                + [st["commitment_to_type"]])
            packed[r, ns * _NL:] = pts.reshape(-1)

        fn = _ts_fn(self._digest, n_in, n_out, B)
        t0 = time.perf_counter()
        with _TRACER.span("prover.synthesize", kind="type_and_sum",
                          rows=B, n_in=n_in, n_out=n_out):
            dev = jnp.asarray(packed)
            rv._count("prove_ts_upload")
            out = fn(self._tables3, dev)
            rv._count("prove_ts_dispatch")
            out_np = np.asarray(jax.device_get(out))
        _observe_chunk("ts", B, B, time.perf_counter() - t0)
        _observe_proofs("ts", B, forged=False)

        proofs = []
        for r, st in enumerate(statements):
            sc = [limbs.limbs_to_int(out_np[r, k])
                  for k in range(out_np.shape[1])]
            proofs.append(tp.TypeAndSumProof(
                commitment_to_type=st["commitment_to_type"],
                challenge=sc[0], type_=sc[1],
                type_blinding_factor=sc[2], equality_of_sum=sc[3],
                input_values=sc[4:4 + n_in],
                input_blinding_factors=sc[4 + n_in:4 + 2 * n_in]))
        return proofs

    def transfer_prove(self, input_witness, output_witness, inputs,
                       outputs, draws=None) -> bytes:
        """Device twin of ``transfer_proof.transfer_prove``: witnesses
        are (type, value, blinding_factor) tuples; returns the
        serialized TransferProof."""
        pp = self.pp
        token_type = input_witness[0][0]
        type_zr = hash_to_zr(token_type.encode())
        if draws is None:
            draws = tp.TransferDraws.random(
                len(input_witness), len(output_witness),
                pp.range_proof_params.bit_length)
        type_bf = draws.type_bf
        commitment_to_type = g1_add(
            g1_mul(pp.pedersen_generators[0], type_zr),
            g1_mul(pp.pedersen_generators[2], type_bf))

        ts = self.prove_type_and_sum([{
            "inputs": inputs, "outputs": outputs,
            "commitment_to_type": commitment_to_type,
            "in_values": [w[1] for w in input_witness],
            "in_bfs": [w[2] for w in input_witness],
            "out_bfs": [w[2] for w in output_witness],
            "type_zr": type_zr, "type_bf": type_bf,
        }], draws=[draws.ts])[0]

        rc = None
        if len(input_witness) != 1 or len(output_witness) != 1:
            from ..crypto import rp as rp_mod

            range_proofs, _ = self._range_prover().prove(
                [w[1] for w in output_witness],
                [fr_sub(w[2], type_bf) for w in output_witness],
                draws=draws.ranges or None)
            rc = rp_mod.RangeCorrectness(range_proofs)

        return tp.TransferProof(
            type_and_sum=ts, range_correctness=rc).serialize()
