/* _frmont: BN254 scalar-field (Fr) batch arithmetic, CPython extension.
 *
 * The native runtime piece of the host phase of the batched TPU verifier
 * (models/range_verifier.py): where the reference leans on gnark-crypto's
 * assembly field arithmetic (SURVEY.md §2.7 "IBM/mathlib -> gnark"), this
 * module provides 4x64-bit Montgomery CIOS multiplication with batch entry
 * points shaped for the verifier's hot loops:
 *
 *   - fold_coeffs: the IPA generator-folding expansion (2n muls/proof)
 *   - powers:      y^i / y^-i ladders
 *   - mul_many / addmul_many: elementwise fused scalar assembly
 *   - batch_inv:   Montgomery-trick inversion (one Fermat pow in C)
 *
 * I/O convention: packed little-endian 32-byte scalars (b"" blobs hold k
 * scalars at 32-byte stride), standard (non-Montgomery) representation at
 * the boundary; conversion to/from Montgomery happens once per call.
 * Parity is pinned against the pure-Python oracle in
 * tests/test_frmont_native.py.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;
typedef uint64_t u64;

/* BN254 r and Montgomery constants (R = 2^256 mod r domain) */
static const u64 MOD[4] = {0x43e1f593f0000001ULL, 0x2833e84879b97091ULL,
                           0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 N0 = 0xc2e1f593efffffffULL; /* -r^{-1} mod 2^64 */
static const u64 R2[4] = {0x1bb8e645ae216da7ULL, 0x53fe3ab1e35c59e3ULL,
                          0x8c49833d53bb8085ULL, 0x0216d0b17f4e44a5ULL};
static const u64 ONE_STD[4] = {1ULL, 0, 0, 0};

/* a >= b ? */
static int geq(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] > b[i]) return 1;
        if (a[i] < b[i]) return 0;
    }
    return 1;
}

static void sub_nored(u64 out[4], const u64 a[4], const u64 b[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a[i] - b[i] - borrow;
        out[i] = (u64)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static void add_mod(u64 out[4], const u64 a[4], const u64 b[4]) {
    u128 carry = 0;
    u64 t[4];
    for (int i = 0; i < 4; i++) {
        u128 s = (u128)a[i] + b[i] + carry;
        t[i] = (u64)s;
        carry = s >> 64;
    }
    if (carry || geq(t, MOD)) sub_nored(out, t, MOD);
    else memcpy(out, t, 32);
}

static void sub_mod(u64 out[4], const u64 a[4], const u64 b[4]) {
    if (geq(a, b)) sub_nored(out, a, b);
    else {
        u64 t[4];
        sub_nored(t, b, a);
        sub_nored(out, MOD, t);
    }
}

/* Montgomery CIOS multiplication: out = a*b*R^{-1} mod r */
static void mont_mul(u64 out[4], const u64 a[4], const u64 b[4]) {
    u64 t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 s = (u128)t[j] + (u128)a[j] * b[i] + carry;
            t[j] = (u64)s;
            carry = s >> 64;
        }
        u128 s = (u128)t[4] + carry;
        t[4] = (u64)s;
        t[5] = (u64)(s >> 64);

        u64 m = t[0] * N0;
        carry = ((u128)t[0] + (u128)m * MOD[0]) >> 64;
        for (int j = 1; j < 4; j++) {
            u128 s2 = (u128)t[j] + (u128)m * MOD[j] + carry;
            t[j - 1] = (u64)s2;
            carry = s2 >> 64;
        }
        s = (u128)t[4] + carry;
        t[3] = (u64)s;
        t[4] = t[5] + (u64)(s >> 64);
        t[5] = 0;
    }
    if (t[4] || geq(t, MOD)) sub_nored(out, t, MOD);
    else memcpy(out, t, 32);
}

static void to_mont(u64 out[4], const u64 a[4]) { mont_mul(out, a, R2); }
static void from_mont(u64 out[4], const u64 a[4]) { mont_mul(out, a, ONE_STD); }

/* out = base^e mod r, all in Montgomery form; e is a standard 4-limb int */
static void mont_pow(u64 out[4], const u64 base[4], const u64 e[4]) {
    u64 acc[4], sq[4];
    to_mont(acc, ONE_STD);
    memcpy(sq, base, 32);
    for (int limb = 0; limb < 4; limb++) {
        u64 bits = e[limb];
        for (int i = 0; i < 64; i++) {
            if (bits & 1) mont_mul(acc, acc, sq);
            bits >>= 1;
            if (limb == 3 && bits == 0 && i == 63) break;
            mont_mul(sq, sq, sq);
        }
    }
    memcpy(out, acc, 32);
}

/* ---------- packed-buffer helpers ---------- */

static int unpack_arg(PyObject *obj, const u64 **out, Py_ssize_t *count,
                      const char *name) {
    /* bytes only: an immutable exporter whose storage outlives the call
     * (the args tuple holds a reference). Mutable buffer-protocol objects
     * (bytearray, numpy) could be resized mid-call after a
     * PyBuffer_Release, so they are rejected rather than risked. */
    char *buf;
    Py_ssize_t len;
    if (!PyBytes_Check(obj)) {
        PyErr_Format(PyExc_TypeError, "%s: expected bytes", name);
        return -1;
    }
    if (PyBytes_AsStringAndSize(obj, &buf, &len) < 0) return -1;
    if (len % 32) {
        PyErr_Format(PyExc_ValueError, "%s: length %zd not a multiple of 32",
                     name, len);
        return -1;
    }
    *out = (const u64 *)buf;
    *count = len / 32;
    return 0;
}

/* ---------- module functions ---------- */

/* mul_many(a: bytes k*32, b: bytes k*32 | 32) -> bytes k*32 */
static PyObject *py_mul_many(PyObject *self, PyObject *args) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return NULL;
    const u64 *a, *b;
    Py_ssize_t ka, kb;
    if (unpack_arg(ao, &a, &ka, "a") < 0) return NULL;
    if (unpack_arg(bo, &b, &kb, "b") < 0) return NULL;
    if (kb != ka && kb != 1) {
        PyErr_SetString(PyExc_ValueError, "b must have k or 1 scalars");
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize(NULL, ka * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    u64 bm_shared[4];
    if (kb == 1) to_mont(bm_shared, b);
    for (Py_ssize_t i = 0; i < ka; i++) {
        u64 am[4], bm[4], t[4];
        to_mont(am, a + 4 * i);
        if (kb == 1) memcpy(bm, bm_shared, 32);
        else to_mont(bm, b + 4 * i);
        mont_mul(t, am, bm);
        from_mont(out + 4 * i, t);
    }
    return res;
}

/* add_many / sub_many(a, b) -> bytes (b broadcastable like mul_many) */
static PyObject *addsub_many(PyObject *args, int is_sub) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return NULL;
    const u64 *a, *b;
    Py_ssize_t ka, kb;
    if (unpack_arg(ao, &a, &ka, "a") < 0) return NULL;
    if (unpack_arg(bo, &b, &kb, "b") < 0) return NULL;
    if (kb != ka && kb != 1) {
        PyErr_SetString(PyExc_ValueError, "b must have k or 1 scalars");
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize(NULL, ka * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    for (Py_ssize_t i = 0; i < ka; i++) {
        const u64 *bi = (kb == 1) ? b : b + 4 * i;
        if (is_sub) sub_mod(out + 4 * i, a + 4 * i, bi);
        else add_mod(out + 4 * i, a + 4 * i, bi);
    }
    return res;
}

static PyObject *py_add_many(PyObject *self, PyObject *args) {
    return addsub_many(args, 0);
}
static PyObject *py_sub_many(PyObject *self, PyObject *args) {
    return addsub_many(args, 1);
}

/* addmul_many(acc, a, b) -> acc + a*b elementwise (b broadcastable) */
static PyObject *py_addmul_many(PyObject *self, PyObject *args) {
    PyObject *acco, *ao, *bo;
    if (!PyArg_ParseTuple(args, "OOO", &acco, &ao, &bo)) return NULL;
    const u64 *acc, *a, *b;
    Py_ssize_t kacc, ka, kb;
    if (unpack_arg(acco, &acc, &kacc, "acc") < 0) return NULL;
    if (unpack_arg(ao, &a, &ka, "a") < 0) return NULL;
    if (unpack_arg(bo, &b, &kb, "b") < 0) return NULL;
    if (ka != kacc || (kb != ka && kb != 1)) {
        PyErr_SetString(PyExc_ValueError, "shape mismatch");
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize(NULL, ka * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    u64 bm_shared[4];
    if (kb == 1) to_mont(bm_shared, b);
    for (Py_ssize_t i = 0; i < ka; i++) {
        u64 am[4], bm[4], t[4], std[4];
        to_mont(am, a + 4 * i);
        if (kb == 1) memcpy(bm, bm_shared, 32);
        else to_mont(bm, b + 4 * i);
        mont_mul(t, am, bm);
        from_mont(std, t);
        add_mod(out + 4 * i, acc + 4 * i, std);
    }
    return res;
}

/* powers(base: bytes32, n, invert=False) -> bytes n*32 : [1, b, b^2, ...] */
static PyObject *py_powers(PyObject *self, PyObject *args) {
    PyObject *bo;
    Py_ssize_t n;
    int invert = 0;
    if (!PyArg_ParseTuple(args, "On|p", &bo, &n, &invert)) return NULL;
    const u64 *b;
    Py_ssize_t kb;
    if (unpack_arg(bo, &b, &kb, "base") < 0) return NULL;
    if (kb != 1 || n < 0) {
        PyErr_SetString(PyExc_ValueError, "base must be one scalar, n >= 0");
        return NULL;
    }
    u64 base_m[4];
    to_mont(base_m, b);
    if (invert) {
        /* base^(r-2) via Fermat */
        u64 e[4];
        memcpy(e, MOD, 32);
        e[0] -= 2;
        u64 inv[4];
        mont_pow(inv, base_m, e);
        memcpy(base_m, inv, 32);
    }
    PyObject *res = PyBytes_FromStringAndSize(NULL, n * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    u64 acc[4];
    to_mont(acc, ONE_STD);
    for (Py_ssize_t i = 0; i < n; i++) {
        from_mont(out + 4 * i, acc);
        mont_mul(acc, acc, base_m);
    }
    return res;
}

/* fold_coeffs(ch: bytes r*32, inv: bytes r*32, n, invert_first) -> n*32
 *
 * Mirrors models/range_verifier._fold_coefficients: coefficients built by
 * repeated doubling, challenges consumed in REVERSE round order (round 1
 * binds the index MSB — reference ipa.go:343-356 fold semantics). */
static PyObject *py_fold_coeffs(PyObject *self, PyObject *args) {
    PyObject *cho, *invo;
    Py_ssize_t n;
    int invert_first;
    if (!PyArg_ParseTuple(args, "OOnp", &cho, &invo, &n, &invert_first))
        return NULL;
    const u64 *ch, *inv;
    Py_ssize_t kc, ki;
    if (unpack_arg(cho, &ch, &kc, "challenges") < 0) return NULL;
    if (unpack_arg(invo, &inv, &ki, "inverses") < 0) return NULL;
    if (kc < 0 || kc > 62) { /* bound before the shift: UB otherwise */
        PyErr_SetString(PyExc_ValueError, "rounds out of range");
        return NULL;
    }
    if (kc != ki || (((Py_ssize_t)1) << kc) != n) {
        PyErr_SetString(PyExc_ValueError, "need 2^rounds == n");
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize(NULL, n * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    /* work in Montgomery form throughout the expansion */
    u64 *coeffs = (u64 *)PyMem_Malloc(n * 32);
    if (!coeffs) {
        Py_DECREF(res);
        return PyErr_NoMemory();
    }
    to_mont(coeffs, ONE_STD);
    Py_ssize_t cur = 1;
    for (Py_ssize_t r = kc - 1; r >= 0; r--) { /* reverse round order */
        u64 lo[4], hi[4];
        if (invert_first) {
            to_mont(lo, inv + 4 * r);
            to_mont(hi, ch + 4 * r);
        } else {
            to_mont(lo, ch + 4 * r);
            to_mont(hi, inv + 4 * r);
        }
        for (Py_ssize_t i = 0; i < cur; i++) {
            u64 c[4];
            memcpy(c, coeffs + 4 * i, 32);
            mont_mul(coeffs + 4 * i, c, lo);
            mont_mul(coeffs + 4 * (cur + i), c, hi);
        }
        cur <<= 1;
    }
    for (Py_ssize_t i = 0; i < n; i++) from_mont(out + 4 * i, coeffs + 4 * i);
    PyMem_Free(coeffs);
    return res;
}

/* batch_inv(a: bytes k*32) -> bytes k*32 (zero maps to error) */
static PyObject *py_batch_inv(PyObject *self, PyObject *args) {
    PyObject *ao;
    if (!PyArg_ParseTuple(args, "O", &ao)) return NULL;
    const u64 *a;
    Py_ssize_t k;
    if (unpack_arg(ao, &a, &k, "a") < 0) return NULL;
    PyObject *res = PyBytes_FromStringAndSize(NULL, k * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    u64 *pref = (u64 *)PyMem_Malloc((k + 1) * 32);
    u64 *am = (u64 *)PyMem_Malloc(k * 32);
    if (!pref || !am) {
        PyMem_Free(pref);
        PyMem_Free(am);
        Py_DECREF(res);
        return PyErr_NoMemory();
    }
    to_mont(pref, ONE_STD);
    for (Py_ssize_t i = 0; i < k; i++) {
        static const u64 ZERO[4] = {0, 0, 0, 0};
        if (memcmp(a + 4 * i, ZERO, 32) == 0) {
            PyMem_Free(pref);
            PyMem_Free(am);
            Py_DECREF(res);
            PyErr_SetString(PyExc_ZeroDivisionError, "inverse of zero in Fr");
            return NULL;
        }
        to_mont(am + 4 * i, a + 4 * i);
        mont_mul(pref + 4 * (i + 1), pref + 4 * i, am + 4 * i);
    }
    u64 e[4], run[4];
    memcpy(e, MOD, 32);
    e[0] -= 2;
    mont_pow(run, pref + 4 * k, e); /* (prod all)^{-1} */
    for (Py_ssize_t i = k - 1; i >= 0; i--) {
        u64 t[4];
        mont_mul(t, run, pref + 4 * i); /* a_i^{-1} in Montgomery */
        from_mont(out + 4 * i, t);
        mont_mul(run, run, am + 4 * i);
    }
    PyMem_Free(pref);
    PyMem_Free(am);
    return res;
}

/* ---------- base-field (Fp) point conversion ----------
 *
 * points_to_limbs: affine (x, y, inf) host points -> Montgomery projective
 * limb encoding the device kernels consume (ops/limbs.py
 * point_to_projective_limbs), without per-coordinate Python bigint math.
 * Identity encodes as (0 : R1 : 0).
 */

static const u64 FP_MOD[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                              0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 FP_N0 = 0x87d20782e4866389ULL;
static const u64 FP_R2[4] = {0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                             0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL};
static const u64 FP_R1[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                             0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};

static int fp_geq(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] > b[i]) return 1;
        if (a[i] < b[i]) return 0;
    }
    return 1;
}

static void fp_mont_mul(u64 out[4], const u64 a[4], const u64 b[4]) {
    u64 t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 s = (u128)t[j] + (u128)a[j] * b[i] + carry;
            t[j] = (u64)s;
            carry = s >> 64;
        }
        u128 s = (u128)t[4] + carry;
        t[4] = (u64)s;
        t[5] = (u64)(s >> 64);

        u64 m = t[0] * FP_N0;
        carry = ((u128)t[0] + (u128)m * FP_MOD[0]) >> 64;
        for (int j = 1; j < 4; j++) {
            u128 s2 = (u128)t[j] + (u128)m * FP_MOD[j] + carry;
            t[j - 1] = (u64)s2;
            carry = s2 >> 64;
        }
        s = (u128)t[4] + carry;
        t[3] = (u64)s;
        t[4] = t[5] + (u64)(s >> 64);
        t[5] = 0;
    }
    if (t[4] || fp_geq(t, FP_MOD)) sub_nored(out, t, FP_MOD);
    else memcpy(out, t, 32);
}

/* points_to_limbs(xy: bytes k*65) -> bytes k*96
 * input per point: x(32 LE) ++ y(32 LE) ++ inf(1 byte)
 * output per point: X_mont(32 LE) ++ Y_mont(32 LE) ++ Z_mont(32 LE) */
static PyObject *py_points_to_limbs(PyObject *self, PyObject *args) {
    PyObject *po;
    if (!PyArg_ParseTuple(args, "O", &po)) return NULL;
    char *buf;
    Py_ssize_t blen;
    if (!PyBytes_Check(po)) {
        PyErr_SetString(PyExc_TypeError, "expected bytes");
        return NULL;
    }
    if (PyBytes_AsStringAndSize(po, &buf, &blen) < 0) return NULL;
    if (blen % 65) {
        PyErr_SetString(PyExc_ValueError, "need k*65 bytes (x||y||inf)");
        return NULL;
    }
    Py_ssize_t k = blen / 65;
    const unsigned char *in = (const unsigned char *)buf;
    PyObject *res = PyBytes_FromStringAndSize(NULL, k * 96);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    for (Py_ssize_t i = 0; i < k; i++) {
        const unsigned char *p = in + 65 * i;
        u64 *o = out + 12 * i;
        if (p[64]) { /* identity: (0 : R1 : 0) */
            memset(o, 0, 32);
            memcpy(o + 4, FP_R1, 32);
            memset(o + 8, 0, 32);
            continue;
        }
        u64 x[4], y[4];
        memcpy(x, p, 32);
        memcpy(y, p + 32, 32);
        fp_mont_mul(o, x, FP_R2);      /* X in Montgomery */
        fp_mont_mul(o + 4, y, FP_R2);  /* Y in Montgomery */
        memcpy(o + 8, FP_R1, 32);      /* Z = 1 in Montgomery */
    }
    return res;
}

/* ---------- fused verifier host phases ----------
 *
 * Scalar assembly of models/range_verifier._host_phase_a/_host_phase_b,
 * whole computation in Montgomery form. Pinned 1:1 against the Python
 * implementations by tests/test_frmont_native.py; layouts:
 *   phase_a -> y_pows(n) ++ yinv_pows(n) ++ [pol_eval] ++ k_fixed(n+2)
 *   phase_b -> fixed(2n+5) ++ var(2r+5)
 */

static void read_scalar(const u64 *buf, Py_ssize_t idx, u64 out[4]) {
    memcpy(out, buf + 4 * idx, 32);
}

/* phase_a(n, x_unused, y, z, delta) all scalars packed; returns packed */
static PyObject *py_phase_a(PyObject *self, PyObject *args) {
    Py_ssize_t n;
    PyObject *so;
    if (!PyArg_ParseTuple(args, "nO", &n, &so)) return NULL;
    const u64 *s;
    Py_ssize_t ks;
    if (unpack_arg(so, &s, &ks, "scalars") < 0) return NULL;
    if (ks != 3) {
        PyErr_SetString(PyExc_ValueError, "need packed [y, z, delta]");
        return NULL;
    }
    u64 y[4], z[4], delta[4];
    read_scalar(s, 0, y);
    read_scalar(s, 1, z);
    read_scalar(s, 2, delta);

    PyObject *res = PyBytes_FromStringAndSize(NULL, (3 * n + 3) * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    u64 *y_pows = out;               /* n */
    u64 *yinv_pows = out + 4 * n;    /* n */
    u64 *pol_eval = out + 8 * n;     /* 1 */
    u64 *k_fixed = out + 8 * n + 4;  /* n + 2 */

    u64 ym[4], yim[4], e[4];
    to_mont(ym, y);
    memcpy(e, MOD, 32);
    e[0] -= 2;
    mont_pow(yim, ym, e); /* y^{-1} in Montgomery */

    u64 one_m[4], acc[4], acci[4];
    to_mont(one_m, ONE_STD);
    memcpy(acc, one_m, 32);
    memcpy(acci, one_m, 32);
    /* ipy = sum y^i ; ip2 = sum 2^i ; two_pows in Montgomery */
    u64 ipy[4] = {0, 0, 0, 0}, ip2[4] = {0, 0, 0, 0};
    u64 two_m[4], p2[4];
    u64 two_std[4] = {2, 0, 0, 0};
    to_mont(two_m, two_std);
    memcpy(p2, one_m, 32);

    u64 zm[4], z_sq[4], z_cube[4], dm[4];
    to_mont(zm, z);
    mont_mul(z_sq, zm, zm);
    mont_mul(z_cube, z_sq, zm);
    to_mont(dm, delta);

    for (Py_ssize_t i = 0; i < n; i++) {
        from_mont(y_pows + 4 * i, acc);
        from_mont(yinv_pows + 4 * i, acci);
        add_mod(ipy, ipy, acc);
        add_mod(ip2, ip2, p2);
        /* k_fixed[i] = z + z^2 * 2^i * yinv^i */
        u64 t[4];
        mont_mul(t, z_sq, p2);
        mont_mul(t, t, acci);
        add_mod(t, t, zm);
        from_mont(k_fixed + 4 * i, t);
        mont_mul(acc, acc, ym);
        mont_mul(acci, acci, yim);
        mont_mul(p2, p2, two_m);
    }
    /* pol_eval = (z - z^2) * ipy - z^3 * ip2 */
    u64 t1[4], t2[4], pe[4];
    sub_mod(t1, zm, z_sq);
    mont_mul(t1, t1, ipy);
    mont_mul(t2, z_cube, ip2);
    sub_mod(pe, t1, t2);
    from_mont(pol_eval, pe);
    /* k_fixed[n] = -delta ; k_fixed[n+1] = -z */
    u64 zero[4] = {0, 0, 0, 0}, nd[4], nz[4];
    sub_mod(nd, zero, dm);
    from_mont(k_fixed + 4 * n, nd);
    sub_mod(nz, zero, zm);
    from_mont(k_fixed + 4 * (n + 1), nz);
    return res;
}

/* phase_b(n, rounds, scalars, yinv_pows, round_ch, round_inv)
 * scalars packed: [a, b, z, x, x_ipa, ip, tau, delta, pol_eval]
 * returns fixed(2n+5) ++ var(2r+5), packed standard form
 * (var layout: D, C, L_r..., R_r..., T1, T2, Com = 2 + 2r + 3) */
static PyObject *py_phase_b(PyObject *self, PyObject *args) {
    Py_ssize_t n, rounds;
    PyObject *so, *yo, *co, *io;
    if (!PyArg_ParseTuple(args, "nnOOOO", &n, &rounds, &so, &yo, &co, &io))
        return NULL;
    const u64 *s, *yinv, *rch, *rinv;
    Py_ssize_t ks, ky, kc, ki;
    if (unpack_arg(so, &s, &ks, "scalars") < 0) return NULL;
    if (unpack_arg(yo, &yinv, &ky, "yinv_pows") < 0) return NULL;
    if (unpack_arg(co, &rch, &kc, "round_ch") < 0) return NULL;
    if (unpack_arg(io, &rinv, &ki, "round_inv") < 0) return NULL;
    if (rounds < 0 || rounds > 62) { /* bound before the shift: UB */
        PyErr_SetString(PyExc_ValueError, "phase_b: rounds out of range");
        return NULL;
    }
    if (ks != 9 || ky != n || kc != rounds || ki != rounds ||
        (((Py_ssize_t)1) << rounds) != n) {
        PyErr_SetString(PyExc_ValueError, "phase_b: shape mismatch");
        return NULL;
    }
    u64 a[4], b[4], z[4], x[4], x_ipa[4], ip[4], tau[4], delta[4], pe[4];
    read_scalar(s, 0, a);
    read_scalar(s, 1, b);
    read_scalar(s, 2, z);
    read_scalar(s, 3, x);
    read_scalar(s, 4, x_ipa);
    read_scalar(s, 5, ip);
    read_scalar(s, 6, tau);
    read_scalar(s, 7, delta);
    read_scalar(s, 8, pe);

    Py_ssize_t n_fixed = 2 * n + 5;
    Py_ssize_t n_var = 2 + 2 * rounds + 3;
    PyObject *res =
        PyBytes_FromStringAndSize(NULL, (n_fixed + n_var) * 32);
    if (!res) return NULL;
    u64 *out = (u64 *)PyBytes_AS_STRING(res);
    u64 *fixed = out;
    u64 *var = out + 4 * n_fixed;

    /* Montgomery inputs */
    u64 am[4], bm[4], zm[4], xm[4], xim[4], ipm[4], z_sq[4], x_sq[4];
    to_mont(am, a);
    to_mont(bm, b);
    to_mont(zm, z);
    to_mont(xm, x);
    to_mont(xim, x_ipa);
    to_mont(ipm, ip);
    mont_mul(z_sq, zm, zm);
    mont_mul(x_sq, xm, xm);

    /* fold coefficients, Montgomery domain, reverse round order */
    u64 *ac = (u64 *)PyMem_Malloc(n * 32);
    u64 *bc = (u64 *)PyMem_Malloc(n * 32);
    if (!ac || !bc) {
        PyMem_Free(ac);
        PyMem_Free(bc);
        Py_DECREF(res);
        return PyErr_NoMemory();
    }
    u64 one_m[4];
    to_mont(one_m, ONE_STD);
    memcpy(ac, one_m, 32);
    memcpy(bc, one_m, 32);
    Py_ssize_t cur = 1;
    for (Py_ssize_t r = rounds - 1; r >= 0; r--) {
        u64 xr[4], xr_inv[4];
        to_mont(xr, rch + 4 * r);
        to_mont(xr_inv, rinv + 4 * r);
        for (Py_ssize_t i = 0; i < cur; i++) {
            u64 c[4];
            /* a: lo=inv, hi=ch ; b: lo=ch, hi=inv */
            memcpy(c, ac + 4 * i, 32);
            mont_mul(ac + 4 * i, c, xr_inv);
            mont_mul(ac + 4 * (cur + i), c, xr);
            memcpy(c, bc + 4 * i, 32);
            mont_mul(bc + 4 * i, c, xr);
            mont_mul(bc + 4 * (cur + i), c, xr_inv);
        }
        cur <<= 1;
    }

    u64 two_std[4] = {2, 0, 0, 0}, two_m[4], p2[4];
    to_mont(two_m, two_std);
    memcpy(p2, one_m, 32);
    for (Py_ssize_t j = 0; j < n; j++) {
        u64 t[4], yv[4];
        /* G_j: a * a_coeffs[j] + z */
        mont_mul(t, am, ac + 4 * j);
        add_mod(t, t, zm);
        from_mont(fixed + 4 * j, t);
        /* H_j: b*b_coeffs[j]*yinv_j - z - z^2*2^j*yinv_j */
        to_mont(yv, yinv + 4 * j);
        u64 h[4], t2[4];
        mont_mul(h, bm, bc + 4 * j);
        mont_mul(h, h, yv);
        sub_mod(h, h, zm);
        mont_mul(t2, z_sq, p2);
        mont_mul(t2, t2, yv);
        sub_mod(h, h, t2);
        from_mont(fixed + 4 * (n + j), h);
        mont_mul(p2, p2, two_m);
    }
    PyMem_Free(ac);
    PyMem_Free(bc);
    /* P: delta ; Q: x_ipa*(a*b - ip) ; cg0: ip - pol_eval ; cg1: tau ;
     * S_G: 0 */
    memcpy(fixed + 4 * (2 * n), delta, 32);
    u64 q[4], pem[4], taum[4];
    mont_mul(q, am, bm);
    sub_mod(q, q, ipm);
    mont_mul(q, q, xim);
    from_mont(fixed + 4 * (2 * n + 1), q);
    to_mont(pem, pe);
    u64 cg0[4];
    sub_mod(cg0, ipm, pem);
    from_mont(fixed + 4 * (2 * n + 2), cg0);
    memcpy(fixed + 4 * (2 * n + 3), tau, 32);
    memset(fixed + 4 * (2 * n + 4), 0, 32);

    /* var: D=-x, C=-1, L_r=-(x_r^2), R_r=-(x_r^-2), T1=-x, T2=-x^2,
     * Com=-z^2 */
    u64 zero[4] = {0, 0, 0, 0}, t[4];
    sub_mod(t, zero, xm);
    from_mont(var + 0, t); /* D */
    u64 neg_one[4];
    sub_mod(neg_one, zero, one_m);
    from_mont(var + 4, neg_one); /* C */
    for (Py_ssize_t r = 0; r < rounds; r++) {
        u64 xr[4], sq[4];
        to_mont(xr, rch + 4 * r);
        mont_mul(sq, xr, xr);
        sub_mod(sq, zero, sq);
        from_mont(var + 4 * (2 + r), sq);
        to_mont(xr, rinv + 4 * r);
        mont_mul(sq, xr, xr);
        sub_mod(sq, zero, sq);
        from_mont(var + 4 * (2 + rounds + r), sq);
    }
    sub_mod(t, zero, xm);
    from_mont(var + 4 * (2 + 2 * rounds), t); /* T1 */
    sub_mod(t, zero, x_sq);
    from_mont(var + 4 * (2 + 2 * rounds + 1), t); /* T2 */
    sub_mod(t, zero, z_sq);
    from_mont(var + 4 * (2 + 2 * rounds + 2), t); /* Com */
    return res;
}

static PyMethodDef Methods[] = {
    {"points_to_limbs", py_points_to_limbs, METH_VARARGS,
     "affine points (x||y||inf @65B) -> Montgomery projective (96B)"},
    {"phase_a", py_phase_a, METH_VARARGS,
     "fused host phase a: y ladders + pol_eval + K fixed scalars"},
    {"phase_b", py_phase_b, METH_VARARGS,
     "fused host phase b: fold + eq1/eq2 scalar assembly"},
    {"mul_many", py_mul_many, METH_VARARGS,
     "elementwise a*b mod r over packed 32-byte scalars (b broadcastable)"},
    {"add_many", py_add_many, METH_VARARGS, "elementwise a+b mod r"},
    {"sub_many", py_sub_many, METH_VARARGS, "elementwise a-b mod r"},
    {"addmul_many", py_addmul_many, METH_VARARGS, "acc + a*b mod r"},
    {"powers", py_powers, METH_VARARGS,
     "powers(base, n, invert=False): [base^0 .. base^(n-1)]"},
    {"fold_coeffs", py_fold_coeffs, METH_VARARGS,
     "IPA fold-coefficient expansion (reverse round order)"},
    {"batch_inv", py_batch_inv, METH_VARARGS, "Montgomery batch inversion"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_frmont",
                                       "BN254 Fr batch arithmetic", -1,
                                       Methods};

PyMODINIT_FUNC PyInit__frmont(void) { return PyModule_Create(&moduledef); }
