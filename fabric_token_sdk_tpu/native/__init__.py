"""Native host-runtime components (C, built on demand).

The compute path is JAX/XLA on the device; the host runtime around it —
field arithmetic feeding the transcripts — is C where the reference uses
gnark-crypto assembly. The extension builds lazily with the system
compiler on first use and degrades to the pure-Python oracle when no
toolchain is available (`load_frmont()` returns None).
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
import threading

_DIR = os.path.dirname(__file__)
_LOCK = threading.Lock()
_CACHED = False
_MODULE = None


def _so_path() -> str:
    tag = f"cpython-{sys.version_info.major}{sys.version_info.minor}"
    return os.path.join(_DIR, f"_frmont.{tag}.so")


def _build() -> str | None:
    src = os.path.join(_DIR, "frmont.c")
    out = _so_path()
    include = sysconfig.get_paths()["include"]
    # compile to a private temp name, then atomically rename: concurrent
    # builders (pytest workers, bench + tests) must never dlopen a
    # half-written .so
    tmp = f"{out}.tmp.{os.getpid()}"
    cmd = ["cc", "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        sys.stderr.write(f"frmont build failed:\n{proc.stderr}\n")
        return None
    try:
        os.replace(tmp, out)
    except OSError:
        os.unlink(tmp)
        return None
    return out


def load_frmont():
    """The `_frmont` module, building it if needed; None when unavailable
    (no compiler). Thread-safe; result cached for the process."""
    global _CACHED, _MODULE
    with _LOCK:
        if _CACHED:
            return _MODULE
        _CACHED = True
        path = _so_path()
        if not os.path.exists(path) or (
                os.path.getmtime(path)
                < os.path.getmtime(os.path.join(_DIR, "frmont.c"))):
            if _build() is None:
                return None
        import importlib.util

        spec = importlib.util.spec_from_file_location("_frmont", path)
        try:
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception:
            return None
        _MODULE = mod
        return _MODULE
