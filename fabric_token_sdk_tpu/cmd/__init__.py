"""Operator CLIs (tokengen)."""
