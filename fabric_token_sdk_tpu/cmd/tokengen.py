"""tokengen: public-parameter generation CLI.

Behavioral mirror of reference cmd/tokengen (main.go:46-51 command set):

  gen dlog      — zkatdlog public params (--base/--exponent set the range
                  bit-length as base^exponent bits of value, mirroring
                  cobra/pp/dlog/gen.go:24-80; or --bits directly), plus the
                  TPU batching extension required by BASELINE.json:
                  --tpu-batch-size / --tpu-mesh-devices embed TpuBatchParams.
  gen fabtoken  — plaintext driver params (--precision).
  pp print      — inspect a serialized public-parameters file.
  update        — bump/refresh params preserving identities.
  certifier-keygen — certifier key pair for a pp set
                  (cobra/certfier/keypairgen.go:27-90).
  artifacts gen — NWO topology artifacts: per-node identities + wired pp
                  + manifest consumable by harness.nwo.Platform
                  (cobra/artifactgen/gen + utils.go WriteTopologies).
  version       — print the framework version.

Identities (issuers/auditors) are registered from PEM/DER public-key files
via --issuer/--auditor (repeatable), standing in for the reference's MSP
cert directories.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

VERSION = "0.1.0"


def _load_identity(path: str) -> bytes:
    raw = pathlib.Path(path).read_bytes()
    if raw.lstrip().startswith(b"-----BEGIN"):
        from cryptography.hazmat.primitives import serialization

        key = serialization.load_pem_public_key(raw)
        return key.public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)
    return raw


def _gen_dlog(args) -> int:
    from ..crypto import setup as dlog_setup

    bits = args.bits
    if bits is None:
        bits = 1
        for _ in range(args.exponent):
            bits *= args.base
    if bits not in dlog_setup.SUPPORTED_PRECISIONS:
        print(f"unsupported bit length {bits}; supported: "
              f"{dlog_setup.SUPPORTED_PRECISIONS}", file=sys.stderr)
        return 2
    pp = dlog_setup.setup(bits)
    for path in args.issuer or []:
        pp.add_issuer(_load_identity(path))
    for path in args.auditor or []:
        pp.add_auditor(_load_identity(path))
    if args.tpu_batch_size or args.tpu_mesh_devices:
        pp.tpu_batch = dlog_setup.TpuBatchParams(
            batch_size=args.tpu_batch_size or 1024,
            mesh_devices=args.tpu_mesh_devices or 1)
    out = pathlib.Path(args.output) / "zkatdlog_pp.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_bytes(pp.serialize())
    print(str(out))
    return 0


def _gen_fabtoken(args) -> int:
    from ..core import fabtoken

    pp = fabtoken.setup(args.precision)
    for path in args.issuer or []:
        pp.issuer_ids.append(_load_identity(path))
    for path in args.auditor or []:
        pp.auditor = _load_identity(path)
    out = pathlib.Path(args.output) / "fabtoken_pp.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_bytes(pp.serialize())
    print(str(out))
    return 0


def _pp_print(args) -> int:
    raw = pathlib.Path(args.path).read_bytes()
    outer = json.loads(raw)
    ident = outer.get("identifier", "")
    print(f"identifier: {ident}")
    if ident == "zkatdlog":
        from ..crypto import setup as dlog_setup

        pp = dlog_setup.PublicParams.deserialize(raw)
        rpp = pp.range_proof_params
        print(f"version: {pp.version}")
        print(f"bit_length: {rpp.bit_length}")
        print(f"rounds: {rpp.number_of_rounds}")
        print(f"max_token: {pp.max_token}")
        print(f"issuers: {len(pp.issuer_ids)}")
        print(f"auditor: {'yes' if pp.auditor else 'no'}")
        if pp.tpu_batch:
            print(f"tpu_batch_size: {pp.tpu_batch.batch_size}")
            print(f"tpu_mesh_devices: {pp.tpu_batch.mesh_devices}")
    elif ident == "fabtoken":
        from ..core.fabtoken.setup import PublicParams

        pp = PublicParams.deserialize(raw)
        print(f"version: {pp.ver}")
        print(f"precision: {pp.quantity_precision}")
        print(f"max_token: {pp.max_token}")
        print(f"issuers: {len(pp.issuer_ids)}")
        print(f"auditor: {'yes' if pp.auditor else 'no'}")
    else:
        print("unknown public parameters identifier", file=sys.stderr)
        return 2
    return 0


def _update(args) -> int:
    """Re-serialize with a fresh version stamp (TMSProvider.Update path,
    reference core/tms.go:117; identities/generators preserved)."""
    raw = pathlib.Path(args.path).read_bytes()
    outer = json.loads(raw)
    if outer.get("identifier") == "zkatdlog":
        from ..crypto import setup as dlog_setup

        pp = dlog_setup.PublicParams.deserialize(raw)
        pathlib.Path(args.path).write_bytes(pp.serialize())
    else:
        from ..core.fabtoken.setup import PublicParams

        pp = PublicParams.deserialize(raw)
        pathlib.Path(args.path).write_bytes(pp.serialize())
    print(args.path)
    return 0


def _certifier_keygen(args) -> int:
    """cobra/certfier/keypairgen.go: validate the pp, emit a key pair."""
    from ..services.identity.x509 import keypair_to_pem, new_signing_identity

    raw = pathlib.Path(args.pppath).read_bytes()
    ident = json.loads(raw).get("identifier", "")
    if args.driver == "dlog" and ident != "zkatdlog":
        print(f"public parameters are [{ident}], not zkatdlog",
              file=sys.stderr)
        return 2
    if args.driver == "fabtoken" and ident != "fabtoken":
        print(f"public parameters are [{ident}], not fabtoken",
              file=sys.stderr)
        return 2
    kp = new_signing_identity()
    priv, pub = keypair_to_pem(kp)
    out = pathlib.Path(args.output)
    out.mkdir(parents=True, exist_ok=True)
    (out / "certifier_sk.pem").write_bytes(priv)
    (out / "certifier_pk.pem").write_bytes(pub)
    print(str(out / "certifier_sk.pem"))
    print(str(out / "certifier_pk.pem"))
    return 0


def _artifacts_gen(args) -> int:
    """artifactgen: topology file -> runnable NWO artifacts.

    Topology JSON: {"driver": "fabtoken"|"zkatdlog", "precision": N,
    "bit_length": N, "nodes": [{"name", "role", "idemix"?}, ...]}.
    Emits per-node key PEMs, the wired public parameters (issuer/auditor
    identities registered), and manifest.json for Platform.from_artifacts.
    """
    from ..services.identity.x509 import keypair_to_pem, new_signing_identity

    topo = json.loads(pathlib.Path(args.topology).read_text())
    driver = topo.get("driver", "fabtoken")
    precision = int(topo.get("precision", 64))
    bit_length = int(topo.get("bit_length", 16))
    nodes = topo.get("nodes", [])
    if not nodes:
        print("topology has no nodes", file=sys.stderr)
        return 2

    out = pathlib.Path(args.output)
    (out / "crypto").mkdir(parents=True, exist_ok=True)
    identities: dict[str, bytes] = {}
    for node in nodes:
        kp = new_signing_identity()
        priv, pub = keypair_to_pem(kp)
        ndir = out / "crypto" / node["name"]
        ndir.mkdir(parents=True, exist_ok=True)
        (ndir / "sk.pem").write_bytes(priv)
        (ndir / "pk.pem").write_bytes(pub)
        identities[node["name"]] = bytes(kp.identity)

    issuers = [n["name"] for n in nodes if n.get("role") == "issuer"]
    auditors = [n["name"] for n in nodes if n.get("role") == "auditor"]
    if len(auditors) > 1:
        # single-auditor pp (same rule Platform._make_pp applies); refuse
        # rather than silently dropping one
        print(f"topology declares {len(auditors)} auditors; at most one "
              "is supported", file=sys.stderr)
        return 2
    if driver == "zkatdlog":
        from ..crypto import setup as dlog_setup

        pp = dlog_setup.setup(bit_length)
        for name in issuers:
            pp.add_issuer(identities[name])
        if auditors:
            pp.add_auditor(identities[auditors[0]])
    else:
        from ..core import fabtoken

        pp = fabtoken.setup(precision)
        for name in issuers:
            pp.issuer_ids.append(identities[name])
        if auditors:
            pp.auditor = identities[auditors[0]]
    (out / "pp.json").write_bytes(pp.serialize())

    manifest = {
        "driver": driver,
        "precision": precision,
        "bit_length": bit_length,
        "nodes": [{"name": n["name"], "role": n.get("role", "owner"),
                   "idemix": bool(n.get("idemix", False))} for n in nodes],
        "pp": "pp.json",
        "crypto_dir": "crypto",
    }
    (out / "manifest.json").write_text(json.dumps(manifest, indent=2))
    print(str(out / "manifest.json"))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tokengen")
    sub = p.add_subparsers(dest="cmd", required=True)

    gen = sub.add_parser("gen", help="generate public parameters")
    gensub = gen.add_subparsers(dest="driver", required=True)

    dlog = gensub.add_parser("dlog", help="zkatdlog (ZK privacy) params")
    dlog.add_argument("--base", type=int, default=2)
    dlog.add_argument("--exponent", type=int, default=6)
    dlog.add_argument("--bits", type=int, default=None,
                      help="range bit-length directly (16/32/64)")
    dlog.add_argument("--issuer", action="append", default=[])
    dlog.add_argument("--auditor", action="append", default=[])
    dlog.add_argument("--tpu-batch-size", type=int, default=0,
                      help="TPU batch size hint embedded in the params")
    dlog.add_argument("--tpu-mesh-devices", type=int, default=0,
                      help="device-mesh size hint for the verification fleet")
    dlog.add_argument("--output", "-o", default=".")
    dlog.set_defaults(fn=_gen_dlog)

    fab = gensub.add_parser("fabtoken", help="plaintext driver params")
    fab.add_argument("--precision", type=int, default=64)
    fab.add_argument("--issuer", action="append", default=[])
    fab.add_argument("--auditor", action="append", default=[])
    fab.add_argument("--output", "-o", default=".")
    fab.set_defaults(fn=_gen_fabtoken)

    pp = sub.add_parser("pp", help="public-parameter utilities")
    ppsub = pp.add_subparsers(dest="ppcmd", required=True)
    pprint = ppsub.add_parser("print")
    pprint.add_argument("path")
    pprint.set_defaults(fn=_pp_print)

    # `utils pp print -i FILE`: the reference's nested utils verb
    # (cmd/tokengen/main.go:49 -> cobra/pp/utils.go -> printpp/print.go);
    # same inspection as `pp print`, kept verb-compatible for operators.
    utils = sub.add_parser("utils", help="public parameters utils")
    utilssub = utils.add_subparsers(dest="utilscmd", required=True)
    upp = utilssub.add_parser("pp", help="public parameters utility "
                                         "commands")
    uppsub = upp.add_subparsers(dest="uppcmd", required=True)
    upprint = uppsub.add_parser("print", help="inspect public parameters")
    upprint.add_argument("--input", "-i", dest="path", required=True,
                         help="path of the public param file")
    upprint.set_defaults(fn=_pp_print)

    upd = sub.add_parser("update", help="refresh serialized parameters")
    upd.add_argument("path")
    upd.set_defaults(fn=_update)

    ck = sub.add_parser("certifier-keygen",
                        help="generate a token certifier key pair")
    ck.add_argument("--driver", "-d", default="dlog",
                    choices=["dlog", "fabtoken"])
    ck.add_argument("--pppath", "-p", required=True,
                    help="path to the public parameters file")
    ck.add_argument("--output", "-o", default=".")
    ck.set_defaults(fn=_certifier_keygen)

    art = sub.add_parser("artifacts", help="NWO artifact generation")
    artsub = art.add_subparsers(dest="artcmd", required=True)
    artgen = artsub.add_parser("gen", help="generate topology artifacts")
    artgen.add_argument("--topology", "-t", required=True,
                        help="topology JSON file")
    artgen.add_argument("--output", "-o", default="artifacts")
    artgen.set_defaults(fn=_artifacts_gen)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=lambda a: print(f"tokengen version {VERSION}") or 0)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args) or 0


if __name__ == "__main__":
    sys.exit(main())
