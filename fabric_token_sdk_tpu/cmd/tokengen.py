"""tokengen: public-parameter generation CLI.

Behavioral mirror of reference cmd/tokengen (main.go:46-51 command set):

  gen dlog      — zkatdlog public params (--base/--exponent set the range
                  bit-length as base^exponent bits of value, mirroring
                  cobra/pp/dlog/gen.go:24-80; or --bits directly), plus the
                  TPU batching extension required by BASELINE.json:
                  --tpu-batch-size / --tpu-mesh-devices embed TpuBatchParams.
  gen fabtoken  — plaintext driver params (--precision).
  pp print      — inspect a serialized public-parameters file.
  update        — bump/refresh params preserving identities.
  version       — print the framework version.

Identities (issuers/auditors) are registered from PEM/DER public-key files
via --issuer/--auditor (repeatable), standing in for the reference's MSP
cert directories.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

VERSION = "0.1.0"


def _load_identity(path: str) -> bytes:
    raw = pathlib.Path(path).read_bytes()
    if raw.lstrip().startswith(b"-----BEGIN"):
        from cryptography.hazmat.primitives import serialization

        key = serialization.load_pem_public_key(raw)
        return key.public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)
    return raw


def _gen_dlog(args) -> int:
    from ..crypto import setup as dlog_setup

    bits = args.bits
    if bits is None:
        bits = 1
        for _ in range(args.exponent):
            bits *= args.base
    if bits not in dlog_setup.SUPPORTED_PRECISIONS:
        print(f"unsupported bit length {bits}; supported: "
              f"{dlog_setup.SUPPORTED_PRECISIONS}", file=sys.stderr)
        return 2
    pp = dlog_setup.setup(bits)
    for path in args.issuer or []:
        pp.add_issuer(_load_identity(path))
    for path in args.auditor or []:
        pp.add_auditor(_load_identity(path))
    if args.tpu_batch_size or args.tpu_mesh_devices:
        pp.tpu_batch = dlog_setup.TpuBatchParams(
            batch_size=args.tpu_batch_size or 1024,
            mesh_devices=args.tpu_mesh_devices or 1)
    out = pathlib.Path(args.output) / "zkatdlog_pp.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_bytes(pp.serialize())
    print(str(out))
    return 0


def _gen_fabtoken(args) -> int:
    from ..core import fabtoken

    pp = fabtoken.setup(args.precision)
    for path in args.issuer or []:
        pp.issuer_ids.append(_load_identity(path))
    for path in args.auditor or []:
        pp.auditor = _load_identity(path)
    out = pathlib.Path(args.output) / "fabtoken_pp.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_bytes(pp.serialize())
    print(str(out))
    return 0


def _pp_print(args) -> int:
    raw = pathlib.Path(args.path).read_bytes()
    outer = json.loads(raw)
    ident = outer.get("identifier", "")
    print(f"identifier: {ident}")
    if ident == "zkatdlog":
        from ..crypto import setup as dlog_setup

        pp = dlog_setup.PublicParams.deserialize(raw)
        rpp = pp.range_proof_params
        print(f"version: {pp.version}")
        print(f"bit_length: {rpp.bit_length}")
        print(f"rounds: {rpp.number_of_rounds}")
        print(f"max_token: {pp.max_token}")
        print(f"issuers: {len(pp.issuer_ids)}")
        print(f"auditor: {'yes' if pp.auditor else 'no'}")
        if pp.tpu_batch:
            print(f"tpu_batch_size: {pp.tpu_batch.batch_size}")
            print(f"tpu_mesh_devices: {pp.tpu_batch.mesh_devices}")
    elif ident == "fabtoken":
        from ..core.fabtoken.setup import PublicParams

        pp = PublicParams.deserialize(raw)
        print(f"version: {pp.ver}")
        print(f"precision: {pp.quantity_precision}")
        print(f"max_token: {pp.max_token}")
        print(f"issuers: {len(pp.issuer_ids)}")
        print(f"auditor: {'yes' if pp.auditor else 'no'}")
    else:
        print("unknown public parameters identifier", file=sys.stderr)
        return 2
    return 0


def _update(args) -> int:
    """Re-serialize with a fresh version stamp (TMSProvider.Update path,
    reference core/tms.go:117; identities/generators preserved)."""
    raw = pathlib.Path(args.path).read_bytes()
    outer = json.loads(raw)
    if outer.get("identifier") == "zkatdlog":
        from ..crypto import setup as dlog_setup

        pp = dlog_setup.PublicParams.deserialize(raw)
        pathlib.Path(args.path).write_bytes(pp.serialize())
    else:
        from ..core.fabtoken.setup import PublicParams

        pp = PublicParams.deserialize(raw)
        pathlib.Path(args.path).write_bytes(pp.serialize())
    print(args.path)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tokengen")
    sub = p.add_subparsers(dest="cmd", required=True)

    gen = sub.add_parser("gen", help="generate public parameters")
    gensub = gen.add_subparsers(dest="driver", required=True)

    dlog = gensub.add_parser("dlog", help="zkatdlog (ZK privacy) params")
    dlog.add_argument("--base", type=int, default=2)
    dlog.add_argument("--exponent", type=int, default=6)
    dlog.add_argument("--bits", type=int, default=None,
                      help="range bit-length directly (16/32/64)")
    dlog.add_argument("--issuer", action="append", default=[])
    dlog.add_argument("--auditor", action="append", default=[])
    dlog.add_argument("--tpu-batch-size", type=int, default=0,
                      help="TPU batch size hint embedded in the params")
    dlog.add_argument("--tpu-mesh-devices", type=int, default=0,
                      help="device-mesh size hint for the verification fleet")
    dlog.add_argument("--output", "-o", default=".")
    dlog.set_defaults(fn=_gen_dlog)

    fab = gensub.add_parser("fabtoken", help="plaintext driver params")
    fab.add_argument("--precision", type=int, default=64)
    fab.add_argument("--issuer", action="append", default=[])
    fab.add_argument("--auditor", action="append", default=[])
    fab.add_argument("--output", "-o", default=".")
    fab.set_defaults(fn=_gen_fabtoken)

    pp = sub.add_parser("pp", help="public-parameter utilities")
    ppsub = pp.add_subparsers(dest="ppcmd", required=True)
    pprint = ppsub.add_parser("print")
    pprint.add_argument("path")
    pprint.set_defaults(fn=_pp_print)

    upd = sub.add_parser("update", help="refresh serialized parameters")
    upd.add_argument("path")
    upd.set_defaults(fn=_update)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=lambda a: print(f"tokengen version {VERSION}") or 0)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args) or 0


if __name__ == "__main__":
    sys.exit(main())
