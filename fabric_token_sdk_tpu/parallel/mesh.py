"""Mesh construction + sharded batched MSM verification.

Sharding design (scaling-book style: pick a mesh, annotate shardings, let
XLA insert collectives):

- 'dp' axis shards the proof/batch dimension — embarrassingly parallel,
  no communication (the 100k-proof replay config in BASELINE.json).
- 'tp' axis shards the MSM *term* dimension inside each proof's check.
  Each device computes a partial sum over its term shard with shared
  doublings, then partial results (one Jacobian point per proof per device)
  are combined with an all_gather over 'tp' followed by a local point-fold.
  Point addition is not a ring reduction XLA knows (no psum over EC), so the
  gather+fold is the TPU-native collective pattern for it; the payload is
  tiny (96 uint32 per proof per device) and rides ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import ec


def make_mesh(n_devices: int | None = None, dp: int | None = None,
              tp: int = 1, devices=None) -> Mesh:
    """Build a (dp, tp) mesh over the available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None:
        dp = n_devices // tp
    if dp * tp != n_devices:
        raise ValueError(f"dp*tp ({dp}*{tp}) != n_devices ({n_devices})")
    arr = np.array(devices).reshape(dp, tp)
    return Mesh(arr, axis_names=("dp", "tp"))


def _partial_then_fold(points, scalars):
    """shard_map body: local partial MSM + all-gather fold over 'tp'."""
    partial = ec.msm(points, scalars)  # (B_local, 3, 16)
    gathered = jax.lax.all_gather(partial, "tp")  # (tp, B_local, 3, 16)
    acc = gathered[0]
    for i in range(1, gathered.shape[0]):
        acc = ec.add(acc, gathered[i])
    return ec.is_identity(acc)


def sharded_msm_is_identity(mesh: Mesh, points: jnp.ndarray,
                            scalars: jnp.ndarray):
    """Batched MSM identity check sharded (B -> dp, T -> tp).

    points: (B, T, 3, 16); scalars: (B, T, 16). B must divide by dp and T by
    tp (callers pad with identity points / zero scalars — identity terms are
    exact no-ops in the shared-doubling MSM).
    Returns a jitted callable's result: (B,) bool, replicated.
    """
    fn = jax.jit(
        jax.shard_map(
            _partial_then_fold,
            mesh=mesh,
            in_specs=(P("dp", "tp", None, None), P("dp", "tp", None)),
            out_specs=P("dp"),
            # the msm fori_loop carries an unvarying identity-point constant;
            # varying-manual-axes checking would demand a pcast inside the
            # generic kernel, so it is disabled for this wrapper.
            check_vma=False,
        )
    )
    return fn(points, scalars)


def shard_batch(mesh: Mesh, arr: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Place an array with its batch axis sharded over 'dp'."""
    spec = [None] * arr.ndim
    spec[axis] = "dp"
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
