"""Mesh construction + sharded batched MSM verification.

Sharding design (scaling-book style: pick a mesh, annotate shardings, let
XLA insert collectives):

- 'dp' axis shards the proof/batch dimension — embarrassingly parallel,
  no communication (the 100k-proof replay config in BASELINE.json).
- 'tp' axis shards the MSM *term* dimension inside each proof's check.
  Each device computes a partial sum over its term shard with shared
  doublings, then partial results (one Jacobian point per proof per device)
  are combined with an all_gather over 'tp' followed by a local point-fold.
  Point addition is not a ring reduction XLA knows (no psum over EC), so the
  gather+fold is the TPU-native collective pattern for it; the payload is
  tiny (96 uint32 per proof per device) and rides ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import ec

#: Optional progress-stamp hook (any object with ``beat(phase, detail)``,
#: normally an obs.heartbeat.Heartbeat). The multichip dryrun worker sets
#: it so the stall detector can attribute a hang to "mesh build" vs
#: "sharded compile" vs "sharded run"; duck-typed so this module never
#: has to import obs/.
_HEARTBEAT = None


def set_heartbeat(hb) -> None:
    """Install (or clear, with None) the mesh-phase heartbeat hook."""
    global _HEARTBEAT
    _HEARTBEAT = hb


def _beat(phase: str, detail: str = "") -> None:
    if _HEARTBEAT is not None:
        _HEARTBEAT.beat(phase, detail)


def _shard_map(fn, mesh, in_specs, out_specs):
    """Version-skew shim over shard_map.

    Newer jax exposes ``jax.shard_map`` whose replication checker is the
    ``check_vma`` kwarg; older releases only have
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Both
    checks are disabled for the same reason: the msm fori_loop carries an
    unvarying identity-point constant that the varying-manual-axes
    checker would demand a pcast for inside the generic kernel."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)


def make_mesh(n_devices: int | None = None, dp: int | None = None,
              tp: int = 1, devices=None) -> Mesh:
    """Build a (dp, tp) mesh over the available devices."""
    _beat("mesh_build", f"n={n_devices} tp={tp}")
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None:
        dp = n_devices // tp
    if dp * tp != n_devices:
        raise ValueError(f"dp*tp ({dp}*{tp}) != n_devices ({n_devices})")
    arr = np.array(devices).reshape(dp, tp)
    return Mesh(arr, axis_names=("dp", "tp"))


def _partial_then_fold(points, scalars):
    """shard_map body: local partial MSM + all-gather fold over 'tp'."""
    partial = ec.msm(points, scalars)  # (B_local, 3, 16)
    gathered = jax.lax.all_gather(partial, "tp")  # (tp, B_local, 3, 16)
    acc = gathered[0]
    for i in range(1, gathered.shape[0]):
        acc = ec.add(acc, gathered[i])
    return ec.is_identity(acc)


def sharded_msm_is_identity(mesh: Mesh, points: jnp.ndarray,
                            scalars: jnp.ndarray):
    """Batched MSM identity check sharded (B -> dp, T -> tp).

    points: (B, T, 3, 16); scalars: (B, T, 16). B must divide by dp and T by
    tp (callers pad with identity points / zero scalars — identity terms are
    exact no-ops in the shared-doubling MSM).
    Returns a jitted callable's result: (B,) bool, replicated.
    """
    fn = jax.jit(
        _shard_map(
            _partial_then_fold,
            mesh=mesh,
            in_specs=(P("dp", "tp", None, None), P("dp", "tp", None)),
            out_specs=P("dp"),
        )
    )
    _beat("sharded_msm", f"B={points.shape[0]} T={points.shape[1]}")
    out = fn(points, scalars)
    out.block_until_ready()
    _beat("sharded_msm_done")
    return out


def shard_batch(mesh: Mesh, arr: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Place an array with its batch axis sharded over 'dp'."""
    spec = [None] * arr.ndim
    spec[axis] = "dp"
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
