"""Self-diagnosing multichip dryrun: heartbeat-stamped worker + monitor.

Five driver rounds of ``MULTICHIP_r0*.json`` read ``rc=124, tail=""`` —
the mesh dryrun hung, the driver SIGKILLed it, and every byte of
diagnostic output died with the process (the phase prints were flushed,
but the DRIVER's pipe capture was lost along with the parent). This
module restructures the dryrun so that outcome is impossible:

- the **worker** (``python -m fabric_token_sdk_tpu.parallel.dryrun``)
  runs the actual mesh verification, stamping every phase into a
  heartbeat file (obs/heartbeat.py) and dumping all-thread stacks on
  SIGUSR1;
- the **monitor** (:func:`monitor`, what ``__graft_entry__`` now calls)
  spawns the worker with its stdout/stderr streamed straight to a log
  file, polls the heartbeat, and REWRITES the report JSON on every tick
  — so even if the monitor itself is SIGKILLed mid-run, the report on
  disk already names the current ``phase``, ``last_heartbeat_age_s``,
  and the captured output ``tail``.

A hang is now detected by the per-phase stall detector instead of the
driver's bare timeout: the monitor pokes the wedged worker with SIGUSR1
(stacks land in the log, hence in ``tail``), kills it, and writes a
phase-attributed diagnosis plus an incident snapshot.

Phase deadlines default to the measured 1-core compile costs (table
build ~4 min, first verify compile ~8 min) with generous headroom; the
tier-1 guard test runs the ``light`` leg (generic sharded MSM on tiny
shapes) with tight deadlines instead.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Worker phases -> stall deadlines (seconds). Sized from the measured
#: 1-core costs: BatchRangeVerifier table build ~240 s, first verify
#: compile ~500 s. A phase missing here gets ``default_deadline_s``.
DEFAULT_DEADLINES = {
    "jax_init": 600.0,
    "sharded_msm": 1500.0,     # generic-leg shard_map compile
    "pp_setup": 900.0,
    "verifier_build": 1800.0,
    "verify": 2400.0,
    "tamper_check": 2400.0,
}

_TAIL_BYTES = 2048


# =========================================================== worker side
def example_batch(B: int, T: int):
    """Deterministic tiny workload: rows alternate identity/non-identity
    sums (shared with ``__graft_entry__.entry``)."""
    import jax.numpy as jnp
    import numpy as np

    from ..crypto import bn254
    from ..ops import limbs

    pts_rows, sc_rows = [], []
    for b in range(B):
        p = bn254.g1_mul(bn254.G1_GENERATOR, 12345 + b)
        scalars = [(7 * b + i + 1) % bn254.R for i in range(T - 1)]
        last = (bn254.R - sum(scalars) % bn254.R) % bn254.R
        if b % 2 == 1:
            last = (last + 1) % bn254.R  # deliberately non-identity row
        scalars.append(last)
        pts_rows.append(limbs.points_to_projective_limbs([p] * T))
        sc_rows.append(limbs.scalars_to_limbs(scalars))
    return (jnp.asarray(np.stack(pts_rows)), jnp.asarray(np.stack(sc_rows)))


def ensure_xla_flags(n_devices: int) -> None:
    """Must run before jax binds a platform (same contract as
    tests/conftest.py). The monitor already sets these in the child's
    environment; this is the standalone-invocation safety net."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={n_devices}"
    if "xla_llvm_disable_expensive_passes" not in flags:
        # Compile-time, not correctness: the big MSM kernels take minutes
        # through LLVM's expensive passes on the 1-core gate host, and
        # the persistent cache cannot amortize them (XLA:CPU AOT entries
        # bake LLVM tuning pseudo-features the loader rejects against
        # raw cpuid host features). Scoped to the dryrun process only.
        flags += " --xla_llvm_disable_expensive_passes=true"
    os.environ["XLA_FLAGS"] = flags.strip()


def run_dryrun(n_devices: int, light: bool = False, hb=None) -> None:
    """The worker body: one sharded verification on an n-device CPU mesh.

    ``light`` runs only the generic sharded-MSM leg on tiny shapes (the
    tier-1 guard's budget); the full run drives the production 16-bit
    BatchRangeVerifier through the mesh plus a tamper check. Raises on
    any verification mismatch."""
    import numpy as np

    t0 = time.perf_counter()

    def phase(name: str, msg: str = "") -> None:
        if hb is not None:
            hb.beat(name, msg)
        print(f"[dryrun +{time.perf_counter() - t0:7.1f}s] {name}"
              + (f": {msg}" if msg else ""), flush=True)

    phase("jax_init", f"configuring {n_devices} virtual devices")
    import jax

    from ..utils.jaxcfg import configure_jax_cache

    jax.config.update("jax_platforms", "cpu")
    configure_jax_cache()
    if len(jax.devices("cpu")) < n_devices:
        raise RuntimeError(
            f"virtual CPU mesh has {len(jax.devices('cpu'))} devices, "
            f"need {n_devices}: XLA_FLAGS was applied too late")
    phase("jax_init_done", f"{len(jax.devices('cpu'))} cpu devices")

    from .mesh import make_mesh, set_heartbeat, sharded_msm_is_identity

    set_heartbeat(hb)
    tp = 2 if n_devices % 2 == 0 else 1
    mesh = make_mesh(n_devices, dp=n_devices // tp, tp=tp)
    phase("mesh_built", f"dp={n_devices // tp} tp={tp}")

    if light or os.environ.get("FTS_DRYRUN_FULL"):
        # generic sharded-MSM leg on tiny shapes: the cheapest program
        # that exercises the full (dp, tp) collective pattern
        B = max(4, n_devices // tp)
        T = 4 * tp
        pts, sc = example_batch(B=B, T=T)
        out = np.asarray(sharded_msm_is_identity(mesh, pts, sc))
        expected = [b % 2 == 0 for b in range(B)]
        assert list(out) == expected, f"sharded verify mismatch: {out}"
        phase("generic_leg_done")
        if light:
            phase("done", "light run complete")
            return

    # ---- the PRODUCTION verifier through the same mesh: tiny 16-bit
    # batch, pass-1 rows dp-sharded, combined RLC terms sharded with the
    # all-gather point-fold. Real proofs, real tables, real shardings.
    from ..crypto import bn254, rp, setup
    from ..models.range_verifier import BatchRangeVerifier

    phase("pp_setup", "building 16-bit public parameters")
    pp = setup.setup(16)
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    phase("prove", "generating proofs")
    proofs, coms = [], []
    for i in range(2):
        value = 101 + i
        bf = bn254.fr_rand()
        com = bn254.g1_add(bn254.g1_mul(cg[0], value),
                           bn254.g1_mul(cg[1], bf))
        proofs.append(rp.range_prove(
            com, value, cg, bf, rpp.left_generators, rpp.right_generators,
            rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length))
        coms.append(com)
    reps = max(1, n_devices // 2)
    proofs, coms = proofs * reps, coms * reps
    phase("verifier_build", f"{len(proofs)} rows, building tables")
    verifier = BatchRangeVerifier(pp, mesh=mesh)
    phase("verify", "sharded production verify")
    accepts = verifier.verify(proofs, coms)
    assert accepts.all(), f"sharded production verify rejected: {accepts}"
    phase("verify_done", "all accepted")
    # one tampered proof must flip its row (exact fallback path)
    import copy

    bad = copy.deepcopy(proofs[0])
    bad.data.tau = (bad.data.tau + 1) % bn254.R
    phase("tamper_check")
    accepts = verifier.verify([bad] + proofs[1:], coms)
    assert not accepts[0] and accepts[1:].all(), \
        f"sharded verify verdict vector wrong: {accepts}"
    phase("done", "tamper check flipped row 0 only")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="multichip dryrun worker (heartbeat-stamped)")
    parser.add_argument("--n-devices", type=int, required=True)
    parser.add_argument("--light", action="store_true",
                        help="generic sharded-MSM leg only (tiny shapes)")
    args = parser.parse_args(argv)

    ensure_xla_flags(args.n_devices)

    import faulthandler
    import signal

    faulthandler.enable()
    if hasattr(signal, "SIGUSR1"):
        # the monitor pokes a stalled worker with SIGUSR1 before killing
        # it: all-thread stacks land on stderr -> the streamed log ->
        # the report's tail
        faulthandler.register(signal.SIGUSR1, all_threads=True)

    from ..obs.heartbeat import Heartbeat
    from ..obs.journal import configure_from_env

    configure_from_env()
    hb_path = os.environ.get("FTS_HEARTBEAT_FILE") or None
    hb = Heartbeat(hb_path)
    run_dryrun(args.n_devices, light=args.light, hb=hb)
    return 0


# ========================================================== monitor side
def _write_report(path: str, report: dict) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _tail_of(path: str, n_bytes: int = _TAIL_BYTES) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - n_bytes))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


def monitor(n_devices: int, light: bool = False,
            report_path: str | None = None,
            deadlines: dict[str, float] | None = None,
            default_deadline_s: float = 900.0, grace_s: float = 120.0,
            poll_s: float = 1.0, total_timeout_s: float | None = None,
            child_argv: list[str] | None = None,
            env: dict | None = None) -> dict:
    """Run the dryrun worker under heartbeat watch; returns the report.

    The report JSON at ``report_path`` (default
    ``$FTS_MULTICHIP_REPORT`` or ``./MULTICHIP_selfdiag.json``) is
    rewritten atomically on every poll tick, so ANY external kill — of
    the worker or of this monitor — leaves a phase-attributed artifact
    behind. ``child_argv`` overrides the spawned command (tests
    substitute a scripted child); the default runs this module as the
    worker.

    The returned dict always has non-empty ``phase`` and a non-empty
    ``tail`` (seeded with the phase + diagnosis when the worker produced
    no output at all) — ``rc=124 with an empty report`` cannot happen by
    construction. ``total_timeout_s`` bounds the WHOLE run on top of the
    per-phase stall deadlines; hitting it is reported as a stall with a
    budget-exceeded diagnosis.
    """
    from ..obs.heartbeat import FileHeartbeatReader, StallDetector
    from ..obs.journal import JOURNAL, configure_from_env

    configure_from_env()
    report_path = (report_path
                   or os.environ.get("FTS_MULTICHIP_REPORT")
                   or os.path.join(os.getcwd(), "MULTICHIP_selfdiag.json"))
    hb_path = f"{report_path}.hb.jsonl"
    log_path = f"{report_path}.log"
    for stale in (hb_path,):
        try:
            os.remove(stale)
        except OSError:
            pass

    if child_argv is None:
        child_argv = [sys.executable, "-u", "-m",
                      "fabric_token_sdk_tpu.parallel.dryrun",
                      "--n-devices", str(n_devices)]
        if light:
            child_argv.append("--light")
    child_env = dict(os.environ if env is None else env)
    child_env.setdefault("PYTHONUNBUFFERED", "1")
    child_env["FTS_HEARTBEAT_FILE"] = hb_path
    flags = child_env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={n_devices}"
    if "xla_llvm_disable_expensive_passes" not in flags:
        flags += " --xla_llvm_disable_expensive_passes=true"
    child_env["XLA_FLAGS"] = flags.strip()

    detector = StallDetector(
        FileHeartbeatReader(hb_path),
        deadlines=dict(DEFAULT_DEADLINES if deadlines is None
                       else deadlines),
        default_deadline_s=default_deadline_s, grace_s=grace_s,
        clock=time.time)

    t0 = time.time()
    report = {
        "schema": "fts-multichip-v2",
        "n_devices": n_devices,
        "light": light,
        "rc": None, "ok": False, "skipped": False,
        "phase": "spawn", "last_heartbeat_age_s": 0.0,
        "tail": "", "elapsed_s": 0.0,
        "stalled": False, "diagnosis": "",
        "log_file": log_path, "heartbeat_file": hb_path,
    }
    _write_report(report_path, report)

    with open(log_path, "wb") as log_f:
        proc = subprocess.Popen(child_argv, cwd=_REPO_ROOT, env=child_env,
                                stdout=log_f, stderr=subprocess.STDOUT)
    stall: tuple[str, float] | None = None
    total_hit = False
    try:
        while True:
            rc = proc.poll()
            now = time.time()
            stamp = detector.reader()
            report["elapsed_s"] = round(now - t0, 3)
            if stamp is not None:
                report["phase"] = stamp.get("phase", "?")
                report["last_heartbeat_age_s"] = round(
                    max(0.0, now - float(stamp.get("t", now))), 3)
            else:
                report["last_heartbeat_age_s"] = report["elapsed_s"]
            report["tail"] = _tail_of(log_path)
            _write_report(report_path, report)
            if rc is not None:
                break
            if (total_timeout_s is not None
                    and now - t0 > total_timeout_s):
                stall = (report["phase"], now - t0)
                total_hit = True
                break
            hit = detector.check()
            if hit is not None:
                stall = hit
                break
            time.sleep(poll_s)

        if stall is not None and proc.poll() is None:
            # stacks first (SIGUSR1 -> faulthandler -> log), then kill
            import signal

            if hasattr(signal, "SIGUSR1"):
                try:
                    proc.send_signal(signal.SIGUSR1)
                    time.sleep(min(3.0, poll_s * 3))
                except OSError:
                    pass
            proc.terminate()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=5.0)

    rc = proc.returncode
    report["rc"] = rc
    report["tail"] = _tail_of(log_path)
    report["elapsed_s"] = round(time.time() - t0, 3)
    if stall is not None:
        phase, age = stall
        report["stalled"] = True
        report["ok"] = False
        report["phase"] = phase
        report["last_heartbeat_age_s"] = round(age, 3)
        if total_hit:
            report["diagnosis"] = (
                f"total dryrun budget exceeded in phase {phase!r} "
                f"({age:.1f}s > total_timeout_s={total_timeout_s:.0f}s); "
                f"worker killed, stacks in tail")
        else:
            report["diagnosis"] = (
                f"stalled in phase {phase!r}: no heartbeat for "
                f"{age:.1f}s (deadline "
                f"{detector.deadline_for(phase):.0f}s); "
                f"worker killed, stacks in tail")
        JOURNAL.incident("multichip_stall", reason=report["diagnosis"],
                         extra={"report": report_path,
                                "phase": phase, "rc": rc})
    else:
        report["ok"] = rc == 0
        report["diagnosis"] = (
            "completed" if rc == 0 else
            f"worker exited rc={rc} in phase {report['phase']!r}")
    if not report["tail"]:
        # the empty-tail rc=124 reports are what this monitor exists to
        # prevent: if the worker really produced no output (died before
        # its first print, unreadable log), the tail still names the
        # phase + diagnosis so the artifact is never blank
        report["tail"] = (f"<no worker output captured> phase="
                          f"{report['phase']!r} rc={rc} "
                          f"diagnosis={report['diagnosis']!r}")
    _write_report(report_path, report)
    return report


if __name__ == "__main__":
    sys.exit(main())
