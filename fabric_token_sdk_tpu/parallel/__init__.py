"""Device-mesh parallelism for batched proof verification.

The reference's only first-class parallelism is goroutine concurrency plus a
sequential per-proof verify loop (SURVEY.md §2.5); here verification scales
over a jax.sharding.Mesh: proofs are data-parallel ('dp') and the MSM term
axis is model-parallel ('tp') with an all-gather + point-fold combine over
ICI (XLA collectives, not NCCL/MPI — SURVEY.md §2.5 "TPU-native equivalent").
"""

from .mesh import (make_mesh, set_heartbeat, shard_batch,  # noqa: F401
                   sharded_msm_is_identity)
