"""Single source of truth for the JAX persistent-compile-cache policy.

The limbed EC kernels trace to large graphs; first compiles take minutes on
both backends. Every entry point (tests, bench, graft entry) funnels through
configure_jax_cache so the policy cannot drift between them.
"""

from __future__ import annotations

import os


def configure_jax_cache() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_CACHE_DIR", "/tmp/jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
