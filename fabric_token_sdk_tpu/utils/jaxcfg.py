"""Single source of truth for the JAX persistent-compile-cache policy.

The limbed EC kernels trace to large graphs; first compiles take minutes on
both backends. Every entry point (tests, bench, graft entry) funnels through
configure_jax_cache so the policy cannot drift between them.
"""

from __future__ import annotations

import os


def raise_stack_limit() -> None:
    """Lift RLIMIT_STACK before XLA compiles anything.

    LLVM's recursive passes compiling the large unrolled EC kernels can
    blow the default 8 MiB thread stack on XLA:CPU (observed as a SIGSEGV
    inside compile_or_get_cached on single-core hosts). Must run before
    jax creates its compilation threads — their stack size is fixed at
    thread creation from the soft limit."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = 512 * 1024 * 1024
        if soft != resource.RLIM_INFINITY and soft < want:
            new_soft = want if hard == resource.RLIM_INFINITY \
                else min(want, hard)
            resource.setrlimit(resource.RLIMIT_STACK, (new_soft, hard))
    except (ImportError, ValueError, OSError):
        pass  # best effort: platform without rlimits or no privilege


def ensure_main_thread_stack() -> None:
    """Give the MAIN thread a big stack by raising RLIMIT_STACK and
    RE-EXECING the interpreter.

    raise_stack_limit() covers threads created afterwards, but the main
    thread's usable stack is fixed at exec time: the kernel computes
    mmap_base from the THEN-current soft limit, so raising it later
    leaves only the original ~8 MiB of growable space. jaxlib's native
    serialize/deserialize of the big MSM executables recurses past that
    ON THE MAIN THREAD — the persistent-cache read/write SIGSEGVs seen
    at jax/_src/compilation_cache.py put/get_executable_and_time.
    Re-exec with the raised limit makes the new process image lay out a
    large main stack; children inherit the raised limit and need no
    re-exec. Must be called BEFORE importing jax."""
    import sys

    if os.environ.get("FTS_STACK_REEXEC"):
        return
    os.environ["FTS_STACK_REEXEC"] = "1"
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = 512 * 1024 * 1024
        if soft == resource.RLIM_INFINITY or soft >= want:
            return  # exec-time limit already big: main stack is fine
        new_soft = want if hard == resource.RLIM_INFINITY \
            else min(want, hard)
        resource.setrlimit(resource.RLIMIT_STACK, (new_soft, hard))
    except (ImportError, ValueError, OSError):
        return  # cannot raise: re-exec would not help
    if "jax" in sys.modules:
        return  # too late: re-exec would replay the caller's side effects
    argv = list(getattr(sys, "orig_argv", []) or [])
    if len(argv) < 2 or not sys.executable:
        return  # interactive session: nothing replayable
    if "-" in argv[1:]:
        return  # program text came from stdin: exec cannot replay it
    sys.stdout.flush()
    sys.stderr.flush()
    try:
        # execv does not search PATH; orig_argv[0] may be a bare "python"
        os.execv(sys.executable, [sys.executable] + argv[1:])
    except OSError:
        pass


def _host_tag() -> str:
    """Fingerprint of the host CPU feature set.

    XLA:CPU AOT cache entries bake in the compile machine's features;
    loading them on a host with a different set fails or SIGILLs
    (observed: /tmp/jax_cache carried over from an avx512+amx machine
    crashed the suite mid-compile). Keying the cache dir by the feature
    set makes stale entries unreachable instead of fatal."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform

    return hashlib.sha256(platform.processor().encode()).hexdigest()[:12]


def install_cache_size_guard(max_hlo_bytes: int | None = None) -> None:
    """Skip persistent-caching of oversized XLA:CPU executables.

    jaxlib's native executable serialize/deserialize SEGFAULTS on the
    biggest MSM kernels (reproduced at compilation_cache.py:265 write and
    :238 read, with unlimited stack — a size-dependent jaxlib bug, not
    resource exhaustion). Entries above the threshold are never written,
    so the poisonous reads can never happen either; those kernels simply
    recompile per process. Threshold is on the HLO-module proto size — a
    cheap, serialize-free proxy measured BEFORE the crashing call.
    """
    import jax  # noqa: F401
    from jax._src import compilation_cache as cc

    if getattr(cc, "_fts_size_guard", False):
        return
    if max_hlo_bytes is None:
        # calibrated: the MSM-class kernels lower to ~55-70 MB HLO /
        # 300-400 MB serialized executables — the size class whose
        # serialize/deserialize crashes; everything smaller has cached
        # reliably across hundreds of runs
        max_hlo_bytes = int(os.environ.get("FTS_CACHE_MAX_HLO_BYTES",
                                           str(30 * 1024 * 1024)))
    orig_put = cc.put_executable_and_time

    def guarded_put(cache_key, module_name, executable, backend,
                    compile_time):
        if backend.platform == "cpu":
            try:
                size = sum(
                    len(m.as_serialized_hlo_module_proto())
                    for m in executable.hlo_modules())
            except Exception:
                size = 0
            if size > max_hlo_bytes:
                import logging

                logging.getLogger("fabric_token_sdk_tpu.jaxcfg").info(
                    "not caching %s: hlo %d bytes > %d (serialize-crash "
                    "guard)", module_name, size, max_hlo_bytes)
                return
        return orig_put(cache_key, module_name, executable, backend,
                        compile_time)

    cc.put_executable_and_time = guarded_put
    cc._fts_size_guard = True


def configure_jax_cache() -> None:
    ensure_main_thread_stack()  # re-execs if jax is not yet imported

    import jax

    raise_stack_limit()
    install_cache_size_guard()
    # BENCH_COMPILE_CACHE_DIR is the bench/serve opt-in for a cache that
    # PERSISTS across container runs (bench.py points it at benchdata/);
    # JAX_CACHE_DIR stays the generic override, /tmp the throwaway default.
    base = os.environ.get("BENCH_COMPILE_CACHE_DIR") \
        or os.environ.get("JAX_CACHE_DIR", "/tmp/jax_cache")
    # Segment by backend platform AND host CPU: the axon (remote-TPU)
    # client writes XLA:CPU AOT artifacts compiled on the REMOTE host into
    # the cache; loading those under the local cpu backend SIGILLs/aborts
    # (root cause of the mid-suite faulthandler crashes).
    platform = (jax.config.jax_platforms or "default").replace(",", "_")
    # ... AND by the virtual-device-count config: XLA:CPU AOT executables
    # bake pseudo target features (+prefer-no-scatter/+prefer-no-gather)
    # that differ between a plain 1-device process and one running under
    # --xla_force_host_platform_device_count=N; entries written by one
    # config fail the other's AOT machine-feature validation and force a
    # full recompile (the round-3 multichip-gate timeout). Separate dirs
    # make the mismatch unreachable.
    ndev = "1"
    flags = os.environ.get("XLA_FLAGS", "")
    for tok in flags.split():
        if tok.startswith("--xla_force_host_platform_device_count="):
            ndev = tok.split("=", 1)[1]
    jax.config.update("jax_compilation_cache_dir",
                      f"{base}-{platform}-{_host_tag()}-d{ndev}")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
